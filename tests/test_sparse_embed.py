"""True sparse embedding updates (train/sparse_embed.py): the
touched-rows-only step must be NUMERICALLY EQUIVALENT to the dense
recsys path it replaces — same rowwise-AdaGrad math per unique row,
duplicate ids aggregated exactly like gather autodiff does, untouched
rows bit-frozen — while never materializing the dense table cotangent
or the full-table optimizer sweep (the criteo step's dominant HBM
traffic, BASELINE.md roofline)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.datasets import get_dataset
from mlapi_tpu.models import get_model
from mlapi_tpu.train import fit
from mlapi_tpu.train.loop import _make_optimizer, make_train_step
from mlapi_tpu.train.sparse_embed import make_sparse_recsys_step

SMALL = dict(
    num_dense=4,
    vocab_sizes=[64] * 6,   # tiny vocab: duplicate ids guaranteed
    embed_dim=8,
    hidden_dims=[32],
    num_classes=2,
)


@pytest.fixture(scope="module")
def model():
    return get_model("wide_deep", **SMALL)


@pytest.fixture(scope="module")
def batch(model):
    rng = np.random.default_rng(3)
    b = 256
    x = np.concatenate(
        [
            rng.normal(size=(b, SMALL["num_dense"])).astype(np.float32),
            rng.integers(0, 64, size=(b, 6)).astype(np.float32),
        ],
        axis=1,
    )
    y = rng.integers(0, 2, size=(b,)).astype(np.int32)
    # b=256 over vocab 64: every table sees many duplicate ids per
    # batch — the aggregation path is exercised on every step.
    return x, y


def _run_dense(model, params, x, y, steps, lr):
    tx = _make_optimizer("recsys-adamw", lr, model=model, params=params)
    opt_state = tx.init(params)
    step = make_train_step(model.apply, tx)
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, x, y)
    return params, float(loss)


def _run_sparse(model, params, x, y, steps, lr):
    base = _make_optimizer("adamw", lr)
    init, step = make_sparse_recsys_step(model, base, lr)
    opt_state = init(params)
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, x, y)
    return params, opt_state, float(loss)


def test_sparse_step_matches_dense_recsys_exactly(model, batch):
    """5 steps of the sparse path == 5 steps of the dense
    recsys-adamw path, leaf for leaf: the sparse scatter update is
    the same rowwise-AdaGrad trajectory, not an approximation."""
    x, y = batch
    p0 = model.init(jax.random.key(0))
    dense_p, dense_loss = _run_dense(model, p0, x, y, 5, 3e-3)
    p0 = model.init(jax.random.key(0))
    sparse_p, _, sparse_loss = _run_sparse(model, p0, x, y, 5, 3e-3)
    assert np.isclose(dense_loss, sparse_loss, rtol=1e-5)
    dl, treedef = jax.tree.flatten(dense_p)
    # flatten_up_to validates sparse_p's structure AGAINST dense_p's
    # treedef, so the zipped leaves are guaranteed aligned.
    sl = treedef.flatten_up_to(sparse_p)
    paths = [str(k) for k, _ in jax.tree_util.tree_flatten_with_path(
        dense_p)[0]]
    for path, a, b in zip(paths, dl, sl):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b),
            rtol=2e-5, atol=2e-6, err_msg=path,
        )


def test_untouched_rows_are_bit_frozen(model, batch):
    """Rows no batch id referenced must be BITWISE unchanged — the
    defining property of the sparse update (the dense path rewrites
    them with identical values; the sparse path never touches them)."""
    x, y = batch
    params = model.init(jax.random.key(1))
    before = np.asarray(params["deep_tables"]).copy()
    ids = np.asarray(model.embedding_ids(jnp.asarray(x)))
    p2, _, _ = _run_sparse(model, params, x, y, 3, 3e-3)
    after = np.asarray(p2["deep_tables"])
    touched = np.zeros((6, 64), bool)
    touched[np.arange(6)[None, :], ids] = True
    assert (before[~touched] == after[~touched]).all()
    assert not np.allclose(before[touched], after[touched])


def test_fit_integration_matches_dense_and_learns(model):
    """fit(optimizer="recsys-sparse-adamw") reproduces the dense
    recsys-adamw run EXACTLY (same minibatch sequence, same rowwise-
    AdaGrad trajectory — measured identical to the printed digits)
    and learns the planted structure well above chance. The dense
    baseline is run here, not assumed: plain adam reaches ~0.75 on
    this config but AdaGrad-on-tables converges slower — the sparse
    path's contract is equivalence with ITS dense counterpart."""
    splits = get_dataset(
        "criteo", num_dense=4, num_categorical=6, vocab_size=512,
        n_train=8192, n_test=1024,
    )
    big = get_model("wide_deep", **dict(SMALL, vocab_sizes=[512] * 6))
    dense = fit(big, splits, steps=150, batch_size=512,
                learning_rate=3e-3, optimizer="recsys-adamw")
    sparse = fit(big, splits, steps=150, batch_size=512,
                 learning_rate=3e-3, optimizer="recsys-sparse-adamw")
    assert sparse.test_accuracy == pytest.approx(
        dense.test_accuracy, abs=1e-3
    )
    assert np.isclose(sparse.final_loss, dense.final_loss, rtol=1e-4)
    assert sparse.test_accuracy > 0.58  # planted structure, 0.5 chance


def test_sharded_fit_on_2x4_mesh(model, mesh_2x4):
    """The scatter update composes with model-axis-sharded tables
    (GSPMD handles cross-shard ids); params keep the declared
    layout."""
    splits = get_dataset(
        "criteo", num_dense=4, num_categorical=6, vocab_size=512,
        n_train=4096, n_test=512,
    )
    big = get_model("wide_deep", **dict(SMALL, vocab_sizes=[512] * 6))
    r = fit(big, splits, steps=60, batch_size=512, learning_rate=3e-3,
            optimizer="recsys-sparse-adamw", mesh=mesh_2x4)
    assert np.isfinite(r.final_loss)
    spec = tuple(r.params["deep_tables"].sharding.spec)
    assert spec in ((None, "model", None), (None, "model"))


def test_guards_are_loud(model):
    base = _make_optimizer("adamw", 1e-3)
    with pytest.raises(ValueError, match="weight_decay"):
        make_sparse_recsys_step(model, base, 1e-3, weight_decay=0.1)
    with pytest.raises(ValueError, match="classification"):
        make_sparse_recsys_step(model, base, 1e-3, task="lm")
    lm = get_model(
        "gpt_lm", vocab_size=64, hidden_size=16, num_layers=1,
        num_heads=2, max_positions=32,
    )
    with pytest.raises(ValueError, match="protocol"):
        make_sparse_recsys_step(lm, base, 1e-3)


@pytest.mark.requires_tpu
def test_sparse_matches_dense_on_tpu(model, batch):
    """The sparse scatter pipeline on REAL Mosaic lowering: TPU
    scatter/segment-sum must reproduce the dense trajectory exactly
    like the CPU run does (this is the alive-window harvest's
    on-chip check for the r05 flagship)."""
    x, y = batch
    p0 = model.init(jax.random.key(0))
    dense_p, dense_loss = _run_dense(model, p0, x, y, 3, 3e-3)
    p0 = model.init(jax.random.key(0))
    sparse_p, _, sparse_loss = _run_sparse(model, p0, x, y, 3, 3e-3)
    assert np.isclose(dense_loss, sparse_loss, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(dense_p["deep_tables"]),
        np.asarray(sparse_p["deep_tables"]),
        rtol=2e-5, atol=2e-6,
    )


def test_sparse_resume_matches_uninterrupted_run(model, tmp_path):
    """The criteo preset trains 2000 steps with --save-every as a real
    workflow: a sparse-optimizer run resumed from its train-state
    checkpoint (custom {"base", "acc"} opt_state pytree through orbax)
    must land on the uninterrupted trajectory."""
    splits = get_dataset(
        "criteo", num_dense=4, num_categorical=6, vocab_size=64,
        n_train=1024, n_test=128,
    )
    kwargs = dict(batch_size=128, learning_rate=3e-3, seed=3,
                  optimizer="recsys-sparse-adamw")
    full = fit(model, splits, steps=40, **kwargs)

    ck = tmp_path / "train_state"
    fit(model, splits, steps=20, checkpoint_dir=str(ck), save_every=10,
        **kwargs)
    resumed = fit(model, splits, steps=40, checkpoint_dir=str(ck),
                  save_every=10, **kwargs)
    for a, b in zip(jax.tree.leaves(full.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        )
