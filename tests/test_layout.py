"""SpecLayout is the single source of truth for mesh axis names:
every TP model's ``param_shardings(layout)`` must consume it, so a
mesh with renamed axes works without touching model code."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mlapi_tpu.models import get_model
from mlapi_tpu.parallel import SpecLayout, create_mesh, params_for_model


@pytest.mark.parametrize(
    "name,kwargs",
    [
        (
            "gpt_lm",
            dict(vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
                 max_positions=32),
        ),
        (
            "bert_classifier",
            dict(num_classes=2, vocab_size=64, hidden_size=32, num_layers=1,
                 num_heads=2, intermediate_size=64, max_positions=32),
        ),
        (
            "wide_deep",
            dict(num_dense=4, vocab_sizes=(64, 64, 64), embed_dim=8),
        ),
    ],
)
def test_param_shardings_consume_layout(name, kwargs):
    model = get_model(name, **kwargs)
    renamed = SpecLayout(data_axis="dp", model_axis="tp")
    leaves = jax.tree.leaves(
        model.param_shardings(renamed), is_leaf=lambda x: isinstance(x, P)
    )
    axes = {a for spec in leaves for a in spec if a is not None}
    assert axes == {"tp"}, f"{name}: expected only renamed axes, got {axes}"
    # Default layout still names the canonical axes.
    default_axes = {
        a
        for spec in jax.tree.leaves(
            model.param_shardings(), is_leaf=lambda x: isinstance(x, P)
        )
        for a in spec
        if a is not None
    }
    assert default_axes == {"model"}


def test_renamed_mesh_end_to_end():
    """A (dp, tp)-named mesh + SpecLayout places params and runs the
    forward identically to the replicated baseline."""
    model = get_model(
        "gpt_lm", vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        max_positions=32, compute_dtype="float32",
    )
    params = model.init(jax.random.key(0))
    mesh = create_mesh((2, 4), axis_names=("dp", "tp"))
    layout = SpecLayout(data_axis="dp", model_axis="tp")
    placed = params_for_model(model, params, mesh, layout)
    assert tuple(placed["wte"].sharding.spec)[0] == "tp"
    ids = np.ones((4, 16), np.int32)
    ref = np.asarray(jax.jit(model.apply)(params, ids))
    out = np.asarray(jax.jit(model.apply)(placed, ids))
    np.testing.assert_allclose(out, ref, atol=1e-5)
