"""Peer-to-peer prefix-KV fetch (``serving/kv_peer.py``, the r17
wire hop between replica tiers; ``--kv-peer-fetch``).

The contract, layer by layer:

- **Wire format**: serialize → deserialize round-trips every leaf
  byte-identically with the geometry header intact; payload bytes are
  EXACTLY the ``num_pages × kv_page_bytes`` closed form for BOTH
  cache formats; truncated/garbled/inconsistent bodies raise (and are
  counted misses at the fetch seam, never installed).
- **The serving stack**: a replica that misses a prefix locally
  fetches the blob from its hinted warm peer on the encode executor
  thread, rebuilds the entry with ZERO prefill FLOPs
  (``PrefixCache.builds`` stays flat — the pinned counter, never
  wall-clock), stages the blob into its local tier, and the
  dispatch-thread paged formation restores pool pages through the
  existing alloc-first ``PagePool.restore_entry`` path. Streams are
  TOKEN-IDENTICAL peer-restored vs never-evicted across
  {gpt-MHA, llama-GQA} × {none, int8}.
- **Failure discipline**: geometry drift and corrupt wire bodies are
  counted misses that go cold; injected ``peer_fetch``/``peer_serve``
  raises are counted failures that go cold — all with
  ``kv_pages_in_use`` conserved and streams completing; pool pressure
  mid-restore rejects loudly with nothing half-installed (the staged
  peer blob takes the same alloc-first path a local spill does).
- **Topology**: the endpoint and the hint header are replica-gated;
  an in-process 2-replica fleet behind the real router warm-starts a
  drained replica's slice on the survivor with ``prefix_builds``
  staying at 1 fleet-wide.

Engines here reuse ``test_paged_kv``/``test_paged_kv_tier``'s
tiny-model CFG so the jitted program factories are shared across the
family (conftest ``paged-family``) instead of compiled again.
"""

import asyncio
import json

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.quant import kv_page_bytes
from mlapi_tpu.serving import faults
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.serving.kv_peer import (
    KVPeer,
    deserialize_blob,
    fp_digest,
    serialize_blob,
)
from mlapi_tpu.serving.kv_tier import KVTierBlob, payload_bytes
from mlapi_tpu.serving.paged_pool import PagePoolExhausted
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)


def _model(kind="gpt_lm", kv_quant="none"):
    kw = dict(CFG, kv_quant=kv_quant)
    if kind == "llama_lm":
        kw["num_kv_heads"] = 2  # GQA: 4 query heads over 2 KV heads
    return get_model(kind, **kw)


@pytest.fixture(scope="module")
def gpt_params():
    return _model().init(jax.random.key(0))


@pytest.fixture(scope="module")
def llama_params():
    return _model("llama_lm").init(jax.random.key(0))


def _engine(model, params, **kw):
    kw.setdefault("chunk", 2)
    kw.setdefault("fused_single", False)
    kw.setdefault("kv_page_size", 8)
    kw.setdefault("kv_tier_bytes", 1 << 24)
    kw.setdefault("kv_peer_fetch", True)
    return TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), **kw
    )


def _wire(warm_engine):
    """An in-process transport serving ``warm_engine``'s blobs — the
    exact serve path (``KVPeer.serve_wire``, fault point included)
    without a socket, so the fetch client, wire format, counters, and
    restore path are all real."""

    def transport(host, port, path, timeout_s):
        digest = path.split("fp=", 1)[1]
        data = warm_engine.kv_peer.serve_wire(digest)
        return (200, data) if data is not None else (404, b"")

    return transport


def _link(cold_engine, warm_engine, fp):
    """Hint ``cold_engine`` that ``warm_engine`` is warm for ``fp``,
    over the in-process transport."""
    cold_engine.kv_peer._transport = _wire(warm_engine)
    cold_engine.kv_peer.note_hint(fp, "127.0.0.1:19")


PRE = "You are a helpful bot."


# --- wire format -------------------------------------------------------


def test_wire_roundtrip_and_validation():
    rng = np.random.default_rng(0)
    payload = {
        "layer_0": {
            "k": rng.standard_normal((3, 8, 4, 8)).astype(np.float32),
            "v": rng.standard_normal((3, 8, 4, 8)).astype(np.float32),
        },
        "layer_1": {
            "k_q": rng.integers(-128, 127, (3, 8, 4, 8)).astype(np.int8),
            "k_scale": rng.standard_normal((3, 8, 4, 1)).astype(
                np.float32
            ),
        },
    }
    blob = KVTierBlob(
        "fp", payload, 8, payload_bytes(payload), 24, 2, 22
    )
    data = serialize_blob(blob)
    out = deserialize_blob("fp", data)
    assert (out.page, out.num_pages, out.nbytes) == (8, 3, blob.nbytes)
    assert (out.bucket, out.lo, out.used) == (24, 2, 22)
    for ln, layer in payload.items():
        for name, a in layer.items():
            np.testing.assert_array_equal(out.payload[ln][name], a)

    # Every corruption class raises (→ a counted miss at the fetch
    # seam), never a wrong install.
    for bad in (
        b"garbage with no header",
        b"{}\n",                                  # header missing fields
        data[: len(data) // 2],                   # truncated payload
        data + b"x",                              # trailing bytes
        data.replace(b'"nbytes": ', b'"nbytes": 9', 1),  # byte total lies
    ):
        with pytest.raises(ValueError):
            deserialize_blob("fp", bad)
    # Leaf shape not [num_pages, page, ...]: refused.
    bad_blob = KVTierBlob(
        "fp",
        {"l": {"k": np.zeros((3, 4, 2), np.float32)}},
        8, 3 * 4 * 2 * 4, 24, 2, 22,
    )
    with pytest.raises(ValueError):
        deserialize_blob("fp", serialize_blob(bad_blob))
    # A negative manifest dim would make the leaf's byte size
    # negative — np.frombuffer(count<0) silently reads the whole
    # remaining buffer and the truncation check never trips — so
    # non-positive dims are refused outright.
    head_line, _, rest = data.partition(b"\n")
    head = json.loads(head_line)
    head["leaves"][0][2] = [3, 8, -1]
    with pytest.raises(ValueError):
        deserialize_blob("fp", json.dumps(head).encode() + b"\n" + rest)
    # TypeError-shaped corruption (non-int metadata, non-list leaf
    # manifest) must surface as the one documented ValueError too —
    # the fetch path's degradation contract keys on it.
    for tamper in (
        {"bucket": {}},
        {"leaves": 5},
        {"leaves": [3]},
    ):
        bad_head = dict(json.loads(head_line), **tamper)
        with pytest.raises(ValueError):
            deserialize_blob(
                "fp", json.dumps(bad_head).encode() + b"\n" + rest
            )


def test_fp_digest_is_stable_and_urlsafe():
    d = fp_digest(PRE)
    assert d == fp_digest(PRE) and len(d) == 32
    assert all(c in "0123456789abcdef" for c in d)
    assert fp_digest("other") != d


# --- peer-restored streams: identity, zero prefill FLOPs ---------------


@pytest.mark.parametrize("fmt", ["none", "int8"])
@pytest.mark.parametrize("kind", ["gpt_lm", "llama_lm"])
def test_peer_restored_stream_identity(kind, fmt, gpt_params, llama_params):
    """The acceptance pin: a cold replica serving a prefix it peer-
    fetched streams TOKEN-IDENTICAL to the warm replica that built it,
    with zero cold prefills (``builds`` == 0) — and the wire bytes
    equal the ``num_pages × kv_page_bytes`` closed form, both cache
    formats, MHA and GQA. The staged blob's pool pages restore
    through ``restore_entry`` (the tier's restore_hits move), so the
    dispatch thread never saw the wire."""
    params = gpt_params if kind == "gpt_lm" else llama_params
    model = _model(kind, fmt)
    warm = _engine(model, params)
    cold = _engine(model, params)
    ref = warm.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert warm.prefix.builds == 1
    n_pages = len(warm.pool.entry_pages(PRE))
    closed = n_pages * kv_page_bytes(model, warm.pool.page)

    _link(cold, warm, PRE)
    out = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert out["token_ids"] == ref["token_ids"]
    assert cold.prefix.builds == 0               # zero prefill FLOPs
    assert cold.kv_peer.fetch_hits == 1
    assert cold.kv_peer.fetch_bytes == closed    # wire closed form
    assert warm.kv_peer.serve_count == 1
    assert warm.kv_peer.serve_bytes == closed
    # The fetched blob was staged locally and its pool pages restored
    # through the alloc-first restore path, not the adopt copy.
    assert cold.kv_tier.entries == 1
    assert cold.kv_tier.restore_hits == 1
    assert cold.kv_tier.spill_count == 0         # staging is not a spill
    # Steady state: the second arrival is a plain device-cache hit.
    out2 = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert out2["token_ids"] == ref["token_ids"]
    assert cold.kv_peer.fetch_hits == 1


def test_peer_serves_from_tier_blob_after_eviction(gpt_params):
    """The warm peer's blob may live in its HOST TIER rather than on
    device (that is the failover reality after pressure): the serve
    path prefers the tier blob and the fetch still restores
    byte-identically."""
    model = _model()
    warm = _engine(model, gpt_params)
    cold = _engine(model, gpt_params)
    ref = warm.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert warm.pool.evict_idle(1) == 1          # blob now tier-only
    assert warm.pool.entry_pages(PRE) is None
    misses_before = warm.kv_tier.restore_misses
    _link(cold, warm, PRE)
    out = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert out["token_ids"] == ref["token_ids"]
    assert cold.prefix.builds == 0
    assert warm.kv_peer.serve_count == 1
    # Serving a peer is not a local restore attempt: the warm tier's
    # restore counters did not move (lookup(count=False)).
    assert warm.kv_tier.restore_misses == misses_before


def test_serve_wire_image_is_cached_and_identical(gpt_params):
    """The serve path caches the serialized wire image per digest
    (blob bytes for a prefix are deterministic per engine config —
    the r13 byte-identity pins), so N-1 peers fetching one hot
    prefix cost ONE device gather + serialize. Counters still count
    every serve (they measure wire traffic out)."""
    model = _model()
    warm = _engine(model, gpt_params)
    warm.generate_text(" q1", max_new_tokens=4, prefix=PRE)
    d = fp_digest(PRE)
    first = warm.kv_peer.serve_wire(d)
    second = warm.kv_peer.serve_wire(d)
    assert second == first                       # byte-identical image
    assert warm.kv_peer.serve_count == 2
    assert len(warm.kv_peer._serve_cache) == 1
    # The cached image deserializes to the same blob either way.
    assert deserialize_blob(PRE, second).nbytes == deserialize_blob(
        PRE, first
    ).nbytes
    # Cap bounds it: serving other prefixes rolls the LRU, never grows.
    for i in range(6):
        p = f"other prefix {i}"
        warm.generate_text(" q", max_new_tokens=2, prefix=p)
        assert warm.kv_peer.serve_wire(fp_digest(p)) is not None
    assert len(warm.kv_peer._serve_cache) <= warm.kv_peer._serve_cache_cap


def test_no_hint_or_disabled_goes_cold(gpt_params):
    """No hint (direct traffic) → no fetch, plain cold build; peer
    fetch disabled → no peer state at all, bit-identical to r16."""
    model = _model()
    warm = _engine(model, gpt_params)
    ref = warm.generate_text(" q1", max_new_tokens=6, prefix=PRE)

    cold = _engine(model, gpt_params)
    cold.kv_peer._transport = _wire(warm)        # linked but unhinted
    out = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert out["token_ids"] == ref["token_ids"]
    assert cold.prefix.builds == 1
    assert cold.kv_peer.fetch_hits == 0
    assert cold.kv_peer.fetch_misses == 0
    assert cold.kv_peer.fetch_failures == 0

    off = _engine(model, gpt_params, kv_peer_fetch=False)
    assert off.kv_peer is None
    assert off.kv_peer_fetch_hits == 0 and off.kv_peer_serve_bytes == 0
    out = off.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert out["token_ids"] == ref["token_ids"]
    assert off.prefix.builds == 1


# --- failure discipline ------------------------------------------------


def test_geometry_drift_dropped_as_miss(gpt_params):
    """A peer running a DIFFERENT bucket geometry serves a blob whose
    bucket cannot match what a local build produces today: counted as
    a fetch miss, never installed, stream served by the cold build —
    and nothing was staged locally."""
    model = _model()
    # A smaller first prompt bucket buckets the 22-token prefix to 32
    # on the peer vs 64 locally — real config drift, not corruption.
    warm = _engine(model, gpt_params, prompt_buckets=(32, 64, 128))
    cold = _engine(model, gpt_params)
    warm.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert warm.prefix._entries[PRE].bucket != cold.prefix._plan(PRE)[1]
    _link(cold, warm, PRE)
    out = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    ref = _engine(model, gpt_params, kv_peer_fetch=False).generate_text(
        " q1", max_new_tokens=6, prefix=PRE
    )
    assert out["token_ids"] == ref["token_ids"]
    assert cold.kv_peer.fetch_misses == 1
    assert cold.kv_peer.fetch_hits == 0
    assert cold.prefix.builds == 1               # the cold build ran
    assert cold.kv_tier.entries == 0             # nothing staged
    # Config drift is persistent: the hint is dropped so future cold
    # arrivals of this prefix never re-transfer the inapplicable blob.
    assert cold.kv_peer.hint_for(PRE) is None
    with cold.prefix._lock:
        cold.prefix._entries.pop(PRE, None)
    cold.pool.drop_entry(PRE)
    cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert cold.kv_peer.fetch_misses == 1        # no second wire hop


def test_corrupt_wire_body_is_miss(gpt_params):
    model = _model()
    cold = _engine(model, gpt_params)
    bodies = [b"total garbage", b""]
    cold.kv_peer._transport = (
        lambda h, p, path, t: (200, bodies.pop(0))
    )
    cold.kv_peer.note_hint(PRE, "127.0.0.1:19")
    out = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert out["token_ids"]
    assert cold.kv_peer.fetch_misses == 1
    assert cold.prefix.builds == 1


def test_peer_404_is_miss_and_drops_hint(gpt_params):
    """A 404 means the hinted peer is not warm after all (evicted,
    restarted): counted a miss AND the hint dropped, so the next miss
    does not re-pay a hop that cannot help."""
    model = _model()
    calls = []
    cold = _engine(model, gpt_params)
    cold.kv_peer._transport = (
        lambda h, p, path, t: (calls.append(path), (404, b""))[1]
    )
    cold.kv_peer.note_hint(PRE, "127.0.0.1:19")
    cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert len(calls) == 1 and cold.kv_peer.fetch_misses == 1
    assert cold.kv_peer.hint_for(PRE) is None
    # A second cold miss (entry evicted) makes no second wire call.
    cold.prefix.max_entries = 1
    cold.generate_text(" q", max_new_tokens=4, prefix="other")
    cold.kv_tier.drop(PRE)
    cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert len(calls) == 1


def test_transport_error_is_failure(gpt_params):
    model = _model()
    cold = _engine(model, gpt_params)

    def boom(h, p, path, t):
        raise ConnectionRefusedError("peer is down")

    cold.kv_peer._transport = boom
    cold.kv_peer.note_hint(PRE, "127.0.0.1:19")
    out = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert out["token_ids"] and cold.prefix.builds == 1
    assert cold.kv_peer.fetch_failures == 1


def test_peer_fault_matrix_degrades_cold_and_conserves_pages(gpt_params):
    """The r12/r13 fault-matrix extension (satellite): a raise at
    ``peer_fetch`` or ``peer_serve`` falls back to the cold prefill
    with ``kv_pages_in_use`` conserved on BOTH replicas and streams
    completing; delays slow, never break."""
    model = _model()
    warm = _engine(model, gpt_params)
    ref = warm.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    warm_pages = warm.kv_pages_in_use

    for spec, counter in (
        ("peer_fetch:raise", "fetch_failures"),
        ("peer_serve:raise", "fetch_failures"),
    ):
        cold = _engine(model, gpt_params)
        _link(cold, warm, PRE)
        with faults.active(spec):
            out = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
        assert out["token_ids"] == ref["token_ids"], spec
        assert cold.prefix.builds == 1, spec     # cold path served
        assert getattr(cold.kv_peer, counter) == 1, spec
        assert cold.kv_peer.fetch_hits == 0, spec
        # Pages conserved everywhere: the cold replica holds exactly
        # its own entry's pages; the warm one is untouched.
        assert cold.kv_pages_in_use == len(cold.pool.entry_pages(PRE))
        assert warm.kv_pages_in_use == warm_pages, spec
        assert warm.kv_peer.serve_count == 0, spec

    # Delays at both points: slowed, byte-complete, counted.
    cold = _engine(model, gpt_params)
    _link(cold, warm, PRE)
    with faults.active("peer_fetch:delay=0.01,peer_serve:delay=0.01"):
        out = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
        assert faults.injected_count() == 2
    assert out["token_ids"] == ref["token_ids"]
    assert cold.prefix.builds == 0 and cold.kv_peer.fetch_hits == 1


def test_pool_exhaustion_mid_restore_loud(gpt_params):
    """Pool pressure while a peer-staged blob restores: the staged
    blob takes the same alloc-first ``restore_entry`` path a local
    spill does, so exhaustion propagates loudly with NOTHING
    half-installed — and the stream serves once pressure lifts."""
    model = _model()
    warm = _engine(model, gpt_params)
    ref = warm.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    cold = _engine(model, gpt_params)
    _link(cold, warm, PRE)
    out = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert out["token_ids"] == ref["token_ids"]
    # Evict the restored pages (blob stays staged in the local tier),
    # then squeeze the pool below the blob's page need.
    assert cold.pool.evict_idle(1) == 1
    n_pages = cold.kv_tier.lookup(PRE, count=False).num_pages
    free = cold.kv_pages_total - cold.kv_pages_in_use
    hold = cold.pool.alloc(free - (n_pages - 1))
    with pytest.raises(PagePoolExhausted):
        cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert cold.kv_pages_in_use == len(hold)     # nothing installed
    assert cold.pool.entry_pages(PRE) is None
    assert cold.kv_tier.entries == 1             # staged blob intact
    cold.pool.release(hold)
    out2 = cold.generate_text(" q1", max_new_tokens=6, prefix=PRE)
    assert out2["token_ids"] == ref["token_ids"]
    assert cold.kv_peer.fetch_hits == 1          # no re-fetch needed


# --- the replica surface (endpoint + header gating) --------------------


async def _asgi_client(app):
    import httpx

    await app.startup()
    transport = httpx.ASGITransport(app=app)
    return httpx.AsyncClient(transport=transport, base_url="http://t")


async def test_kv_endpoint_serves_and_404s(gpt_params, monkeypatch):
    import httpx  # noqa: F401 — the fixture family imports it anyway

    from mlapi_tpu.serving import build_app

    monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
    eng = _engine(_model(), gpt_params)
    eng.generate_text(" q1", max_new_tokens=4, prefix=PRE)
    app = build_app(eng)
    cl = await _asgi_client(app)
    try:
        r = await cl.get(f"/kv/prefix?fp={fp_digest(PRE)}")
        assert r.status_code == 200
        assert r.headers["content-type"] == "application/octet-stream"
        blob = deserialize_blob(PRE, r.content)
        n_pages = len(eng.pool.entry_pages(PRE))
        assert blob.nbytes == n_pages * kv_page_bytes(
            eng.model, eng.pool.page
        )
        assert eng.kv_peer.serve_count == 1
        assert (await cl.get("/kv/prefix?fp=" + "0" * 32)).status_code == 404
        assert (await cl.get("/kv/prefix")).status_code == 422
        # The /metrics peer block exports all six counters.
        snap = (await cl.get("/metrics")).json()
        c = snap["counters"]
        assert c["generate.kv_peer_serve_count"] == 1
        assert c["generate.kv_peer_serve_bytes"] == blob.nbytes
        for k in ("hits", "misses", "bytes", "failures"):
            assert c[f"generate.kv_peer_fetch_{k}"] == 0
    finally:
        await cl.aclose()
        await app.shutdown()


async def test_endpoint_absent_and_metrics_silent_when_disabled(
    gpt_params, monkeypatch,
):
    from mlapi_tpu.serving import build_app

    monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
    eng = _engine(_model(), gpt_params, kv_peer_fetch=False)
    app = build_app(eng)
    cl = await _asgi_client(app)
    try:
        r = await cl.get(f"/kv/prefix?fp={fp_digest(PRE)}")
        assert r.status_code == 404              # route never installed
        snap = (await cl.get("/metrics")).json()
        assert not any(
            k.startswith("generate.kv_peer") for k in snap["counters"]
        )
    finally:
        await cl.aclose()
        await app.shutdown()


async def test_endpoint_absent_on_non_replica_even_when_enabled(
    gpt_params, monkeypatch,
):
    """The endpoint install is replica-gated like the hint header: a
    direct-facing server with the flag on (but no router fleet) must
    not expose a cache-presence oracle that hands raw KV bytes to
    arbitrary callers."""
    from mlapi_tpu.serving import build_app

    monkeypatch.delenv("MLAPI_TPU_REPLICA", raising=False)
    monkeypatch.delenv("MLAPI_TPU_REPLICAS", raising=False)
    eng = _engine(_model(), gpt_params)          # kv_peer_fetch=True
    eng.generate_text(" q1", max_new_tokens=4, prefix=PRE)
    app = build_app(eng)
    cl = await _asgi_client(app)
    try:
        r = await cl.get(f"/kv/prefix?fp={fp_digest(PRE)}")
        assert r.status_code == 404
        assert eng.kv_peer.serve_count == 0
    finally:
        await cl.aclose()
        await app.shutdown()


async def test_warm_peer_header_gated_to_replicas(gpt_params, monkeypatch):
    """The hint header is trusted only on router replicas (the
    x-mlapi-router-depth trust model): a direct caller must not be
    able to aim this server's KV fetches at an arbitrary host."""
    from mlapi_tpu.serving import build_app

    async def post(eng_env_replica: bool):
        if eng_env_replica:
            monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
        else:
            monkeypatch.delenv("MLAPI_TPU_REPLICA", raising=False)
        eng = _engine(_model(), gpt_params)
        app = build_app(eng)
        cl = await _asgi_client(app)
        try:
            r = await cl.post(
                "/generate",
                json={"text": " q", "prefix": PRE, "max_new_tokens": 2},
                headers={"x-mlapi-warm-peer": "10.0.0.9:8001"},
            )
            assert r.status_code == 200
        finally:
            await cl.aclose()
            await app.shutdown()
        return eng

    eng = await post(True)
    assert eng.kv_peer.hint_for(PRE) == ("10.0.0.9", 8001)
    eng = await post(False)
    assert eng.kv_peer.hint_for(PRE) is None


def test_malformed_hint_never_becomes_a_connect(gpt_params):
    peer = KVPeer(object())
    for bad in ("", "nohost", "host:notaport", ":", "host:"):
        peer.note_hint("fp", bad)
        assert peer.hint_for("fp") is None


# --- the 2-replica e2e: a drained replica's slice warm-starts ----------


async def test_drained_slice_warm_starts_on_survivor(
    gpt_params, monkeypatch,
):
    """The satellite e2e, real sockets end to end: replica A builds a
    prefix (1 cold build), A drains, the router remaps A's slice to B
    with a warm-peer hint, and B serves the prefix by fetching A's
    blob over real HTTP — ``prefix_builds`` stays at 1 FLEET-WIDE,
    token streams identical before and after the failover."""
    import httpx

    from mlapi_tpu.serving import build_app
    from mlapi_tpu.serving.router import Router, build_router_app, hrw_order
    from mlapi_tpu.serving.server import Server

    monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
    engines = [_engine(_model(), gpt_params) for _ in range(2)]
    servers = []
    for eng in engines:
        srv = Server(
            build_app(eng, admission_control=False),
            host="127.0.0.1", port=0,
        )
        await srv.start()
        servers.append(srv)
    router = Router(
        [("127.0.0.1", s.port) for s in servers], health_poll_s=0.05
    )
    front = Server(build_router_app(router), host="127.0.0.1", port=0)
    await front.start()
    try:
        # A prefix whose HRW head is replica 0 ("A").
        names = [r.name for r in router.replicas]
        pre = next(
            f"warm start prompt {i}"
            for i in range(1000)
            if hrw_order(
                f"warm start prompt {i}".encode()[
                    : router.affinity_prefix_bytes
                ],
                names,
            )[0] == names[0]
        )
        a_eng, b_eng = engines
        payload = {"text": " go", "prefix": pre, "max_new_tokens": 6}
        async with httpx.AsyncClient(timeout=60.0) as c:
            url = f"http://127.0.0.1:{front.port}/generate"
            r1 = await c.post(url, json=payload)
            assert r1.status_code == 200
            assert a_eng.prefix.builds == 1 and b_eng.prefix.builds == 0

            # Drain A; the poll flips it; its slice remaps to B.
            await a_eng.drain(0.05)
            for _ in range(100):
                await asyncio.sleep(0.05)
                if router.replicas[0].state == "draining":
                    break
            assert router.replicas[0].state == "draining"

            r2 = await c.post(url, json=payload)
            assert r2.status_code == 200
            assert r2.json()["token_ids"] == r1.json()["token_ids"]
        # The whole point: B served A's slice WITHOUT a cold prefill —
        # one build fleet-wide — because it fetched A's blob (A still
        # serves GET /kv while draining).
        assert b_eng.prefix.builds == 0
        assert a_eng.prefix.builds + b_eng.prefix.builds == 1
        assert b_eng.kv_peer.fetch_hits == 1
        assert a_eng.kv_peer.serve_count == 1
        assert b_eng.kv_peer.fetch_bytes == a_eng.kv_peer.serve_bytes > 0
        assert router.warm_peer_hints >= 1
        # And the router's aggregated /metrics sums the peer counters
        # across the fleet like every other generate counter.
        async with httpx.AsyncClient(timeout=30.0) as c:
            snap = (
                await c.get(f"http://127.0.0.1:{front.port}/metrics")
            ).json()
        assert snap["counters"]["generate.kv_peer_fetch_hits"] == 1
        assert snap["counters"]["generate.kv_peer_serve_count"] == 1
        assert snap["counters"]["generate.prefix_builds"] == 1
        assert snap["counters"]["router.warm_peer_hints"] >= 1
    finally:
        await front.stop()
        await router.stop()
        for s in servers:
            await s.stop()
