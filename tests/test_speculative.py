"""Speculative decoding (`ops/speculative.py`): greedy-exact stream,
full acceptance with draft == target, cache bookkeeping across
fully-accepted rounds (the draft's unfed k-th proposal), and the
budget/window fallbacks."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.speculative import speculative_generate
from mlapi_tpu.text import ByteTokenizer

T_CFG = dict(
    vocab_size=260, hidden_size=48, num_layers=3, num_heads=4,
    max_positions=160, compute_dtype="float32",
)
D_CFG = dict(
    vocab_size=260, hidden_size=24, num_layers=1, num_heads=2,
    max_positions=160, compute_dtype="float32",
)


def _greedy_ref(model, params, prompt, n):
    return np.asarray(
        model.generate(params, jnp.asarray(prompt), max_new_tokens=n)
    )[0].tolist()


def _train_repeater(model, seed=0):
    tok = ByteTokenizer()
    pattern = np.asarray(tok.token_ids("abcab" * 12), np.int32)
    seqs = np.tile(pattern, (32, 1))
    x, y = seqs[:, :-1], seqs[:, 1:]
    params = model.init(jax.random.key(seed))
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    for _ in range(90):
        params, opt, _ = step(params, opt)
    return params


@pytest.mark.parametrize("k", [1, 3, 5])
def test_stream_equals_plain_greedy_random_models(k):
    """Exactness holds regardless of draft quality: random draft +
    random target — every emitted token is the target's greedy
    choice."""
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    prompt = np.arange(9, dtype=np.int32)[None] % 200 + 3
    ref = _greedy_ref(target, tp, prompt, 24)
    got, stats = speculative_generate(
        target, tp, draft, dp, prompt, max_new_tokens=24, k=k,
    )
    assert got == ref, (k, stats)
    assert stats.emitted + stats.fallback_steps + 1 == 24


def test_draft_equals_target_accepts_everything():
    """With draft == target every proposal matches: acceptance is
    100% and every full round emits k+1 tokens — also exercises the
    fully-accepted round's draft bookkeeping (the unfed k-th
    proposal)."""
    target = get_model("gpt_lm", **T_CFG)
    tp = target.init(jax.random.key(0))
    prompt = np.arange(7, dtype=np.int32)[None] % 150 + 5
    ref = _greedy_ref(target, tp, prompt, 25)
    got, stats = speculative_generate(
        target, tp, target, tp, prompt, max_new_tokens=25, k=3,
    )
    assert got == ref
    assert stats.acceptance_rate == 1.0, stats
    assert stats.tokens_per_round == 4.0  # k+1 every round


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_trained_draft_accepts_on_domain():
    """A small draft trained on the same pattern as the target
    accepts a meaningful fraction — the speedup story, measured."""
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = _train_repeater(target)
    dp = _train_repeater(draft, seed=3)
    tok = ByteTokenizer()
    prompt = np.asarray(tok.token_ids("abcababcab"), np.int32)[None]
    ref = _greedy_ref(target, tp, prompt, 30)
    got, stats = speculative_generate(
        target, tp, draft, dp, prompt, max_new_tokens=30, k=4,
    )
    assert got == ref
    assert stats.acceptance_rate > 0.5, (
        f"in-domain draft only accepted {stats.acceptance_rate:.2f}"
    )


def test_llama_family_supported():
    cfg = dict(T_CFG, hidden_size=32, num_layers=2)
    cfg.pop("num_heads")
    target = get_model("llama_lm", **cfg, num_heads=4, num_kv_heads=2)
    tp = target.init(jax.random.key(0))
    prompt = np.arange(6, dtype=np.int32)[None] % 120 + 3
    ref = _greedy_ref(target, tp, prompt, 12)
    got, stats = speculative_generate(
        target, tp, target, tp, prompt, max_new_tokens=12, k=2,
    )
    assert got == ref
    assert stats.acceptance_rate == 1.0


def test_window_edge_falls_back_to_plain_steps():
    """Near the model window there is no room for a k+1 block: the
    loop degrades to plain steps and still emits the exact stream."""
    cfg = dict(T_CFG, max_positions=48)
    target = get_model("gpt_lm", **cfg)
    tp = target.init(jax.random.key(0))
    prompt = np.arange(8, dtype=np.int32)[None] % 100 + 3
    n = 40  # prompt + n == max_positions: the tail has no block room
    ref = _greedy_ref(target, tp, prompt, n)
    got, stats = speculative_generate(
        target, tp, target, tp, prompt, max_new_tokens=n, k=4,
    )
    assert got == ref
    assert stats.fallback_steps > 0


@pytest.fixture
def anyio_backend():
    return "asyncio"


def _engines():
    from mlapi_tpu.serving.engine import TextGenerationEngine

    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    tok = ByteTokenizer()
    # fused_single=False: these tests exercise the HOST spec phase and
    # its admission handoff; the batch-1 fused fast path would serve
    # the solo requests as one program and never run host rounds.
    plain = TextGenerationEngine(
        target, tp, tokenizer=tok, chunk=4, fused_single=False,
    )
    spec = TextGenerationEngine(
        target, tp, tokenizer=tok, chunk=4, draft=(draft, dp), spec_k=3,
        fused_single=False,
    )
    return plain, spec


def test_engine_spec_stream_matches_plain_engine():
    """--draft-checkpoint serving: a single greedy request decodes
    through speculative rounds and emits exactly what the draft-less
    engine emits; sampled requests bypass speculation entirely."""
    plain, spec = _engines()
    ref = plain.generate_text("abcabcab", max_new_tokens=24)
    got = spec.generate_text("abcabcab", max_new_tokens=24)
    assert got["token_ids"] == ref["token_ids"]
    assert spec.spec_rounds > 0, "speculation never engaged"

    base_rounds = spec.spec_rounds
    s_ref = plain.generate_text("ab", max_new_tokens=8,
                                temperature=0.8, seed=3)
    s_got = spec.generate_text("ab", max_new_tokens=8,
                               temperature=0.8, seed=3)
    assert s_got["token_ids"] == s_ref["token_ids"]
    assert spec.spec_rounds == base_rounds, "sampled request speculated"


@pytest.mark.anyio
async def test_engine_spec_hands_off_to_admission():
    """A joiner arriving mid-speculation is admitted: the spec phase
    yields at a round boundary and the normal loop takes over — both
    streams stay exact."""
    import asyncio

    plain, spec = _engines()
    ref_a = plain.generate_text("abcabcab", max_new_tokens=40)
    ref_b = plain.generate_text("xyz", max_new_tokens=6)
    await spec.start()
    try:
        a = await spec.submit("abcabcab", max_new_tokens=40)
        first = await a.queue.get()
        b = await spec.submit("xyz", max_new_tokens=6)
        got_b = []
        while True:
            item = await b.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item
            got_b.extend(item["token_ids"])
        got_a = list(first["token_ids"])
        while True:
            item = await a.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item
            got_a.extend(item["token_ids"])
        assert got_a == ref_a["token_ids"]
        assert got_b == ref_b["token_ids"]
        assert spec.admitted >= 1, "joiner was not admitted"
        # After the joiner finished, the long stream's tail must have
        # RE-engaged speculation (draft-cache replay), not decoded
        # token-at-a-time forever.
        assert spec.spec_rounds >= 2, spec.spec_rounds
    finally:
        await spec.stop()


def test_batch_and_vocab_validation():
    target = get_model("gpt_lm", **T_CFG)
    tp = target.init(jax.random.key(0))
    with pytest.raises(ValueError, match="single-row"):
        speculative_generate(
            target, tp, target, tp,
            np.zeros((2, 4), np.int32), max_new_tokens=4,
        )
    other = get_model("gpt_lm", **dict(D_CFG, vocab_size=128))
    with pytest.raises(ValueError, match="vocabulary"):
        speculative_generate(
            target, tp, other, other.init(jax.random.key(1)),
            np.zeros((1, 4), np.int32), max_new_tokens=4,
        )
