"""FSDP (ZeRO-style parameter + optimizer-state sharding) over the
third mesh axis.

The contract under test (ISSUE 1, docs/DESIGN.md §12):

- every ≥1-D param leaf above the size threshold is actually sharded
  over ``fsdp`` (largest free divisible dim), small leaves replicate;
- optimizer moments take the SAME layout as their params (that is the
  memory win — AdamW moments are 2x the params);
- the loss trajectory is the plain-DP trajectory to printed digits
  (FSDP changes where state lives, not the math);
- checkpoints round-trip sharded state and resume bit-exact;
- FSDP composes with TP, LoRA masking, and the sparse criteo path.
"""

import os

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mlapi_tpu.datasets import get_dataset
from mlapi_tpu.models import get_model
from mlapi_tpu.parallel import (
    FSDP_MIN_SIZE,
    create_mesh,
    fsdp_spec_tree,
    params_for_model,
    shard_batch_for_mesh,
    state_shardings_like,
)
from mlapi_tpu.train import fit

MLP_KW = dict(num_features=64, num_classes=10, hidden_dims=[256, 128])
TINY_BERT = dict(
    num_classes=2, vocab_size=256, hidden_size=32, num_layers=2,
    num_heads=2, intermediate_size=64, max_positions=64,
)


@pytest.fixture(scope="module")
def mesh_fsdp8():
    """(data=1, fsdp=8, model=1): pure FSDP over all 8 virtual devices."""
    return create_mesh((1, 8, 1))


@pytest.fixture(scope="module")
def mesh_2x2x2():
    return create_mesh((2, 2, 2))


def _specs(tree):
    return {
        jax.tree_util.keystr(path): tuple(leaf.sharding.spec)
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
    }


def test_three_dim_mesh_gets_fsdp_axis():
    mesh = create_mesh((1, 8, 1))
    assert mesh.axis_names == ("data", "fsdp", "model")
    assert mesh.shape["fsdp"] == 8


def test_every_large_leaf_sharded_over_fsdp(mesh_fsdp8):
    """The spec rule, end to end through placement: every leaf at or
    above the threshold with a free divisible dim carries ``fsdp``;
    every leaf below the threshold does not."""
    for name, kwargs in (("mlp", MLP_KW), ("bert_classifier", TINY_BERT)):
        model = get_model(name, **kwargs)
        placed = params_for_model(
            model, model.init(jax.random.key(0)), mesh_fsdp8
        )
        for key, leaf in jax.tree_util.tree_leaves_with_path(placed):
            spec = tuple(leaf.sharding.spec)
            free_divisible = any(
                (i >= len(spec) or spec[i] is None) and d % 8 == 0
                for i, d in enumerate(leaf.shape)
            ) or "fsdp" in spec
            if leaf.size >= FSDP_MIN_SIZE and free_divisible:
                assert "fsdp" in spec, (
                    f"{name}{jax.tree_util.keystr(key)} {leaf.shape} "
                    f"not fsdp-sharded: {spec}"
                )
            else:
                assert "fsdp" not in spec, (
                    f"{name}{jax.tree_util.keystr(key)} {leaf.shape} "
                    f"sharded below threshold: {spec}"
                )


def test_fsdp_composes_with_tp_specs():
    """On a (1, 2, 4) mesh a TP model's specs keep their ``model``
    placement and gain ``fsdp`` on a DIFFERENT dim of the same leaf."""
    mesh = create_mesh((1, 2, 4))
    model = get_model("bert_classifier", **TINY_BERT)
    params = model.init(jax.random.key(0))
    specs = fsdp_spec_tree(
        params, model.param_shardings(), mesh.shape["fsdp"]
    )
    ffn_up = specs["layer_0"]["ffn_up"]["kernel"]
    assert tuple(ffn_up) == ("fsdp", "model")
    word = specs["embeddings"]["word"]
    assert tuple(word) == ("model", "fsdp")
    # No leaf ever uses one axis twice.
    for spec in jax.tree.leaves(specs, is_leaf=lambda s: isinstance(s, P)):
        named = [a for a in spec if a is not None]
        assert len(named) == len(set(named)), spec


def test_moments_shard_like_params(mesh_fsdp8):
    """state_shardings_like mirrors param shardings onto adam moments
    (exact-shape match) and keeps step counters replicated."""
    import optax

    model = get_model("mlp", **MLP_KW)
    placed = params_for_model(
        model, model.init(jax.random.key(0)), mesh_fsdp8
    )
    tx = optax.adamw(1e-3)
    opt_sh = state_shardings_like(
        jax.eval_shape(tx.init, placed), placed, mesh_fsdp8
    )
    opt = jax.jit(tx.init, out_shardings=opt_sh)(placed)
    p_specs = _specs(placed)
    for key, leaf in jax.tree_util.tree_leaves_with_path(opt):
        ks = jax.tree_util.keystr(key)
        for p_key, p_spec in p_specs.items():
            if ks.endswith(p_key) and leaf.ndim:
                assert tuple(leaf.sharding.spec) == p_spec, (ks, p_key)
                break
        else:
            assert tuple(leaf.sharding.spec) == (), ks  # counters


def test_batch_shards_over_data_and_fsdp(mesh_2x2x2):
    x = np.zeros((8, 3), np.float32)
    placed = shard_batch_for_mesh(x, mesh_2x2x2)
    assert tuple(placed.sharding.spec)[0] == ("data", "fsdp")
    with pytest.raises(ValueError, match="not divisible"):
        shard_batch_for_mesh(np.zeros((6, 3), np.float32), mesh_2x2x2)


def test_trajectory_matches_plain_dp_digits_mlp(mesh_fsdp8):
    """The equivalence bar, stated honestly: the first steps are
    BIT-IDENTICAL to plain DP (the loss/grad math is unchanged), and
    a 100-step trajectory stays on the same path to the precision the
    collective allows — reduce-scatter sums partial gradients in a
    different order than all-reduce, so a single-ulp rounding
    difference enters within a few steps and amplifies chaotically
    (measured: bit-exact through step 2, ~1e-4 relative at step 40,
    ~1e-2 at step 120 — with identical eval accuracies throughout).
    docs/DESIGN.md §12 records the caveat."""
    splits = get_dataset("digits")
    kw = dict(
        batch_size=64, learning_rate=1e-3, optimizer="adamw",
        seed=0, eval_every=1,
    )
    r_dp = fit(get_model("mlp", **MLP_KW), splits,
               mesh=create_mesh((8, 1)), steps=2, **kw)
    r_fsdp = fit(get_model("mlp", **MLP_KW), splits,
                 mesh=mesh_fsdp8, steps=2, **kw)
    for h_dp, h_f in zip(r_dp.history, r_fsdp.history):
        assert h_dp["loss"] == h_f["loss"]  # bit-exact

    kw = dict(
        steps=100, batch_size=64, learning_rate=1e-3,
        optimizer="adamw", seed=0, eval_every=20,
    )
    r_dp = fit(get_model("mlp", **MLP_KW), splits,
               mesh=create_mesh((8, 1)), **kw)
    r_fsdp = fit(get_model("mlp", **MLP_KW), splits,
                 mesh=mesh_fsdp8, **kw)
    for h_dp, h_f in zip(r_dp.history, r_fsdp.history):
        assert h_f["loss"] == pytest.approx(h_dp["loss"], rel=2e-2)
        assert abs(h_dp["test_accuracy"] - h_f["test_accuracy"]) <= 0.02


def test_trajectory_matches_plain_dp_small_bert(mesh_fsdp8):
    splits = get_dataset("sst2", max_len=32)
    kw = dict(
        steps=8, batch_size=32, learning_rate=1e-3, optimizer="adamw",
        seed=0,
    )
    r_dp = fit(get_model("bert_classifier", **TINY_BERT), splits,
               mesh=create_mesh((8, 1)), **kw)
    r_fsdp = fit(get_model("bert_classifier", **TINY_BERT), splits,
                 mesh=mesh_fsdp8, **kw)
    assert f"{r_dp.final_loss:.6f}" == f"{r_fsdp.final_loss:.6f}"


def test_checkpoint_roundtrip_resume_exact_2x2x2(mesh_2x2x2, tmp_path):
    """Sharded save -> restore -> resume replays the uninterrupted
    trajectory bit-for-bit, and restored leaves land back on the mesh
    in their FSDP layout."""
    splits = get_dataset("digits")
    kw = dict(
        batch_size=64, learning_rate=1e-3, optimizer="adamw", seed=0,
        mesh=mesh_2x2x2, async_save=False,
    )
    ck = os.fspath(tmp_path / "state")
    ref = fit(get_model("mlp", **MLP_KW), splits, steps=16, **kw)
    fit(get_model("mlp", **MLP_KW), splits, steps=8,
        checkpoint_dir=ck, save_every=4, **kw)
    resumed = fit(get_model("mlp", **MLP_KW), splits, steps=16,
                  checkpoint_dir=ck, save_every=4, **kw)
    for a, b in zip(
        jax.tree.leaves(ref.params), jax.tree.leaves(resumed.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    kernel = resumed.params["dense_0"]["kernel"]
    assert "fsdp" in tuple(kernel.sharding.spec)


def test_fsdp_tp_lora_train_step(mesh_2x2x2):
    """LoRA under FSDP x TP: masked optimizer state (moments only for
    adapters) builds, places, and trains finite."""
    from mlapi_tpu.models.lora import LoraModel

    inner = get_model(
        "gpt_lm", vocab_size=260, hidden_size=32, num_layers=1,
        num_heads=2, max_positions=32, compute_dtype="float32",
    )
    model = LoraModel(inner, rank=4)
    splits = get_dataset("docs_text", seq_len=32)
    r = fit(
        model, splits, steps=3, batch_size=16, learning_rate=1e-3,
        optimizer="adamw", mesh=mesh_2x2x2,
        init_params=model.init(jax.random.key(0)),
    )
    assert np.isfinite(r.final_loss)


def test_sparse_criteo_fsdp_matches_plain_mesh():
    """The r05 sparse-embedding scatter keeps its [F, V]-native update
    exact when the dense leaves are FSDP-sharded: same losses as the
    (2, 4) DP x TP reference, per printed digits."""
    wd_kw = dict(
        num_dense=3, vocab_sizes=[64] * 4, embed_dim=8,
        hidden_dims=[32], num_classes=2,
    )
    splits = get_dataset(
        "criteo", num_dense=3, num_categorical=4, vocab_size=64,
        n_train=512, n_test=64,
    )
    kw = dict(
        steps=5, batch_size=64, learning_rate=1e-3,
        optimizer="recsys-sparse-adamw", seed=0,
    )
    r_ref = fit(get_model("wide_deep", **wd_kw), splits,
                mesh=create_mesh((2, 4)), **kw)
    r_fsdp = fit(get_model("wide_deep", **wd_kw), splits,
                 mesh=create_mesh((1, 2, 4)), **kw)
    assert f"{r_ref.final_loss:.6f}" == f"{r_fsdp.final_loss:.6f}"


def test_bench_reports_per_device_state_bytes():
    """The committed memory number: FSDP (1, 8, 1) must report a
    multiple less per-device param+opt bytes than replicated DP
    (8, 1, 1) on the same config (digits-mlp: two large kernels over
    8 devices -> ~6x; bert-base reaches ~8x)."""
    from mlapi_tpu.train.bench import bench_train

    dp = bench_train(
        "digits-mlp", bench_steps=2, warmup_steps=1,
        mesh_shape=(8, 1, 1),
    )
    fsdp = bench_train(
        "digits-mlp", bench_steps=2, warmup_steps=1,
        mesh_shape=(1, 8, 1),
    )
    dp_bytes = dp["param_bytes_per_device"] + dp["opt_bytes_per_device"]
    f_bytes = (
        fsdp["param_bytes_per_device"] + fsdp["opt_bytes_per_device"]
    )
    assert dp_bytes > 0 and f_bytes > 0
    ratio = dp_bytes / f_bytes
    assert ratio >= 4.0, (
        f"FSDP per-device state only {ratio:.2f}x below replicated "
        f"({dp_bytes} vs {f_bytes})"
    )
    # Same program, same math: the benched losses agree.
    assert f"{dp['final_loss']:.5f}" == f"{fsdp['final_loss']:.5f}"


def test_serving_loads_fsdp_trained_checkpoint(tmp_path, mesh_fsdp8):
    """The train->serve handoff: a final checkpoint written from
    FSDP-sharded params restores on a single device (serve-anywhere
    contract of checkpoint/io.py)."""
    from mlapi_tpu.checkpoint import load_checkpoint, save_checkpoint

    splits = get_dataset("digits")
    model = get_model("mlp", **MLP_KW)
    r = fit(model, splits, steps=4, batch_size=64, learning_rate=1e-3,
            optimizer="adamw", mesh=mesh_fsdp8)
    out = tmp_path / "ckpt"
    save_checkpoint(out, r.params, step=4, config={"model": "mlp"})
    abstract = jax.eval_shape(lambda: model.init(jax.random.key(0)))
    params, meta = load_checkpoint(out, abstract)
    for leaf in jax.tree.leaves(params):
        assert len(leaf.sharding.device_set) == 1
    ref = np.asarray(
        jax.jit(model.apply)(
            jax.device_get(r.params), np.asarray(splits.x_test[:8])
        )
    )
    got = np.asarray(
        jax.jit(model.apply)(params, np.asarray(splits.x_test[:8]))
    )
    np.testing.assert_allclose(got, ref, atol=1e-6)
