"""Config 5: BERT classifier — forward contract, HF→JAX conversion
logit parity against torch (the SURVEY §7 'silent-accuracy killer'
guard), TP sharding, and SST-2 training."""

import jax
import numpy as np
import pytest

from mlapi_tpu.datasets import get_dataset
from mlapi_tpu.models import get_model
from mlapi_tpu.train import fit

TINY = dict(
    num_classes=2,
    vocab_size=512,
    hidden_size=32,
    num_layers=2,
    num_heads=2,
    intermediate_size=64,
    max_positions=64,
)


@pytest.fixture(scope="module")
def tiny_bert():
    return get_model("bert_classifier", compute_dtype="float32", **TINY)


def test_forward_shape_and_mask(tiny_bert):
    params = tiny_bert.init(jax.random.key(0))
    ids = np.zeros((2, 16), np.int32)
    ids[0, :5] = [1, 7, 8, 9, 2]
    ids[1, :3] = [1, 7, 2]
    logits = jax.jit(tiny_bert.apply)(params, ids)
    assert logits.shape == (2, 2)
    assert np.isfinite(np.asarray(logits)).all()
    # Padding must not affect the result: same content, longer pad.
    ids_padded = np.zeros((1, 32), np.int32)
    ids_padded[0, :5] = [1, 7, 8, 9, 2]
    a = jax.jit(tiny_bert.apply)(params, ids[:1])
    b = jax.jit(tiny_bert.apply)(params, ids_padded)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_hf_torch_logit_parity(tiny_bert):
    """Random-init torch BertForSequenceClassification (same dims) →
    convert → logits must match torch's to float32 tolerance."""
    torch = pytest.importorskip("torch")
    from transformers import BertConfig, BertForSequenceClassification

    from mlapi_tpu.models.bert import params_from_hf_torch

    config = BertConfig(
        vocab_size=TINY["vocab_size"],
        hidden_size=TINY["hidden_size"],
        num_hidden_layers=TINY["num_layers"],
        num_attention_heads=TINY["num_heads"],
        intermediate_size=TINY["intermediate_size"],
        max_position_embeddings=TINY["max_positions"],
        num_labels=TINY["num_classes"],
        hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
        hidden_act="gelu",
    )
    torch_model = BertForSequenceClassification(config).eval()
    params = params_from_hf_torch(torch_model, tiny_bert)

    rng = np.random.default_rng(0)
    ids = rng.integers(3, TINY["vocab_size"], size=(3, 20)).astype(np.int64)
    ids[:, 0] = 1
    mask = np.ones_like(ids)
    mask[0, 15:] = 0
    ids[0, 15:] = 0

    with torch.no_grad():
        expected = torch_model(
            input_ids=torch.tensor(ids),
            attention_mask=torch.tensor(mask),
        ).logits.numpy()

    got = jax.jit(tiny_bert.apply)(
        params, ids.astype(np.int32), mask.astype(np.int32)
    )
    np.testing.assert_allclose(np.asarray(got), expected, atol=2e-4, rtol=2e-4)


def test_tp_sharded_forward(tiny_bert, mesh_2x4):
    from mlapi_tpu.parallel import params_for_model, shard_batch_for_mesh

    params = params_for_model(
        tiny_bert, tiny_bert.init(jax.random.key(0)), mesh_2x4
    )
    # QKV kernels really are column-sharded over the model axis.
    spec = tuple(params["layer_0"]["q"]["kernel"].sharding.spec)
    assert spec in ((None, "model"),)
    ids = shard_batch_for_mesh(
        np.ones((8, 16), np.int32), mesh_2x4
    )
    logits = jax.jit(tiny_bert.apply)(params, ids)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_bert_learns_synthetic_sst2():
    sst2 = get_dataset(
        "sst2", max_len=32, vocab_size=512, n_train=4096, n_test=512
    )
    assert sst2.source == "synthetic"
    model = get_model("bert_classifier", compute_dtype="float32", **TINY)
    result = fit(
        model, sst2, steps=150, batch_size=64, learning_rate=5e-4,
        optimizer="adamw",
    )
    # Planted sentiment words: bag-of-embeddings separable.
    assert result.test_accuracy > 0.8


def test_serve_bert_text_endpoint(tmp_path):
    import httpx

    from mlapi_tpu.checkpoint import save_checkpoint
    from mlapi_tpu.serving import (
        InferenceEngine,
        TextClassificationEngine,
        build_app,
    )

    sst2 = get_dataset(
        "sst2", max_len=32, vocab_size=512, n_train=2048, n_test=256
    )
    model = get_model("bert_classifier", compute_dtype="float32", **TINY)
    result = fit(model, sst2, steps=100, batch_size=64, learning_rate=5e-4,
                 optimizer="adamw")
    save_checkpoint(
        tmp_path / "ck",
        result.params,
        step=100,
        config={
            "model": "bert_classifier",
            "model_kwargs": {"compute_dtype": "float32", **TINY},
            "max_len": 32,
        },
        vocab=sst2.vocab,
    )
    engine = InferenceEngine.from_checkpoint(tmp_path / "ck", buckets=(1, 2, 4))
    assert isinstance(engine, TextClassificationEngine)

    async def drive():
        app = build_app(engine, max_wait_ms=0.0)
        await app.startup()
        try:
            transport = httpx.ASGITransport(app=app)
            async with httpx.AsyncClient(
                transport=transport, base_url="http://t"
            ) as c:
                good = await c.post(
                    "/predict",
                    json={"text": "a wonderful delightful movie"},
                )
                assert good.status_code == 200
                body = good.json()
                assert set(body) == {"prediction", "probability"}
                assert body["prediction"] in ("positive", "negative")
                bad = await c.post("/predict", json={"nope": 1})
                assert bad.status_code == 422
        finally:
            await app.shutdown()

    import anyio

    anyio.run(drive)
