"""Prefill/decode disaggregation (r18): role-split replicas with
proactive chunk-granularity KV push (``serving/kv_peer.py``'s
``KVPush``, ``--replica-role``, ``POST /kv/push``).

The contract, layer by layer — every claim asserted from counters and
exact byte arithmetic, never wall-clock:

- **Wire format**: the r17 blob framing extended with
  ``{xfer, chunk, num_chunks, span}`` round-trips byte-identically;
  every corruption class raises (a counted receive failure, never a
  staged wrong chunk); the fin message carries the first token and
  the geometry the decode replica validates.
- **The engine pair**: a prefill-role engine runs the EXISTING
  chunked prefill and pushes each finished chunk's KV at its
  boundary; the decode-role engine assembles the chunks and its
  formation installs them through the pool's alloc-first donated
  scatter into a PRIVATE table row — streams are TOKEN-IDENTICAL
  disaggregated-vs-mixed across {gpt-MHA, llama-GQA} × {none, int8},
  paged AND contiguous, with the decode side's ``prefix_builds`` AND
  ``prefill_chunks`` both at ZERO (the zero-decode-side-prefill
  claim) and the pushed bytes equal to the
  ``num_pages × kv_page_bytes`` closed form.
- **Failure discipline**: ``kv_push_send``/``kv_push_recv`` raises
  degrade to the cold prefill with ``kv_pages_in_use`` conserved on
  BOTH replicas and streams completing; delays slow, never break;
  geometry drift between differently-configured replicas is a
  counted fallback; pool exhaustion during the install propagates
  loudly with nothing half-installed.
- **Topology**: the handoff headers and the push endpoint are
  replica-gated and role-gated; an all-mixed engine/app is
  bit-identical to r17 (no endpoint, no counters, no role field);
  the real-socket e2e drives a P=1+D=1 fleet through the role-aware
  router and pins the two-hop flow end to end, including the
  role-starved degradation to mixed routing.

Engines reuse the paged family's tiny-model CFG (conftest
``paged-family``) so the jitted program factories are shared across
the family instead of compiled again.
"""

import asyncio
import json

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.quant import kv_page_bytes
from mlapi_tpu.serving import faults
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.serving.kv_peer import (
    deserialize_push,
    serialize_push_chunk,
    serialize_push_fin,
)
from mlapi_tpu.serving.paged_pool import PagePoolExhausted
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)


def _model(kind="gpt_lm", kv_quant="none"):
    kw = dict(CFG, kv_quant=kv_quant)
    if kind == "llama_lm":
        kw["num_kv_heads"] = 2  # GQA: 4 query heads over 2 KV heads
    return get_model(kind, **kw)


@pytest.fixture(scope="module")
def gpt_params():
    return _model().init(jax.random.key(0))


@pytest.fixture(scope="module")
def llama_params():
    return _model("llama_lm").init(jax.random.key(0))


def _engine(model, params, role="mixed", **kw):
    kw.setdefault("chunk", 2)
    kw.setdefault("fused_single", False)
    kw.setdefault("kv_page_size", 8)
    # cp = 64: a 100-token prompt buckets to 128 = TWO prefill chunks,
    # so the chunk-granularity push is exercised for real (the
    # family's default (16, 64, 128) buckets would make it one).
    kw.setdefault("prompt_buckets", (16, 64))
    return TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(),
        replica_role=role, **kw,
    )


def _link(pre, dec):
    """Wire the prefill engine's push transport straight into the
    decode engine's receive path — the exact serve path (fault points
    included) without a socket."""

    def transport(host, port, path, body, timeout_s):
        try:
            dec.kv_push.receive(body)
            return 200, b"{}"
        except ValueError:
            return 400, b""

    pre.kv_push._transport = transport


LONG = "y" * 100   # buckets to 128 = 2 x 64-token prefill chunks
XFERS = iter(f"xf-test-{i}" for i in range(10_000))


async def _wait_for(pred, timeout_s: float = 60.0) -> None:
    """Condition-based wait (MLA006): the batch's page release runs
    on the dispatch thread AFTER the terminal frame reaches the
    client — poll the counter instead of racing it."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s
    while not pred():
        if loop.time() >= deadline:
            raise AssertionError(
                f"condition never became true within {timeout_s}s"
            )
        await asyncio.sleep(0.005)


def _handoff(pre, dec, text, n_new, **kw):
    """One disaggregated request through the engine pair: prefill +
    push on ``pre``, then the stream on ``dec``. Returns (decode
    output, transfer-complete)."""
    xfer = next(XFERS)
    pre.generate_text(
        text, max_new_tokens=n_new, push_to=("127.0.0.1", 1, xfer), **kw
    )
    ok = pre.kv_push.wait_sent(xfer, 30.0)
    return dec.generate_text(
        text, max_new_tokens=n_new, kv_xfer=xfer if ok else "absent", **kw
    ), ok


# --- wire format -------------------------------------------------------


def test_push_wire_roundtrip_and_validation():
    rng = np.random.default_rng(0)
    kv = {
        "layer_0": {
            "k": rng.standard_normal((1, 64, 4, 8)).astype(np.float32),
            "v": rng.standard_normal((1, 64, 4, 8)).astype(np.float32),
        },
        "layer_1": {
            "k_q": rng.integers(-128, 127, (1, 64, 4, 8)).astype(np.int8),
            "k_scale": rng.standard_normal((1, 64, 4, 1)).astype(
                np.float32
            ),
        },
    }
    data = serialize_push_chunk("xf1", 1, 2, (64, 128), kv)
    out = deserialize_push(data)
    assert (out["kind"], out["xfer"]) == ("chunk", "xf1")
    assert (out["chunk"], out["num_chunks"]) == (1, 2)
    assert out["span"] == (64, 128)
    for ln, layer in kv.items():
        for name, a in layer.items():
            np.testing.assert_array_equal(out["payload"][ln][name], a)

    fin = deserialize_push(serialize_push_fin("xf1", 2, 37, 128, 100))
    assert fin == {
        "kind": "fin", "xfer": "xf1", "num_chunks": 2,
        "first_token": 37, "bucket": 128, "used": 100,
    }

    # Every corruption class raises (→ a counted receive failure),
    # never a staged wrong chunk.
    head_line, _, rest = data.partition(b"\n")
    head = json.loads(head_line)
    for bad in (
        b"garbage with no header",
        b"{}\n",                                   # missing fields
        data[: len(data) // 2],                    # truncated payload
        data + b"x",                               # trailing bytes
        data.replace(b'"nbytes": ', b'"nbytes": 9', 1),  # total lies
        serialize_push_fin("xf1", 2, 1, 8, 4) + b"junk",  # fin + tail
    ):
        with pytest.raises(ValueError):
            deserialize_push(bad)
    for tamper in (
        {"xfer": ""},
        {"kind": "nope"},
        {"chunk": 5},                              # outside num_chunks
        {"span": [8, 8]},                          # empty span
        {"leaves": 5},
        {"num_chunks": 0},
    ):
        bad_head = dict(head, **tamper)
        with pytest.raises(ValueError):
            deserialize_push(
                json.dumps(bad_head).encode() + b"\n" + rest
            )
    # Leaf shape must be [1, span, ...] with positive dims (a
    # negative dim would defeat the truncation check — the
    # deserialize_blob lesson applied here).
    bad_head = dict(head)
    bad_head["leaves"] = [["layer_0", "k", [1, 64, -4, 8], "<f4"]] + head[
        "leaves"
    ][1:]
    with pytest.raises(ValueError):
        deserialize_push(json.dumps(bad_head).encode() + b"\n" + rest)


# --- the acceptance matrix: disaggregated == mixed ---------------------


@pytest.mark.parametrize("fmt", ["none", "int8"])
@pytest.mark.parametrize("kind", ["gpt_lm", "llama_lm"])
def test_disagg_stream_identity(kind, fmt, gpt_params, llama_params):
    """THE acceptance pin: a prompt prefilled on the prefill replica
    and decoded on the decode replica streams TOKEN-IDENTICAL to a
    mixed replica serving it alone — with decode-side prefill FLOPs
    exactly ZERO (``prefix_builds == 0`` AND ``prefill_chunks == 0``,
    ``kv_push_applied == 1``) and the pushed bytes equal to the
    ``num_pages × kv_page_bytes`` closed form. Both cache formats,
    MHA and GQA; the 128-slot prompt pushes as TWO 64-token chunks
    (the r10/r15 chunk seam, not one blob)."""
    params = gpt_params if kind == "gpt_lm" else llama_params
    model = _model(kind, fmt)
    mixed = _engine(model, params)
    pre = _engine(model, params, role="prefill")
    dec = _engine(model, params, role="decode")
    _link(pre, dec)

    ref = mixed.generate_text(LONG, max_new_tokens=8)
    out, ok = _handoff(pre, dec, LONG, 8)
    assert ok
    assert out["token_ids"] == ref["token_ids"]
    # Zero decode-side prefill FLOPs, from counters.
    assert dec.prefix.builds == 0
    assert dec.prefill_chunks == 0
    assert dec.kv_push_applied == 1
    # Chunk granularity + the exact closed form on BOTH ends: the
    # 128-slot bucket is 16 pages of 8 slots.
    assert pre.kv_push.push_sent == 2
    closed = 16 * kv_page_bytes(model, 8)
    assert pre.kv_push_bytes_sent == closed
    assert dec.kv_push_bytes_recv == closed
    assert dec.kv_push_bytes_applied == closed
    # The prefill side ran ITS chunked prefill (the existing seam).
    assert pre.prefill_chunks == 2
    # Pages conserved everywhere once the streams finish.
    assert pre.kv_pages_in_use == 0 and dec.kv_pages_in_use == 0
    assert dec.kv_push.staged_count == 0


def test_disagg_contiguous_engines(gpt_params):
    """The same identity on CONTIGUOUS engines: the pushed blob
    installs via the admission scatter instead of pool pages."""
    model = _model()
    mixed = _engine(model, gpt_params, kv_page_size=None)
    pre = _engine(model, gpt_params, role="prefill", kv_page_size=None)
    dec = _engine(model, gpt_params, role="decode", kv_page_size=None)
    _link(pre, dec)
    ref = mixed.generate_text(LONG, max_new_tokens=8)
    out, ok = _handoff(pre, dec, LONG, 8)
    assert ok and out["token_ids"] == ref["token_ids"]
    assert dec.prefill_chunks == 0 and dec.kv_push_applied == 1
    assert pre.kv_push.push_sent == 2


def test_disagg_short_prompt_single_chunk(gpt_params):
    """A bucket-sized prompt is one chunk: one push, same identity,
    and sampled (seeded) requests ride the same contract — the
    prefill replica's first token came from the same sample program
    at the same key/step."""
    model = _model()
    mixed = _engine(model, gpt_params)
    pre = _engine(model, gpt_params, role="prefill")
    dec = _engine(model, gpt_params, role="decode")
    _link(pre, dec)
    ref = mixed.generate_text(
        "hi there", max_new_tokens=6, temperature=0.8, seed=11
    )
    out, ok = _handoff(
        pre, dec, "hi there", 6, temperature=0.8, seed=11
    )
    assert ok and out["token_ids"] == ref["token_ids"]
    assert pre.kv_push.push_sent == 1
    assert dec.kv_push_applied == 1 and dec.prefill_chunks == 0


def test_mixed_default_is_inert(gpt_params):
    """The default (all-mixed) engine carries NO push state: the
    flag's absence is bit-identical to r17."""
    eng = _engine(_model(), gpt_params)
    assert eng.kv_push is None and eng.replica_role == "mixed"
    assert eng.kv_push_applied == 0 and eng.kv_push_bytes_sent == 0
    out = eng.generate_text(LONG, max_new_tokens=6)
    assert len(out["token_ids"]) == 6


# --- failure discipline ------------------------------------------------


def test_send_fault_degrades_cold_pages_conserved(gpt_params):
    """``kv_push_send`` raise: the transfer fails, the remaining
    chunks are dropped, and the decode replica serves the stream by
    the COLD prefill — pages conserved on both replicas, counted."""
    model = _model()
    mixed = _engine(model, gpt_params)
    pre = _engine(model, gpt_params, role="prefill")
    dec = _engine(model, gpt_params, role="decode")
    _link(pre, dec)
    ref = mixed.generate_text(LONG, max_new_tokens=8)
    with faults.active("kv_push_send:raise"):
        out, ok = _handoff(pre, dec, LONG, 8)
    assert not ok
    assert out["token_ids"] == ref["token_ids"]
    assert pre.kv_push_send_failures == 1
    assert pre.kv_push.push_sent == 0          # first chunk died
    assert dec.kv_push_applied == 0
    assert dec.kv_push_fallbacks == 1          # cold path, counted
    assert dec.prefill_chunks == 2             # the cold prefill ran
    assert pre.kv_pages_in_use == 0 and dec.kv_pages_in_use == 0


def test_recv_fault_degrades_cold_pages_conserved(gpt_params):
    """``kv_push_recv`` raise: the decode replica's intake 500s (the
    sender counts the transfer failure) — same cold-prefill
    degradation, pages conserved on both ends."""
    model = _model()
    mixed = _engine(model, gpt_params)
    pre = _engine(model, gpt_params, role="prefill")
    dec = _engine(model, gpt_params, role="decode")

    def transport(host, port, path, body, timeout_s):
        try:
            dec.kv_push.receive(body)
            return 200, b"{}"
        except ValueError:
            return 400, b""
        except faults.InjectedFault:
            return 500, b""   # what the real endpoint's 500 looks like

    pre.kv_push._transport = transport
    ref = mixed.generate_text(LONG, max_new_tokens=8)
    with faults.active("kv_push_recv:raise"):
        out, ok = _handoff(pre, dec, LONG, 8)
    assert not ok
    assert out["token_ids"] == ref["token_ids"]
    assert pre.kv_push_send_failures == 1
    assert dec.kv_push_applied == 0 and dec.kv_push_fallbacks == 1
    assert dec.prefill_chunks == 2
    assert pre.kv_pages_in_use == 0 and dec.kv_pages_in_use == 0


def test_push_delays_slow_never_break(gpt_params):
    model = _model()
    mixed = _engine(model, gpt_params)
    pre = _engine(model, gpt_params, role="prefill")
    dec = _engine(model, gpt_params, role="decode")
    _link(pre, dec)
    ref = mixed.generate_text(LONG, max_new_tokens=8)
    with faults.active(
        "kv_push_send:every=1:delay=0.01,kv_push_recv:every=1:delay=0.01"
    ):
        out, ok = _handoff(pre, dec, LONG, 8)
        assert faults.injected_count() >= 2
    assert ok and out["token_ids"] == ref["token_ids"]
    assert dec.kv_push_applied == 1


def test_geometry_drift_falls_back_cold(gpt_params):
    """A prefill replica running a different bucket config pushes a
    transfer whose geometry the decode replica's own encode cannot
    reproduce: a counted fallback to the cold prefill, stream still
    correct."""
    model = _model()
    pre = _engine(model, gpt_params, role="prefill")
    # REAL config drift, not corruption: a 20-token prompt buckets to
    # 64 on the prefill side's (16, 64) ladder but to 32 on the
    # decode side's (32, 64) one.
    dec = _engine(
        model, gpt_params, role="decode", prompt_buckets=(32, 64),
    )
    _link(pre, dec)
    text = "z" * 20
    ref = _engine(model, gpt_params, prompt_buckets=(32, 64)).generate_text(
        text, max_new_tokens=6
    )
    xfer = next(XFERS)
    pre.generate_text(
        text, max_new_tokens=6, push_to=("127.0.0.1", 1, xfer)
    )
    assert pre.kv_push.wait_sent(xfer, 30.0)
    out = dec.generate_text(text, max_new_tokens=6, kv_xfer=xfer)
    assert out["token_ids"] == ref["token_ids"]
    assert dec.kv_push_applied == 0
    assert dec.kv_push_fallbacks == 1
    assert dec.kv_pages_in_use == 0


def test_format_drift_contiguous_falls_back(gpt_params):
    """A peer running a DIFFERENT cache format (int8 vs none) pushes
    a transfer whose bucket/used happen to match — the contiguous
    install must still validate the tree against the local model's
    own cache leaves and degrade to the counted cold prefill, never
    a formation error (or a silent astype of wrong-format bytes)."""
    pre = _engine(
        _model(kv_quant="int8"),
        _model(kv_quant="int8").init(jax.random.key(0)),
        role="prefill", kv_page_size=None,
    )
    dec = _engine(_model(), gpt_params, role="decode", kv_page_size=None)
    _link(pre, dec)
    ref = _engine(_model(), gpt_params, kv_page_size=None).generate_text(
        LONG, max_new_tokens=6
    )
    xfer = next(XFERS)
    pre.generate_text(LONG, max_new_tokens=6,
                      push_to=("127.0.0.1", 1, xfer))
    assert pre.kv_push.wait_sent(xfer, 30.0)
    out = dec.generate_text(LONG, max_new_tokens=6, kv_xfer=xfer)
    assert out["token_ids"] == ref["token_ids"]
    assert dec.kv_push_applied == 0
    assert dec.kv_push_fallbacks == 1


def test_unknown_or_incomplete_transfer_falls_back(gpt_params):
    """Naming a transfer that never arrived (or only partially
    arrived) is a counted fallback, never a hang or an error."""
    model = _model()
    dec = _engine(model, gpt_params, role="decode")
    ref = _engine(model, gpt_params).generate_text(
        LONG, max_new_tokens=6
    )
    out = dec.generate_text(LONG, max_new_tokens=6, kv_xfer="no-such")
    assert out["token_ids"] == ref["token_ids"]
    assert dec.kv_push_fallbacks == 1 and dec.kv_push_applied == 0
    # Partial: one chunk staged, no fin.
    kv = {
        "layer_0": {"k": np.zeros((1, 64, 4, 8), np.float32)},
    }
    dec.kv_push.receive(serialize_push_chunk("part", 0, 2, (0, 64), kv))
    out = dec.generate_text(LONG, max_new_tokens=6, kv_xfer="part")
    assert out["token_ids"] == ref["token_ids"]
    assert dec.kv_push_fallbacks == 2


def test_pool_exhaustion_mid_install_loud(gpt_params):
    """Pool pressure while a pushed transfer installs: the alloc-first
    ordering propagates ``PagePoolExhausted`` loudly with NOTHING
    half-installed, and the replica serves once pressure lifts."""
    model = _model()
    pre = _engine(model, gpt_params, role="prefill")
    dec = _engine(model, gpt_params, role="decode")
    _link(pre, dec)
    ref = _engine(model, gpt_params).generate_text(LONG, max_new_tokens=6)
    xfer = next(XFERS)
    pre.generate_text(LONG, max_new_tokens=6,
                      push_to=("127.0.0.1", 1, xfer))
    assert pre.kv_push.wait_sent(xfer, 30.0)
    free = dec.kv_pages_total - dec.kv_pages_in_use
    hold = dec.pool.alloc(free - 4)   # < the 16 pages the blob needs
    with pytest.raises(PagePoolExhausted):
        dec.generate_text(LONG, max_new_tokens=6, kv_xfer=xfer)
    assert dec.kv_pages_in_use == len(hold)   # nothing half-installed
    dec.pool.release(hold)
    out = dec.generate_text(LONG, max_new_tokens=6)
    assert out["token_ids"] == ref["token_ids"]


def test_staging_store_is_bounded(gpt_params):
    """A remote peer cannot pin unbounded host RAM: the staging store
    LRU-evicts past its cap."""
    dec = _engine(_model(), gpt_params, role="decode")
    kv = {"layer_0": {"k": np.zeros((1, 8, 4, 8), np.float32)}}
    cap = dec.kv_push._STAGE_CAP
    for i in range(cap + 8):
        dec.kv_push.receive(
            serialize_push_chunk(f"spam-{i}", 0, 2, (0, 8), kv)
        )
    assert dec.kv_push.staged_count <= cap


# --- the replica surface (headers, endpoint, role gating) ---------------


async def _asgi_client(app):
    import httpx

    await app.startup()
    transport = httpx.ASGITransport(app=app)
    return httpx.AsyncClient(transport=transport, base_url="http://t")


async def test_handoff_endpoint_and_push_intake(gpt_params, monkeypatch):
    """The app surface end to end over ASGI: the prefill replica's
    /generate answers a handoff verdict for role-headed requests, the
    decode replica's /kv/push stages chunks (400 on garbage), and the
    decode replica's /generate with the transfer header streams
    token-identical to mixed with zero local prefill."""
    from mlapi_tpu.serving import build_app

    monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
    model = _model()
    mixed = _engine(model, gpt_params)
    pre = _engine(model, gpt_params, role="prefill")
    dec = _engine(model, gpt_params, role="decode")
    ref = mixed.generate_text(LONG, max_new_tokens=6)

    app_d = build_app(dec)
    cl_d = await _asgi_client(app_d)
    app_p = build_app(pre)
    cl_p = await _asgi_client(app_p)

    # Route the prefill engine's pushes through the REAL endpoint.
    loop = asyncio.get_running_loop()

    def transport(host, port, path, body, timeout_s):
        fut = asyncio.run_coroutine_threadsafe(
            cl_d.post(path, content=body), loop
        )
        r = fut.result(timeout_s)
        return r.status_code, r.content

    pre.kv_push._transport = transport
    try:
        body = {"text": LONG, "max_new_tokens": 6}
        r = await cl_p.post(
            "/generate", json=body,
            headers={
                "x-mlapi-decode-peer": "127.0.0.1:1",
                "x-mlapi-kv-xfer": "app-x1",
            },
        )
        assert r.status_code == 200
        hand = r.json()
        assert hand["handoff"] is True and hand["complete"] is True
        assert hand["first_token"] == ref["token_ids"][0]
        assert dec.kv_push_recv == 2    # both chunks landed via HTTP

        r = await cl_d.post(
            "/generate", json=body,
            headers={"x-mlapi-kv-xfer": "app-x1"},
        )
        assert r.status_code == 200
        assert r.json()["token_ids"] == ref["token_ids"]
        assert dec.prefill_chunks == 0 and dec.kv_push_applied == 1

        # Garbage intake: 400, counted, sender-visible.
        r = await cl_d.post("/kv/push", content=b"not a push")
        assert r.status_code == 400
        assert dec.kv_push_recv_failures == 1

        # /metrics exports the full push block on both roles.
        snap = (await cl_d.get("/metrics")).json()
        c = snap["counters"]
        assert c["generate.kv_push_applied"] == 1
        assert c["generate.kv_push_recv"] == 2
        assert c["generate.kv_push_recv_failures"] == 1
        snap = (await cl_p.get("/metrics")).json()
        assert snap["counters"]["generate.kv_push_sent"] == 2
        assert snap["counters"]["generate.kv_push_bytes_sent"] > 0
        # /healthz names the role on role-carrying replicas.
        assert (await cl_p.get("/healthz")).json()["role"] == "prefill"
        assert (await cl_d.get("/healthz")).json()["role"] == "decode"
    finally:
        await cl_p.aclose()
        await app_p.shutdown()
        await cl_d.aclose()
        await app_d.shutdown()


async def test_mixed_app_has_no_push_surface(gpt_params, monkeypatch):
    """Default topology (mixed role): no /kv/push route, no
    generate.kv_push_* counters, no healthz role field, and the
    disaggregation headers are ignored — bit-identical to r17."""
    from mlapi_tpu.serving import build_app

    monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
    eng = _engine(_model(), gpt_params)
    app = build_app(eng)
    cl = await _asgi_client(app)
    try:
        assert (await cl.post("/kv/push", content=b"x")).status_code == 404
        r = await cl.post(
            "/generate",
            json={"text": "hi", "max_new_tokens": 2},
            headers={
                "x-mlapi-decode-peer": "10.0.0.9:1",
                "x-mlapi-kv-xfer": "spoof",
            },
        )
        assert r.status_code == 200
        assert "token_ids" in r.json()      # served normally, no handoff
        snap = (await cl.get("/metrics")).json()
        assert not any(
            k.startswith("generate.kv_push") for k in snap["counters"]
        )
        assert "role" not in (await cl.get("/healthz")).json()
    finally:
        await cl.aclose()
        await app.shutdown()


async def test_push_endpoint_absent_off_replica(gpt_params, monkeypatch):
    """A decode-role server OUTSIDE a router fleet does not expose
    the push intake (no trusted pusher exists there)."""
    from mlapi_tpu.serving import build_app

    monkeypatch.delenv("MLAPI_TPU_REPLICA", raising=False)
    monkeypatch.delenv("MLAPI_TPU_REPLICAS", raising=False)
    eng = _engine(_model(), gpt_params, role="decode")
    app = build_app(eng)
    cl = await _asgi_client(app)
    try:
        assert (await cl.post("/kv/push", content=b"x")).status_code == 404
        # And the transfer header is ignored: served cold, counted
        # nothing (the scan is replica-gated).
        r = await cl.post(
            "/generate",
            json={"text": "hi", "max_new_tokens": 2},
            headers={"x-mlapi-kv-xfer": "spoof"},
        )
        assert r.status_code == 200
        assert eng.kv_push_fallbacks == 0
    finally:
        await cl.aclose()
        await app.shutdown()


# --- the role-aware router e2e -----------------------------------------


async def test_role_split_fleet_e2e(gpt_params, monkeypatch):
    """The tentpole e2e, real sockets end to end: a P=1 prefill +
    D=1 decode fleet behind the role-aware router serves a plain
    long-prompt /generate through the TWO-HOP path — stream identical
    to a direct mixed engine, decode-side prefill FLOPs zero, router
    counters moving — and degrades to mixed routing (cold prefill on
    the decode replica, counted) when the prefill pool goes away."""
    import httpx

    from mlapi_tpu.serving import build_app
    from mlapi_tpu.serving.router import Router, build_router_app
    from mlapi_tpu.serving.server import Server

    monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
    model = _model()
    pre = _engine(model, gpt_params, role="prefill")
    dec = _engine(model, gpt_params, role="decode")
    ref = _engine(model, gpt_params).generate_text(LONG, max_new_tokens=6)

    servers = []
    for eng in (pre, dec):
        srv = Server(
            build_app(eng, admission_control=False),
            host="127.0.0.1", port=0,
        )
        await srv.start()
        servers.append(srv)
    router = Router(
        [("127.0.0.1", s.port) for s in servers],
        roles=["prefill", "decode"],
        health_poll_s=0.05,
    )
    front = Server(build_router_app(router), host="127.0.0.1", port=0)
    await front.start()
    try:
        assert router.role_split
        url = f"http://127.0.0.1:{front.port}/generate"
        payload = {"text": LONG, "max_new_tokens": 6}
        async with httpx.AsyncClient(timeout=120.0) as c:
            r = await c.post(url, json=payload)
            assert r.status_code == 200
            assert r.json()["token_ids"] == ref["token_ids"]
            # Two-hop verdict, from counters on every party.
            assert router.role_disagg_forwards == 1
            assert router.role_push_incomplete == 0
            assert pre.kv_push.push_sent == 2
            assert dec.kv_push_applied == 1
            assert dec.prefill_chunks == 0 and dec.prefix.builds == 0
            await _wait_for(
                lambda: pre.kv_pages_in_use == 0
                and dec.kv_pages_in_use == 0
            )

            # Streaming relays through the same two-hop path.
            async with c.stream(
                "POST", url, json=dict(payload, stream=True)
            ) as resp:
                assert resp.status_code == 200
                lines = [ln async for ln in resp.aiter_lines() if ln]
            frames = [json.loads(ln) for ln in lines]
            ids: list = []
            for f in frames[:-1]:
                ids.extend(f["token_ids"])
            assert frames[-1]["done"] is True
            assert frames[-1]["token_ids"] == ref["token_ids"]
            assert dec.kv_push_applied == 2

            # Aggregated /metrics sums the push counters fleet-wide.
            snap = (
                await c.get(f"http://127.0.0.1:{front.port}/metrics")
            ).json()
            assert snap["counters"]["generate.kv_push_sent"] == 4
            assert snap["counters"]["generate.kv_push_applied"] == 2
            assert snap["counters"]["router.role_disagg_forwards"] == 2

            # Role-starved fallback: the prefill pool drains away —
            # the decode replica accepts the cold prefill, counted.
            await pre.drain(0.05)
            for _ in range(200):
                await asyncio.sleep(0.05)
                if router.replicas[0].state == "draining":
                    break
            assert router.replicas[0].state == "draining"
            r = await c.post(url, json=payload)
            assert r.status_code == 200
            assert r.json()["token_ids"] == ref["token_ids"]
            assert router.role_fallback_mixed >= 1
            assert dec.prefill_chunks == 2      # the cold prefill ran
    finally:
        await front.stop()
        await router.stop()
        for s in servers:
            await s.stop()
