"""Config system + train CLI: presets, YAML round trip, end-to-end
train→checkpoint→serve handoff through the CLIs (the capability the
reference implements as notebook → pickle → server, SURVEY §3.4)."""

import dataclasses
import json

import numpy as np

import pytest
import yaml

from mlapi_tpu.config import TrainConfig, get_preset, preset_names
from mlapi_tpu.serving import InferenceEngine
from mlapi_tpu.train.__main__ import run as train_run


def test_ladder_presets_registered():
    names = preset_names(only_available=False)
    for expected in (
        "iris-linear",
        "mnist-softmax",
        "fashion-mlp",
        "criteo-widedeep",
        "sst2-bert",
    ):
        assert expected in names
    # Only runnable presets are advertised to the CLI.
    for runnable in preset_names():
        assert runnable in names


def test_yaml_roundtrip(tmp_path):
    cfg = get_preset("fashion-mlp")
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(cfg.to_json()))
    restored = TrainConfig.from_yaml(p)
    assert restored == cfg
    assert restored.mesh_shape == (8, 1)


def test_unknown_preset_raises():
    with pytest.raises(ValueError, match="unknown preset"):
        get_preset("resnet-imagenet")


def test_train_cli_to_serving_engine(tmp_path):
    """The full handoff: train iris-linear via the CLI entry, load the
    checkpoint into an InferenceEngine, predict."""
    cfg = dataclasses.replace(get_preset("iris-linear"), steps=200)
    out = tmp_path / "ck"
    summary = train_run(cfg, str(out))
    assert summary["test_accuracy"] >= 0.93
    assert (out / "MANIFEST.json").exists()

    engine = InferenceEngine.from_checkpoint(out)
    assert engine.feature_names == (
        "sepal_length", "sepal_width", "petal_length", "petal_width",
    )
    labels, probs = engine.predict_labels([[5.1, 3.5, 1.4, 0.2]])
    assert labels == ["Iris-setosa"]
    assert 0.5 < probs[0] <= 1.0

    manifest = json.loads((out / "MANIFEST.json").read_text())
    assert manifest["config"]["train_config"]["name"] == "iris-linear"


def test_train_cli_mesh_fallback_when_devices_missing(tmp_path):
    """A config demanding more devices than visible degrades to
    unsharded with a warning instead of crashing (mesh wants 8x1;
    virtual CPU has 8 so force an impossible shape)."""
    cfg = dataclasses.replace(
        get_preset("iris-linear"), mesh_shape=(64, 1), steps=50
    )
    summary = train_run(cfg, None)
    assert summary["test_accuracy"] is not None


def test_train_bench_reports_throughput():
    """--bench mode: step time / examples/s rows come back sane for a
    mesh preset (VERDICT r2 #4: training perf must be measurable)."""
    from mlapi_tpu.train.bench import bench_train

    row = bench_train("fashion-mlp", bench_steps=2, warmup_steps=1)
    assert row["preset"] == "fashion-mlp"
    assert row["step_ms"] > 0
    assert row["examples_per_s"] > 0
    assert row["batch_size"] == 256
    assert row["devices"] == 8 and row["mesh"] == [8, 1]
    assert np.isfinite(row["final_loss"])
