"""Page-native prefill + chunked prefill/decode interleaving (r10):
``models/gpt.paged_prefill_fn``, the page-native formation/admission
paths in ``serving/batch_run.py``, the interleaved long-prompt
prefill, page-aligned stacked prefix sharing, and the paged ×
speculative composition.

The contracts these tests pin:

- **Adopt-copy bytes are exactly zero** on the page-native path and
  exactly one prefill copy per formation/admission on the legacy
  adopt path — both sides from dtype/shape arithmetic
  (``ops/quant.kv_tree_bytes``), never wall-clock — with greedy token
  streams IDENTICAL between the two paths across
  {gpt-MHA, llama-GQA} × {none, int8} × {einsum, flash}.
- **Interleaving bounds head-of-line blocking**: a long prompt
  admitted into a running batch delays the running streams by at most
  ONE prefill-chunk dispatch (``engine.interleave_max_stall``),
  short joiners still admit DURING the window, and the long prompt's
  stream is identical with interleaving on, off, and solo.
- **Pool exhaustion mid-prefill rejects loudly** without poisoning
  the pool.
- **Stacked (cross-prefix) groups share ref-counted pages** when the
  store-time page alignment holds (zero adopt bytes, COW divergence
  for partial group-end tiles), and fall back to copy semantics —
  loudly counted — when a cap-clamped entry breaks alignment.
- **Paged × speculative**: solo and batched speculation engage on
  paged batches (streams pinned to the plain engine), the batched
  handoff realigns as a host page-table shift when deltas are page
  multiples and as the counted device row-gather otherwise, and —
  since r11 — the last two declines are LIFTED: strict-admit mode
  (the spec warm grid compiles pool-shaped programs) and
  mesh-sharded pools (flash-extend's ``shard_map`` leg), both pinned
  as passing end-to-end stream-identity tests.
"""

import asyncio

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.quant import kv_tree_bytes
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.serving.paged_pool import PagePoolExhausted
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)
# Long-context variant for the chunked-prefill interleaving tests: a
# 200-token prompt rounds to a [256]-wide bucket (two 128-wide chunks)
# and still leaves decode room inside the window.
LONG_CFG = dict(CFG, max_positions=320)


def _model(kind="gpt_lm", kv_quant="none", impl="einsum", cfg=CFG):
    kw = dict(cfg, kv_quant=kv_quant, decode_attn_impl=impl)
    if kind == "llama_lm":
        kw["num_kv_heads"] = 2  # GQA: 4 query heads over 2 KV heads
    return get_model(kind, **kw)


@pytest.fixture(scope="module")
def gpt_params():
    return _model().init(jax.random.key(0))


@pytest.fixture(scope="module")
def llama_params():
    return _model("llama_lm").init(jax.random.key(0))


@pytest.fixture(scope="module")
def long_gpt_params():
    return _model(cfg=LONG_CFG).init(jax.random.key(1))


def _engine(model, params, **kw):
    kw.setdefault("chunk", 2)
    # Pin the chunked batch lifecycle: the fused fast paths build
    # transient in-program caches and never touch the pool.
    kw.setdefault("fused_single", False)
    kw.setdefault("kv_page_size", 8)
    return TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), **kw
    )


async def _collect(req) -> list[int]:
    out: list[int] = []
    while True:
        item = await req.queue.get()
        if item is None:
            return out
        if isinstance(item, Exception):
            raise item
        out.extend(item["token_ids"])


async def _quiesce(eng, expect: int = 0) -> None:
    """Wait for the decode thread's batch teardown: the completion
    sentinels are pushed BEFORE ``_paged_cleanup`` releases the
    batch's pages (the realign/write-back tail runs after delivery),
    so a pool assert straight after ``gather`` races it."""
    for _ in range(500):
        if eng.kv_pages_in_use == expect:
            return
        await asyncio.sleep(0.01)


def _cache_bytes(model, b: int, width: int) -> int:
    """Exact bytes of a contiguous [b, width] cache tree — what one
    legacy adopt scatter copies (pure eval_shape arithmetic)."""
    return kv_tree_bytes(
        jax.eval_shape(lambda: model.init_cache(b, width))
    )


# --- page-native vs legacy adopt: streams + exact byte accounting ------


@pytest.mark.parametrize("impl", ["einsum", "flash"])
@pytest.mark.parametrize("fmt", ["none", "int8"])
@pytest.mark.parametrize("kind", ["gpt_lm", "llama_lm"])
def test_stream_identical_and_adopt_bytes_exact(
    kind, fmt, impl, gpt_params, llama_params
):
    params = gpt_params if kind == "gpt_lm" else llama_params
    model = _model(kind, fmt, impl)
    native = _engine(model, params)
    legacy = _engine(model, params, prefill_page_native=False)
    prompt = "hello world"  # 11 tokens -> the 16 bucket
    a = native.generate_text(prompt, max_new_tokens=6)
    b = legacy.generate_text(prompt, max_new_tokens=6)
    assert a["token_ids"] == b["token_ids"], (kind, fmt, impl)
    # The whole claim, from dtype/shape arithmetic: page-native moved
    # ZERO adopt bytes; legacy re-copied exactly one [1, 16] cache.
    assert native.prefill_adopt_bytes == 0
    assert legacy.prefill_adopt_bytes == _cache_bytes(model, 1, 16)
    # Every page went back either way.
    assert native.kv_pages_in_use == 0
    assert legacy.kv_pages_in_use == 0


def test_adopt_bytes_accumulate_per_formation(gpt_params):
    model = _model()
    legacy = _engine(model, gpt_params, prefill_page_native=False)
    legacy.generate_text("hello world", max_new_tokens=4)   # bucket 16
    legacy.generate_text("b" * 40, max_new_tokens=4)        # bucket 64
    assert legacy.prefill_adopt_bytes == (
        _cache_bytes(model, 1, 16) + _cache_bytes(model, 1, 64)
    )


async def test_admission_page_native_zero_adopt(gpt_params):
    """Mid-batch admission writes the joiner's bucket straight into
    its mapped pages: zero adopt bytes page-native, exactly one
    [1, bucket] copy per joiner legacy — streams identical."""
    model = _model()
    outs = {}
    for native in (True, False):
        eng = _engine(
            model, gpt_params, max_wait_ms=0.0,
            prefill_page_native=native,
        )
        await eng.start()
        try:
            r1 = await eng.submit("the first long request",
                                  max_new_tokens=48, stream=True)
            head = await r1.queue.get()
            assert not isinstance(head, Exception)
            r2 = await eng.submit("joiner", max_new_tokens=6)
            outs[native] = await asyncio.gather(
                _collect(r1), _collect(r2)
            )
            outs[native][0] = head["token_ids"] + outs[native][0]
            assert eng.admitted >= 1
            if native:
                assert eng.prefill_adopt_bytes == 0
            else:
                # formation (bucket 64) + one admitted joiner
                # (bucket 16), each exactly one cache copy.
                assert eng.prefill_adopt_bytes == (
                    _cache_bytes(model, 1, 64)
                    + _cache_bytes(model, 1, 16)
                )
        finally:
            await eng.stop()
    assert outs[True] == outs[False]


# --- chunked prefill/decode interleaving -------------------------------


async def test_interleaved_long_prompt_bounded_stall(long_gpt_params):
    """The tentpole's serving half: a 200-token prompt admitted into a
    running batch prefills as chunks interleaved with decode — running
    streams stall by at most ONE prefill-chunk dispatch (engine
    counters, not wall-clock), a short joiner still admits during the
    window, and every stream is identical with interleaving on, off,
    and solo."""
    model = _model(cfg=LONG_CFG)
    long_prompt = "x" * 200
    outs = {}
    for ilv in (True, False):
        eng = _engine(
            model, long_gpt_params, max_wait_ms=0.0,
            prefill_interleave=ilv,
        )
        if ilv:
            # Solo reference: the same prompt through formation-time
            # chunked prefill (its own batch, different cache tier) —
            # placement-invariance says the stream cannot move.
            solo = eng.generate_text(long_prompt, max_new_tokens=6)
            assert eng.prefill_chunks >= 2
        await eng.start()
        try:
            r1 = await eng.submit("hi", max_new_tokens=130, stream=True)
            head = await r1.queue.get()
            assert not isinstance(head, Exception)
            r2 = await eng.submit(long_prompt, max_new_tokens=6)
            r3 = await eng.submit("yo", max_new_tokens=4)
            outs[ilv] = await asyncio.gather(
                _collect(r1), _collect(r2), _collect(r3)
            )
            outs[ilv][0] = head["token_ids"] + outs[ilv][0]
            if ilv:
                assert eng.interleaved_prefills == 1
                # THE bound: live decode rows never waited behind more
                # than one consecutive prefill-chunk dispatch.
                assert eng.interleave_max_stall == 1
                assert eng.admitted >= 2  # r2 interleaved + r3 one-shot
                assert outs[ilv][1] == solo["token_ids"]
                assert eng.prefill_adopt_bytes == 0
            assert eng.prefill_chunk_queue_depth == 0
            await _quiesce(eng)
            assert eng.kv_pages_in_use == 0
        finally:
            await eng.stop()
    # Interleaving on/off: every stream byte-identical.
    assert outs[True] == outs[False]


def test_pool_exhaustion_mid_prefill_loud_and_clean(long_gpt_params):
    """A long-prompt prefill that cannot fit the pool fails BEFORE any
    device work, loudly, leaving the pool consistent: the next request
    that fits still serves."""
    model = _model(cfg=LONG_CFG)
    tiny = _engine(
        model, long_gpt_params, kv_page_size=8, kv_pages=10,
    )
    with pytest.raises(PagePoolExhausted, match="kv-pages"):
        tiny.generate_text("x" * 200, max_new_tokens=6)
    assert tiny.kv_pages_in_use == 0
    out = tiny.generate_text("hi", max_new_tokens=2)
    assert len(out["token_ids"]) == 2
    assert tiny.kv_pages_in_use == 0


# --- page-aligned stacked (cross-prefix) sharing -----------------------


async def test_stacked_group_shares_pages_zero_adopt(gpt_params):
    """Two requests behind DIFFERENT prefixes form one stacked batch;
    store-time page alignment makes the right-alignment shifts page
    multiples, so both rows point at their entries' ref-counted pages:
    no widened-stack copy (zero adopt bytes, no fallback), streams
    equal the contiguous engine's."""
    model = _model()
    pa, pb = "You are a helpful bot.", "tl;dr"  # buckets 64 / 16
    cont = _engine(model, gpt_params, kv_page_size=None,
                   max_wait_ms=300.0)
    paged = _engine(model, gpt_params, max_wait_ms=300.0)
    for eng in (cont, paged):
        # Register both entries (their own solo batches), then group.
        eng.generate_text(" q0", max_new_tokens=2, prefix=pa)
        eng.generate_text(" q0", max_new_tokens=2, prefix=pb)
    outs = {}
    for key, eng in (("cont", cont), ("paged", paged)):
        await eng.start()
        try:
            before = eng.batch_calls
            ra = await eng.submit(" qa", max_new_tokens=6, prefix=pa)
            rb = await eng.submit(" qb", max_new_tokens=6, prefix=pb)
            outs[key] = await asyncio.gather(_collect(ra), _collect(rb))
            # One batch served both -> the stacked (mixed) path ran.
            assert eng.batch_calls == before + 1
        finally:
            await eng.stop()
    assert outs["paged"] == outs["cont"]
    assert paged.kv_prefix_copy_fallback == 0
    assert paged.prefill_adopt_bytes == 0  # no widened-stack scatter
    # Only the two entries' own page holds remain.
    entry_holds = sum(
        len(paged.pool.entry_pages(p)) for p in (pa, pb)
    )
    await _quiesce(paged, entry_holds)
    assert paged.kv_pages_in_use == entry_holds


async def test_stacked_group_unaligned_falls_back_loudly(gpt_params):
    """A cap-clamped entry cannot page-align (135 tokens, aligned 144
    > cap 143): a stacked group containing it keeps r09 copy
    semantics, counted in the fallback gauge — streams still match
    the contiguous engine."""
    model = _model()
    pu, pb = "c" * 135, "tl;dr"  # 135 stays unaligned at page 12
    cont = _engine(model, gpt_params, kv_page_size=None,
                   max_wait_ms=300.0)
    paged = _engine(model, gpt_params, kv_page_size=12,
                    max_wait_ms=300.0)
    for eng in (cont, paged):
        eng.generate_text(" q", max_new_tokens=2, prefix=pu)
        eng.generate_text(" q", max_new_tokens=2, prefix=pb)
    outs = {}
    for key, eng in (("cont", cont), ("paged", paged)):
        await eng.start()
        try:
            ra = await eng.submit(" qa", max_new_tokens=4, prefix=pu)
            rb = await eng.submit(" qb", max_new_tokens=4, prefix=pb)
            outs[key] = await asyncio.gather(_collect(ra), _collect(rb))
        finally:
            await eng.stop()
    assert outs["paged"] == outs["cont"]
    assert paged.kv_prefix_copy_fallback >= 1
    assert paged.prefill_adopt_bytes > 0  # the widened stack copied


async def test_stacked_same_width_shares_with_cow(gpt_params):
    """Two DISTINCT cap-clamped prefixes of the same (unaligned)
    width: shifts are zero (page multiples), so the stacked group
    SHARES pages, and the partial group-end tile diverges per row by
    COW — the sharing + divergence composition, pinned against the
    contiguous engine."""
    model = _model()
    p1, p2 = "c" * 135, "d" * 135
    cont = _engine(model, gpt_params, kv_page_size=None,
                   max_wait_ms=300.0)
    paged = _engine(model, gpt_params, kv_page_size=12,
                    max_wait_ms=300.0)
    for eng in (cont, paged):
        eng.generate_text(" q", max_new_tokens=2, prefix=p1)
        eng.generate_text(" q", max_new_tokens=2, prefix=p2)
    cows_before = paged.pool.cow_copies
    adopt_before = paged.prefill_adopt_bytes
    outs = {}
    for key, eng in (("cont", cont), ("paged", paged)):
        await eng.start()
        try:
            r1 = await eng.submit(" qa", max_new_tokens=4, prefix=p1)
            r2 = await eng.submit(" qb", max_new_tokens=4, prefix=p2)
            outs[key] = await asyncio.gather(_collect(r1), _collect(r2))
        finally:
            await eng.stop()
    assert outs["paged"] == outs["cont"]
    assert paged.kv_prefix_copy_fallback == 0     # shared, not copied
    assert paged.prefill_adopt_bytes == adopt_before
    assert paged.pool.cow_copies >= cows_before + 2  # one per row
    # Wait out the batch teardown before reusing the pool from this
    # thread, then: the shared pages came out unscathed.
    await _quiesce(paged, sum(
        len(paged.pool.entry_pages(p)) for p in (p1, p2)
    ))
    again = paged.generate_text(" qa", max_new_tokens=4, prefix=p1)
    assert again["token_ids"] == outs["paged"][0]


# --- paged × speculative ----------------------------------------------

T_CFG = dict(
    vocab_size=260, hidden_size=48, num_layers=3, num_heads=4,
    max_positions=160, compute_dtype="float32",
)
D_CFG = dict(
    vocab_size=260, hidden_size=24, num_layers=1, num_heads=2,
    max_positions=160, compute_dtype="float32",
)


@pytest.fixture(scope="module")
def spec_models():
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    return target, target.init(jax.random.key(0)), draft, draft.init(
        jax.random.key(1)
    )


def test_solo_spec_engages_on_paged_batches(spec_models):
    """The r09 'spec phases decline paged batches' guard LIFTS for
    solo speculation: it needs no realign, only per-round page
    mapping. Stream pinned to the draft-less contiguous engine."""
    target, tp, draft, dp = spec_models
    plain = _engine(target, tp, kv_page_size=None)
    spec = _engine(target, tp, draft=(draft, dp), spec_k=3)
    for prompt in ("speculate on pages", "another stream"):
        a = plain.generate_text(prompt, max_new_tokens=20)
        b = spec.generate_text(prompt, max_new_tokens=20)
        assert a["token_ids"] == b["token_ids"], prompt
    assert spec.spec_rounds > 0  # it actually engaged
    assert spec.kv_pages_in_use == 0


@pytest.mark.parametrize("page,counter", [
    (1, "spec_realign_table_ops"),   # deltas always page multiples
    (8, "spec_realign_repacks"),     # delta 7: sub-page -> row gather
])
async def test_batched_spec_paged_realign(spec_models, page, counter):
    """Batched speculation on a paged batch: rows with different
    budgets desynchronize (draft == target -> full acceptance, so the
    handoff delta is exactly n_new1 - n_new2 = 7) and the realign runs
    as a host table shift at page 1 / the counted device row-gather
    at page 8. Streams pinned to the draft-less contiguous engine."""
    target, tp, _, _ = spec_models
    plain = _engine(target, tp, kv_page_size=None, max_wait_ms=2000.0)
    spec = _engine(
        target, tp, kv_page_size=page, draft=(target, tp), spec_k=4,
        max_wait_ms=2000.0,
    )
    outs = {}
    for key, eng in (("plain", plain), ("spec", spec)):
        await eng.start()
        try:
            r1 = await eng.submit("aaaa", max_new_tokens=11)
            r2 = await eng.submit("bbbb", max_new_tokens=4)
            outs[key] = await asyncio.gather(_collect(r1), _collect(r2))
        finally:
            await eng.stop()
    assert outs["spec"] == outs["plain"]
    assert spec.spec_rounds > 0
    # The batched phase pushes each row's terminal sentinel INSIDE the
    # round loop the moment its budget is met; the handoff realign —
    # and the counter this test pins — runs on the decode thread after
    # the loop breaks. gather() returning therefore does NOT mean the
    # batch tail ran: condition-wait on the counter itself (bounded
    # poll on counters, never a bare sleep as synchronization).
    for _ in range(500):
        if getattr(spec, counter) >= 1:
            break
        await asyncio.sleep(0.01)
    assert getattr(spec, counter) >= 1, counter
    await _quiesce(spec)
    assert spec.kv_pages_in_use == 0


def test_paged_spec_strict_admit_engages(spec_models):
    """FORMER DECLINE PIN, now a passing end-to-end test (r11): in
    strict (tunnel) mode the spec warm grid compiles POOL-SHAPED
    verify/realign programs for paged engines (``SpecPhase.warm``
    branches on ``eng.pool``), so paged batches speculate without a
    mid-batch compile — and an engine whose paged shapes were NOT
    warmed still declines safely inside the phase (the warmed-key
    gate, unchanged)."""
    target, tp, draft, dp = spec_models
    plain = _engine(target, tp, kv_page_size=None)
    ref = plain.generate_text("declined", max_new_tokens=8)

    strict = _engine(
        target, tp, draft=(draft, dp), prompt_buckets=(16,),
        max_batch=2,
    )
    pages_before = strict.kv_pages_in_use
    shapes = strict.spec.warm()
    assert shapes >= 2  # solo + one batched size, paged-shaped
    # Null-table warm writes die in the null page: pool untouched.
    assert strict.kv_pages_in_use == pages_before
    strict._strict_admit = True
    out = strict.generate_text("declined", max_new_tokens=8)
    assert out["token_ids"] == ref["token_ids"]
    assert strict.spec_rounds > 0  # the decline is gone

    # Unwarmed strict engine: the phase's own gate still declines —
    # correct output, no speculation, no mid-batch compile.
    cold = _engine(target, tp, draft=(draft, dp))
    cold._strict_admit = True
    out = cold.generate_text("declined", max_new_tokens=8)
    assert out["token_ids"] == ref["token_ids"]
    assert cold.spec_rounds == 0


def test_paged_spec_mesh_sharded_pool_engages(spec_models):
    """FORMER DECLINE PIN, now a passing end-to-end test (r11): spec
    over a MESH-SHARDED pool. The einsum verify partitions as a plain
    GSPMD gather+einsum; the flash verify routes through the
    flash-extend ``shard_map`` leg (``extend_attention_tp`` /
    ``paged_extend_attention_tp``) so the opaque kernel runs per head
    shard. Streams pinned to the draft-less contiguous engine for
    BOTH impls."""
    import dataclasses

    from mlapi_tpu.parallel import create_mesh

    target, tp, draft, dp = spec_models
    plain = _engine(target, tp, kv_page_size=None)
    ref = plain.generate_text("declined", max_new_tokens=8)
    mesh = create_mesh((1, 2), devices=jax.devices()[:2])
    for impl in ("einsum", "flash"):
        t_i = dataclasses.replace(target, decode_attn_impl=impl)
        d_i = dataclasses.replace(draft, decode_attn_impl=impl)
        meshed = _engine(t_i, tp, draft=(d_i, dp), mesh=mesh)
        out = meshed.generate_text("declined", max_new_tokens=8)
        assert out["token_ids"] == ref["token_ids"], impl
        assert meshed.spec_rounds > 0, impl  # the decline is gone
        assert meshed.kv_pages_in_use == 0


# --- observability ------------------------------------------------------


async def test_metrics_exports_prefill_gauges(gpt_params):
    import httpx

    from mlapi_tpu.serving import build_app

    eng = _engine(_model(), gpt_params)
    eng.generate_text("warm the reservoirs", max_new_tokens=4)
    app = build_app(eng)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as c:
            snap = (await c.get("/metrics")).json()
        cnt, g = snap["counters"], snap["gauges"]
        assert cnt["generate.prefill_adopt_bytes"] == 0
        assert cnt["generate.kv_prefix_copy_fallback"] == 0
        assert cnt["generate.interleaved_prefills"] == 0
        assert cnt["generate.spec_realign_table_ops"] == 0
        assert cnt["generate.spec_realign_repacks"] == 0
        assert g["generate.prefill_chunk_queue_depth"] == 0
        assert g["generate.interleave_max_stall"] == 0
        # The latency reservoirs saw the warm request above.
        assert g["generate.ttft_p50_ms"] is not None
        assert g["generate.intertoken_p50_ms"] is not None
    finally:
        await app.shutdown()


# --- soak: interleaved admissions under churn (heavy) -------------------


@pytest.mark.heavy
@pytest.mark.slow  # 7.1 s measured call — r16 tier-1 buyback (conftest);
# interleaving correctness stays pinned by the counter-based tests.
async def test_interleaved_churn_no_leaks(long_gpt_params):
    """Several consecutive interleaved long-prompt admissions against
    a continuously-decoding stream: every window must activate, every
    page return, and the stall bound must hold across the whole run."""
    model = _model(cfg=LONG_CFG)
    eng = _engine(model, long_gpt_params, max_wait_ms=0.0)
    refs = [
        eng.generate_text("x" * (129 + 7 * i), max_new_tokens=5)
        ["token_ids"]
        for i in range(3)
    ]
    await eng.start()
    try:
        r1 = await eng.submit("hi", max_new_tokens=200, stream=True)
        head = await r1.queue.get()
        assert not isinstance(head, Exception)
        longs = [
            await eng.submit("x" * (129 + 7 * i), max_new_tokens=5)
            for i in range(3)
        ]
        outs = await asyncio.gather(
            _collect(r1), *[_collect(r) for r in longs]
        )
        assert [o for o in outs[1:]] == refs
        assert eng.interleaved_prefills >= 1
        assert eng.interleave_max_stall <= 1
        await _quiesce(eng)
        assert eng.kv_pages_in_use == 0
    finally:
        await eng.stop()
