"""Config-1 golden parity: JAX linear classifier vs the reference.

The reference's single published number is the notebook's held-out
accuracy 0.9666666666666667 on the 30-sample Iris test split
(``Logistic Regression.ipynb`` cell output, 80/20 split,
``random_state=1``). We reproduce the identical split and require our
TPU-native trainer to match or beat it, and additionally cross-check
prediction/probability agreement against an sklearn oracle trained on
the same data (SURVEY §4 "golden parity").
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.datasets import load_iris
from mlapi_tpu.models import get_model
from mlapi_tpu.train import fit, evaluate

REFERENCE_ACCURACY = 0.9666666666666667


@pytest.fixture(scope="module")
def iris():
    return load_iris()


@pytest.fixture(scope="module")
def trained(iris):
    model = get_model(
        "linear", num_features=iris.num_features, num_classes=iris.num_classes
    )
    result = fit(model, iris, steps=500, learning_rate=0.1, weight_decay=1e-3)
    return model, result


def test_split_matches_reference(iris):
    # 150 rows -> 120 train / 30 test, exactly the notebook's split.
    assert iris.x_train.shape == (120, 4)
    assert iris.x_test.shape == (30, 4)
    assert iris.vocab.labels == (
        "Iris-setosa",
        "Iris-versicolor",
        "Iris-virginica",
    )


def test_accuracy_meets_reference(trained):
    _, result = trained
    assert result.test_accuracy is not None
    assert result.test_accuracy >= REFERENCE_ACCURACY


def test_sklearn_oracle_agreement(iris, trained):
    """Predictions agree with an sklearn LogisticRegression oracle on
    the test rows where the oracle itself is confident."""
    sklearn = pytest.importorskip("sklearn")
    from sklearn.linear_model import LogisticRegression

    model, result = trained
    oracle = LogisticRegression(max_iter=1000).fit(iris.x_train, iris.y_train)
    oracle_pred = oracle.predict(iris.x_test)
    oracle_conf = oracle.predict_proba(iris.x_test).max(axis=1)

    logits = jax.jit(model.apply)(result.params, jnp.asarray(iris.x_test))
    ours = np.argmax(np.asarray(logits), axis=-1)

    confident = oracle_conf > 0.9
    assert confident.sum() >= 15  # sanity: oracle is confident on half+ rows
    np.testing.assert_array_equal(ours[confident], oracle_pred[confident])


def test_single_forward_is_one_matmul_shared(trained, iris):
    """Prediction and probability come from ONE forward pass — unlike
    the reference, which recomputes the matmul (main.py:21-22)."""
    model, result = trained
    x = jnp.asarray(iris.x_test[:1])
    logits = jax.jit(model.apply)(result.params, x)
    probs = jax.nn.softmax(logits, axis=-1)
    pred = int(jnp.argmax(logits, axis=-1)[0])
    assert 0.0 < float(probs[0, pred]) <= 1.0
    np.testing.assert_allclose(float(jnp.sum(probs)), 1.0, rtol=1e-5)


def test_data_parallel_fit_matches(iris, mesh8):
    """Same training, batch sharded over an 8-device data mesh —
    accuracy must not degrade (the all-reduce is numerically the same
    full-batch gradient)."""
    model = get_model(
        "linear", num_features=iris.num_features, num_classes=iris.num_classes
    )
    result = fit(
        model, iris, steps=500, learning_rate=0.1, weight_decay=1e-3, mesh=mesh8
    )
    assert result.test_accuracy >= REFERENCE_ACCURACY


def test_evaluate_matches_manual(trained, iris):
    model, result = trained
    acc = evaluate(model.apply, result.params, iris.x_test, iris.y_test)
    assert acc == pytest.approx(result.test_accuracy)
