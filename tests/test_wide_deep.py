"""Config 4: Wide&Deep on synthetic Criteo with model-axis sharded
embedding tables (SURVEY §7 step 6 — the first config where layout
matters)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mlapi_tpu.datasets import get_dataset
from mlapi_tpu.models import get_model
from mlapi_tpu.train import fit

SMALL = dict(
    num_dense=4,
    vocab_sizes=[512] * 6,
    embed_dim=8,
    hidden_dims=[32],
    num_classes=2,
)


@pytest.fixture(scope="module")
def criteo_small():
    return get_dataset(
        "criteo",
        num_dense=4,
        num_categorical=6,
        vocab_size=512,
        n_train=8192,
        n_test=1024,
    )


@pytest.fixture(scope="module")
def model():
    return get_model("wide_deep", **SMALL)


def test_forward_shapes(model):
    params = model.init(jax.random.key(0))
    x = np.zeros((3, model.num_features), np.float32)
    logits = jax.jit(model.apply)(params, x)
    assert logits.shape == (3, 2)


def test_param_shardings_mirror_params(model):
    params = model.init(jax.random.key(0))
    specs = model.param_shardings()
    # Same tree structure — tree_map must not raise.
    jax.tree.map(lambda a, s: None, params, specs,
                 is_leaf=lambda x: isinstance(x, P))
    assert specs["deep_tables"] == P(None, "model", None)
    assert specs["wide_dense"] == P()


def test_out_of_range_ids_are_wrapped(model):
    params = model.init(jax.random.key(0))
    x = np.zeros((2, model.num_features), np.float32)
    x[:, model.num_dense :] = 1e9  # way past vocab
    logits = jax.jit(model.apply)(params, x)
    assert np.isfinite(np.asarray(logits)).all()


def test_learns_planted_structure(criteo_small, model):
    result = fit(
        model, criteo_small, steps=300, batch_size=512, learning_rate=3e-3,
    )
    # Planted per-id effects: way better than chance, only reachable
    # by actually learning the embeddings.
    assert result.test_accuracy > 0.75


def test_sharded_training_on_2x4_mesh(criteo_small, model, mesh_2x4):
    result = fit(
        model, criteo_small, steps=300, batch_size=512, learning_rate=3e-3,
        mesh=mesh_2x4,
    )
    assert result.test_accuracy > 0.75
    # The embedding tables really live sharded on the model axis
    # (GSPMD may normalise away the trailing None).
    spec = tuple(result.params["deep_tables"].sharding.spec)
    assert spec in ((None, "model", None), (None, "model"))


def test_serve_wide_deep_checkpoint(tmp_path, criteo_small, model):
    from mlapi_tpu.checkpoint import save_checkpoint
    from mlapi_tpu.serving import InferenceEngine

    result = fit(model, criteo_small, steps=100, batch_size=512,
                 learning_rate=3e-3)
    save_checkpoint(
        tmp_path / "ck",
        result.params,
        step=100,
        config={
            "model": "wide_deep",
            "model_kwargs": SMALL,
            "feature_names": list(criteo_small.feature_names),
        },
        vocab=criteo_small.vocab,
    )
    engine = InferenceEngine.from_checkpoint(tmp_path / "ck", buckets=(1, 2, 4))
    labels, probs = engine.predict_labels(criteo_small.x_test[:4])
    assert set(labels) <= {"click", "no-click"}
    assert all(0.0 < p <= 1.0 for p in probs)