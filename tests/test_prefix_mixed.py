"""Cross-batch prefix regions: requests naming DIFFERENT prefixes
share one decode batch. Each row's prefix KV is right-aligned to the
group's common region end ``p_len = max(prefix_len)`` and masked by a
per-row ``lo`` vector (`serving.prefix.PrefixCache.stacked`,
`models/gpt.py` mask helpers' vector ``prefix_lo``).

The pin is the same equivalence the single-prefix tests hold: every
stream must be byte-identical to serving the concatenated
prefix+text through the plain path, now with rows whose prefixes —
and prefix LENGTHS — differ inside one batch."""

import asyncio

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio

CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=256,
    compute_dtype="float32",
)

P_A = "abcdefgh" * 3      # 24 tokens → bucket 64, padded (lo > 0)
P_B = "zyxwvuts" * 8      # 64 tokens → bucket 64, aligned (lo == 0)
P_C = "mnop" * 2          # 8 tokens → bucket 16 (different WIDTH)


@pytest.fixture
def anyio_backend():
    return "asyncio"


def _engine(model_name="gpt_lm", **kw) -> TextGenerationEngine:
    cfg = dict(CFG)
    if model_name == "llama_lm":
        cfg.pop("num_heads")
        cfg.update(num_heads=4, num_kv_heads=2)
    model = get_model(model_name, **cfg)
    return TextGenerationEngine(
        model,
        model.init(jax.random.key(0)),
        tokenizer=ByteTokenizer(),
        chunk=4,
        max_wait_ms=200.0,
        **kw,
    )


async def _collect(gen) -> list[int]:
    out: list[int] = []
    while True:
        item = await gen.queue.get()
        if item is None:
            return out
        if isinstance(item, Exception):
            raise item
        out.extend(item["token_ids"])


async def _run_pair(eng, specs):
    """Submit all (prefix, text, n) at once so the collector batches
    them; returns the collected streams in submit order. Prefix
    entries are registered up front — the co-batch window must not
    race the first-use prefix prefill."""
    for prefix, _, _ in specs:
        eng.prefix.entry(prefix)
    await eng.start()
    try:
        gens = []
        for prefix, text, n in specs:
            gens.append(
                await eng.submit(text, max_new_tokens=n, prefix=prefix)
            )
        return await asyncio.gather(*[_collect(g) for g in gens])
    finally:
        await eng.stop()


@pytest.mark.parametrize("model_name", ["gpt_lm", "llama_lm"])
async def test_two_prefixes_one_batch_exact_streams(model_name):
    """Same-width buckets, different contents (one padded, one
    aligned): both streams must equal their plain-path solo runs, and
    the engine must have served them in ONE batch."""
    eng = _engine(model_name)
    ref_a = eng.generate_text(P_A + "ij", max_new_tokens=10)
    ref_b = eng.generate_text(P_B + "kl", max_new_tokens=10)
    base = eng.batch_calls
    got_a, got_b = await _run_pair(
        eng, [(P_A, "ij", 10), (P_B, "kl", 10)]
    )
    assert got_a == ref_a["token_ids"]
    assert got_b == ref_b["token_ids"]
    assert eng.batch_calls == base + 1, "prefixes were not co-batched"


async def test_different_prefix_widths_right_aligned():
    """Different prefix BUCKETS (64 vs 16): the narrow prefix
    right-aligns into the wide region; both streams stay exact."""
    eng = _engine()
    ref_a = eng.generate_text(P_A + "ij", max_new_tokens=12)
    ref_c = eng.generate_text(P_C + "kl", max_new_tokens=12)
    base = eng.batch_calls
    got_a, got_c = await _run_pair(
        eng, [(P_A, "ij", 12), (P_C, "kl", 12)]
    )
    assert got_a == ref_a["token_ids"]
    assert got_c == ref_c["token_ids"]
    assert eng.batch_calls == base + 1


async def test_mixed_batch_compaction_after_short_row_finishes():
    """A short and a long request with different prefixes: after the
    short row finishes the batch compacts, and the surviving row's
    per-row lo must follow it through the gather."""
    eng = _engine()
    ref_long = eng.generate_text(P_B + "kl", max_new_tokens=40)
    ref_short = eng.generate_text(P_C + "ij", max_new_tokens=4)
    got_short, got_long = await _run_pair(
        eng, [(P_C, "ij", 4), (P_B, "kl", 40)]
    )
    assert got_short == ref_short["token_ids"]
    assert got_long == ref_long["token_ids"]


async def test_three_prefixes_batch_and_seeded_sampling():
    """Three distinct prefixes in one batch, one of them sampled with
    a seed: sampled streams must also be byte-identical to their solo
    plain-path runs (per-row PRNG streams are position-independent)."""
    eng = _engine()
    ref_a = eng.generate_text(P_A + "ij", max_new_tokens=8)
    ref_b = eng.generate_text(
        P_B + "kl", max_new_tokens=8, temperature=0.9, seed=7
    )
    ref_c = eng.generate_text(P_C + "mn", max_new_tokens=8)
    for p in (P_A, P_B, P_C):
        eng.prefix.entry(p)
    await eng.start()
    try:
        g_a = await eng.submit("ij", max_new_tokens=8, prefix=P_A)
        g_b = await eng.submit(
            "kl", max_new_tokens=8, temperature=0.9, seed=7, prefix=P_B
        )
        g_c = await eng.submit("mn", max_new_tokens=8, prefix=P_C)
        got = await asyncio.gather(
            _collect(g_a), _collect(g_b), _collect(g_c)
        )
    finally:
        await eng.stop()
    assert got[0] == ref_a["token_ids"]
    assert got[1] == ref_b["token_ids"]
    assert got[2] == ref_c["token_ids"]


async def test_plain_and_prefix_requests_do_not_mix():
    """A plain request must not join a prefix batch (it would pay the
    whole region in dead cache slots)."""
    eng = _engine()
    ref_p = eng.generate_text(P_A + "ij", max_new_tokens=6)
    ref_n = eng.generate_text("hello", max_new_tokens=6)
    base = eng.batch_calls
    await eng.start()
    try:
        g_p = await eng.submit("ij", max_new_tokens=6, prefix=P_A)
        g_n = await eng.submit("hello", max_new_tokens=6)
        got_p, got_n = await asyncio.gather(_collect(g_p), _collect(g_n))
    finally:
        await eng.stop()
    assert got_p == ref_p["token_ids"]
    assert got_n == ref_n["token_ids"]
    assert eng.batch_calls >= base + 2, "plain joined a prefix batch"
