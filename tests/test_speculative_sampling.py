"""Speculative SAMPLING (`ops/speculative.speculative_sample`): the
Leviathan/Chen acceptance-rejection scheme at temperature > 0.

The load-bearing property is DISTRIBUTIONAL: the emitted stream must
be distributed exactly as plain target sampling under the same warp,
regardless of draft quality. Pinned here two ways:

- draft == target → the acceptance ratio p/q is exactly 1 and every
  usable proposal must be accepted (the scheme's internal identity);
- an end-to-end total-variation bound: the empirical joint
  distribution of the first two sampled tokens over many seeds
  matches the exact model-computed joint (enumerated per t0) — a
  deterministic check (fixed seed list), not a flaky one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.speculative import (
    _warped_probs,
    speculative_generate,
    speculative_sample,
)

T_CFG = dict(
    vocab_size=32, hidden_size=32, num_layers=2, num_heads=4,
    max_positions=64, compute_dtype="float32",
)
D_CFG = dict(
    vocab_size=32, hidden_size=16, num_layers=1, num_heads=2,
    max_positions=64, compute_dtype="float32",
)


def _models(seed_t=0, seed_d=1):
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    return (
        target, target.init(jax.random.key(seed_t)),
        draft, draft.init(jax.random.key(seed_d)),
    )


def test_draft_equals_target_accepts_everything_sampled():
    """p == q bitwise → u * q < p is u < 1: always true. Every usable
    proposal accepted, every full round emits k+1."""
    target = get_model("gpt_lm", **T_CFG)
    tp = target.init(jax.random.key(0))
    prompt = (np.arange(6, dtype=np.int32)[None] % 20) + 3
    got, stats = speculative_sample(
        target, tp, target, tp, prompt,
        max_new_tokens=21, k=4, temperature=0.9, seed=7,
    )
    assert len(got) == 21
    assert stats.acceptance_rate == 1.0, stats
    assert stats.tokens_per_round == 5.0


def test_full_acceptance_with_topk_topp_warps():
    """The warp pipeline (temperature + top-k + top-p) is shared
    between draft sampling and verify: with draft == target the
    filtered distributions stay bitwise equal too."""
    target = get_model("gpt_lm", **T_CFG)
    tp = target.init(jax.random.key(2))
    prompt = (np.arange(5, dtype=np.int32)[None] % 20) + 2
    got, stats = speculative_sample(
        target, tp, target, tp, prompt,
        max_new_tokens=13, k=3, temperature=0.7,
        top_k=8, top_p=0.9, seed=11,
    )
    assert len(got) == 13
    assert stats.acceptance_rate == 1.0, stats


def test_deterministic_given_seed():
    target, tp, draft, dp = _models()
    prompt = (np.arange(4, dtype=np.int32)[None] % 25) + 1
    a, _ = speculative_sample(
        target, tp, draft, dp, prompt,
        max_new_tokens=16, k=3, temperature=1.0, seed=5,
    )
    b, _ = speculative_sample(
        target, tp, draft, dp, prompt,
        max_new_tokens=16, k=3, temperature=1.0, seed=5,
    )
    c, _ = speculative_sample(
        target, tp, draft, dp, prompt,
        max_new_tokens=16, k=3, temperature=1.0, seed=6,
    )
    assert a == b
    assert a != c  # 32-token vocab, 16 draws: collision ~ never


def test_greedy_temperature_delegates_to_exact_scheme():
    target, tp, draft, dp = _models()
    prompt = (np.arange(5, dtype=np.int32)[None] % 25) + 1
    ref, _ = speculative_generate(
        target, tp, draft, dp, prompt, max_new_tokens=12, k=3,
    )
    got, _ = speculative_sample(
        target, tp, draft, dp, prompt,
        max_new_tokens=12, k=3, temperature=0.0, seed=9,
    )
    assert got == ref


def test_budget_capped_round_and_length():
    """n smaller than a full round: usable < k caps acceptance and
    the final token draws from the full target distribution."""
    target, tp, draft, dp = _models()
    prompt = (np.arange(4, dtype=np.int32)[None] % 25) + 1
    got, stats = speculative_sample(
        target, tp, draft, dp, prompt,
        max_new_tokens=3, k=5, temperature=1.0, seed=3,
    )
    assert len(got) == 3
    assert stats.drafted <= 2 * 5  # usable clamped below k each round


def test_window_edge_falls_back_to_plain_sampled_steps():
    cfg = dict(T_CFG, max_positions=24)
    target = get_model("gpt_lm", **cfg)
    draft = get_model("gpt_lm", **dict(D_CFG, max_positions=24))
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    prompt = (np.arange(6, dtype=np.int32)[None] % 20) + 1
    n = 18  # prompt + n == max_positions
    got, stats = speculative_sample(
        target, tp, draft, dp, prompt,
        max_new_tokens=n, k=4, temperature=0.8, seed=2,
    )
    assert len(got) == n
    assert stats.fallback_steps > 0


def _exact_joint(target, tp, prompt, temperature):
    """Enumerate the exact 2-token joint under plain target sampling:
    p(t0) from the prompt logits, p(t1 | t0) from a teacher-forced
    forward per t0 — the ground truth the sampled scheme must match."""
    v = target.vocab_size
    temps = jnp.asarray([temperature], jnp.float32)
    tk = jnp.zeros((1,), jnp.int32)
    tp_ = jnp.ones((1,), jnp.float32)
    logits0 = target.apply(tp, jnp.asarray(prompt))[0, -1][None]
    p0 = np.asarray(_warped_probs(logits0, temps, tk, tp_))[0]
    joint = np.zeros((v, v))
    for t0 in range(v):
        if p0[t0] < 1e-9:
            continue
        seq = np.concatenate([prompt[0], [t0]])[None].astype(np.int32)
        lg1 = target.apply(tp, jnp.asarray(seq))[0, -1][None]
        p1 = np.asarray(_warped_probs(lg1, temps, tk, tp_))[0]
        joint[t0] = p0[t0] * p1
    return joint


@pytest.mark.parametrize("q_kind", ["uniform", "adversarial"])
def test_accept_residual_kernel_recovers_exact_target_dist(q_kind):
    """THE Leviathan identity, tested at the kernel level: draw the
    proposal x ~ q on the host, run ``sample_verify_fn`` (k=1)
    against a real target cache, and tally the round's emitted
    token. Whatever q is — uniform, or adversarially peaked on a
    wrong token (high rejection, residual-dominated) — the emitted
    marginal must equal the exact warped target distribution p.
    Deterministic (fixed seeds); noise floor ~sqrt(V/4N) ≈ 0.06."""
    from mlapi_tpu.models.gpt import prefill_fn
    from mlapi_tpu.ops.speculative import sample_verify_fn

    target = get_model("gpt_lm", **T_CFG)
    tp = target.init(jax.random.key(4))
    v = target.vocab_size
    prompt = (np.arange(3, dtype=np.int32)[None] % 20) + 5
    p_len = prompt.shape[1]
    total = p_len + 4
    temperature = 1.1
    temps = jnp.asarray([temperature], jnp.float32)
    z0 = jnp.zeros((1,), jnp.int32)
    o1 = jnp.ones((1,), jnp.float32)
    t0 = 7

    # Exact target distribution after [prompt, t0].
    seq = np.concatenate([prompt[0], [t0]])[None].astype(np.int32)
    lg = target.apply(tp, jnp.asarray(seq))[0, -1][None]
    p_exact = np.asarray(_warped_probs(lg, temps, z0, o1))[0]

    if q_kind == "uniform":
        q = np.full(v, 1.0 / v, np.float32)
    else:
        # Peaked on the target's LEAST likely token: ~max rejection.
        q = np.full(v, 0.02 / (v - 1), np.float32)
        q[int(p_exact.argmin())] = 0.98
        q /= q.sum()
    q_j = jnp.asarray(q[None])  # [k=1, V]

    _, cache0 = prefill_fn(target, total)(
        tp, jnp.asarray(prompt),
        jnp.asarray(np.asarray(
            jax.random.key_data(jax.random.key(0)))[None]),
        jnp.zeros((1,), jnp.float32), z0, z0, o1,
    )
    cache0 = jax.tree.map(np.asarray, cache0)  # host template

    n_runs = 2000
    rng = np.random.default_rng(12)
    props = rng.choice(v, size=n_runs, p=q)
    counts = np.zeros(v)
    fn = sample_verify_fn(target, 2)
    for i in range(n_runs):
        cache = jax.tree.map(jnp.asarray, cache0)
        _, packed = fn(
            tp, cache, jnp.int32(t0),
            jnp.asarray(np.asarray([props[i]], np.int32)),
            jnp.int32(p_len), z0, q_j,
            jnp.asarray(np.asarray(
                jax.random.key_data(jax.random.key(1000 + i)))[None]),
            temps, z0, o1, jnp.int32(1), jnp.int32(1),
        )
        counts[int(np.asarray(packed)[0])] += 1
    emp = counts / n_runs
    tv = 0.5 * np.abs(emp - p_exact).sum()
    # A broken rule is far outside this: always-accept reproduces q
    # (TV vs p ≈ 0.9 for the adversarial q); a wrong residual skews
    # the rejected mass similarly.
    assert tv < 0.12, f"TV {tv:.3f} vs exact target dist (q={q_kind})"


def test_marginal_t1_matches_exact_within_tv():
    """Tighter marginal check on the SECOND token alone (the first
    speculative one): empirical vs exact marginal over v=32 cells has
    a much lower noise floor than the joint."""
    target, tp, draft, dp = _models(seed_t=4, seed_d=9)
    prompt = (np.arange(3, dtype=np.int32)[None] % 20) + 5
    temperature = 1.2
    n_runs = 600
    v = target.vocab_size
    counts = np.zeros(v)
    for seed in range(n_runs):
        toks, _ = speculative_sample(
            target, tp, draft, dp, prompt,
            max_new_tokens=2, k=1, temperature=temperature, seed=seed,
        )
        counts[toks[1]] += 1
    emp = counts / n_runs
    exact = _exact_joint(target, tp, prompt, temperature).sum(axis=0)
    tv = 0.5 * np.abs(emp - exact).sum()
    # Noise floor ~ sqrt(v / (4 N)) ≈ 0.11 for v=32, N=600; sampling
    # from the DRAFT's marginal instead lands several× higher.
    assert tv < 0.2, f"TV {tv:.3f} vs exact marginal"


# -- engine integration (--spec-sample serving) --------------------------


def _spec_sample_engine(draft_equals_target=False):
    from mlapi_tpu.serving.engine import TextGenerationEngine
    from mlapi_tpu.text import ByteTokenizer

    t_cfg = dict(
        vocab_size=260, hidden_size=48, num_layers=3, num_heads=4,
        max_positions=160, compute_dtype="float32",
    )
    d_cfg = dict(
        vocab_size=260, hidden_size=24, num_layers=1, num_heads=2,
        max_positions=160, compute_dtype="float32",
    )
    target = get_model("gpt_lm", **t_cfg)
    tp = target.init(jax.random.key(0))
    if draft_equals_target:
        draft, dp = target, tp
    else:
        draft = get_model("gpt_lm", **d_cfg)
        dp = draft.init(jax.random.key(1))
    tok = ByteTokenizer()
    return TextGenerationEngine(
        target, tp, tokenizer=tok, chunk=4,
        draft=(draft, dp), spec_k=3, spec_sample=True,
    )


def test_engine_spec_sample_engages_and_is_solo_deterministic():
    """--spec-sample serving: a sampled single-stream request decodes
    through speculative rounds; two identical solo runs on the same
    engine emit identical streams (per-seed determinism holds when no
    admission churn perturbs the round boundaries)."""
    eng = _spec_sample_engine()
    a = eng.generate_text("abcabcab", max_new_tokens=24,
                          temperature=0.8, seed=5)
    assert eng.spec_rounds > 0, "sampled request never speculated"
    b = eng.generate_text("abcabcab", max_new_tokens=24,
                          temperature=0.8, seed=5)
    assert a["token_ids"] == b["token_ids"]
    c = eng.generate_text("abcabcab", max_new_tokens=24,
                          temperature=0.8, seed=6)
    assert a["token_ids"] != c["token_ids"]


def test_engine_spec_sample_draft_equals_target_accepts_all():
    """Same-model draft through the ENGINE path (bucketed pads, live
    cache): the p/q ratio must stay exactly 1 — acceptance 100%."""
    eng = _spec_sample_engine(draft_equals_target=True)
    eng.generate_text("abcab", max_new_tokens=16,
                      temperature=0.9, seed=2)
    assert eng.spec_rounds > 0
    assert eng.spec_drafted == eng.spec_accepted > 0


def test_engine_greedy_exactness_unchanged_with_spec_sample_on():
    """The flag must not disturb the greedy byte-exact contract."""
    from mlapi_tpu.serving.engine import TextGenerationEngine
    from mlapi_tpu.text import ByteTokenizer

    t_cfg = dict(
        vocab_size=260, hidden_size=48, num_layers=3, num_heads=4,
        max_positions=160, compute_dtype="float32",
    )
    target = get_model("gpt_lm", **t_cfg)
    tp = target.init(jax.random.key(0))
    tok = ByteTokenizer()
    plain = TextGenerationEngine(target, tp, tokenizer=tok, chunk=4)
    ref = plain.generate_text("abcabcab", max_new_tokens=20)
    eng = _spec_sample_engine()
    got = eng.generate_text("abcabcab", max_new_tokens=20)
    assert got["token_ids"] == ref["token_ids"]
