"""LM training path: docs corpus loader, the lm task in the train
loop, and the train->checkpoint->/generate pipeline for decoders."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mlapi_tpu.datasets import get_dataset
from mlapi_tpu.models import get_model
from mlapi_tpu.train import fit
from mlapi_tpu.train.loop import evaluate_lm, make_train_step

TINY_GPT = dict(
    vocab_size=260, hidden_size=32, num_layers=1, num_heads=2,
    max_positions=64, compute_dtype="float32",
)


@pytest.fixture(scope="module")
def corpus():
    return get_dataset("docs_text", seq_len=64)


def test_corpus_shapes_and_provenance(corpus):
    assert corpus.source == "real"
    # LM anchors must reproduce from a clean checkout: the dataset
    # defaults to the commit-pinned snapshot (datasets/_corpus.py),
    # and measurements carry the provenance.
    assert corpus.extras["corpus"] == "frozen@012402d"
    assert corpus.x_train.ndim == 2 and corpus.x_train.shape[1] == 64
    assert np.array_equal(corpus.x_train, corpus.y_train)  # LM: y == x
    assert corpus.x_train.max() < 260  # byte tokenizer range
    assert corpus.extras["tokenizer"]["kind"] == "bytes"
    assert len(corpus.x_test) >= 1


def test_provenance_requires_exact_manifest_coverage(tmp_path):
    """A frozen@ claim must imply "the four known files hashed clean"
    (ADVICE r05 #1): a directory with a FOREIGN or vacuous
    MANIFEST.json (no 'files', extra files, missing files) is a user
    corpus and reports live:<path>; only hash corruption of a real
    snapshot raises."""
    import hashlib
    import json

    from mlapi_tpu.datasets._corpus import DOC_SOURCES, corpus_provenance

    # No manifest at all -> live.
    assert corpus_provenance(tmp_path) == f"live:{tmp_path}"

    # Empty / foreign manifest used to pass the hash loop vacuously
    # and report frozen@? — must be live now.
    (tmp_path / "MANIFEST.json").write_text(json.dumps({"files": {}}))
    assert corpus_provenance(tmp_path) == f"live:{tmp_path}"
    (tmp_path / "MANIFEST.json").write_text(
        json.dumps({"files": {"OTHER.md": {"sha256": "0" * 64}}})
    )
    assert corpus_provenance(tmp_path) == f"live:{tmp_path}"

    # Exact coverage with verifying hashes -> frozen@commit.
    files = {}
    for rel in DOC_SOURCES:
        from pathlib import Path

        name = Path(rel).name
        (tmp_path / name).write_text(f"content of {name}\n")
        files[name] = {
            "sha256": hashlib.sha256(
                (tmp_path / name).read_bytes()
            ).hexdigest()
        }
    (tmp_path / "MANIFEST.json").write_text(
        json.dumps({"source_commit": "abc1234", "files": files})
    )
    assert corpus_provenance(tmp_path) == "frozen@abc1234"

    # Superset coverage (one extra tracked file) -> live, not frozen.
    extra = dict(files)
    extra["EXTRA.md"] = {"sha256": "0" * 64}
    (tmp_path / "MANIFEST.json").write_text(
        json.dumps({"source_commit": "abc1234", "files": extra})
    )
    assert corpus_provenance(tmp_path) == f"live:{tmp_path}"

    # Exact coverage + corrupted bytes -> raises, never a quiet label.
    (tmp_path / "MANIFEST.json").write_text(
        json.dumps({"source_commit": "abc1234", "files": files})
    )
    (tmp_path / "README.md").write_text("tampered\n")
    with pytest.raises(ValueError, match="corrupted"):
        corpus_provenance(tmp_path)

    # The shipped snapshot still verifies end to end.
    from mlapi_tpu.datasets._corpus import frozen_corpus

    assert corpus_provenance(frozen_corpus()).startswith("frozen@")


def test_live_mode_sweeps_docs_markdown(tmp_path, monkeypatch):
    """docs_text's live mode follows the repo docs as they grow: any
    docs/*.md beyond DOC_SOURCES joins the corpus (the pre-unification
    glob, restored per ADVICE r05 #2); frozen/user-dir modes stay
    pinned to DOC_SOURCES."""
    import mlapi_tpu.datasets._corpus as _corpus

    (tmp_path / "docs").mkdir()
    (tmp_path / "README.md").write_text("readme prose " * 50)
    (tmp_path / "SURVEY.md").write_text("survey prose " * 50)
    (tmp_path / "BASELINE.md").write_text("baseline prose " * 50)
    (tmp_path / "docs" / "DESIGN.md").write_text("design prose " * 50)
    (tmp_path / "docs" / "NEWDOC.md").write_text("NEWDOC prose " * 200)
    monkeypatch.setattr(_corpus, "repo_root", lambda: tmp_path)

    live = get_dataset("docs_text", seq_len=32, root="live")
    pinned = get_dataset("docs_text", seq_len=32, root=str(tmp_path))
    # The extra doc makes the live stream strictly longer.
    assert len(live.x_train) > len(pinned.x_train)
    assert live.extras["corpus"] == f"live:{tmp_path}"


def test_train_test_windows_do_not_overlap():
    d = get_dataset("docs_text", seq_len=64, stride=32)
    # Tail split with stride guard: no train window may reach into
    # the region the test windows cover.
    flat_test = d.x_test.reshape(-1)
    first_test_window = d.x_test[0]
    for w in d.x_train[-4:]:
        assert not np.array_equal(w, first_test_window)
    assert len(flat_test)


def test_lm_loss_masks_pads():
    m = get_model("gpt_lm", **TINY_GPT)
    import optax

    params = m.init(jax.random.key(0))
    step = make_train_step(m.apply, optax.sgd(0.0), task="lm")
    x = np.full((2, 8), 7, np.int32)
    x_padded = x.copy()
    x_padded[:, 6:] = 0  # pad tail: loss must ignore those targets
    tx_state = optax.sgd(0.0).init(params)
    _, _, loss_full = step(params, tx_state, jnp.asarray(x), jnp.asarray(x))
    p2 = m.init(jax.random.key(0))
    s2 = optax.sgd(0.0).init(p2)
    _, _, loss_pad = step(p2, s2, jnp.asarray(x_padded), jnp.asarray(x_padded))
    assert np.isfinite(float(loss_full)) and np.isfinite(float(loss_pad))
    # Not asserting equality (different visible context), just that the
    # pad-masked loss is computed over fewer targets without NaN.


def test_make_train_step_rejects_unknown_task():
    import optax

    m = get_model("gpt_lm", **TINY_GPT)
    with pytest.raises(ValueError, match="unknown task"):
        make_train_step(m.apply, optax.sgd(0.1), task="regression")


def test_fit_autodetects_lm_and_learns(corpus):
    m = get_model("gpt_lm", **TINY_GPT)
    r = fit(
        m, corpus, steps=60, batch_size=32, learning_rate=1e-3,
        optimizer="adamw",
    )
    assert np.isfinite(r.final_loss)
    # Next-token accuracy on English bytes: random is ~1/60 over the
    # used byte alphabet; even 60 steps beats 10%.
    assert r.test_accuracy > 0.10, r.test_accuracy


def test_evaluate_lm_perfect_on_copycat():
    """Sanity-check the metric itself with a constant-sequence set a
    trained copy model would ace — using logits rigged to echo the
    previous token."""
    x = np.full((4, 10), 9, np.int32)

    def apply_fn(params, ids):
        return jax.nn.one_hot(ids, 260) * 100.0  # predict current id

    acc = evaluate_lm(apply_fn, {}, x)
    assert acc == 1.0  # every target equals the previous token


def test_docs_preset_cli_end_to_end(tmp_path):
    """The full pipeline: preset -> fit -> checkpoint (with tokenizer
    fingerprint) -> generation engine serves it."""
    from mlapi_tpu.config import TrainConfig
    from mlapi_tpu.serving.engine import InferenceEngine
    from mlapi_tpu.train.__main__ import run

    cfg = TrainConfig(
        name="docs-gpt-test",
        model="gpt_lm",
        model_kwargs=dict(TINY_GPT),
        dataset="docs_text",
        dataset_kwargs={"seq_len": 64},
        steps=5,
        batch_size=16,
        optimizer="adamw",
        learning_rate=1e-3,
    )
    out = tmp_path / "ck"
    run(cfg, out=str(out))
    eng = InferenceEngine.from_checkpoint(out)
    assert hasattr(eng.model, "generate")
    gen = np.asarray(
        eng.model.generate(
            eng.params,
            jnp.asarray([[10, 11, 12]], jnp.int32),
            max_new_tokens=4,
        )
    )
    assert gen.shape == (1, 4)
