"""LM training path: docs corpus loader, the lm task in the train
loop, and the train->checkpoint->/generate pipeline for decoders."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mlapi_tpu.datasets import get_dataset
from mlapi_tpu.models import get_model
from mlapi_tpu.train import fit
from mlapi_tpu.train.loop import evaluate_lm, make_train_step

TINY_GPT = dict(
    vocab_size=260, hidden_size=32, num_layers=1, num_heads=2,
    max_positions=64, compute_dtype="float32",
)


@pytest.fixture(scope="module")
def corpus():
    return get_dataset("docs_text", seq_len=64)


def test_corpus_shapes_and_provenance(corpus):
    assert corpus.source == "real"
    # LM anchors must reproduce from a clean checkout: the dataset
    # defaults to the commit-pinned snapshot (datasets/_corpus.py),
    # and measurements carry the provenance.
    assert corpus.extras["corpus"] == "frozen@012402d"
    assert corpus.x_train.ndim == 2 and corpus.x_train.shape[1] == 64
    assert np.array_equal(corpus.x_train, corpus.y_train)  # LM: y == x
    assert corpus.x_train.max() < 260  # byte tokenizer range
    assert corpus.extras["tokenizer"]["kind"] == "bytes"
    assert len(corpus.x_test) >= 1


def test_train_test_windows_do_not_overlap():
    d = get_dataset("docs_text", seq_len=64, stride=32)
    # Tail split with stride guard: no train window may reach into
    # the region the test windows cover.
    flat_test = d.x_test.reshape(-1)
    first_test_window = d.x_test[0]
    for w in d.x_train[-4:]:
        assert not np.array_equal(w, first_test_window)
    assert len(flat_test)


def test_lm_loss_masks_pads():
    m = get_model("gpt_lm", **TINY_GPT)
    import optax

    params = m.init(jax.random.key(0))
    step = make_train_step(m.apply, optax.sgd(0.0), task="lm")
    x = np.full((2, 8), 7, np.int32)
    x_padded = x.copy()
    x_padded[:, 6:] = 0  # pad tail: loss must ignore those targets
    tx_state = optax.sgd(0.0).init(params)
    _, _, loss_full = step(params, tx_state, jnp.asarray(x), jnp.asarray(x))
    p2 = m.init(jax.random.key(0))
    s2 = optax.sgd(0.0).init(p2)
    _, _, loss_pad = step(p2, s2, jnp.asarray(x_padded), jnp.asarray(x_padded))
    assert np.isfinite(float(loss_full)) and np.isfinite(float(loss_pad))
    # Not asserting equality (different visible context), just that the
    # pad-masked loss is computed over fewer targets without NaN.


def test_make_train_step_rejects_unknown_task():
    import optax

    m = get_model("gpt_lm", **TINY_GPT)
    with pytest.raises(ValueError, match="unknown task"):
        make_train_step(m.apply, optax.sgd(0.1), task="regression")


def test_fit_autodetects_lm_and_learns(corpus):
    m = get_model("gpt_lm", **TINY_GPT)
    r = fit(
        m, corpus, steps=60, batch_size=32, learning_rate=1e-3,
        optimizer="adamw",
    )
    assert np.isfinite(r.final_loss)
    # Next-token accuracy on English bytes: random is ~1/60 over the
    # used byte alphabet; even 60 steps beats 10%.
    assert r.test_accuracy > 0.10, r.test_accuracy


def test_evaluate_lm_perfect_on_copycat():
    """Sanity-check the metric itself with a constant-sequence set a
    trained copy model would ace — using logits rigged to echo the
    previous token."""
    x = np.full((4, 10), 9, np.int32)

    def apply_fn(params, ids):
        return jax.nn.one_hot(ids, 260) * 100.0  # predict current id

    acc = evaluate_lm(apply_fn, {}, x)
    assert acc == 1.0  # every target equals the previous token


def test_docs_preset_cli_end_to_end(tmp_path):
    """The full pipeline: preset -> fit -> checkpoint (with tokenizer
    fingerprint) -> generation engine serves it."""
    from mlapi_tpu.config import TrainConfig
    from mlapi_tpu.serving.engine import InferenceEngine
    from mlapi_tpu.train.__main__ import run

    cfg = TrainConfig(
        name="docs-gpt-test",
        model="gpt_lm",
        model_kwargs=dict(TINY_GPT),
        dataset="docs_text",
        dataset_kwargs={"seq_len": 64},
        steps=5,
        batch_size=16,
        optimizer="adamw",
        learning_rate=1e-3,
    )
    out = tmp_path / "ck"
    run(cfg, out=str(out))
    eng = InferenceEngine.from_checkpoint(out)
    assert hasattr(eng.model, "generate")
    gen = np.asarray(
        eng.model.generate(
            eng.params,
            jnp.asarray([[10, 11, 12]], jnp.int32),
            max_new_tokens=4,
        )
    )
    assert gen.shape == (1, 4)
