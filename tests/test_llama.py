"""Llama-family decoder: causal correctness, rotary/pad invariance,
GQA cache shapes, KV-cache decode parity with the full forward, and
the shared-engine integration (same hazards as test_gpt, plus the
rotated-key cache)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.models import get_model

TINY = dict(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,  # GQA on by default: the family's point
    max_positions=64,
    compute_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    return get_model("llama_lm", **TINY)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def test_forward_shapes(model, params):
    ids = np.ones((3, 10), np.int32)
    logits = jax.jit(model.apply)(params, ids)
    assert logits.shape == (3, 10, TINY["vocab_size"])


def test_causality(model, params):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (2, 16)).astype(np.int32)
    base = np.asarray(jax.jit(model.apply)(params, ids))
    ids2 = ids.copy()
    ids2[:, 10:] = (ids2[:, 10:] + 7) % 64
    out = np.asarray(jax.jit(model.apply)(params, ids2))
    np.testing.assert_allclose(out[:, :10], base[:, :10], atol=1e-5)
    assert not np.allclose(out[:, 10:], base[:, 10:], atol=1e-5)


def test_gqa_cache_is_group_factor_smaller(model):
    cache = model.init_cache(2, 32)
    k = cache["layer_0"]["k"]
    assert k.shape == (2, 32, 2, 8)  # kv_heads=2, not num_heads=4


def test_kv_cache_decode_matches_full_forward(model, params):
    """Token-by-token decode through the ROTATED-key cache must agree
    with re-running the full forward each step — the hazard rotary
    adds over GPT's position-table cache."""
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 64, (2, 8)).astype(np.int32)
    n_new = 6

    generated = np.asarray(
        model.generate(params, jnp.asarray(prompt), max_new_tokens=n_new)
    )
    seq = prompt.copy()
    ref = []
    for _ in range(n_new):
        logits = np.asarray(jax.jit(model.apply)(params, seq))
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        ref.append(nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(generated, np.stack(ref, axis=1))


def test_left_pad_bucketing_is_invariant(model, params):
    """A prompt left-padded into a larger bucket (with pad_lens set)
    must generate the same tokens — rotary positions shift by n_pad
    and pad keys are masked."""
    prompt = np.random.default_rng(2).integers(1, 64, (1, 6)).astype(np.int32)
    plain = np.asarray(
        model.generate(params, jnp.asarray(prompt), max_new_tokens=5)
    )
    padded = np.zeros((1, 16), np.int32)
    padded[0, 10:] = prompt[0]
    bucketed = np.asarray(
        model.generate(
            params, jnp.asarray(padded), max_new_tokens=5,
            pad_lens=np.array([10]),
        )
    )
    np.testing.assert_array_equal(plain, bucketed)


def test_mha_variant_and_ffn_rounding():
    m = get_model(
        "llama_lm", vocab_size=32, hidden_size=48, num_layers=1,
        num_heads=4, max_positions=32, compute_dtype="float32",
    )
    assert m.kv_heads == 4  # None -> MHA
    assert m.ffn_size == 128  # 8/3*48=128 exactly
    p = m.init(jax.random.key(1))
    out = m.apply(p, np.ones((1, 4), np.int32))
    assert np.isfinite(np.asarray(out)).all()


def test_rejects_indivisible_kv_heads():
    with pytest.raises(ValueError, match="multiple of"):
        get_model(
            "llama_lm", vocab_size=32, hidden_size=32, num_layers=1,
            num_heads=4, num_kv_heads=3, max_positions=32,
        )


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_serving_engine_round_trip(model, params, tmp_path):
    """Checkpoint -> TextGenerationEngine -> batched decode: the
    shared GPT machinery must drive this family unchanged."""
    from mlapi_tpu.checkpoint import save_checkpoint
    from mlapi_tpu.serving.engine import InferenceEngine
    from mlapi_tpu.text import ByteTokenizer

    tok = ByteTokenizer()
    cfg = dict(TINY, vocab_size=260)
    m = get_model("llama_lm", **cfg)
    save_checkpoint(
        tmp_path / "ck", m.init(jax.random.key(0)), step=1,
        config={"model": "llama_lm", "model_kwargs": cfg,
                "tokenizer": tok.fingerprint()},
    )
    eng = InferenceEngine.from_checkpoint(tmp_path / "ck")
    assert type(eng.model).__name__ == "LlamaLM"
    # warmup drives the engine's REAL batched path (prefill_fn +
    # chunked decode + one compaction) with this model — the shared
    # machinery, not just model.generate.
    eng.warmup(full=False)
    ids = np.asarray([list(b"hi")], np.int32)
    out = np.asarray(
        eng.model.generate(eng.params, jnp.asarray(ids), max_new_tokens=4)
    )
    assert out.shape == (1, 4)


def test_tp_sharded_forward(model, params):
    """params_for_model places the declared Megatron layout on a
    (2, 4) mesh and the sharded forward matches the replicated one."""
    from mlapi_tpu.parallel import create_mesh, params_for_model

    mesh = create_mesh((2, 4))
    sharded = params_for_model(model, params, mesh)
    ids = np.random.default_rng(5).integers(0, 64, (2, 16)).astype(np.int32)
    ref = np.asarray(jax.jit(model.apply)(params, ids))
    out = np.asarray(jax.jit(model.apply)(sharded, ids))
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_learns_copy_task(model):
    """Trainability: a 1-layer llama learns to copy the previous
    token (same planted task style as the GPT suite)."""
    import optax

    m = get_model(
        "llama_lm", vocab_size=16, hidden_size=32, num_layers=1,
        num_heads=4, num_kv_heads=2, max_positions=32,
        compute_dtype="float32",
    )
    params = m.init(jax.random.key(0))
    rng = np.random.default_rng(3)
    x = rng.integers(1, 16, (64, 12)).astype(np.int32)

    def loss_fn(p, ids):
        logits = m.apply(p, ids)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], ids[:, 1:]
        ).mean()

    tx = optax.adam(3e-3)
    state = tx.init(params)

    @jax.jit
    def step(p, s, ids):
        l, g = jax.value_and_grad(loss_fn)(p, ids)
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    # Target: predict token t from token t-1 on COPY sequences
    # (each row repeats one symbol), which a single attention layer
    # solves quickly.
    xc = np.repeat(rng.integers(1, 16, (64, 1)), 12, axis=1).astype(np.int32)
    l0 = None
    for i in range(150):
        params, state, l = step(params, state, xc)
        if i == 0:
            l0 = float(l)
    assert float(l) < 0.1 * l0, (l0, float(l))


def test_ring_attention_backend_matches_full(model, params):
    """attention_impl='ring' (sequence-parallel) is a drop-in backend
    for this family too — logits must match the full-attention model
    with the same params."""
    from mlapi_tpu.parallel import create_mesh

    mesh = create_mesh((2, 4), axis_names=("data", "seq"))
    ring = get_model("llama_lm", **TINY, attention_impl="ring", mesh=mesh)
    ids = np.random.default_rng(9).integers(0, 64, (2, 32)).astype(np.int32)
    ref = np.asarray(jax.jit(model.apply)(params, ids))
    out = np.asarray(jax.jit(ring.apply)(params, ids))
    np.testing.assert_allclose(out, ref, atol=2e-4)


def test_rope_is_identity_at_position_zero_tp():
    """Root-cause pin for the (2, 4)-mesh numeric failure this family
    carried since the seed: it was never accumulation order — XLA's
    SPMD partitioner on this jax version MISCOMPILES slice+concat
    over a dim the ``model`` axis shards finer than one KV head
    (``wk`` is [h, kvh*hd] = [32, 16]; tp=4 > kvh=2 splits heads), so
    the old rotate-half returned values wrong by O(1) even at
    position 0, where rope must be the identity. ``_rope`` now uses a
    constant-index gather, which partitions correctly; this test
    reruns the exact trigger and pins the identity."""
    from mlapi_tpu.models.llama import _rope
    from mlapi_tpu.parallel import create_mesh, params_for_model

    m = get_model("llama_lm", **TINY)
    params = m.init(jax.random.key(0))
    mesh = create_mesh((2, 4))
    sharded = params_for_model(m, params, mesh)
    ids = np.random.default_rng(5).integers(0, 64, (2, 16)).astype(np.int32)

    def k_roped_pos0(p, ids):
        from mlapi_tpu.models.llama import _rms_norm

        x = p["wte"][ids]
        layer = p["layer_0"]
        xn = _rms_norm(x, layer["rms1_scale"]).astype(jnp.float32)
        b, l = ids.shape
        k = (xn @ layer["wk"].astype(jnp.float32)).reshape(
            b, l, m.kv_heads, m.head_dim
        )
        zeros = jnp.zeros((b, l), jnp.int32)
        return k, _rope(k, zeros, m.rope_theta)

    k, roped = jax.jit(k_roped_pos0)(sharded, ids)
    np.testing.assert_allclose(
        np.asarray(roped), np.asarray(k), atol=1e-6,
        err_msg="rope at position 0 must be the identity, sharded too",
    )


def test_flash_attention_backend_matches_full(model, params):
    """attention_impl='flash' feeds raw GQA kv heads to the kernel
    (no repeated K/V tensor) — logits must match the full backend."""
    flash = get_model("llama_lm", **TINY, attention_impl="flash")
    ids = np.random.default_rng(11).integers(0, 64, (2, 32)).astype(np.int32)
    ref = np.asarray(jax.jit(model.apply)(params, ids))
    out = np.asarray(jax.jit(flash.apply)(params, ids))
    np.testing.assert_allclose(out, ref, atol=1e-5)
