"""Fused speculative generation (`ops/speculative.fused_spec_fn`):
the ENTIRE propose/verify/accept loop as one XLA program
(`lax.while_loop`), no host round-trip per round.

Pin: byte-identical to the host-loop `speculative_generate` (itself
pinned byte-identical to plain target greedy) for random and equal
draft/target pairs, both decoder families, across k — plus the
window-headroom validation."""

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.speculative import (
    speculative_generate,
    speculative_generate_fused,
)

T_CFG = dict(
    vocab_size=260, hidden_size=48, num_layers=3, num_heads=4,
    max_positions=160, compute_dtype="float32",
)
D_CFG = dict(
    vocab_size=260, hidden_size=24, num_layers=1, num_heads=2,
    max_positions=160, compute_dtype="float32",
)


@pytest.mark.parametrize("k", [1, 3, 5])
def test_fused_matches_host_loop_random_models(k):
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    prompt = (np.arange(9, dtype=np.int32)[None] % 200) + 3
    ref, ref_stats = speculative_generate(
        target, tp, draft, dp, prompt, max_new_tokens=24, k=k,
    )
    got, stats = speculative_generate_fused(
        target, tp, draft, dp, prompt, max_new_tokens=24, k=k,
    )
    assert got == ref, (k, stats)
    # Same acceptance algebra; the host loop's budget-1 PLAIN steps
    # (fallback_steps) are usable-0 rounds in the fused loop — each
    # emits exactly the bonus token, so rounds line up as the sum.
    assert stats.rounds == ref_stats.rounds + ref_stats.fallback_steps
    assert stats.accepted == ref_stats.accepted


def test_fused_draft_equals_target_full_acceptance():
    target = get_model("gpt_lm", **T_CFG)
    tp = target.init(jax.random.key(0))
    prompt = (np.arange(7, dtype=np.int32)[None] % 150) + 5
    ref, _ = speculative_generate(
        target, tp, target, tp, prompt, max_new_tokens=21, k=4,
    )
    got, stats = speculative_generate_fused(
        target, tp, target, tp, prompt, max_new_tokens=21, k=4,
    )
    assert got == ref
    assert stats.acceptance_rate == 1.0, stats


def test_fused_llama_family():
    cfg = dict(T_CFG, hidden_size=32, num_layers=2)
    cfg.pop("num_heads")
    target = get_model("llama_lm", **cfg, num_heads=4, num_kv_heads=2)
    tp = target.init(jax.random.key(0))
    prompt = (np.arange(6, dtype=np.int32)[None] % 120) + 3
    ref, _ = speculative_generate(
        target, tp, target, tp, prompt, max_new_tokens=12, k=2,
    )
    got, stats = speculative_generate_fused(
        target, tp, target, tp, prompt, max_new_tokens=12, k=2,
    )
    assert got == ref
    assert stats.acceptance_rate == 1.0


def test_fused_budget_not_round_multiple():
    """n not a multiple of k+1: the budget-capped final round
    (usable < k) must land exactly n tokens."""
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(2))
    dp = draft.init(jax.random.key(3))
    prompt = (np.arange(8, dtype=np.int32)[None] % 150) + 5
    ref, _ = speculative_generate(
        target, tp, draft, dp, prompt, max_new_tokens=13, k=4,
    )
    got, _ = speculative_generate_fused(
        target, tp, draft, dp, prompt, max_new_tokens=13, k=4,
    )
    assert got == ref
    assert len(got) == 13


def test_fused_window_headroom_validated():
    cfg = dict(T_CFG, max_positions=32)
    target = get_model("gpt_lm", **cfg)
    tp = target.init(jax.random.key(0))
    prompt = (np.arange(8, dtype=np.int32)[None] % 100) + 3
    with pytest.raises(ValueError, match="cache slots"):
        speculative_generate_fused(
            target, tp, target, tp, prompt, max_new_tokens=24, k=4,
        )
