"""Fused speculative generation (`ops/speculative.fused_spec_fn`):
the ENTIRE propose/verify/accept loop as one XLA program
(`lax.while_loop`), no host round-trip per round.

Pin: byte-identical to the host-loop `speculative_generate` (itself
pinned byte-identical to plain target greedy) for random and equal
draft/target pairs, both decoder families, across k — plus the
window-headroom validation."""

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.speculative import (
    speculative_generate,
    speculative_generate_fused,
)

T_CFG = dict(
    vocab_size=260, hidden_size=48, num_layers=3, num_heads=4,
    max_positions=160, compute_dtype="float32",
)
D_CFG = dict(
    vocab_size=260, hidden_size=24, num_layers=1, num_heads=2,
    max_positions=160, compute_dtype="float32",
)


@pytest.mark.parametrize("k", [1, 3, 5])
def test_fused_matches_host_loop_random_models(k):
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    prompt = (np.arange(9, dtype=np.int32)[None] % 200) + 3
    ref, ref_stats = speculative_generate(
        target, tp, draft, dp, prompt, max_new_tokens=24, k=k,
    )
    got, stats = speculative_generate_fused(
        target, tp, draft, dp, prompt, max_new_tokens=24, k=k,
    )
    assert got == ref, (k, stats)
    # Same acceptance algebra; the host loop's budget-1 PLAIN steps
    # (fallback_steps) are usable-0 rounds in the fused loop — each
    # emits exactly the bonus token, so rounds line up as the sum.
    assert stats.rounds == ref_stats.rounds + ref_stats.fallback_steps
    assert stats.accepted == ref_stats.accepted


def test_fused_draft_equals_target_full_acceptance():
    target = get_model("gpt_lm", **T_CFG)
    tp = target.init(jax.random.key(0))
    prompt = (np.arange(7, dtype=np.int32)[None] % 150) + 5
    ref, _ = speculative_generate(
        target, tp, target, tp, prompt, max_new_tokens=21, k=4,
    )
    got, stats = speculative_generate_fused(
        target, tp, target, tp, prompt, max_new_tokens=21, k=4,
    )
    assert got == ref
    assert stats.acceptance_rate == 1.0, stats


def test_fused_llama_family():
    cfg = dict(T_CFG, hidden_size=32, num_layers=2)
    cfg.pop("num_heads")
    target = get_model("llama_lm", **cfg, num_heads=4, num_kv_heads=2)
    tp = target.init(jax.random.key(0))
    prompt = (np.arange(6, dtype=np.int32)[None] % 120) + 3
    ref, _ = speculative_generate(
        target, tp, target, tp, prompt, max_new_tokens=12, k=2,
    )
    got, stats = speculative_generate_fused(
        target, tp, target, tp, prompt, max_new_tokens=12, k=2,
    )
    assert got == ref
    assert stats.acceptance_rate == 1.0


def test_fused_budget_not_round_multiple():
    """n not a multiple of k+1: the budget-capped final round
    (usable < k) must land exactly n tokens."""
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(2))
    dp = draft.init(jax.random.key(3))
    prompt = (np.arange(8, dtype=np.int32)[None] % 150) + 5
    ref, _ = speculative_generate(
        target, tp, draft, dp, prompt, max_new_tokens=13, k=4,
    )
    got, _ = speculative_generate_fused(
        target, tp, draft, dp, prompt, max_new_tokens=13, k=4,
    )
    assert got == ref
    assert len(got) == 13


def test_fused_sampled_draft_equals_target_accepts_all():
    """Sampled fused: p == q bitwise with draft == target → every
    usable proposal accepted, deterministic per seed."""
    from mlapi_tpu.ops.speculative import speculative_sample_fused

    target = get_model("gpt_lm", **T_CFG)
    tp = target.init(jax.random.key(0))
    prompt = (np.arange(6, dtype=np.int32)[None] % 150) + 5
    got, stats = speculative_sample_fused(
        target, tp, target, tp, prompt,
        max_new_tokens=16, k=3, temperature=0.8,
        top_k=12, top_p=0.9, seed=7,
    )
    assert len(got) == 16
    assert stats.acceptance_rate == 1.0, stats
    again, _ = speculative_sample_fused(
        target, tp, target, tp, prompt,
        max_new_tokens=16, k=3, temperature=0.8,
        top_k=12, top_p=0.9, seed=7,
    )
    assert again == got
    other, _ = speculative_sample_fused(
        target, tp, target, tp, prompt,
        max_new_tokens=16, k=3, temperature=0.8,
        top_k=12, top_p=0.9, seed=8,
    )
    assert other != got


def test_fused_sampled_greedy_delegates():
    from mlapi_tpu.ops.speculative import speculative_sample_fused

    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    prompt = (np.arange(7, dtype=np.int32)[None] % 150) + 5
    ref, _ = speculative_generate_fused(
        target, tp, draft, dp, prompt, max_new_tokens=12, k=3,
    )
    got, _ = speculative_sample_fused(
        target, tp, draft, dp, prompt,
        max_new_tokens=12, k=3, temperature=0.0, seed=4,
    )
    assert got == ref


def test_fused_sampled_marginal_matches_exact():
    """Distributional pin for the fused sampled scheme: the SECOND
    token's empirical marginal over fixed seeds matches the exact
    warped target marginal (the same bound the host-loop scheme
    passes); a draft-biased or wrong-residual scheme lands far
    outside. Deterministic (fixed seed list)."""
    import jax.numpy as jnp

    from mlapi_tpu.ops.speculative import (
        _warped_probs,
        speculative_sample_fused,
    )

    cfg_t = dict(
        vocab_size=32, hidden_size=32, num_layers=2, num_heads=4,
        max_positions=64, compute_dtype="float32",
    )
    cfg_d = dict(
        vocab_size=32, hidden_size=16, num_layers=1, num_heads=2,
        max_positions=64, compute_dtype="float32",
    )
    target = get_model("gpt_lm", **cfg_t)
    draft = get_model("gpt_lm", **cfg_d)
    tp = target.init(jax.random.key(4))
    dp = draft.init(jax.random.key(9))
    prompt = (np.arange(3, dtype=np.int32)[None] % 20) + 5
    temperature = 1.2
    v = 32
    n_runs = 600
    counts = np.zeros(v)
    for seed in range(n_runs):
        toks, _ = speculative_sample_fused(
            target, tp, draft, dp, prompt,
            max_new_tokens=2, k=1, temperature=temperature, seed=seed,
        )
        counts[toks[1]] += 1
    emp = counts / n_runs

    temps = jnp.asarray([temperature], jnp.float32)
    z0 = jnp.zeros((1,), jnp.int32)
    o1 = jnp.ones((1,), jnp.float32)
    logits0 = target.apply(tp, jnp.asarray(prompt))[0, -1][None]
    p0 = np.asarray(_warped_probs(logits0, temps, z0, o1))[0]
    exact = np.zeros(v)
    for t0 in range(v):
        if p0[t0] < 1e-9:
            continue
        seq = np.concatenate([prompt[0], [t0]])[None].astype(np.int32)
        lg1 = target.apply(tp, jnp.asarray(seq))[0, -1][None]
        p1 = np.asarray(_warped_probs(lg1, temps, z0, o1))[0]
        exact += p0[t0] * p1
    tv = 0.5 * np.abs(emp - exact).sum()
    assert tv < 0.2, f"TV {tv:.3f} vs exact marginal"


def test_fused_window_headroom_validated():
    cfg = dict(T_CFG, max_positions=32)
    target = get_model("gpt_lm", **cfg)
    tp = target.init(jax.random.key(0))
    prompt = (np.arange(8, dtype=np.int32)[None] % 100) + 3
    with pytest.raises(ValueError, match="cache slots"):
        speculative_generate_fused(
            target, tp, target, tp, prompt, max_new_tokens=24, k=4,
        )
