"""The scale-out router's core, as units: HRW affinity stability
under replica add/remove, the power-of-two fallback ladder, the
health/backpressure state machine, /metrics aggregation semantics,
the failover-once rule, byte-identical stream passthrough, and the
``router_forward`` fault seam (submit + mid-stream).

Replicas here are FAKE — tiny apps on the framework's own server over
real sockets — so every routing/forwarding path runs against real
HTTP without an engine in sight (the 2-replica spawned-engine e2e
lives in ``test_router_e2e.py``). The router imports no jax; neither
do these tests' hot paths.
"""

import asyncio
import json

import httpx
import pytest

from mlapi_tpu.serving import faults
from mlapi_tpu.serving.asgi import (
    App,
    Request,
    StreamingResponse,
    json_response,
)
from mlapi_tpu.serving.router import (
    DOWN,
    DRAINING,
    LIVE,
    NoReplicaAvailable,
    ReplicaState,
    Router,
    _SubmitError,
    build_router_app,
    hrw_order,
)
from mlapi_tpu.serving.server import Server

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


# ---------------------------------------------------------------------------
# HRW (rendezvous) hashing: the affinity map's stability contract.
# ---------------------------------------------------------------------------

KEYS = [f"prefix-{i}".encode() for i in range(240)]


def test_hrw_remove_remaps_only_the_removed_slice():
    names = ["h:1", "h:2", "h:3"]
    before = {k: hrw_order(k, names)[0] for k in KEYS}
    after = {k: hrw_order(k, ["h:1", "h:2"])[0] for k in KEYS}
    moved = [k for k in KEYS if before[k] != after[k]]
    # EVERY key that moved was on the removed replica; no key between
    # the survivors was touched — the property that lets one replica
    # drain without invalidating its peers' warm caches.
    assert moved, "no keys mapped to the removed replica at all?"
    assert all(before[k] == "h:3" for k in moved)
    assert all(after[k] == before[k] for k in KEYS if before[k] != "h:3")


def test_hrw_add_steals_only_for_the_new_replica():
    names = ["h:1", "h:2", "h:3"]
    before = {k: hrw_order(k, names)[0] for k in KEYS}
    after = {k: hrw_order(k, names + ["h:4"])[0] for k in KEYS}
    moved = [k for k in KEYS if before[k] != after[k]]
    assert moved, "a fourth replica should win some keys"
    assert all(after[k] == "h:4" for k in moved)


def test_hrw_spreads_keys_across_replicas():
    names = ["h:1", "h:2", "h:3"]
    counts = {n: 0 for n in names}
    for k in KEYS:
        counts[hrw_order(k, names)[0]] += 1
    # Loose balance bound: a uniform 64-bit hash puts each replica
    # within a comfortable margin of 1/3 over 240 keys.
    assert all(c >= len(KEYS) * 0.15 for c in counts.values()), counts


def test_hrw_is_deterministic_across_list_order():
    assert hrw_order(b"k", ["a:1", "b:2", "c:3"]) == hrw_order(
        b"k", ["c:3", "a:1", "b:2"]
    )


# ---------------------------------------------------------------------------
# choose(): affinity, the fallback ladder, round_robin, shedding.
# ---------------------------------------------------------------------------


def _router(n=3, **kw) -> Router:
    return Router([("127.0.0.1", 9000 + i) for i in range(n)], **kw)


def _preferred(router: Router, key: bytes) -> ReplicaState:
    order = hrw_order(key, [r.name for r in router.replicas])
    return next(r for r in router.replicas if r.name == order[0])


def test_affinity_routes_to_hrw_preferred():
    router = _router()
    key = b"system prompt abc"
    for _ in range(5):
        assert router.choose(key) is _preferred(router, key)
    assert router.affinity_hits == 5
    assert router.affinity_fallbacks == 0


def test_fallback_is_less_loaded_of_two_when_preferred_down():
    router = _router(3)
    key = b"some prefix"
    pref = _preferred(router, key)
    pref.state = DOWN
    others = [r for r in router.replicas if r is not pref]
    others[0].queue_depth = 100
    others[1].queue_depth = 0
    # p2c over exactly 2 routable replicas always samples both; the
    # less-loaded one must win every time.
    for _ in range(8):
        assert router.choose(key) is others[1]
    assert router.affinity_fallbacks == 8


def test_draining_preferred_falls_back_without_remapping_others():
    router = _router(3)
    keys = [f"k{i}".encode() for i in range(60)]
    before = {k: _preferred(router, k) for k in keys}
    victim = router.replicas[0]
    victim.state = DRAINING
    for k in keys:
        chosen = router.choose(k)
        if before[k] is not victim:
            # Unaffected slice: the drain of replica 0 must not move
            # these (their caches stay warm).
            assert chosen is before[k]
        else:
            assert chosen is not victim
    assert router.affinity_fallbacks == sum(
        1 for k in keys if before[k] is victim
    )


def test_queue_depth_limit_gates_routing():
    router = _router(2, queue_depth_limit=4)
    key = b"pfx"
    pref = _preferred(router, key)
    pref.queue_depth = 5
    assert router.choose(key) is not pref
    pref.queue_depth = 3
    assert router.choose(key) is pref


def test_round_robin_policy_cycles():
    router = _router(3, policy="round_robin")
    seen = [router.choose(b"same-key").name for _ in range(6)]
    assert seen[:3] == seen[3:6]
    assert len(set(seen[:3])) == 3
    assert router.affinity_hits == 0  # the A/B baseline never affines


def test_no_routable_replica_raises_with_retry_hint():
    router = _router(2)
    for r in router.replicas:
        r.state = DOWN
    with pytest.raises(NoReplicaAvailable):
        router.choose(b"k")


def test_shed_window_expires():
    import time as _time

    router = _router(2)
    key = b"pfx"
    pref = _preferred(router, key)
    pref.shed_until = _time.monotonic() + 30.0
    assert router.choose(key) is not pref
    pref.shed_until = 0.0
    assert router.choose(key) is pref


def _plain_request(headers=None) -> Request:
    return Request(
        {
            "method": "POST",
            "path": "/generate",
            "headers": list(headers or []),
        },
        b"{}",
    )


async def test_warm_peer_hint_is_hrw_head_on_every_non_preferred_forward():
    """The warmth contract (r17): a forward that misses the key's
    HRW-preferred replica carries that head as the warm-peer hint —
    and a forward that LANDS on the head carries none (nobody is
    warmer than the target itself)."""
    router = _router(3)
    key = b"some prefix"
    pref = _preferred(router, key)
    seen = []

    async def fake_attempt(r, request, warm_peer=None):
        seen.append((r, warm_peer))
        return json_response({}, 200)

    router._attempt = fake_attempt
    await router.forward(_plain_request(), key)
    assert seen[-1] == (pref, None)
    assert router.warm_peer_hints == 0
    # Preferred draining: the fallback forward names the head.
    pref.state = DRAINING
    await router.forward(_plain_request(), key)
    target, hint = seen[-1]
    assert target is not pref and hint is pref
    assert router.warm_peer_hints == 1
    # No key (and round_robin policy): no warmth map, no hint.
    await router.forward(_plain_request(), None)
    assert seen[-1][1] is None
    rr = _router(3, policy="round_robin")
    rr._attempt = fake_attempt
    await rr.forward(_plain_request(), key)
    assert seen[-1][1] is None and rr.warm_peer_hints == 0


async def test_warm_peer_hint_survives_failover_hop():
    """The affinity-map blind spot (satellite fix): the failover's
    second choose() excludes the failed replica and re-ranks the rest
    — it has no memory of the ORIGINAL preferred. The hint must ride
    the original HRW head through the retry hop anyway."""
    router = _router(3)
    key = b"some prefix"
    pref = _preferred(router, key)
    calls = []

    async def fake_attempt(r, request, warm_peer=None):
        calls.append((r, warm_peer))
        if len(calls) == 1:
            raise _SubmitError("injected pre-submit", retryable=True)
        return json_response({}, 200)

    router._attempt = fake_attempt
    await router.forward(_plain_request(), key)
    (first, hint1), (second, hint2) = calls
    assert first is pref and hint1 is None
    assert second is not pref
    assert hint2 is pref          # the head survived the retry hop
    assert router.failovers == 1
    assert router.warm_peer_hints == 1


def test_build_upstream_stamps_and_strips_warm_peer():
    """Anti-spoof parity with x-mlapi-router-depth: a client-sent
    warm-peer header is dropped (it could aim a replica's KV fetches
    at an arbitrary host), and the router-authored one appears
    exactly once, naming the hinted replica."""
    router = _router(2)
    target, peer = router.replicas
    req = _plain_request(
        headers=[
            (b"x-mlapi-warm-peer", b"evil.example:9"),
            (b"content-type", b"application/json"),
        ]
    )
    head = router._build_upstream(req, target, peer).split(
        b"\r\n\r\n"
    )[0].lower()
    assert head.count(b"x-mlapi-warm-peer") == 1
    assert b"x-mlapi-warm-peer: " + peer.name.encode() in head
    assert b"evil.example" not in head
    # No hint: the header is absent entirely.
    head2 = router._build_upstream(req, target, None).split(
        b"\r\n\r\n"
    )[0].lower()
    assert b"x-mlapi-warm-peer" not in head2


def test_role_pools_and_disagg_gate():
    """Role-split units (r18): wants_disagg fires only in a
    role-split fleet for plain prompt bodies (prefix-carrying and
    unparseable bodies stay on the affinity path); _pick_role picks
    inside one pool by HRW (key) or load (None) and returns None for
    a starved pool; an all-mixed router has no role surface at all —
    bit-identical to r17."""
    mixed = _router(3)
    assert not mixed.role_split
    assert not mixed.wants_disagg(json.dumps({"text": "hi"}).encode())

    router = Router(
        [("127.0.0.1", 9000 + i) for i in range(4)],
        roles=["prefill", "prefill", "decode", "decode"],
    )
    assert router.role_split
    assert router.wants_disagg(json.dumps({"text": "hi"}).encode())
    assert not router.wants_disagg(
        json.dumps({"text": "hi", "prefix": "sys"}).encode()
    )
    assert not router.wants_disagg(b"not json")
    assert not router.wants_disagg(json.dumps({"text": ""}).encode())

    key = b"some prompt"
    dec = router._pick_role(key, "decode")
    assert dec is not None and dec.role == "decode"
    # HRW stability: same key, same decode pick, every time.
    assert router._pick_role(key, "decode") is dec
    pre = router._pick_role(None, "prefill")
    assert pre is not None and pre.role == "prefill"
    # A starved pool returns None (the forward degrades to mixed
    # routing, counted) — never a member of the other pool.
    for r in router.replicas:
        if r.role == "prefill":
            r.state = DOWN
    assert router._pick_role(None, "prefill") is None
    assert router._pick_role(key, "decode") is not None

    # Role validation is loud.
    with pytest.raises(ValueError):
        Router([("h", 1)], roles=["imaginary"])
    with pytest.raises(ValueError):
        Router([("h", 1), ("h", 2)], roles=["mixed"])


def test_build_upstream_stamps_and_strips_disagg_headers():
    """The r18 headers ride the same anti-spoof contract as
    warm-peer: client-sent copies are stripped (they could aim a
    replica's pushes at an arbitrary host or claim a foreign
    transfer), router-authored extras appear exactly once."""
    router = _router(2)
    target = router.replicas[0]
    req = _plain_request(
        headers=[
            (b"x-mlapi-decode-peer", b"evil.example:9"),
            (b"x-mlapi-kv-xfer", b"stolen"),
        ]
    )
    head = router._build_upstream(
        req, target, None,
        {"x-mlapi-decode-peer": "10.0.0.2:8001", "x-mlapi-kv-xfer": "xf1"},
    ).split(b"\r\n\r\n")[0].lower()
    assert head.count(b"x-mlapi-decode-peer") == 1
    assert b"x-mlapi-decode-peer: 10.0.0.2:8001" in head
    assert head.count(b"x-mlapi-kv-xfer") == 1
    assert b"x-mlapi-kv-xfer: xf1" in head
    assert b"evil.example" not in head and b"stolen" not in head
    # No extras: both headers absent entirely.
    head2 = router._build_upstream(req, target, None).split(
        b"\r\n\r\n"
    )[0].lower()
    assert b"x-mlapi-decode-peer" not in head2
    assert b"x-mlapi-kv-xfer" not in head2


def test_routing_key_prefers_prefix_field_and_truncates():
    router = _router(2, affinity_prefix_bytes=8)
    body = json.dumps(
        {"text": "completely different", "prefix": "shared-system-prompt"}
    ).encode()
    assert router.routing_key(body) == b"shared-s"
    assert router.routing_key(json.dumps({"text": "hello"}).encode()) == (
        b"hello"
    )
    assert router.routing_key(b"not json") is None
    assert router.routing_key(json.dumps({"stream": True}).encode()) is None


# ---------------------------------------------------------------------------
# Fake replicas over real sockets: polling, forwarding, faults.
# ---------------------------------------------------------------------------


def make_replica(name: str, state: dict):
    """A fake replica speaking the real control+data surface: unary
    and streaming /generate (echoing which replica served), /healthz
    with the draining flag, /metrics with counters/gauges."""
    app = App(title=name)
    state.setdefault("requests", 0)
    state.setdefault("qd", 0)
    state.setdefault("counters", {})

    @app.post("/generate")
    async def generate(request):
        state["requests"] += 1
        body = json.loads(request.body)
        if state.get("shed"):
            return json_response(
                {"detail": "overloaded"}, 503,
                headers={"retry-after": str(state.get("retry_after", 2))},
            )
        if body.get("stream"):
            async def frames():
                for fr in state.get(
                    "frames",
                    [
                        {"token_ids": [1, 2], "replica": name},
                        {"done": True, "text": "hi", "replica": name},
                    ],
                ):
                    yield json.dumps(fr).encode() + b"\n"
                    if state.get("die_after_first_frame"):
                        raise ConnectionResetError("replica died")

            return StreamingResponse(
                frames(), content_type="application/x-ndjson"
            )
        return {"replica": name, "text": "hi"}

    @app.get("/healthz")
    async def healthz():
        return {
            "status": "draining" if state.get("draining") else "ok",
            "queue_depth": state["qd"],
        }

    @app.get("/metrics")
    async def metrics():
        return {
            "counters": dict(state["counters"]),
            "gauges": {"generate.queue_depth": state["qd"]},
        }

    return app


class _Fleet:
    def __init__(self):
        self.states: list[dict] = []
        self.servers: list[Server] = []

    async def add(self, name: str) -> dict:
        state: dict = {}
        srv = Server(make_replica(name, state), host="127.0.0.1", port=0)
        await srv.start()
        self.states.append(state)
        self.servers.append(srv)
        return state

    @property
    def endpoints(self):
        return [("127.0.0.1", s.port) for s in self.servers]

    async def stop(self):
        for s in self.servers:
            await s.stop()


@pytest.fixture
async def fleet():
    f = _Fleet()
    await f.add("A")
    await f.add("B")
    yield f
    await f.stop()


async def _client(router: Router):
    transport = httpx.ASGITransport(app=build_router_app(router))
    return httpx.AsyncClient(transport=transport, base_url="http://router")


async def test_health_poll_state_transitions(fleet):
    router = Router(fleet.endpoints, health_poll_s=0.05, assume_live=False)
    assert all(r.state == DOWN for r in router.replicas)
    await router.start()
    try:
        assert all(r.state == LIVE for r in router.replicas)
        fleet.states[0]["draining"] = True
        fleet.states[1]["qd"] = 7
        for _ in range(100):
            await asyncio.sleep(0.05)
            if (
                router.replicas[0].state == DRAINING
                and router.replicas[1].queue_depth == 7
            ):
                break
        assert router.replicas[0].state == DRAINING
        assert router.replicas[1].queue_depth == 7
        # Kill replica 0's listener: two failed polls mark it down.
        await fleet.servers[0].stop()
        for _ in range(200):
            await asyncio.sleep(0.05)
            if router.replicas[0].state == DOWN:
                break
        assert router.replicas[0].state == DOWN
        assert router.replicas[1].state == LIVE
    finally:
        await router.stop()


async def test_metrics_aggregation_sums_counters_labels_gauges(fleet):
    fleet.states[0]["counters"] = {"generate.requests": 3, "only.a": 1}
    fleet.states[1]["counters"] = {"generate.requests": 5}
    fleet.states[1]["qd"] = 9
    router = Router(fleet.endpoints)
    router.affinity_hits = 11
    snap = await router.metrics_snapshot()
    assert snap["counters"]["generate.requests"] == 8  # summed
    assert snap["counters"]["only.a"] == 1
    assert snap["counters"]["router.affinity_hits"] == 11
    b = router.replicas[1].name
    assert snap["gauges"][f"replica.{b}.generate.queue_depth"] == 9
    assert snap["gauges"][f"router.replica.{b}.queue_depth"] == 9
    assert snap["gauges"]["router.replicas_live"] == 2
    assert snap["replicas_stale"] == []


async def test_affinity_repeats_land_on_one_replica(fleet):
    router = Router(fleet.endpoints)
    async with await _client(router) as c:
        served = set()
        for _ in range(4):
            r = await c.post(
                "/generate", json={"text": "same shared prompt here"}
            )
            assert r.status_code == 200
            served.add(r.json()["replica"])
    assert len(served) == 1
    assert router.affinity_hits == 4
    # Exactly one fake replica saw all four requests.
    assert sorted(s["requests"] for s in fleet.states) == [0, 4]


async def test_stream_relay_is_byte_identical(fleet):
    fleet.states[0]["frames"] = fleet.states[1]["frames"] = [
        {"token_ids": [5, 6, 7]},
        {"token_ids": [8]},
        {"done": True, "text": "xy", "token_ids": [5, 6, 7, 8]},
    ]
    router = Router(fleet.endpoints)
    payload = {"text": "stream me", "stream": True}
    # Direct to the replica affinity picks, then through the router.
    key = router.routing_key(json.dumps(payload).encode())
    pref = router.choose(key)
    async with httpx.AsyncClient() as direct:
        d = await direct.post(
            f"http://{pref.name}/generate", json=payload
        )
    async with await _client(router) as c:
        v = await c.post("/generate", json=payload)
    assert v.status_code == d.status_code == 200
    assert v.content == d.content  # byte-for-byte, terminal frame included
    assert v.headers["content-type"] == d.headers["content-type"]


async def test_failover_once_on_dead_replica(fleet):
    # Point one endpoint at a dead port: connect refused is the
    # provably-not-submitted failure — exactly one failover hop.
    dead_port = fleet.servers[0].port
    await fleet.servers[0].stop()
    router = Router(
        [("127.0.0.1", dead_port), ("127.0.0.1", fleet.servers[1].port)]
    )
    async with await _client(router) as c:
        responses = [
            await c.post("/generate", json={"text": f"p{i}"})
            for i in range(6)
        ]
    assert all(r.status_code == 200 for r in responses)
    assert all(r.json()["replica"] == "B" for r in responses)
    # The dead replica was marked down on first contact, so at most
    # the keys that preferred it cost a failover — and only until the
    # state flipped (no polling here; the forward path marked it).
    assert router.replicas[0].state == DOWN
    assert 1 <= router.failovers <= 6
    assert fleet.states[1]["requests"] == 6


async def test_replica_503_sheds_and_fails_over_with_no_duplicate(fleet):
    key_text = "shed-me shed-me"
    router = Router(fleet.endpoints)
    pref = router.choose(router.routing_key(
        json.dumps({"text": key_text}).encode()
    ))
    shed_state = fleet.states[0 if pref.name.endswith(
        str(fleet.servers[0].port)) else 1]
    other_state = fleet.states[1] if shed_state is fleet.states[0] else (
        fleet.states[0]
    )
    shed_state["shed"] = True
    async with await _client(router) as c:
        r = await c.post("/generate", json={"text": key_text})
    assert r.status_code == 200
    assert router.failovers == 1
    # The shedding replica answered exactly once (the 503) — the
    # failover hop did not resubmit there.
    assert shed_state["requests"] == 1
    assert other_state["requests"] == 1
    # And its shed window is open: the next same-key request skips it
    # without costing another 503 round trip.
    async with await _client(router) as c:
        r2 = await c.post("/generate", json={"text": key_text})
    assert r2.status_code == 200
    assert shed_state["requests"] == 1
    assert router.failovers == 1  # fallback, not failover, this time


async def test_all_replicas_shedding_relays_503_with_retry_after(fleet):
    for s in fleet.states:
        s["shed"] = True
        s["retry_after"] = 3
    router = Router(fleet.endpoints)
    async with await _client(router) as c:
        r = await c.post("/generate", json={"text": "anything"})
    assert r.status_code == 503
    assert "retry-after" in r.headers
    # The second hop's 503 is the REPLICA's response, relayed.
    assert r.json() == {"detail": "overloaded"}


async def test_all_replicas_down_sheds_at_router_door():
    router = Router([("127.0.0.1", 1), ("127.0.0.1", 2)])
    for r in router.replicas:
        r.state = DOWN
    async with await _client(router) as c:
        r = await c.post("/generate", json={"text": "x"})
    assert r.status_code == 503
    assert r.headers.get("retry-after")
    assert router.shed_no_replica == 1


async def test_router_forward_fault_at_submit_single_failover(fleet):
    """The fault-matrix submit leg: a raise BEFORE the first request
    byte triggers exactly one failover hop and no duplicate submit —
    the faulted replica never sees the request at all."""
    router = Router(fleet.endpoints)
    with faults.active("router_forward:raise"):  # one shot, first call
        async with await _client(router) as c:
            r = await c.post("/generate", json={"text": "fault me"})
        assert r.status_code == 200
    assert router.failovers == 1
    # ONE replica served it; the fleet saw exactly one request total.
    assert sum(s["requests"] for s in fleet.states) == 1
    assert faults.injected_count() == 0  # disarmed resets the counter


async def test_router_forward_fault_midstream_terminal_frame(fleet):
    """The fault-matrix mid-stream leg: a raise while relaying yields
    a WELL-FORMED error terminal frame — parseable NDJSON with a
    code — never a truncated stream, and never a retry."""
    router = Router(fleet.endpoints)
    # after=1 skips the submit-seam fire; the relay of the first
    # chunk is call 2 and raises.
    with faults.active("router_forward:after=1:raise"):
        async with await _client(router) as c:
            r = await c.post(
                "/generate", json={"text": "stream", "stream": True}
            )
            assert r.status_code == 200
            lines = r.content.decode().strip().splitlines()
    frames = [json.loads(ln) for ln in lines]  # every line parses
    assert frames[-1]["code"] == "upstream_error"
    assert "error" in frames[-1]
    assert router.failovers == 0  # never mid-stream
    assert router.stream_upstream_errors == 1
    # Fresh work flows afterward (the conservation half: the router
    # state machine survived the injected failure).
    async with await _client(router) as c:
        ok = await c.post("/generate", json={"text": "after the fault"})
    assert ok.status_code == 200


async def test_router_forward_delay_slows_never_breaks(fleet):
    """The fault-matrix delay leg: a delay at the seam slows the
    relay (submit and every chunk) but every stream still completes
    byte-complete with its real terminal frame."""
    router = Router(fleet.endpoints)
    with faults.active("router_forward:delay=0.01"):
        async with await _client(router) as c:
            r = await c.post(
                "/generate", json={"text": "slowly", "stream": True}
            )
            assert r.status_code == 200
            frames = [
                json.loads(ln)
                for ln in r.content.decode().strip().splitlines()
            ]
        assert frames[-1]["done"] is True  # real terminal frame
        assert faults.injected_count() >= 2  # submit + chunks all fired
    assert router.failovers == 0
    assert router.stream_upstream_errors == 0


async def test_upstream_death_midstream_appends_error_frame():
    """Not injected — a RAW replica that tears the TCP stream after
    one chunk (no terminal 0-chunk, socket just closes): the relayed
    frame survives, the router appends its well-formed error terminal
    frame, and the client never sees a truncated line."""
    frame1 = json.dumps({"token_ids": [1, 2]}).encode() + b"\n"

    async def torn_replica(reader, writer):
        await reader.readuntil(b"\r\n\r\n")
        body = (
            b"HTTP/1.1 200 OK\r\ncontent-type: application/x-ndjson\r\n"
            b"transfer-encoding: chunked\r\nconnection: close\r\n\r\n"
            + b"%x\r\n" % len(frame1) + frame1 + b"\r\n"
        )
        writer.write(body)
        await writer.drain()
        writer.close()  # mid-stream death: no terminal chunk

    srv = await asyncio.start_server(torn_replica, "127.0.0.1", 0)
    port = srv.sockets[0].getsockname()[1]
    try:
        router = Router([("127.0.0.1", port)])
        async with await _client(router) as c:
            r = await c.post(
                "/generate", json={"text": "doomed", "stream": True}
            )
            lines = r.content.decode().strip().splitlines()
        frames = [json.loads(ln) for ln in lines]
        assert frames[0]["token_ids"] == [1, 2]  # the relayed real frame
        assert frames[-1]["code"] == "upstream_error"
        assert router.stream_upstream_errors == 1
        assert router.failovers == 0  # never mid-stream
    finally:
        srv.close()
        await srv.wait_closed()


async def test_predict_routes_by_load_and_healthz_reports(fleet):
    """/predict has no prefix economics: it spreads by p2c. The
    router-level /healthz reports per-replica state for the layer
    above."""
    router = Router(fleet.endpoints, health_poll_s=0.05)

    # Fake /predict on the replicas (the fake app only has /generate).
    for srv, st in zip(fleet.servers, fleet.states):
        app = srv.app

        @app.post("/predict")
        async def predict(request, _st=st):
            _st["requests"] += 1
            return {"prediction": "x", "probability": 0.5}

    async with await _client(router) as c:
        for _ in range(10):
            r = await c.post("/predict", json={"features": [1.0]})
            assert r.status_code == 200
        h = (await c.get("/healthz")).json()
    assert h["router"] is True
    assert h["replicas_live"] == 2
    assert {rep["state"] for rep in h["replicas"]} == {"live"}
    # p2c over equal load spreads (seeded rng: both replicas serve).
    assert all(s["requests"] > 0 for s in fleet.states)


async def test_router_healthz_degraded_when_fleet_down(fleet):
    router = Router(fleet.endpoints)
    for r in router.replicas:
        r.state = DOWN
    h = router.health_snapshot()
    assert h["status"] == "degraded"
    assert h["replicas_down"] == 2
