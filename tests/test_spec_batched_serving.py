"""Engine-integrated BATCHED speculation: a freshly-formed all-greedy
batch speculates as a whole (per-row positions), then REALIGNS the
cache (per-row roll + n_pad bump — effective positions invariant) to
hand off to the scalar-pos chunk loop when admission candidates
arrive. Every stream must stay byte-identical to its draft-less solo
run — including streams that continue on the chunk loop AFTER the
realign, which is the part that would break first if the roll
arithmetic were wrong."""

import asyncio

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio

T_CFG = dict(
    vocab_size=260, hidden_size=48, num_layers=3, num_heads=4,
    max_positions=256, compute_dtype="float32",
)
D_CFG = dict(
    vocab_size=260, hidden_size=24, num_layers=1, num_heads=2,
    max_positions=256, compute_dtype="float32",
)


@pytest.fixture
def anyio_backend():
    return "asyncio"


def _engines(**kw):
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    tok = ByteTokenizer()
    # A wide batching window makes co-batch formation deterministic
    # on a loaded box: if a submit ever misses the window, the
    # engine legitimately serves it via admission instead (solo spec
    # yields to joiners), which would make the engage asserts racy.
    plain = TextGenerationEngine(target, tp, tokenizer=tok, chunk=4,
                                 max_wait_ms=2000.0)
    spec = TextGenerationEngine(
        target, tp, tokenizer=tok, chunk=4, max_wait_ms=2000.0,
        draft=(draft, dp), spec_k=3, **kw,
    )
    return plain, spec


async def _collect(gen) -> list[int]:
    out: list[int] = []
    while True:
        item = await gen.queue.get()
        if item is None:
            return out
        if isinstance(item, Exception):
            raise item
        out.extend(item["token_ids"])


async def test_batched_greedy_batch_speculates_and_stays_exact():
    plain, spec = _engines()
    prompts = ["abcabcab", "xyzxyz", "hello wor"]
    refs = [
        plain.generate_text(p, max_new_tokens=18)["token_ids"]
        for p in prompts
    ]
    await spec.start()
    try:
        gens = [
            await spec.submit(p, max_new_tokens=18) for p in prompts
        ]
        got = await asyncio.gather(*[_collect(g) for g in gens])
    finally:
        await spec.stop()
    assert got == refs
    assert spec.spec_rounds > 0, "batch never speculated"


async def test_batched_spec_handoff_realign_exact_tail():
    """Force the realign handoff DETERMINISTICALLY: the patched yield
    seam ends the batched spec phase after 3 rounds, mid-generation,
    with rows at desynchronized positions. Their TAILS then decode
    through the scalar-pos chunk loop on the ROLLED cache — byte-exact
    streams prove the per-row roll + n_pad bump preserved every
    effective position."""
    plain, spec = _engines()
    prompts = ["abcabcab", "xyzxyz"]
    refs = [
        plain.generate_text(p, max_new_tokens=48)["token_ids"]
        for p in prompts
    ]
    calls = {"n": 0}
    real = spec._spec_should_yield

    def yield_after_three():
        calls["n"] += 1
        return calls["n"] > 3 or real()

    spec._spec_should_yield = yield_after_three
    await spec.start()
    try:
        gens = [
            await spec.submit(p, max_new_tokens=48) for p in prompts
        ]
        got = await asyncio.gather(*[_collect(g) for g in gens])
    finally:
        await spec.stop()
    assert got[0] == refs[0]
    assert got[1] == refs[1]
    assert 0 < spec.spec_rounds <= 3, spec.spec_rounds


async def test_batched_spec_joiner_integration_exact():
    """Integration smoke: a joiner submitted mid-batch. Whether it
    lands during the spec phase (phase yields before or between
    rounds) or after, every stream must stay exact — the engine may
    legitimately serve the whole thing without speculating if the
    joiner arrives during the phase's first compiles."""
    plain, spec = _engines()
    prompts = ["abcabcab", "xyzxyz"]
    refs = [
        plain.generate_text(p, max_new_tokens=48)["token_ids"]
        for p in prompts
    ]
    ref_j = plain.generate_text("qrs", max_new_tokens=6)["token_ids"]
    await spec.start()
    try:
        gens = [
            await spec.submit(p, max_new_tokens=48) for p in prompts
        ]
        first = await gens[0].queue.get()
        joiner = await spec.submit("qrs", max_new_tokens=6)
        got_j = await _collect(joiner)
        got = [list(first["token_ids"]) + await _collect(gens[0]),
               await _collect(gens[1])]
    finally:
        await spec.stop()
    assert got[0] == refs[0]
    assert got[1] == refs[1]
    assert got_j == ref_j


async def test_batched_spec_uneven_budgets_freeze_and_finish():
    """Rows with very different budgets: the short row freezes as a
    dummy while the long rows keep speculating; all exact."""
    plain, spec = _engines()
    specs = [("abcabcab", 30), ("xy", 3), ("hello wor", 21)]
    refs = [
        plain.generate_text(p, max_new_tokens=n)["token_ids"]
        for p, n in specs
    ]
    await spec.start()
    try:
        gens = [
            await spec.submit(p, max_new_tokens=n) for p, n in specs
        ]
        got = await asyncio.gather(*[_collect(g) for g in gens])
    finally:
        await spec.stop()
    assert got == refs


async def test_sampled_row_disables_batched_spec():
    """A batch containing any sampled row must not speculate (greedy
    exactness is the only batched contract); streams stay exact on
    the plain chunk path."""
    plain, spec = _engines()
    ref_a = plain.generate_text("abcab", max_new_tokens=10)["token_ids"]
    ref_b = plain.generate_text(
        "xyz", max_new_tokens=10, temperature=0.8, seed=3
    )["token_ids"]
    await spec.start()
    try:
        g_a = await spec.submit("abcab", max_new_tokens=10)
        g_b = await spec.submit(
            "xyz", max_new_tokens=10, temperature=0.8, seed=3
        )
        got_a, got_b = await asyncio.gather(_collect(g_a), _collect(g_b))
    finally:
        await spec.stop()
    assert got_a == ref_a
    assert got_b == ref_b
    assert spec.spec_rounds == 0, "mixed batch speculated"
