"""Many-adapter LoRA serving (``serving/adapter_store.py`` +
``models/lora.py``, the r21 tenant tier; ``--adapter-slots``).

The contract, layer by layer:

- **Wire format**: serialize → deserialize round-trips every adapter
  leaf byte-identically with the geometry header intact; the payload
  byte count is EXACT dtype/shape arithmetic (``adapter_bytes``);
  truncated/garbled/mismatched bodies raise (counted misses at the
  fetch seam, never installed). The disk artifact IS the wire image
  (``save_adapter``/``load_adapter``, one validator).
- **The slot path**: greedy streams are TOKEN-IDENTICAL slot-path vs
  the eagerly-merged ``W + a @ b`` reference across
  {gpt-MHA, llama-GQA} × {none, int8} caches, paged and contiguous —
  grouped (one scalar-slot program per single-tenant batch) and
  gathered (per-row slot indices, mixed tenants in ONE batch) both;
  mixed-batch per-row streams equal each tenant run solo; base
  programs stay byte-identical before and after adapter traffic.
- **Residency**: host-store LRU with optional disk spill; device
  slots install once (donated scatter), are pinned by live batches,
  and evict LRU when hold-free; exhaustion is a LOUD
  ``AdapterSlotsExhausted`` with nothing half-installed, and the
  scheduler's reservation gate defers rather than forming a lane
  that would die on it.
- **The amortization pin**: HBM is ``base_bytes + N × slot_bytes``
  in closed form (the /metrics gauge), never wall-clock; a cold
  tenant onboards by peer fetch with ``prefix_builds``-family
  counters flat (no prefill FLOPs spent on weight movement).

Engines reuse the family CFG (conftest ``paged-family``) so the
jitted program factories are shared instead of compiled again.
"""

import asyncio
import json

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.models.lora import DEFAULT_TARGETS, _kernel_of, merge_adapter
from mlapi_tpu.serving import faults
from mlapi_tpu.serving.adapter_store import (
    ADAPTER_ID_RE,
    AdapterSlotsExhausted,
    AdapterStore,
    AdapterUnavailable,
    adapter_bytes,
    deserialize_adapter,
    load_adapter,
    save_adapter,
    serialize_adapter,
)
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.serving.requests import _SyncSink
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)


def _model(kind="gpt_lm", kv_quant="none"):
    kw = dict(CFG, kv_quant=kv_quant)
    if kind == "llama_lm":
        kw["num_kv_heads"] = 2  # GQA: 4 query heads over 2 KV heads
    return get_model(kind, **kw)


@pytest.fixture(scope="module")
def gpt_params():
    return _model().init(jax.random.key(0))


@pytest.fixture(scope="module")
def llama_params():
    return _model("llama_lm").init(jax.random.key(0))


def _engine(model, params, **kw):
    kw.setdefault("chunk", 2)
    kw.setdefault("fused_single", False)
    kw.setdefault("kv_page_size", 8)
    kw.setdefault("adapter_slots", 4)
    return TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), **kw
    )


RANK = 4


def _mk_adapter(params, seed=0, rank=RANK, scale=0.3):
    """A random CANONICAL serving payload against ``params`` — every
    DEFAULT_TARGET the model holds, at the base kernel dtype, ``b``
    already carrying its scale (the ``export_adapter`` contract). The
    scale is large enough that greedy continuations actually CHANGE
    vs base (the identity pins would pass vacuously otherwise)."""
    rng = np.random.default_rng(seed)
    payload: dict = {}
    for ln in sorted(
        (k for k in params if k.startswith("layer_")),
        key=lambda k: int(k.split("_")[1]),
    ):
        for t in DEFAULT_TARGETS:
            node = params[ln].get(t) if t in params[ln] else None
            kernel = _kernel_of(node) if node is not None else None
            if kernel is None:
                continue
            d_in, d_out = kernel.shape
            dt = np.dtype(kernel.dtype)
            payload.setdefault(ln, {})[t] = {
                "a": (scale * rng.standard_normal((d_in, rank))).astype(dt),
                "b": (scale * rng.standard_normal((rank, d_out))).astype(dt),
            }
    return payload


def _wire(warm_engine):
    """An in-process transport serving ``warm_engine``'s host-store
    adapters — the exact serve path (``AdapterPeer.serve_wire``)
    without a socket, so the fetch client, wire format, counters, and
    install path are all real."""

    def transport(host, port, path, timeout_s):
        aid = path.rsplit("/", 1)[1]
        data = warm_engine.adapter_peer.serve_wire(aid)
        return (200, data) if data is not None else (404, b"")

    return transport


def _link(cold_engine, warm_engine, aid):
    cold_engine.adapter_peer._transport = _wire(warm_engine)
    cold_engine.adapter_peer.note_hint(aid, "127.0.0.1:19")


# --- wire format + artifact --------------------------------------------


def test_wire_roundtrip_and_validation(gpt_params, tmp_path):
    payload = _mk_adapter(gpt_params)
    data = serialize_adapter("t1", payload)
    out, rank, nbytes = deserialize_adapter("t1", data)
    assert rank == RANK and nbytes == adapter_bytes(payload)
    for ln, layer in payload.items():
        for t, pair in layer.items():
            for ab in ("a", "b"):
                np.testing.assert_array_equal(out[ln][t][ab], pair[ab])

    # Every corruption class raises (→ a counted miss at the fetch
    # seam), never a wrong install.
    for bad in (
        b"garbage with no header",
        b"{}\n",                                  # header missing fields
        data[: len(data) // 2],                   # truncated payload
        data + b"x",                              # trailing bytes
        data.replace(b'"nbytes": ', b'"nbytes": 9', 1),  # total lies
    ):
        with pytest.raises(ValueError):
            deserialize_adapter("t1", bad)
    # The id is part of the validated manifest: a blob served under
    # the wrong name is refused (tenant isolation on the wire).
    with pytest.raises(ValueError):
        deserialize_adapter("other-tenant", data)
    # Ragged ranks across leaves are refused — slot pools force ONE
    # engine-wide rank.
    head_line, _, rest = data.partition(b"\n")
    head = json.loads(head_line)
    head["leaves"][0][3] = [head["leaves"][0][3][0], RANK + 1]
    with pytest.raises(ValueError):
        deserialize_adapter(
            "t1", json.dumps(head).encode() + b"\n" + rest
        )

    # The disk artifact is the wire image, same validator.
    p = tmp_path / "t1.lora"
    assert save_adapter(str(p), "t1", payload) == adapter_bytes(payload)
    aid, loaded, rank2, nbytes2 = load_adapter(str(p))
    assert (aid, rank2, nbytes2) == ("t1", RANK, adapter_bytes(payload))
    np.testing.assert_array_equal(
        loaded["layer_0"]["qkv"]["a"], payload["layer_0"]["qkv"]["a"]
    )


def test_adapter_id_grammar():
    for ok in ("t1", "tenant-42", "A.b_c-d", "x" * 64):
        assert ADAPTER_ID_RE.match(ok)
    for bad in ("", "x" * 65, "a b", "a/b", "a\nb", "../etc", "ü"):
        assert not ADAPTER_ID_RE.match(bad)


# --- the slot path: token identity vs the merged reference -------------


@pytest.mark.parametrize("fmt", ["none", "int8"])
@pytest.mark.parametrize("kind", ["gpt_lm", "llama_lm"])
def test_slot_stream_identity(kind, fmt, gpt_params, llama_params):
    """The acceptance pin: greedy streams are TOKEN-IDENTICAL slot
    path vs the eagerly-merged ``W + a @ b`` reference, MHA and GQA,
    both cache formats — and the adapter demonstrably bites (differs
    from base), installs exactly once, runs the GROUPED program for
    the single-tenant batch, and leaves the base programs serving
    byte-identical streams afterwards."""
    params = gpt_params if kind == "gpt_lm" else llama_params
    model = _model(kind, fmt)
    payload = _mk_adapter(params, seed=3)
    eng = _engine(model, params)
    eng.register_adapter("t1", payload)

    base_ref = eng.generate_text(" q1", max_new_tokens=8)
    merged = _engine(
        model, merge_adapter(params, payload), adapter_slots=0
    )
    ref = merged.generate_text(" q1", max_new_tokens=8)
    out = eng.generate_text(" q1", max_new_tokens=8, adapter="t1")
    assert out["token_ids"] == ref["token_ids"]
    assert out["token_ids"] != base_ref["token_ids"]  # it bites
    assert eng.adapter_installs == 1
    assert eng.adapter_grouped_batches == 1
    assert eng.adapter_gathered_batches == 0
    # Steady state: the slot is resident — no second install.
    out2 = eng.generate_text(" q1", max_new_tokens=8, adapter="t1")
    assert out2["token_ids"] == ref["token_ids"]
    assert eng.adapter_installs == 1
    # Base traffic after adapter traffic: byte-identical to before
    # (slot 0 is the permanently-zero NULL row; plain params build
    # the very same no-adapter program).
    assert eng.generate_text(
        " q1", max_new_tokens=8
    )["token_ids"] == base_ref["token_ids"]


def test_slot_stream_identity_contiguous(gpt_params):
    """The same pin on the CONTIGUOUS cache (no paged pool): the lora
    trace rides the one dispatch seam, whichever cache family."""
    model = _model()
    payload = _mk_adapter(gpt_params, seed=3)
    eng = _engine(model, gpt_params, kv_page_size=None)
    assert eng.pool is None
    eng.register_adapter("t1", payload)
    merged = _engine(
        model, merge_adapter(gpt_params, payload),
        adapter_slots=0, kv_page_size=None,
    )
    ref = merged.generate_text(" q1", max_new_tokens=8)
    out = eng.generate_text(" q1", max_new_tokens=8, adapter="t1")
    assert out["token_ids"] == ref["token_ids"]


def test_mixed_tenant_batch_matches_solo(gpt_params):
    """Mixed tenants in ONE batch (the gathered-BGMV program):
    per-row streams equal each tenant run solo — including a base
    (slot-0) row gathering its exactly-zero delta — and a same-tenant
    pair still takes the grouped scalar-slot program."""
    model = _model()
    p1 = _mk_adapter(gpt_params, seed=3)
    p2 = _mk_adapter(gpt_params, seed=4)
    eng = _engine(model, gpt_params)
    eng.register_adapter("t1", p1)
    eng.register_adapter("t2", p2)

    prompts = [" alpha", " brav0", " charl"]
    tenants = ["t1", None, "t2"]
    solos = [
        eng.generate_text(p, max_new_tokens=6, adapter=a)["token_ids"]
        for p, a in zip(prompts, tenants)
    ]
    grouped0 = eng.adapter_grouped_batches

    outs: list = [[] for _ in prompts]
    sinks = [
        _SyncSink(
            eng._encode(p, 6, 0.0, 0, None, adapter=a), outs[i]
        )
        for i, (p, a) in enumerate(zip(prompts, tenants))
    ]
    eng._run_batch(sinks)
    assert all(s.error is None for s in sinks)
    assert outs == solos                          # per-row identity
    assert eng.adapter_gathered_batches == 1
    assert eng.adapter_grouped_batches == grouped0  # not grouped

    # Same-tenant pair: all live rows share one slot → grouped.
    outs2: list = [[], []]
    sinks2 = [
        _SyncSink(eng._encode(p, 6, 0.0, 0, None, adapter="t1"), o)
        for p, o in zip(prompts[:2], outs2)
    ]
    eng._run_batch(sinks2)
    assert all(s.error is None for s in sinks2)
    assert outs2[0] == solos[0]
    assert eng.adapter_grouped_batches == grouped0 + 1
    assert eng.adapter_gathered_batches == 1

    # No leakage: base traffic after the mixed batches is untouched.
    assert eng.generate_text(
        prompts[1], max_new_tokens=6
    )["token_ids"] == solos[1]


def test_prefix_with_adapter_folds_into_prompt(gpt_params):
    """The prefix cache holds BASE-model KV; an adapter request
    naming a prefix folds it into the prompt (identical semantics,
    zero cache pollution) and counts the decline where the cache's
    other fallbacks land."""
    model = _model()
    eng = _engine(model, gpt_params)
    eng.register_adapter("t1", _mk_adapter(gpt_params, seed=3))
    pre = "You are a helpful bot."
    ref = eng.generate_text(pre + " q1", max_new_tokens=6, adapter="t1")
    fb0 = eng.prefix.fallbacks
    out = eng.generate_text(
        " q1", max_new_tokens=6, prefix=pre, adapter="t1"
    )
    assert out["token_ids"] == ref["token_ids"]
    assert eng.prefix.builds == 0                 # never built base KV
    assert eng.prefix.fallbacks == fb0 + 1


# --- residency: store LRU/spill, slot LRU, exhaustion ------------------


def test_store_lru_and_disk_spill(gpt_params, tmp_path):
    """Host-store mechanics, no device: LRU eviction under a byte
    budget; disk mode keeps the index RAM-light (the blob lives as
    its wire file) and restores byte-identically; a vanished file is
    a miss, not a crash."""
    p1 = _mk_adapter(gpt_params, seed=1)
    nb = adapter_bytes(p1)
    ram = AdapterStore(max_bytes=2 * nb + 1)
    for i, s in enumerate((1, 2, 3)):
        ram.put(f"t{i}", _mk_adapter(gpt_params, seed=s))
    assert ram.entries == 2 and ram.evictions == 1
    assert not ram.has("t0") and ram.has("t2")    # t0 was coldest
    assert ram.bytes_in_use == 2 * nb
    # get() touches LRU order: t1 read → t2 becomes the next victim.
    assert ram.get("t1") is not None
    ram.put("t3", _mk_adapter(gpt_params, seed=4))
    assert ram.has("t1") and not ram.has("t2")

    disk = AdapterStore(max_bytes=8 * nb, disk_dir=str(tmp_path))
    disk.put("t1", p1)
    files = list(tmp_path.glob("adstore-*.bin"))
    assert len(files) == 1                        # spilled to its file
    got, rank, nbytes = disk.get("t1")
    assert rank == RANK and nbytes == nb
    np.testing.assert_array_equal(
        got["layer_0"]["qkv"]["b"], p1["layer_0"]["qkv"]["b"]
    )
    files[0].unlink()                             # simulate loss
    assert disk.get("t1") is None                 # miss, index dropped
    assert disk.entries == 0


def test_slot_exhaustion_loud_and_lru_eviction(gpt_params):
    """Slot-pool mechanics through the engine: a held (live-batch)
    adapter is pinned; installing past capacity with every slot held
    is a LOUD AdapterSlotsExhausted with nothing half-installed;
    releasing makes the LRU resident evictable and the next install
    recycles its slot."""
    model = _model()
    eng = _engine(model, gpt_params, adapter_slots=1)
    p1 = _mk_adapter(gpt_params, seed=1)
    p2 = _mk_adapter(gpt_params, seed=2)
    eng.register_adapter("t1", p1)
    eng.register_adapter("t2", p2)
    ref2 = _engine(
        model, merge_adapter(gpt_params, p2), adapter_slots=0
    ).generate_text(" q1", max_new_tokens=6)

    slot = eng.adapters.acquire("t1", eng.adapter_store)  # pin t1
    assert slot == 1 and eng.adapter_slots_in_use == 1
    assert not eng.adapters.can_claim(["t2"])
    with pytest.raises(AdapterSlotsExhausted):
        eng.adapters.acquire("t2", eng.adapter_store)
    assert eng.adapter_slots_in_use == 1          # nothing half-done
    assert eng.adapters.resident("t1")
    eng.adapters.release("t1")
    assert eng.adapters.can_claim(["t2"])

    # The next t2 request evicts hold-free t1 and reuses ITS slot —
    # and decodes correctly through the recycled row.
    out = eng.generate_text(" q1", max_new_tokens=6, adapter="t2")
    assert out["token_ids"] == ref2["token_ids"]
    assert eng.adapter_evictions == 1
    assert eng.adapters.resident("t2") and not eng.adapters.resident("t1")
    # Double-release is a loud assert, not a silent negative hold.
    with pytest.raises(AssertionError):
        eng.adapters.release("t2")


async def test_scheduler_defers_on_slot_pressure(gpt_params):
    """The reservation-gate satellite: with ONE slot and a live
    tenant lane holding it, a second tenant's group DEFERS (counted)
    instead of forming a lane that would die on exhaustion — then
    claims, evicts, and serves once the holder finishes."""
    model = _model()
    eng = _engine(
        model, gpt_params, adapter_slots=1, max_wait_ms=0.0,
    )
    eng.register_adapter("t1", _mk_adapter(gpt_params, seed=1))
    eng.register_adapter("t2", _mk_adapter(gpt_params, seed=2))
    await eng.start()
    try:
        ra = await eng.submit(
            " a", max_new_tokens=48, adapter="t1", stream=True
        )
        # Wait for t1's first streamed chunk: its lane is now LIVE and
        # holds the only slot, with decode units still pending — so
        # t2's group below must hit the gate, not slip in after t1
        # drained (the race a loaded 1-core box loses).
        first = await ra.queue.get()
        assert first is not None and not isinstance(first, Exception)
        rb = await eng.submit(
            " b", max_new_tokens=4, adapter="t2", stream=True
        )

        async def collect(req, pre=()):
            out: list = list(pre)
            while True:
                item = await req.queue.get()
                if item is None:
                    return out, None
                if isinstance(item, Exception):
                    return out, item
                out.extend(item["token_ids"])

        (ta, ea), (tb, eb) = await asyncio.gather(
            collect(ra, first["token_ids"]), collect(rb)
        )
        assert ea is None and eb is None
        assert len(ta) == 48 and len(tb) == 4
        assert eng.sched_adapters_deferred >= 1
        assert eng.adapter_evictions == 1         # t2 recycled t1's slot
    finally:
        await eng.stop()


# --- cold fetch: tenant onboarding over the wire -----------------------


def test_cold_fetch_from_peer_counters_flat(gpt_params):
    """A cold replica serving a tenant it never saw fetches the blob
    from its hinted warm peer and streams TOKEN-IDENTICAL — with the
    wire bytes the exact closed form, the blob staged into the host
    store, and the ``prefix_builds``-family counters FLAT (onboarding
    moves weights, never spends prefill FLOPs)."""
    model = _model()
    payload = _mk_adapter(gpt_params, seed=3)
    warm = _engine(model, gpt_params)
    warm.register_adapter("t1", payload)
    ref = warm.generate_text(" q1", max_new_tokens=6, adapter="t1")
    cold = _engine(model, gpt_params)
    _link(cold, warm, "t1")

    out = cold.generate_text(" q1", max_new_tokens=6, adapter="t1")
    assert out["token_ids"] == ref["token_ids"]
    closed = adapter_bytes(payload)
    assert cold.adapter_fetch_hits == 1
    assert cold.adapter_fetch_bytes == closed
    assert warm.adapter_serve_count == 1
    assert warm.adapter_serve_bytes == closed
    assert cold.adapter_store_entries == 1        # staged locally
    assert cold.adapter_installs == 1
    assert cold.prefix.builds == 0                # counters flat
    assert cold.prefix.fallbacks == 0
    # Steady state: resident — no second wire hop.
    out2 = cold.generate_text(" q1", max_new_tokens=6, adapter="t1")
    assert out2["token_ids"] == ref["token_ids"]
    assert cold.adapter_fetch_hits == 1


def test_fetch_failure_modes(gpt_params):
    """404 → counted miss AND the hint dropped (the peer is not warm
    after all); corrupt body → counted miss, never installed;
    transport error → counted failure. Every mode surfaces as the
    404-mapped AdapterUnavailable, request never queued."""
    model = _model()
    cold = _engine(model, gpt_params)
    cold.adapter_peer._transport = lambda h, p, path, t: (404, b"")
    cold.adapter_peer.note_hint("t1", "127.0.0.1:19")
    with pytest.raises(AdapterUnavailable):
        cold.generate_text(" q1", max_new_tokens=4, adapter="t1")
    assert cold.adapter_fetch_misses == 1
    assert cold.adapter_peer.hint_for("t1") is None

    cold.adapter_peer._transport = lambda h, p, path, t: (200, b"junk")
    cold.adapter_peer.note_hint("t1", "127.0.0.1:19")
    with pytest.raises(AdapterUnavailable):
        cold.generate_text(" q1", max_new_tokens=4, adapter="t1")
    assert cold.adapter_fetch_misses == 2
    assert cold.adapter_store_entries == 0        # never installed

    def boom(h, p, path, t):
        raise ConnectionRefusedError("peer is down")

    cold.adapter_peer._transport = boom
    cold.adapter_peer.note_hint("t1", "127.0.0.1:19")
    with pytest.raises(AdapterUnavailable):
        cold.generate_text(" q1", max_new_tokens=4, adapter="t1")
    assert cold.adapter_fetch_failures == 1
    # Malformed / unknown ids 404 before any queueing.
    with pytest.raises(AdapterUnavailable):
        cold.generate_text(" q1", max_new_tokens=4, adapter="../etc")
    off = _engine(model, gpt_params, adapter_slots=0)
    with pytest.raises(AdapterUnavailable):
        off.generate_text(" q1", max_new_tokens=4, adapter="t1")


def test_adapter_fault_matrix(gpt_params):
    """The r12-style fault-matrix satellite: a raise at
    ``adapter_fetch`` is a counted fetch failure resolving to the 404
    contract; a raise at ``adapter_install`` fails the batch LOUDLY on
    untouched slot state (free list intact, nothing resident) and the
    next clean run installs and serves; delays slow, never break."""
    model = _model()
    payload = _mk_adapter(gpt_params, seed=3)
    warm = _engine(model, gpt_params)
    warm.register_adapter("t1", payload)
    ref = warm.generate_text(" q1", max_new_tokens=6, adapter="t1")

    cold = _engine(model, gpt_params)
    _link(cold, warm, "t1")
    with faults.active("adapter_fetch:raise"):
        with pytest.raises(AdapterUnavailable):
            cold.generate_text(" q1", max_new_tokens=6, adapter="t1")
    assert cold.adapter_fetch_failures == 1
    assert cold.adapter_fetch_hits == 0
    assert warm.adapter_serve_count == 0          # raised before wire

    eng = _engine(model, gpt_params)
    eng.register_adapter("t1", payload)
    with faults.active("adapter_install:raise"):
        with pytest.raises(faults.InjectedFault):
            eng.generate_text(" q1", max_new_tokens=6, adapter="t1")
    assert eng.adapter_installs == 0              # untouched state
    assert eng.adapter_slots_in_use == 0
    out = eng.generate_text(" q1", max_new_tokens=6, adapter="t1")
    assert out["token_ids"] == ref["token_ids"]   # clean recovery
    assert eng.adapter_installs == 1

    cold = _engine(model, gpt_params)
    _link(cold, warm, "t1")
    with faults.active("adapter_fetch:delay=0.01,adapter_install:delay=0.01"):
        out = cold.generate_text(" q1", max_new_tokens=6, adapter="t1")
        assert faults.injected_count() == 2
    assert out["token_ids"] == ref["token_ids"]
    assert cold.adapter_fetch_hits == 1


# --- the amortization pin ----------------------------------------------


def test_hbm_amortization_closed_form(gpt_params):
    """HBM is ``base_bytes + N × slot_bytes``, all three terms pure
    dtype/shape arithmetic recomputed here independently — never
    wall-clock, never device introspection. Each resident tenant
    costs EXACTLY one slot row across every pool leaf."""
    model = _model()
    eng = _engine(model, gpt_params, adapter_slots=4)
    base = sum(
        int(np.prod(v.shape)) * np.dtype(v.dtype).itemsize
        for v in jax.tree.leaves(eng.params)
        if hasattr(v, "dtype")
    )
    slot = 0
    for ln in (k for k in eng.params if k.startswith("layer_")):
        for t in DEFAULT_TARGETS:
            kernel = (
                _kernel_of(eng.params[ln][t]) if t in eng.params[ln]
                else None
            )
            if kernel is None:
                continue
            d_in, d_out = kernel.shape
            itemsize = np.dtype(kernel.dtype).itemsize
            slot += (d_in * RANK + RANK * d_out) * itemsize
    assert eng.adapter_slot_bytes == 0            # pools not built yet
    assert eng.adapter_resident_bytes == base

    for i, s in enumerate((1, 2, 3)):
        eng.register_adapter(f"t{i}", _mk_adapter(gpt_params, seed=s))
        eng.generate_text(" q", max_new_tokens=2, adapter=f"t{i}")
        assert eng.adapter_slots_in_use == i + 1
        assert eng.adapter_slot_bytes == slot
        assert eng.adapter_resident_bytes == base + (i + 1) * slot


# --- the replica surface (endpoint, schema, hints, metrics) ------------


async def _asgi_client(app):
    import httpx

    await app.startup()
    transport = httpx.ASGITransport(app=app)
    return httpx.AsyncClient(transport=transport, base_url="http://t")


async def test_adapter_endpoint_and_metrics(gpt_params, monkeypatch):
    from mlapi_tpu.serving import build_app

    monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
    model = _model()
    payload = _mk_adapter(gpt_params, seed=3)
    eng = _engine(model, gpt_params)
    eng.register_adapter("t1", payload)
    ref = eng.generate_text(" q1", max_new_tokens=4, adapter="t1")
    app = build_app(eng)
    cl = await _asgi_client(app)
    try:
        r = await cl.get("/adapter/t1")
        assert r.status_code == 200
        assert r.headers["content-type"] == "application/octet-stream"
        got, rank, nbytes = deserialize_adapter("t1", r.content)
        assert rank == RANK and nbytes == adapter_bytes(payload)
        assert eng.adapter_serve_count == 1
        assert (await cl.get("/adapter/nope")).status_code == 404
        assert (await cl.get("/adapter/..%2Fetc")).status_code == 404

        # The /generate schema field: routed through the slot path,
        # identical to the engine-level stream; unknown tenants 404.
        r = await cl.post(
            "/generate",
            json={"text": " q1", "max_new_tokens": 4, "adapter": "t1"},
        )
        assert r.status_code == 200
        assert r.json()["token_ids"] == ref["token_ids"]
        r = await cl.post(
            "/generate",
            json={"text": " q1", "max_new_tokens": 4, "adapter": "ghost"},
        )
        assert r.status_code == 404

        snap = (await cl.get("/metrics")).json()
        c, g = snap["counters"], snap["gauges"]
        assert c["generate.adapter_serve_count"] == 1
        assert c["generate.adapter_installs"] == 1
        assert c["generate.adapter_grouped_batches"] >= 1
        for k in ("fetch_hits", "fetch_misses", "fetch_bytes",
                  "fetch_failures", "gathered_batches",
                  "store_evictions", "evictions"):
            assert c[f"generate.adapter_{k}"] == 0
        assert c["generate.sched_adapters_deferred"] == 0
        assert g["generate.adapter_slots_total"] == 4
        assert g["generate.adapter_slots_in_use"] == 1
        assert g["generate.adapter_slot_bytes"] == eng.adapter_slot_bytes
        assert g["generate.adapter_resident_bytes"] == (
            eng.adapter_resident_bytes
        )
        assert g["generate.adapter_store_entries"] == 1
    finally:
        await cl.aclose()
        await app.shutdown()


async def test_endpoint_and_metrics_absent_when_disabled(
    gpt_params, monkeypatch,
):
    from mlapi_tpu.serving import build_app

    monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
    eng = _engine(_model(), gpt_params, adapter_slots=0)
    app = build_app(eng)
    cl = await _asgi_client(app)
    try:
        assert (await cl.get("/adapter/t1")).status_code == 404
        # An adapter-carrying request against a slotless replica is
        # the same 404 contract (resolved before queueing).
        r = await cl.post(
            "/generate",
            json={"text": " q", "max_new_tokens": 2, "adapter": "t1"},
        )
        assert r.status_code == 404
        snap = (await cl.get("/metrics")).json()
        assert not any(
            k.startswith("generate.adapter")
            for k in {**snap["counters"], **snap["gauges"]}
        )
    finally:
        await cl.aclose()
        await app.shutdown()


async def test_endpoint_absent_on_non_replica(gpt_params, monkeypatch):
    """Replica-gated like GET /kv/prefix: a direct-facing server must
    not hand tenant weight blobs to arbitrary callers."""
    from mlapi_tpu.serving import build_app

    monkeypatch.delenv("MLAPI_TPU_REPLICA", raising=False)
    monkeypatch.delenv("MLAPI_TPU_REPLICAS", raising=False)
    eng = _engine(_model(), gpt_params)
    eng.register_adapter("t1", _mk_adapter(gpt_params))
    app = build_app(eng)
    cl = await _asgi_client(app)
    try:
        assert (await cl.get("/adapter/t1")).status_code == 404
        assert eng.adapter_serve_count == 0
    finally:
        await cl.aclose()
        await app.shutdown()


async def test_warm_peer_hint_gated_to_replicas(gpt_params, monkeypatch):
    """The EXISTING x-mlapi-warm-peer header doubles as the adapter
    warmth hint (the tenant's prefix-affinity peer is where its
    adapter is warm) — trusted only on router replicas."""
    from mlapi_tpu.serving import build_app

    async def post(replica: bool):
        if replica:
            monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
        else:
            monkeypatch.delenv("MLAPI_TPU_REPLICA", raising=False)
        eng = _engine(_model(), gpt_params)
        eng.register_adapter("t1", _mk_adapter(gpt_params))
        app = build_app(eng)
        cl = await _asgi_client(app)
        try:
            r = await cl.post(
                "/generate",
                json={"text": " q", "max_new_tokens": 2, "adapter": "t1"},
                headers={"x-mlapi-warm-peer": "10.0.0.9:8001"},
            )
            assert r.status_code == 200
        finally:
            await cl.aclose()
            await app.shutdown()
        return eng

    eng = await post(True)
    assert eng.adapter_peer.hint_for("t1") == ("10.0.0.9", 8001)
    eng = await post(False)
    assert eng.adapter_peer.hint_for("t1") is None


def test_router_key_precedence_and_disagg_gate():
    """Router policy units (pure functions, no sockets): the affinity
    key prefers prefix > adapter > text, and adapter bodies never
    take the role-split two-hop path (the tenant's slot working set
    stays in one role pool)."""
    from mlapi_tpu.serving.router import Router

    r = Router([("127.0.0.1", 1), ("127.0.0.1", 2)])
    assert r.routing_key_of({"prefix": "P", "adapter": "t1"}) == b"P"
    assert r.routing_key_of({"adapter": "t1", "text": "x"}) == b"t1"
    assert r.routing_key_of({"text": "x"}) == b"x"
    assert r.routing_key_of({"adapter": 7, "text": ""}) is None

    rs = Router(
        [("127.0.0.1", 1), ("127.0.0.1", 2)],
        roles=["prefill", "decode"],
    )
    assert rs.wants_disagg_of({"text": "x"})
    assert not rs.wants_disagg_of({"text": "x", "adapter": "t1"})
    assert not rs.wants_disagg_of({"text": "x", "prefix": "P"})
