"""Checkpoint-restart training (SURVEY §5 failure-detection row):
an interrupted run resumed from its train-state checkpoint must land
on the same trajectory as an uninterrupted one."""

import jax
import numpy as np
import pytest

from mlapi_tpu.datasets import get_dataset
from mlapi_tpu.models import get_model
from mlapi_tpu.parallel import initialize_from_env
from mlapi_tpu.train import fit


def test_resume_matches_uninterrupted_run(tmp_path):
    mnist = get_dataset("mnist", synthetic_train=1024, synthetic_test=128)
    model = get_model("linear", num_features=784, num_classes=10)
    kwargs = dict(batch_size=128, learning_rate=1e-2, seed=3)

    # Uninterrupted 60 steps.
    full = fit(model, mnist, steps=60, **kwargs)

    # 30 steps, "crash", resume to 60.
    ck = tmp_path / "train_state"
    fit(model, mnist, steps=30, checkpoint_dir=str(ck), save_every=10, **kwargs)
    # save_every skips the final step, so newest committed state is 20...
    # crash semantics: the step-30 run ended without a final save.
    resumed = fit(model, mnist, steps=60, checkpoint_dir=str(ck),
                  save_every=10, **kwargs)

    # Same optimizer trajectory ⇒ (near-)identical params. Exact step
    # replay is guaranteed by (seed, step)-keyed batching; float
    # reassociation across restore gives at most tiny drift.
    for a, b in zip(jax.tree.leaves(full.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_resume_skips_when_no_checkpoint(tmp_path):
    iris = get_dataset("iris")
    model = get_model("linear", num_features=4, num_classes=3)
    result = fit(model, iris, steps=50, checkpoint_dir=str(tmp_path / "none"),
                 save_every=0)
    assert result.test_accuracy is not None


def test_resume_rejects_changed_hyperparameters(tmp_path):
    """A checkpoint trained with lr=1e-2 must not silently continue
    under lr=1e-3 — the result would match neither configuration."""
    mnist = get_dataset("mnist", synthetic_train=256, synthetic_test=64)
    model = get_model("linear", num_features=784, num_classes=10)
    ck = tmp_path / "train_state"

    fit(model, mnist, steps=20, checkpoint_dir=str(ck), save_every=10,
        batch_size=64, learning_rate=1e-2, seed=3)
    with pytest.raises(ValueError, match="different hyperparameters"):
        fit(model, mnist, steps=40, checkpoint_dir=str(ck), save_every=10,
            batch_size=64, learning_rate=1e-3, seed=3)
    # resume=False starts fresh instead of raising.
    fit(model, mnist, steps=20, checkpoint_dir=str(ck), save_every=0,
        batch_size=64, learning_rate=1e-3, seed=3, resume=False)


def test_initialize_from_env_is_noop_single_host(monkeypatch):
    monkeypatch.delenv("MLAPI_TPU_COORDINATOR", raising=False)
    monkeypatch.delenv("MLAPI_TPU_MULTIHOST", raising=False)
    assert initialize_from_env() is False
