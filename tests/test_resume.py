"""Checkpoint-restart training (SURVEY §5 failure-detection row):
an interrupted run resumed from its train-state checkpoint must land
on the same trajectory as an uninterrupted one."""

import jax
import numpy as np
import pytest

from mlapi_tpu.datasets import get_dataset
from mlapi_tpu.models import get_model
from mlapi_tpu.parallel import initialize_from_env
from mlapi_tpu.train import fit


def test_resume_matches_uninterrupted_run(tmp_path):
    mnist = get_dataset("mnist", synthetic_train=1024, synthetic_test=128)
    model = get_model("linear", num_features=784, num_classes=10)
    kwargs = dict(batch_size=128, learning_rate=1e-2, seed=3)

    # Uninterrupted 60 steps.
    full = fit(model, mnist, steps=60, **kwargs)

    # 30 steps, "crash", resume to 60.
    ck = tmp_path / "train_state"
    fit(model, mnist, steps=30, checkpoint_dir=str(ck), save_every=10, **kwargs)
    # save_every skips the final step, so newest committed state is 20...
    # crash semantics: the step-30 run ended without a final save.
    resumed = fit(model, mnist, steps=60, checkpoint_dir=str(ck),
                  save_every=10, **kwargs)

    # Same optimizer trajectory ⇒ (near-)identical params. Exact step
    # replay is guaranteed by (seed, step)-keyed batching; float
    # reassociation across restore gives at most tiny drift.
    for a, b in zip(jax.tree.leaves(full.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_resume_skips_when_no_checkpoint(tmp_path):
    iris = get_dataset("iris")
    model = get_model("linear", num_features=4, num_classes=3)
    result = fit(model, iris, steps=50, checkpoint_dir=str(tmp_path / "none"),
                 save_every=0)
    assert result.test_accuracy is not None


def test_resume_rejects_changed_hyperparameters(tmp_path):
    """A checkpoint trained with lr=1e-2 must not silently continue
    under lr=1e-3 — the result would match neither configuration."""
    mnist = get_dataset("mnist", synthetic_train=256, synthetic_test=64)
    model = get_model("linear", num_features=784, num_classes=10)
    ck = tmp_path / "train_state"

    fit(model, mnist, steps=20, checkpoint_dir=str(ck), save_every=10,
        batch_size=64, learning_rate=1e-2, seed=3)
    with pytest.raises(ValueError, match="different hyperparameters"):
        fit(model, mnist, steps=40, checkpoint_dir=str(ck), save_every=10,
            batch_size=64, learning_rate=1e-3, seed=3)
    # resume=False starts fresh instead of raising.
    fit(model, mnist, steps=20, checkpoint_dir=str(ck), save_every=0,
        batch_size=64, learning_rate=1e-3, seed=3, resume=False)


def test_initialize_from_env_is_noop_single_host(monkeypatch):
    monkeypatch.delenv("MLAPI_TPU_COORDINATOR", raising=False)
    monkeypatch.delenv("MLAPI_TPU_MULTIHOST", raising=False)
    assert initialize_from_env() is False


def test_keep_last_gc_retains_newest(tmp_path):
    """keep_last=N: only the N newest committed step dirs survive a
    run; resume still works from the newest."""
    mnist = get_dataset("mnist", synthetic_train=512, synthetic_test=64)
    model = get_model("linear", num_features=784, num_classes=10)
    ck = tmp_path / "ts"
    fit(model, mnist, steps=50, batch_size=64, learning_rate=1e-2, seed=1,
        checkpoint_dir=str(ck), save_every=10, keep_last=2)
    steps = sorted(p.name for p in ck.iterdir() if p.name.startswith("step_"))
    # Saves at 10,20,30,40 (save_every skips the final step); keep 2.
    assert steps == ["step_00000030", "step_00000040"], steps


def test_gc_checkpoints_only_touches_committed(tmp_path):
    from mlapi_tpu.checkpoint import gc_checkpoints, save_checkpoint

    params = {"w": np.zeros((2, 2), np.float32)}
    for s in (1, 2, 3):
        save_checkpoint(tmp_path / f"step_{s:08d}", params, step=s)
    # An uncommitted dir (in-progress save on another process) and a
    # non-step dir must both be left alone.
    (tmp_path / "step_00000099").mkdir()
    (tmp_path / "notes").mkdir()
    deleted = gc_checkpoints(tmp_path, keep_last=1)
    names = sorted(p.name for p in tmp_path.iterdir())
    assert names == ["notes", "step_00000003", "step_00000099"], names
    assert len(deleted) == 2


def test_async_save_matches_sync_save(tmp_path):
    """async_save runs the same trajectory and commits the same
    checkpoints as the synchronous path."""
    mnist = get_dataset("mnist", synthetic_train=512, synthetic_test=64)
    model = get_model("linear", num_features=784, num_classes=10)
    kwargs = dict(batch_size=64, learning_rate=1e-2, seed=2, save_every=10)
    a = fit(model, mnist, steps=30, checkpoint_dir=str(tmp_path / "sync"),
            async_save=False, **kwargs)
    b = fit(model, mnist, steps=30, checkpoint_dir=str(tmp_path / "async"),
            async_save=True, **kwargs)
    for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)
    sync_steps = sorted(
        p.name for p in (tmp_path / "sync").iterdir()
        if p.name.startswith("step_")
    )
    async_steps = sorted(
        p.name for p in (tmp_path / "async").iterdir()
        if p.name.startswith("step_")
    )
    assert sync_steps == async_steps and sync_steps


def test_debug_checks_catches_nan(tmp_path):
    """debug_checks=True turns a NaN inside the step into an
    immediate checkify error at step 1 (SURVEY §5 sanitizers row) —
    instead of surfacing steps later as a non-finite loss."""

    class PoisonedSplits:
        x_train = np.zeros((32, 4), np.float32)
        y_train = np.zeros((32,), np.int64)
        x_test = np.zeros((0, 4), np.float32)
        y_test = np.zeros((0,), np.int64)

    PoisonedSplits.x_train[3, 2] = np.nan  # one bad feature row
    model = get_model("linear", num_features=4, num_classes=3)
    with pytest.raises(Exception, match="(?i)nan"):
        fit(model, PoisonedSplits(), steps=5, debug_checks=True)


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_cli_survives_sigkill_and_resumes(tmp_path):
    """Crash-consistency end to end through the CLI: SIGKILL the
    training process mid-run, rerun the same command, and the run
    resumes from the newest committed step and finishes. This is the
    real failure-recovery contract — no cooperative shutdown, no
    atexit hooks, just the commit-marker checkpoint protocol."""
    import os
    import signal
    import subprocess
    import sys
    import time

    ck = tmp_path / "ts"
    yaml_cfg = tmp_path / "cfg.yaml"
    yaml_cfg.write_text(
        "name: kill-test\n"
        "model: linear\n"
        "model_kwargs: {num_features: 784, num_classes: 10}\n"
        "dataset: mnist\n"
        "dataset_kwargs: {synthetic_train: 2048, synthetic_test: 128}\n"
        "steps: 4000\n"
        "batch_size: 128\n"
        "learning_rate: 0.01\n"
        f"checkpoint_dir: {ck}\n"
    )
    from pathlib import Path

    repo_root = Path(__file__).resolve().parents[1]
    env = dict(
        os.environ,
        MLAPI_TPU_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
    )
    cmd = [
        sys.executable, "-m", "mlapi_tpu.train",
        "--config", str(yaml_cfg),
        "--save-every", "200", "--keep-last", "2",
    ]
    # Log to a file, not a PIPE: an undrained pipe can block the child
    # in write() before it ever commits a checkpoint.
    log_path = tmp_path / "run1.log"
    with open(log_path, "wb") as log:
        proc = subprocess.Popen(
            cmd, env=env, stdout=log, stderr=subprocess.STDOUT,
            cwd=repo_root,
        )
    try:
        # Wait for at least one COMMITTED checkpoint, then pull the plug.
        deadline = time.time() + 120
        committed = None
        while time.time() < deadline:
            # Glob BEFORE checking liveness: a trainer that commits and
            # then exits within one poll interval still counts.
            steps = sorted(ck.glob("step_*/MANIFEST.json"))
            if steps:
                committed = steps[-1].parent.name
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f"trainer exited early: {log_path.read_text()[-500:]}"
                )
            time.sleep(0.2)
        assert committed, "no checkpoint committed within 120s"
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # Rerun: must resume (not restart) and complete.
    out = subprocess.run(
        cmd, env=env, capture_output=True, timeout=300, cwd=repo_root,
    )
    assert out.returncode == 0, out.stdout.decode()[-800:]
    text = out.stdout.decode() + out.stderr.decode()
    assert "resuming from" in text, text[-800:]
    import json as _json

    summary = _json.loads(
        [l for l in out.stdout.decode().splitlines() if l.startswith("{")][-1]
    )
    assert summary["steps"] == 4000
    # keep_last=2 retention held across the crash/resume cycle.
    kept = sorted(p.name for p in ck.iterdir() if p.name.startswith("step_"))
    assert len(kept) <= 3, kept  # 2 committed + possibly 1 in-flight
