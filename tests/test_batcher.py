"""Micro-batcher: coalescing guarantee, ordering, error propagation
(SURVEY §4 'serving perf smoke': N concurrent requests must become
<= ceil(N/B) device calls)."""

import asyncio
import math
import threading

import numpy as np
import pytest

from mlapi_tpu.serving.scoring import MicroBatcher

pytestmark = pytest.mark.anyio


class FakeEngine:
    """Engine stub: label = str(first feature), optional blocking gate."""

    max_batch = 16

    def __init__(self):
        self.gate = threading.Event()
        self.gate.set()
        self.batch_sizes: list[int] = []

    def predict_labels(self, batch: np.ndarray):
        self.gate.wait()
        self.batch_sizes.append(len(batch))
        return [str(float(row[0])) for row in batch], np.full(len(batch), 0.5)


async def test_coalesces_to_ceil_n_over_b():
    engine = FakeEngine()
    # max_inflight=1: the plug batch holds the ONLY dispatch slot, and
    # slot-first collection means the collector cannot form another
    # batch until the gate opens — the 48 submits all accumulate in
    # the queue first, making the coalescing count deterministic even
    # on a heavily loaded host (this test used to flake under CPU
    # contention when collection raced the submits).
    batcher = MicroBatcher(
        engine, max_batch=16, max_wait_ms=5.0, max_inflight=1
    )
    await batcher.start()
    try:
        # Plug the dispatch thread so every subsequent submit queues up
        # behind one in-flight batch — deterministic coalescing.
        engine.gate.clear()
        plug = asyncio.create_task(batcher.submit(np.zeros(4)))
        deadline = asyncio.get_running_loop().time() + 10.0
        while batcher.device_calls < 1:  # plug batch is in the executor
            assert asyncio.get_running_loop().time() < deadline, (
                "plug batch never reached the executor"
            )
            await asyncio.sleep(0.01)

        n = 48
        tasks = [
            asyncio.create_task(batcher.submit(np.full(4, i))) for i in range(n)
        ]
        while batcher.requests < n + 1:
            await asyncio.sleep(0.01)
        engine.gate.set()

        results = await asyncio.gather(plug, *tasks)
        assert batcher.device_calls == 1 + math.ceil(n / 16)
        # Every request got its own row's answer back, in order.
        assert [r[0] for r in results[1:]] == [str(float(i)) for i in range(n)]
    finally:
        await batcher.stop()


async def test_single_request_low_latency_path():
    engine = FakeEngine()
    batcher = MicroBatcher(engine, max_wait_ms=0.0)
    await batcher.start()
    try:
        label, prob = await batcher.submit(np.full(4, 7.0))
        assert label == "7.0" and prob == 0.5
        assert batcher.device_calls == 1
        assert engine.batch_sizes == [1]
    finally:
        await batcher.stop()


async def test_engine_error_propagates_to_caller():
    class BoomEngine(FakeEngine):
        def predict_labels(self, batch):
            raise RuntimeError("device exploded")

    batcher = MicroBatcher(BoomEngine(), max_wait_ms=0.0)
    await batcher.start()
    try:
        with pytest.raises(RuntimeError, match="device exploded"):
            await batcher.submit(np.zeros(4))
        # Batcher survives the failure and keeps serving.
        assert batcher.device_calls >= 0
    finally:
        await batcher.stop()


async def test_submit_before_start_rejected():
    batcher = MicroBatcher(FakeEngine())
    with pytest.raises(RuntimeError, match="not started"):
        await batcher.submit(np.zeros(4))
