"""Knowledge distillation (r03 VERDICT "Next" #5): the train step can
learn against a teacher checkpoint's softened logits — the mechanism
that turns an independently-trained speculative-decoding draft into
one that matches its target's distribution."""

import jax
import numpy as np
import pytest

from mlapi_tpu.checkpoint import save_checkpoint
from mlapi_tpu.models import get_model
from mlapi_tpu.train import fit

CFG = dict(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_heads=2,
    max_positions=64,
    compute_dtype="float32",
)
D_CFG = dict(CFG, hidden_size=16, num_layers=1)


class _LmSplits:
    """Tiny in-memory LM dataset (x == y, next-token objective)."""

    def __init__(self, n=256, L=24, seed=0):
        rng = np.random.default_rng(seed)
        # A learnable-but-stochastic pattern: arithmetic sequences mod
        # vocab with 20% random corruption, so the teacher's learned
        # distribution is soft (distillation has something to transfer
        # beyond the hard labels).
        starts = rng.integers(1, 40, size=(n, 1))
        x = ((starts + np.arange(L)) % 60 + 1).astype(np.int32)
        noise = rng.integers(1, 61, size=x.shape).astype(np.int32)
        x = np.where(rng.random(x.shape) < 0.2, noise, x)
        self.x_train = x
        self.y_train = self.x_train
        self.x_test = self.x_train[:32]
        self.y_test = self.x_test
        self.feature_names = ()
        self.vocab = None
        self.source = "synthetic"
        self.extras = {"task": "lm"}


@pytest.fixture(scope="module")
def teacher_checkpoint(tmp_path_factory):
    model = get_model("gpt_lm", **CFG)
    r = fit(model, _LmSplits(), steps=120, batch_size=64,
            learning_rate=3e-3, optimizer="adamw")
    ck = tmp_path_factory.mktemp("teacher") / "ck"
    save_checkpoint(
        ck, r.params, step=120,
        config={"model": "gpt_lm", "model_kwargs": CFG},
    )
    return ck


def _mean_kl(teacher, tp, student, sp, x):
    tl = np.asarray(jax.nn.log_softmax(teacher.apply(tp, x)))
    sl = np.asarray(jax.nn.log_softmax(student.apply(sp, x)))
    return float(np.mean(np.sum(np.exp(tl) * (tl - sl), axis=-1)))


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_distilled_student_matches_teacher_better(teacher_checkpoint):
    from mlapi_tpu.checkpoint import load_checkpoint

    splits = _LmSplits()
    student = get_model("gpt_lm", **D_CFG)
    ind = fit(student, splits, steps=120, batch_size=64,
              learning_rate=3e-3, optimizer="adamw")
    # alpha=0, T=1: the objective IS the measured KL-to-teacher, so
    # the comparison below tests the mechanism, not a tuning choice.
    dist = fit(student, splits, steps=240, batch_size=64,
               learning_rate=3e-3, optimizer="adamw",
               distill_from=str(teacher_checkpoint),
               distill_temperature=1.0, distill_alpha=0.0)
    teacher = get_model("gpt_lm", **CFG)
    tp, _ = load_checkpoint(teacher_checkpoint)
    x = splits.x_test
    kl_ind = _mean_kl(teacher, tp, student, ind.params, x)
    kl_dist = _mean_kl(teacher, tp, student, dist.params, x)
    # The distillation objective IS KL-to-teacher: the distilled
    # student must be measurably closer than the hard-label one.
    assert kl_dist < 0.8 * kl_ind, (kl_dist, kl_ind)
    assert np.isfinite(dist.final_loss)


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_distill_resume_config_guard(teacher_checkpoint, tmp_path):
    """A distilled run's train-state records the teacher; resuming the
    same run works, and the recorded config carries the distillation
    fields (the trajectory-defining hyperparameters)."""
    import json

    splits = _LmSplits()
    student = get_model("gpt_lm", **D_CFG)
    ckdir = tmp_path / "state"
    fit(student, splits, steps=40, batch_size=64, learning_rate=3e-3,
        optimizer="adamw", distill_from=str(teacher_checkpoint),
        checkpoint_dir=str(ckdir), save_every=20, async_save=False)
    steps = sorted(ckdir.glob("step_*/MANIFEST.json"))
    assert steps
    cfg = json.loads(steps[-1].read_text())["config"]
    assert "distill_from_hash" in cfg
    assert cfg["distill_temperature"] == 2.0
    # Resume past the saved step with the same distillation setup.
    r = fit(student, splits, steps=60, batch_size=64,
            learning_rate=3e-3, optimizer="adamw",
            distill_from=str(teacher_checkpoint),
            checkpoint_dir=str(ckdir), save_every=20, async_save=False)
    assert r.steps == 60


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_distill_cli_flag(tmp_path, monkeypatch):
    """--distill-from plumbs through the train CLI (teacher and
    student must share a vocab, so train a 3-step docs-gpt teacher)."""
    from mlapi_tpu.train.__main__ import main

    teacher_out = tmp_path / "teacher"
    out = tmp_path / "draft"
    monkeypatch.setenv("MLAPI_TPU_PLATFORM", "cpu")
    main(["--preset", "docs-gpt", "--steps", "3",
          "--out", str(teacher_out)])
    main([
        "--preset", "docs-gpt-draft-distilled",
        "--steps", "3",
        "--distill-from", str(teacher_out),
        "--out", str(out),
    ])
    assert (out / "MANIFEST.json").exists()
