"""Continuous batching: requests are admitted into a RUNNING decode
batch at chunk boundaries (tier-aligned admission — the design
analyzed in BASELINE.md r03 and built in r03), instead of waiting for
the whole batch to finish.

The load-bearing property is *token-exactness*: a request admitted
mid-batch, into any free row, at any decode position, with any
temperature/seed, produces byte-identical tokens to the same request
run solo through ``generate_text``. That is what per-row pad masks,
per-row position shifts, per-row PRNG streams, and per-row
sampling-step indices buy (``models/gpt.py::_pick_token``,
``admit_scatter_fn``).
"""

import asyncio

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio

CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=96,
    compute_dtype="float32",
)


@pytest.fixture
def anyio_backend():
    return "asyncio"


def _engine(**kw) -> TextGenerationEngine:
    model = get_model("gpt_lm", **CFG)
    return TextGenerationEngine(
        model,
        model.init(jax.random.key(0)),
        tokenizer=ByteTokenizer(),
        chunk=2,  # many admission boundaries even for short runs
        # These tests exercise the CHUNKED path's admission machinery;
        # the batch-1 fused fast path would (correctly) serve the solo
        # requests in one dispatch and never form a joinable batch.
        fused_single=False,
        **kw,
    )


async def _collect(gen) -> list[int]:
    """Drain one request's stream to completion."""
    out: list[int] = []
    while True:
        item = await gen.queue.get()
        if item is None:
            return out
        if isinstance(item, Exception):
            raise item
        out.extend(item["token_ids"])


async def test_admitted_request_matches_solo_run():
    """A request submitted while another is mid-decode joins the
    RUNNING batch (no second batch is started) and its tokens —
    greedy AND seeded-sampled — equal the solo run's."""
    eng = _engine()
    await eng.start()
    try:
        solo_a = eng.generate_text("abcdef", max_new_tokens=40, seed=1)
        solo_b = eng.generate_text(
            "xyz", max_new_tokens=6, temperature=0.9, seed=7, top_k=40
        )
        base_batches = eng.batch_calls

        a = await eng.submit("abcdef", max_new_tokens=40, seed=1)
        first = await a.queue.get()  # prefill done → batch is running
        b = await eng.submit(
            "xyz", max_new_tokens=6, temperature=0.9, seed=7, top_k=40
        )
        got_b = await _collect(b)
        got_a = first["token_ids"] + await _collect(a)

        assert eng.admitted >= 1, "request was not admitted mid-batch"
        assert eng.batch_calls - base_batches == 1, (
            "joiner started its own batch instead of joining"
        )
        assert got_a == solo_a["token_ids"]
        assert got_b == solo_b["token_ids"]
    finally:
        await eng.stop()


async def test_admission_grows_batch_along_pow2_chain():
    """A solo batch (device batch 1) grows 1→2→4 as joiners arrive;
    every output stays exact."""
    eng = _engine(max_batch=4)
    await eng.start()
    try:
        solos = [
            eng.generate_text(
                t, max_new_tokens=n, temperature=temp, seed=s
            )["token_ids"]
            for t, n, temp, s in _REQS
        ]
        gens = []
        first_chunks = []
        for i, (t, n, temp, s) in enumerate(_REQS):
            g = await eng.submit(
                t, max_new_tokens=n, temperature=temp, seed=s
            )
            gens.append(g)
            if i == 0:
                first_chunks.append(await g.queue.get())
        outs = []
        for i, g in enumerate(gens):
            got = await _collect(g)
            if i == 0:
                got = first_chunks[0]["token_ids"] + got
            outs.append(got)
        assert outs == solos
        assert eng.growths >= 1, "batch never grew for the joiners"
        assert eng.admitted >= 1
    finally:
        await eng.stop()


_REQS = [
    ("abcdefabcdef", 48, 0.0, 0),
    ("zz", 8, 0.8, 3),
    ("qqq", 6, 0.0, 0),
    ("mn", 10, 1.1, 11),
]


async def test_incompatible_joiner_waits_for_next_batch():
    """A joiner whose token budget cannot fit the running cache is
    NOT admitted (and NOT truncated): it is swept into its own batch
    after the running one ends, and completes in full."""
    eng = _engine()
    await eng.start()
    try:
        base = eng.batch_calls
        a = await eng.submit("abcd", max_new_tokens=24, seed=2)
        await a.queue.get()
        # 64 new tokens can never fit behind a running cache of
        # total=80 at pos>=17 — must wait.
        b = await eng.submit("xy", max_new_tokens=64)
        got_b = await _collect(b)
        await _collect(a)
        assert len(got_b) == 64, "joiner was truncated, not deferred"
        assert eng.batch_calls - base == 2, (
            "incompatible joiner should have formed a second batch"
        )
    finally:
        await eng.stop()


async def test_swept_incompatible_requests_split_into_batches():
    """Two deferred requests that are window-incompatible WITH EACH
    OTHER (each valid alone) must be re-checked at sweep time and
    served in separate batches — not blindly batched and truncated
    (code-review regression)."""
    eng = _engine()
    eng._strict_admit = True  # force both arrivals to defer
    await eng.start()
    try:
        a = await eng.submit("abcd", max_new_tokens=24)
        await a.queue.get()
        # bucket 64 + 30 fits (94 <= 96); bucket 16 + 70 fits (86);
        # together 64 + 70 = 134 > 96 — incompatible pair.
        r1 = await eng.submit("a" * 40, max_new_tokens=30)
        r2 = await eng.submit("xy", max_new_tokens=70)
        got1 = await _collect(r1)
        got2 = await _collect(r2)
        await _collect(a)
        assert len(got1) == 30, "r1 truncated by an incompatible batch"
        assert len(got2) == 70, "r2 truncated by an incompatible batch"
    finally:
        await eng.stop()


async def test_cancelled_pending_joiner_is_dropped():
    """A request cancelled while waiting for admission is dropped at
    the next boundary without occupying a device row."""
    eng = _engine()
    await eng.start()
    try:
        a = await eng.submit("abcd", max_new_tokens=30)
        await a.queue.get()
        b = await eng.submit("xy", max_new_tokens=4)
        b.cancel()
        await _collect(a)
        assert eng.admitted == 0
    finally:
        await eng.stop()


async def test_strict_mode_gates_unwarmed_shapes():
    """After a full warmup, admission only takes warmed
    (bucket, cache, batch) shapes — anything else defers to the next
    batch instead of compiling mid-run."""
    eng = _engine()
    eng._strict_admit = True  # warmed sets empty → nothing admissible
    await eng.start()
    try:
        base = eng.batch_calls
        a = await eng.submit("abcd", max_new_tokens=24)
        await a.queue.get()
        b = await eng.submit("xy", max_new_tokens=4)
        got_b = await _collect(b)
        await _collect(a)
        assert len(got_b) == 4
        assert eng.admitted == 0, "strict mode admitted an unwarmed shape"
        assert eng.batch_calls - base == 2
    finally:
        await eng.stop()


async def test_warmup_populates_admission_grid(monkeypatch):
    """Full warmup records the admission/growth shape sets and turns
    strict gating on; a subsequent joiner with a warmed shape IS
    admitted under strict mode."""
    monkeypatch.setenv("MLAPI_TPU_WARMUP", "full")
    eng = _engine(max_batch=2, prompt_buckets=(16,))
    eng.warmup()
    assert eng._strict_admit
    assert eng._warmed_joiner == {16}, "joiner prefill bucket not warmed"
    assert eng._warmed_growth, "no growth shapes warmed"
    total = 16 + 32  # bucket + default tier (default_max_new_tokens=32)
    assert (16, total, 1) in eng._warmed_scatter
    assert (1, 2, total) in eng._warmed_growth
    await eng.start()
    try:
        a = await eng.submit("abcd", max_new_tokens=32, seed=4)
        await a.queue.get()
        b = await eng.submit("xy", max_new_tokens=2, seed=9)
        got_b = await _collect(b)
        await _collect(a)
        solo_b = eng.generate_text("xy", max_new_tokens=2, seed=9)
        assert got_b == solo_b["token_ids"]
        assert eng.admitted >= 1, (
            "warmed shape was not admitted under strict mode"
        )
    finally:
        await eng.stop()


async def test_admission_at_nondefault_tier_with_eager_compiles(monkeypatch):
    """With strict gating on, a batch running at a HIGHER cache tier
    than the warmed default still admits joiners when the attach is
    low-RTT: the expensive prefill is warmed per bucket, and the
    trivial scatter is allowed to compile on demand."""
    monkeypatch.setenv("MLAPI_TPU_WARMUP", "full")
    eng = _engine(max_batch=2, prompt_buckets=(16,))
    eng.warmup()
    assert eng._strict_admit
    assert eng._admit_eager  # CPU attach: sub-ms dispatch RTT
    await eng.start()
    try:
        # n_new=48 > default 32 → cache tier 64, total 80: a shape no
        # scatter was warmed for.
        a = await eng.submit("abcd", max_new_tokens=48, seed=2)
        await a.queue.get()
        assert (16, 80, 1) not in eng._warmed_scatter
        b = await eng.submit("xy", max_new_tokens=4, seed=5)
        got_b = await _collect(b)
        await _collect(a)
        solo = eng.generate_text("xy", max_new_tokens=4, seed=5)
        assert got_b == solo["token_ids"]
        assert eng.admitted >= 1, "long-tier batch refused a joiner"
    finally:
        await eng.stop()


def test_window_edge_request_gets_partial_final_chunk():
    """When max_positions clamps the cache, (total - bucket) need not
    be a chunk multiple; the final decode chunk must run at the
    remainder size so a window-edge request still receives every
    token it was promised (code-review regression: the whole-chunk
    stop errored it as truncated — and the pre-r03 loop silently ran
    past the cache end)."""
    model = get_model("gpt_lm", **CFG)
    eng = TextGenerationEngine(
        model, model.init(jax.random.key(0)),
        tokenizer=ByteTokenizer(), chunk=16,
    )
    # 70-char prompt → oversize exact bucket 70; n_new=24 fits the
    # model window (94 <= 96) but total clamps to 96: room is 26 =
    # one 16-chunk + a 10-remainder.
    out = eng.generate_text("x" * 70, max_new_tokens=24)
    assert len(out["token_ids"]) == 24


def test_chunked_prefill_matches_single_prefill():
    """A prompt longer than the largest bucket prefills in
    fixed-width extend_core chunks (one compiled program per cache
    tier, traced offset) and produces the same tokens as a single
    full-width prefill — greedy and seeded-sampled."""
    cfg = dict(CFG, max_positions=320)
    model = get_model("gpt_lm", **cfg)
    params = model.init(jax.random.key(0))
    tok = ByteTokenizer()
    chunked = TextGenerationEngine(
        model, params, tokenizer=tok, chunk=4,
        prompt_buckets=(16, 64, 128),
    )
    wide = TextGenerationEngine(
        model, params, tokenizer=tok, chunk=4,
        prompt_buckets=(16, 64, 256),
    )
    text = "abcdefgh" * 25  # 200 tokens: chunked 2x128 vs one 256
    for kw in (
        dict(max_new_tokens=8),
        dict(max_new_tokens=8, temperature=0.9, seed=4, top_k=30),
    ):
        a = chunked.generate_text(text, **kw)
        b = wide.generate_text(text, **kw)
        assert a["token_ids"] == b["token_ids"], kw
    assert chunked.prefill_chunks == 4  # 2 chunks x 2 runs
    assert wide.prefill_chunks == 0


async def test_staggered_soak_every_stream_exact():
    """Randomized staggered arrivals across buckets, lengths, and
    sampling configs: every stream must match its solo run exactly,
    through any mix of admission, compaction, and growth."""
    rng = np.random.default_rng(0)
    eng = _engine(max_batch=4)
    cases = []
    for i in range(10):
        n = int(rng.integers(2, 30))
        temp = float(rng.choice([0.0, 0.7, 1.2]))
        text = "ab" * int(rng.integers(1, 12))
        cases.append((text, n, temp, i))
    solos = [
        eng.generate_text(t, max_new_tokens=n, temperature=temp, seed=s)[
            "token_ids"
        ]
        for t, n, temp, s in cases
    ]
    await eng.start()
    try:
        gens = []
        for t, n, temp, s in cases:
            gens.append(
                await eng.submit(
                    t, max_new_tokens=n, temperature=temp, seed=s
                )
            )
            await asyncio.sleep(float(rng.uniform(0, 0.02)))
        outs = [await _collect(g) for g in gens]
        assert outs == solos
    finally:
        await eng.stop()
