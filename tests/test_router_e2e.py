"""Scale-out serving end to end: REAL generative engines behind the
prefix-affinity router.

Two layers:

- **In-process fleet** (tier-1): two full ``TextGenerationEngine``
  replicas, each behind its own real-socket HTTP server, fronted by
  the router on a third socket — the complete relay path over real
  chunked HTTP. Pins: streams byte-identical router-vs-direct
  (including the deadline and drain terminal frames), affinity
  measurably beating forced round-robin on the prefix-cache counters
  (``PrefixCache.builds`` — asserted from counters, never
  wall-clock), and drain redistribution without remapping the
  healthy replica's affinity slice.
- **Spawned-process CLI topology** (``slow`` — outside the tier-1
  window's time budget; the chaos-drill profile runs it):
  ``--router --replicas 2`` spawns real replica processes, SIGTERM
  to one flips it draining, the router observes via the cached
  health poll, in-flight streams finish, and the supervisor
  respawns it back to a 2-live fleet.
"""

import asyncio
import json
import socket

import httpx
import jax
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving import faults
from mlapi_tpu.serving.app import build_app
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.serving.router import Router, build_router_app, hrw_order
from mlapi_tpu.serving.server import Server
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.disarm()


# Same tiny config as test_robustness: identical programs, one shared
# in-process compile.
CFG = dict(
    vocab_size=260,
    hidden_size=16,
    num_layers=1,
    num_heads=2,
    max_positions=96,
    compute_dtype="float32",
)

_MODEL = get_model("gpt_lm", **CFG)
_PARAMS = _MODEL.init(jax.random.key(0))


def _engine(**kw) -> TextGenerationEngine:
    kw.setdefault("chunk", 4)
    kw.setdefault("fused_single", False)
    return TextGenerationEngine(
        _MODEL, _PARAMS, tokenizer=ByteTokenizer(), **kw
    )


class _Fleet:
    """Two real engine replicas on real sockets + a router front."""

    def __init__(self):
        self.engines: list[TextGenerationEngine] = []
        self.servers: list[Server] = []
        self.front: Server | None = None
        self.router: Router | None = None

    async def start(self, n: int = 2, **router_kw):
        for _ in range(n):
            eng = _engine()
            # Deadlines must reach the engine (the terminal-frame
            # relay pin), so admission control cannot shed them at
            # the door first.
            srv = Server(
                build_app(eng, admission_control=False),
                host="127.0.0.1", port=0,
            )
            await srv.start()
            self.engines.append(eng)
            self.servers.append(srv)
        self.router = Router(
            [("127.0.0.1", s.port) for s in self.servers], **router_kw
        )
        self.front = Server(
            build_router_app(self.router), host="127.0.0.1", port=0
        )
        await self.front.start()
        return self

    def engine_for(self, replica) -> TextGenerationEngine:
        return self.engines[
            [s.port for s in self.servers].index(replica.port)
        ]

    def prefix_preferring(self, replica, tag: str) -> str:
        """A prefix string whose HRW top choice is ``replica`` — the
        deterministic way to aim traffic in these tests."""
        names = [r.name for r in self.router.replicas]
        for i in range(1000):
            p = f"{tag} system prompt {i}"
            key = p.encode()[: self.router.affinity_prefix_bytes]
            if hrw_order(key, names)[0] == replica.name:
                return p
        raise AssertionError("no preferring prefix found in 1000 tries")

    async def stop(self):
        if self.front is not None:
            await self.front.stop()
        for s in self.servers:
            await s.stop()


@pytest.fixture
async def fleet():
    f = await _Fleet().start()
    yield f
    await f.stop()


def _url(port: int) -> str:
    return f"http://127.0.0.1:{port}"


async def test_streams_byte_identical_router_vs_direct(fleet):
    """The relay contract: an NDJSON stream through the router is
    byte-for-byte the stream a direct client of the replica sees —
    token frames, the done frame, and the deadline terminal frame."""
    payload = {
        "text": "the quick brown fox", "max_new_tokens": 10, "stream": True,
    }
    pref = fleet.router.choose(
        fleet.router.routing_key(json.dumps(payload).encode())
    )
    async with httpx.AsyncClient(timeout=60.0) as c:
        direct = await c.post(f"http://{pref.name}/generate", json=payload)
        via = await c.post(
            _url(fleet.front.port) + "/generate", json=payload
        )
        assert direct.status_code == via.status_code == 200
        assert via.content == direct.content
        assert via.headers["content-type"] == direct.headers["content-type"]
        frames = [json.loads(ln) for ln in via.content.splitlines()]
        assert frames[-1]["done"] is True and frames[-1]["token_ids"]

        # Unary parity too (same engine state, deterministic greedy).
        unary = dict(payload, stream=False)
        d2 = await c.post(f"http://{pref.name}/generate", json=unary)
        v2 = await c.post(_url(fleet.front.port) + "/generate", json=unary)
        assert v2.content == d2.content

        # Deadline terminal frame: an already-expired budget dies at
        # the first dispatch boundary (queued) on both paths — the
        # in-band error frame must relay byte-for-byte.
        dl = dict(payload, deadline_ms=0.001)
        d3 = await c.post(f"http://{pref.name}/generate", json=dl)
        v3 = await c.post(_url(fleet.front.port) + "/generate", json=dl)
        assert v3.content == d3.content
        last = json.loads(v3.content.splitlines()[-1])
        assert last["code"] == "deadline_exceeded"


async def test_drain_terminal_frame_relays_byte_for_byte(fleet):
    """A replica draining mid-stream ends the relayed stream with the
    replica's own DrainCancelled frame, byte-for-byte — the router
    adds nothing and truncates nothing."""
    payload = {"text": "drain me", "max_new_tokens": 64, "stream": True}
    pref = fleet.router.choose(
        fleet.router.routing_key(json.dumps(payload).encode())
    )
    eng = fleet.engine_for(pref)
    lines: list[bytes] = []
    # Slow each decode dispatch so the stream is still mid-flight when
    # the drain lands (the tiny model would otherwise finish all 64
    # tokens before the first relayed line is even consumed).
    with faults.active("decode:delay=0.05"):
        async with httpx.AsyncClient(timeout=60.0) as c:
            async with c.stream(
                "POST", _url(fleet.front.port) + "/generate", json=payload
            ) as resp:
                assert resp.status_code == 200
                drained = False
                async for ln in resp.aiter_lines():
                    lines.append(ln.encode())
                    if not drained:
                        # First chunk arrived: the stream is in
                        # flight. Drain with a tiny budget so it
                        # cancels NOW.
                        drained = True
                        await eng.drain(0.05)
    # The exact frame a direct client sees (serving/app.py builds it
    # from the DrainCancelled exception with fixed text).
    assert lines[-1] == (
        b'{"error": "server draining: generation cancelled", '
        b'"code": "draining"}'
    )
    for ln in lines:
        json.loads(ln)  # nothing truncated mid-line


async def test_affinity_beats_round_robin_on_prefix_counters(fleet):
    """The cache-economics claim, from counters only: with affinity
    routing the fleet pays ONE cold prefill per distinct prefix; with
    forced round-robin every replica pays its own. Asserted on
    ``PrefixCache.builds`` in-process and on the exported
    ``generate.prefix_builds`` /metrics counter."""
    eps = [("127.0.0.1", s.port) for s in fleet.servers]

    async def drive(policy: str, prefixes: list[str]) -> Router:
        router = Router(eps, policy=policy)
        front = Server(build_router_app(router), host="127.0.0.1", port=0)
        await front.start()
        try:
            async with httpx.AsyncClient(timeout=60.0) as c:
                for p in prefixes:
                    for _ in range(2):  # each prefix arrives twice
                        r = await c.post(
                            _url(front.port) + "/generate",
                            json={
                                "text": " go", "prefix": p,
                                "max_new_tokens": 2,
                            },
                        )
                        assert r.status_code == 200, r.text
        finally:
            await front.stop()
        return router

    builds0 = [e.prefix_builds for e in fleet.engines]
    aff = await drive(
        "affinity", [f"affinity shared prompt {i}" for i in range(4)]
    )
    builds1 = [e.prefix_builds for e in fleet.engines]
    rr = await drive(
        "round_robin", [f"rr shared prompt {i}" for i in range(4)]
    )
    builds2 = [e.prefix_builds for e in fleet.engines]

    aff_builds = sum(builds1) - sum(builds0)
    rr_builds = sum(builds2) - sum(builds1)
    # Affinity: one cold build per distinct prefix, fleet-wide; the
    # second arrival is a warm hit on the SAME replica.
    assert aff_builds == 4, (builds0, builds1)
    assert aff.affinity_hits == 8
    assert aff.affinity_fallbacks == 0
    # Round-robin: the second arrival lands on the OTHER replica,
    # which pays the prefill again — 2x the cold builds.
    assert rr_builds == 8, (builds1, builds2)
    assert rr_builds > aff_builds
    # And the counter is exported per replica for the bench to scrape.
    async with httpx.AsyncClient() as c:
        snaps = [
            (await c.get(_url(s.port) + "/metrics")).json()
            for s in fleet.servers
        ]
    assert [
        s["counters"]["generate.prefix_builds"] for s in snaps
    ] == builds2
    # Prefix hits happened only where builds were avoided.
    assert sum(
        s["counters"]["generate.prefix_hits"] for s in snaps
    ) >= 4


async def test_drain_redistributes_without_remapping(fleet):
    """One replica drains: the router's cached health poll observes
    it, new work for its slice falls back to the live replica, the
    live replica's OWN affinity slice never moves (HRW no-remap), and
    nothing needs a failover (the poll catches it before a connect
    does)."""
    router = Router(
        [("127.0.0.1", s.port) for s in fleet.servers],
        health_poll_s=0.05,
    )
    front = Server(build_router_app(router), host="127.0.0.1", port=0)
    await front.start()
    try:
        victim, survivor = router.replicas
        fleet.router = router  # prefix_preferring reads router state
        vic_prefix = fleet.prefix_preferring(victim, "victim")
        sur_prefix = fleet.prefix_preferring(survivor, "survivor")
        vic_eng = fleet.engine_for(victim)
        sur_eng = fleet.engine_for(survivor)

        async with httpx.AsyncClient(timeout=60.0) as c:
            async def gen(prefix):
                r = await c.post(
                    _url(front.port) + "/generate",
                    json={
                        "text": " go", "prefix": prefix,
                        "max_new_tokens": 2,
                    },
                )
                assert r.status_code == 200, r.text
            # Warm both slices: each lands on its preferred replica.
            await gen(vic_prefix)
            await gen(sur_prefix)
            assert vic_eng.requests == 1 and sur_eng.requests == 1

            # Drain the victim; the poll (50 ms cadence) must flip it.
            await vic_eng.drain(0.05)
            for _ in range(100):
                await asyncio.sleep(0.05)
                if router.replicas[0].state == "draining":
                    break
            assert router.replicas[0].state == "draining"

            hits_before = router.affinity_hits
            # The victim's slice redistributes to the survivor...
            await gen(vic_prefix)
            # ...and the survivor's own slice stays put (no remap).
            await gen(sur_prefix)
        assert vic_eng.requests == 1          # no new work while draining
        assert sur_eng.requests == 3
        assert router.affinity_hits == hits_before + 1  # survivor's key
        assert router.affinity_fallbacks >= 1           # victim's key
        assert router.failovers == 0  # the poll caught it, not a failure
    finally:
        await front.stop()
        await router.stop()


async def test_router_faults_conserve_replica_pages():
    """The acceptance sweep for the router↔replica hop on PAGED
    replicas: ``router_forward`` raise at submit, raise mid-stream,
    and delay — every stream ends in a terminal frame, and the
    replicas' page refcounts return to baseline (no request that
    died on the hop may leak its KV pages)."""
    engines = [
        _engine(kv_page_size=8, kv_pages=24) for _ in range(2)
    ]
    servers = []
    for eng in engines:
        srv = Server(
            build_app(eng, admission_control=False),
            host="127.0.0.1", port=0,
        )
        await srv.start()
        servers.append(srv)
    router = Router([("127.0.0.1", s.port) for s in servers])
    front = Server(build_router_app(router), host="127.0.0.1", port=0)
    await front.start()
    try:
        async with httpx.AsyncClient(timeout=60.0) as c:
            for spec in (
                "router_forward:raise",           # at submit → failover
                "router_forward:after=1:raise",   # mid-stream → frame
                "router_forward:delay=0.01",      # slows, never breaks
            ):
                with faults.active(spec):
                    r = await c.post(
                        _url(front.port) + "/generate",
                        json={
                            "text": "fault sweep", "stream": True,
                            "max_new_tokens": 8,
                        },
                    )
                assert r.status_code == 200, (spec, r.text)
                frames = [
                    json.loads(ln)
                    for ln in r.content.decode().strip().splitlines()
                ]
                # Always a terminal frame: the replica's done frame,
                # or the router's upstream_error frame (mid-stream
                # raise tears the upstream connection).
                assert (
                    frames[-1].get("done") is True
                    or frames[-1].get("code") == "upstream_error"
                ), (spec, frames[-1])
        # Page conservation on every replica: cancelled/faulted relays
        # release their rows' pages like any client disconnect.
        for eng in engines:
            for _ in range(100):
                if eng.kv_pages_in_use == 0:
                    break
                await asyncio.sleep(0.05)
            assert eng.kv_pages_in_use == 0
            assert int(eng.pool.ref[1:].sum()) == 0
        # And the fleet serves fresh work afterward.
        async with httpx.AsyncClient(timeout=60.0) as c:
            ok = await c.post(
                _url(front.port) + "/generate",
                json={"text": "after the sweep", "max_new_tokens": 4},
            )
        assert ok.status_code == 200 and ok.json()["token_ids"]
    finally:
        await front.stop()
        for s in servers:
            await s.stop()


# ---------------------------------------------------------------------------
# The spawned-process CLI topology (slow profile: real processes, real
# SIGTERM, supervisor respawn — minutes, not tier-1 seconds).
# ---------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.heavy
def test_cli_router_topology_sigterm_drain(tmp_path):
    """The full ``--router`` lifecycle as processes: spawn, health,
    affinity serving, SIGTERM-drain of one replica observed via the
    router's poll, in-flight stream completion, and supervisor
    respawn back to a 2-live fleet."""
    import os
    import signal
    import subprocess
    import sys
    import time
    import urllib.request

    from mlapi_tpu.checkpoint import save_checkpoint

    ck = tmp_path / "gpt_ck"
    save_checkpoint(
        ck, _PARAMS, step=1,
        config={
            "model": "gpt_lm", "model_kwargs": CFG,
            "tokenizer": ByteTokenizer().fingerprint(),
        },
    )
    port = _free_port()
    env = dict(
        os.environ, MLAPI_TPU_PLATFORM="cpu", MLAPI_TPU_WARMUP="minimal",
    )
    sup = subprocess.Popen(
        [
            sys.executable, "-m", "mlapi_tpu.serving",
            "--checkpoint", str(ck), "--port", str(port),
            "--router", "--replicas", "2",
            "--health-poll-s", "0.2", "--drain-timeout-s", "8",
            "--no-admission-control",
        ],
        env=env,
    )

    def get(p, path, timeout=5.0):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{p}{path}", timeout=timeout
        ) as r:
            return json.loads(r.read())

    def post(p, path, body, timeout=60.0):
        req = urllib.request.Request(
            f"http://127.0.0.1:{p}{path}",
            data=json.dumps(body).encode(),
            headers={"content-type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())

    try:
        # Both replicas polled live behind the router.
        deadline = time.time() + 300
        while time.time() < deadline:
            if sup.poll() is not None:
                pytest.fail(f"supervisor died rc={sup.returncode}")
            try:
                h = get(port, "/healthz", timeout=2)
                if h.get("status") == "ok" and h.get("replicas_live") == 2:
                    break
            except Exception:
                pass
            time.sleep(1.0)
        else:
            pytest.fail("router fleet never became healthy")

        # Serving through the router works; repeated prefixes affine.
        status, out = post(
            port, "/generate",
            {"text": "the quick", "prefix": "cli sys", "max_new_tokens": 4},
        )
        assert status == 200 and out["token_ids"]

        # Aim a stream at a KNOWN replica, then SIGTERM that replica
        # mid-stream: drain must let the stream finish.
        names = [f"127.0.0.1:{port + 1}", f"127.0.0.1:{port + 2}"]
        victim_name = None
        vic_prefix = None
        for i in range(1000):
            p = f"drill prompt {i}"
            if hrw_order(p.encode()[:64], names)[0] == names[0]:
                victim_name, vic_prefix = names[0], p
                break
        assert victim_name is not None
        victim_port = port + 1
        victim_pid = get(victim_port, "/healthz")["pid"]

        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        body = json.dumps(
            {
                "text": " run", "prefix": vic_prefix,
                "max_new_tokens": 48, "stream": True,
            }
        )
        conn.request(
            "POST", "/generate", body,
            {"content-type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        first = resp.readline()  # at least one frame in flight
        assert first.strip()
        os.kill(victim_pid, signal.SIGTERM)
        rest = resp.read()  # drain lets the stream run to completion
        conn.close()
        lines = (first + rest).decode().strip().splitlines()
        frames = [json.loads(ln) for ln in lines]
        assert frames[-1].get("done") is True, frames[-1]

        # The router observed the drain/death and kept serving: the
        # victim's slice redistributes (same prefix, still 200).
        deadline = time.time() + 60
        while time.time() < deadline:
            h = get(port, "/healthz")
            if h["replicas_live"] < 2 or h["replicas_draining"] > 0:
                break
            time.sleep(0.3)
        status, out = post(
            port, "/generate",
            {"text": " go", "prefix": vic_prefix, "max_new_tokens": 4},
        )
        assert status == 200 and out["token_ids"]

        # The supervisor respawns the dead replica; the poll folds it
        # back in (fresh engine boot: generous deadline).
        deadline = time.time() + 300
        while time.time() < deadline:
            try:
                h = get(port, "/healthz", timeout=2)
                if h.get("replicas_live") == 2:
                    break
            except Exception:
                pass
            time.sleep(1.0)
        else:
            pytest.fail("drained replica never respawned to live")

        # Aggregated metrics carry the story: summed engine counters
        # plus router counters. (The respawned replica's counters
        # restarted from zero with its process — aggregation sums
        # what the CURRENT fleet reports, so only the survivor's
        # traffic is guaranteed visible.)
        m = get(port, "/metrics")
        assert m["counters"]["router.forwarded"] >= 3
        assert m["counters"]["generate.requests"] >= 1
        assert m["counters"]["router.affinity_hits"] >= 1
        assert m["gauges"]["router.replicas_live"] == 2
    finally:
        sup.send_signal(signal.SIGTERM)
        try:
            sup.wait(30)
        except subprocess.TimeoutExpired:
            sup.kill()
            sup.wait(10)
