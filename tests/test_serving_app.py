"""API contract tests against the ASGI app (in-process, httpx).

Mirrors what the reference *would* test (SURVEY §4): the `/predict`
schema/response contract of ``main.py:16-27`` and the `/files/`
multipart contract of ``main.py:29-38`` — plus the subsystems the
reference lacked (health, metrics, clean errors)."""

import asyncio

import httpx
import pytest

from mlapi_tpu.checkpoint import save_checkpoint
from mlapi_tpu.datasets import load_iris
from mlapi_tpu.models import get_model
from mlapi_tpu.serving import InferenceEngine, build_app
from mlapi_tpu.train import fit

SETOSA = {
    "sepal_length": 5.1,
    "sepal_width": 3.5,
    "petal_length": 1.4,
    "petal_width": 0.2,
}


@pytest.fixture(scope="module")
def iris_checkpoint(tmp_path_factory):
    iris = load_iris()
    model = get_model(
        "linear", num_features=iris.num_features, num_classes=iris.num_classes
    )
    result = fit(model, iris, steps=300, learning_rate=0.1, weight_decay=1e-3)
    path = tmp_path_factory.mktemp("ckpt") / "iris"
    save_checkpoint(
        path,
        result.params,
        step=result.steps,
        config={
            "model": "linear",
            "model_kwargs": {
                "num_features": iris.num_features,
                "num_classes": iris.num_classes,
            },
            "feature_names": list(iris.feature_names),
        },
        vocab=iris.vocab,
    )
    return path


pytestmark = pytest.mark.anyio


@pytest.fixture()
async def client(iris_checkpoint):
    engine = InferenceEngine.from_checkpoint(iris_checkpoint)
    app = build_app(engine, max_wait_ms=0.0)
    await app.startup()
    transport = httpx.ASGITransport(app=app)
    async with httpx.AsyncClient(
        transport=transport, base_url="http://test"
    ) as c:
        yield c
    await app.shutdown()


async def test_predict_contract(client):
    r = await client.post("/predict", json=SETOSA)
    assert r.status_code == 200
    body = r.json()
    assert set(body) == {"prediction", "probability"}
    assert body["prediction"] == "Iris-setosa"
    assert 0.8 < body["probability"] <= 1.0


async def test_predict_coerces_numeric_strings(client):
    # pydantic coerces "5.1" -> 5.1, same as the reference's pydantic v1.
    r = await client.post("/predict", json={k: str(v) for k, v in SETOSA.items()})
    assert r.status_code == 200
    assert r.json()["prediction"] == "Iris-setosa"


async def test_predict_missing_field_422(client):
    bad = dict(SETOSA)
    del bad["petal_width"]
    r = await client.post("/predict", json=bad)
    assert r.status_code == 422
    detail = r.json()["detail"]
    assert any("petal_width" in str(item.get("loc", "")) for item in detail)


async def test_predict_nonpositive_deadline_422(client):
    # Same contract as /generate: 0 would silently mean "no deadline"
    # and a negative budget would burn a queue slot just to 504.
    for bad_ms in (0, -5):
        r = await client.post(
            "/predict", json={**SETOSA, "deadline_ms": bad_ms}
        )
        assert r.status_code == 422, r.text
        detail = r.json()["detail"]
        assert any(
            "deadline_ms" in str(item.get("loc", "")) for item in detail
        )


async def test_predict_non_numeric_422(client):
    r = await client.post("/predict", json={**SETOSA, "sepal_length": "wide"})
    assert r.status_code == 422


async def test_invalid_json_400(client):
    r = await client.post("/predict", content=b"{not json")
    assert r.status_code == 400


async def test_unknown_route_404_and_wrong_method_405(client):
    assert (await client.post("/nope", json={})).status_code == 404
    assert (await client.get("/predict")).status_code == 405


async def test_files_roundtrip(client):
    csv = b"sepal_length,species\n5.1,Iris-setosa\n6.2,Iris-virginica\n"
    r = await client.post(
        "/files/",
        files={"file": ("iris.csv", csv, "text/csv")},
        data={"token": "tok123"},
    )
    assert r.status_code == 200
    body = r.json()
    assert body["token"] == "tok123"
    assert body["file"]["columns"] == ["sepal_length", "species"]
    assert body["file"]["rows"] == 2
    assert body["file"]["records"][0]["species"] == "Iris-setosa"
    assert body["file"]["truncated"] is False


async def test_files_missing_token_422(client):
    r = await client.post("/files/", files={"file": ("a.csv", b"a\n1\n")})
    assert r.status_code == 422


async def test_files_non_utf8_400(client):
    r = await client.post(
        "/files/",
        files={"file": ("a.csv", b"\xff\xfe\x00bad")},
        data={"token": "t"},
    )
    assert r.status_code == 400


async def test_healthz_and_metrics(client):
    await client.post("/predict", json=SETOSA)
    h = (await client.get("/healthz")).json()
    assert h["status"] == "ok"
    assert h["classes"] == ["Iris-setosa", "Iris-versicolor", "Iris-virginica"]
    m = (await client.get("/metrics")).json()
    assert m["counters"]["batcher.requests"] >= 1
    route_keys = [k for k in m["histograms"] if "/predict" in k]
    assert route_keys and m["histograms"][route_keys[0]]["count"] >= 1


async def test_error_responses_are_counted_in_metrics(client):
    """4xx/5xx traffic must be visible in /metrics — handler-raised
    HTTPErrors unwind through the metrics middleware."""
    await client.post("/predict", json={"sepal_length": "nope"})  # 422
    await client.post("/predict", content=b"{broken")  # 400
    m = (await client.get("/metrics")).json()
    statuses = {
        k: v for k, v in m["counters"].items() if "/predict" in k
    }
    assert any("status=422" in k for k in statuses), statuses
    assert any("status=400" in k for k in statuses), statuses


async def test_concurrent_predictions_all_resolve(client):
    rs = await asyncio.gather(
        *(client.post("/predict", json=SETOSA) for _ in range(32))
    )
    assert all(r.status_code == 200 for r in rs)
    assert all(r.json()["prediction"] == "Iris-setosa" for r in rs)


async def test_array_schema_for_unnamed_features(tmp_path):
    """Models without named features (MNIST-family) serve via
    {"features": [...]} with length validation."""
    import jax

    from mlapi_tpu.models import get_model
    from mlapi_tpu.utils.vocab import LabelVocab

    model = get_model("mlp", num_features=16, num_classes=3, hidden_dims=(8,))
    engine = InferenceEngine(
        model,
        model.init(jax.random.key(0)),
        LabelVocab(labels=("a", "b", "c")),
        feature_names=(),
        buckets=(1, 2, 4),
    )
    app = build_app(engine, max_wait_ms=0.0)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(transport=transport, base_url="http://t") as c:
            ok = await c.post("/predict", json={"features": [0.1] * 16})
            assert ok.status_code == 200
            assert ok.json()["prediction"] in ("a", "b", "c")
            bad = await c.post("/predict", json={"features": [0.1] * 5})
            assert bad.status_code == 422
            detail = bad.json()["detail"]  # FastAPI-shaped list
            assert detail[0]["loc"] == ["features"]
            assert "expected 16 features" in detail[0]["msg"]
    finally:
        await app.shutdown()


async def test_openapi_and_docs(iris_checkpoint):
    """Parity with FastAPI's free schema surface (reference main.py:8):
    /openapi.json describes the real routes + body models, /docs is a
    self-contained HTML page (no CDN — air-gapped)."""
    iris_engine = InferenceEngine.from_checkpoint(iris_checkpoint)
    app = build_app(iris_engine)
    transport = httpx.ASGITransport(app=app)
    async with httpx.AsyncClient(
        transport=transport, base_url="http://test"
    ) as client:
        r = await client.get("/openapi.json")
        assert r.status_code == 200
        doc = r.json()
        assert doc["openapi"].startswith("3.")
        assert "/predict" in doc["paths"]
        post = doc["paths"]["/predict"]["post"]
        ref = post["requestBody"]["content"]["application/json"]["schema"]
        name = ref["$ref"].rsplit("/", 1)[1]
        schema = doc["components"]["schemas"][name]
        # The Iris feature schema is fully described: 4 required floats.
        assert set(schema["required"]) == set(iris_engine.feature_names)
        assert all(
            schema["properties"][f]["type"] == "number"
            for f in iris_engine.feature_names
        )
        assert "422" in post["responses"]
        # Multipart route documented via the explicit form contract.
        files_op = doc["paths"]["/files/"]["post"]
        assert "multipart/form-data" in files_op["requestBody"]["content"]
        # Docs page: self-contained HTML that references the schema.
        d = await client.get("/docs")
        assert d.status_code == 200
        assert d.headers["content-type"].startswith("text/html")
        assert "/openapi.json" in d.text
        assert "http://" not in d.text.replace("http://test", "")  # no CDN


async def test_405_carries_allow_header_and_options_works(client):
    """RFC 9110: 405 MUST list allowed methods; OPTIONS advertises
    them without invoking the handler."""
    r = await client.get("/predict")
    assert r.status_code == 405
    assert r.headers.get("allow") == "POST, OPTIONS"
    o = await client.request("OPTIONS", "/predict")
    assert o.status_code == 204
    assert o.headers.get("allow") == "POST, OPTIONS"
    assert "content-type" not in o.headers
    # /healthz is GET-only; POST to it must advertise GET.
    p = await client.post("/healthz", json={})
    assert p.status_code == 405
    assert p.headers.get("allow") == "GET, OPTIONS"
