"""Multi-model serving (r22): the scoring fast path, per-tenant
quotas, and weighted-slack fairness — ROADMAP item 1.

What this module pins, all from counters (never wall-clock):

- **Identity**: a generative engine's greedy stream is byte-identical
  to its solo run while a co-resident ScorePath rides its unit queue
  — across {gpt, llama} x {paged, contiguous}. Score units change
  dispatch ORDER, never tokens.
- **Throughput**: the folded scoring path still coalesces N requests
  into <= ceil(N/B) device calls (requests/device_calls >= 3 with a
  formed batch >= 8 rows) — the batched-vs-serial half of the
  acceptance bar, from dispatch counts.
- **One scheduler**: co-resident scoring batches ride the generative
  UnitScheduler as typed ``score`` units (``sched_dispatches`` ==
  ``device_calls``), and the trace shows decode units dispatching
  AFTER score units — neither direction starves the other.
- **Quota pin**: a tenant at its page quota defers (counted per
  tenant AND on the engine) while another tenant's stream completes
  untouched; the deferred group runs after the release — eviction of
  a peer's pages never happens.
- **Tenant brownout first**: one hot tenant's depth clamps ITS
  oversized budgets while the fleet-wide ladder stays at rung 0 and
  an idle tenant keeps its full budget.
- **Surface**: per-model routes, /healthz ``models`` block, and the
  ``model.<id>.*`` / ``tenant.<t>.*`` metric families exist in
  multi-model mode — and do NOT exist in single-model mode (the
  one-entry registry is bit-identical to r21).

Same tiny-model CFG and engine shapes as test_paged_kv/test_scheduler
ON PURPOSE: the module shares the conftest ``paged-family`` cache
window, so registry traffic re-drives the family's compiled
prefill/decode programs instead of re-paying the ladder.
"""

import asyncio
import math
import threading
import types

import httpx
import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving import faults
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.serving.registry import ModelRegistry, TenantLedger
from mlapi_tpu.serving.scoring import ScorePath
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)


def _model(kind="gpt_lm"):
    kw = dict(CFG)
    if kind == "llama_lm":
        kw["num_kv_heads"] = 2  # GQA: 4 query heads over 2 KV heads
    return get_model(kind, **kw)


@pytest.fixture(scope="module")
def gpt_params():
    return _model().init(jax.random.key(0))


@pytest.fixture(scope="module")
def llama_params():
    return _model("llama_lm").init(jax.random.key(0))


def _engine(model, params, paged=True, **kw):
    kw.setdefault("chunk", 2)
    # Pin the chunked lifecycle (same as test_scheduler): fused fast
    # paths would collapse a lane to one opaque unit.
    kw.setdefault("fused_single", False)
    # Window 0: formation driven by queue order alone — deterministic.
    kw.setdefault("max_wait_ms", 0.0)
    if paged:
        kw.setdefault("kv_page_size", 8)
    return TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), **kw,
    )


class _ScoreStub:
    """Scoring-engine stub for the path-level tests: label =
    str(first feature), optional blocking gate, batch sizes recorded
    (the test_batcher idiom)."""

    max_batch = 16

    def __init__(self):
        self.gate = threading.Event()
        self.gate.set()
        self.batch_sizes: list[int] = []

    def predict_labels(self, batch: np.ndarray):
        self.gate.wait()
        self.batch_sizes.append(len(batch))
        return (
            [str(float(row[0])) for row in batch],
            np.full(len(batch), 0.5),
        )


class _TabStub(_ScoreStub):
    """Enough surface for build_app's registry loop + /predict +
    /healthz: a tabular 4-feature binary classifier."""

    kind = "tabular"
    feature_names = ("f0", "f1", "f2", "f3")
    num_features = 4
    meta = {"stub": True}

    def __init__(self):
        super().__init__()
        self.model = self
        self.vocab = types.SimpleNamespace(labels=["neg", "pos"])

    def warmup(self, full=False):
        pass

    def predict_labels(self, batch: np.ndarray):
        self.gate.wait()
        self.batch_sizes.append(len(batch))
        labels = ["pos" if row[0] > 0 else "neg" for row in batch]
        return labels, np.full(len(batch), 0.75)


async def _collect(req):
    """(tokens, terminal_error_or_None) — errors are in-band."""
    out: list[int] = []
    while True:
        item = await req.queue.get()
        if item is None:
            return out, None
        if isinstance(item, Exception):
            return out, item
        out.extend(item["token_ids"])


async def _wait_for(pred, timeout_s: float = 60.0,
                    interval_s: float = 0.005) -> None:
    """Condition-based wait (MLA006 discipline): generous deadline,
    loud failure — never a tuned iteration budget."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s
    while not pred():
        if loop.time() >= deadline:
            raise AssertionError(
                f"condition never became true within {timeout_s}s"
            )
        await asyncio.sleep(interval_s)


# Two groups the collector can NEVER window together (and a pending
# group can never join the other's lane): max(bucket) + max(n_new) =
# 128 + 34 > 160 = max_positions, while each alone fits.
_SHORT = ("hello world", 34)
_LONG = ("x" * 100, 8)


# --- identity: scoring co-resident never changes tokens ----------------


@pytest.mark.parametrize(
    "kind,paged",
    [
        ("gpt_lm", True),
        ("gpt_lm", False),
        ("llama_lm", True),
        ("llama_lm", False),
    ],
)
async def test_streams_identical_with_scoring_coresident(
    kind, paged, gpt_params, llama_params
):
    """Greedy multi-vs-solo identity across the model x layout
    matrix: the same engine's solo greedy run, then the same request
    streamed while score units interleave between its decode chunks
    (decode delay armed so the overlap provably happens) — tokens
    byte-identical, and the score units demonstrably rode the unit
    queue."""
    params = gpt_params if kind == "gpt_lm" else llama_params
    eng = _engine(_model(kind), params, paged=paged, sched_max_batches=2)
    ref = eng.generate_text(_SHORT[0], max_new_tokens=16)["token_ids"]
    sp = ScorePath(
        _ScoreStub(), model_id="clf", max_wait_ms=0.0,
        sched_source=lambda: eng.sched,
    )
    await eng.start()
    await sp.start()
    try:
        faults.arm("decode:every=1:delay=0.01")
        r = await eng.submit(_SHORT[0], max_new_tokens=16, stream=True)
        await _wait_for(lambda: eng.sched_batches_live == 1)
        out = await asyncio.gather(
            *[sp.submit(np.full(4, float(i))) for i in range(4)]
        )
        assert [label for label, _ in out] == [
            str(float(i)) for i in range(4)
        ]
        toks, err = await _collect(r)
        assert err is None
        assert toks == ref
        # Counter evidence the scoring traffic used the ONE scheduler.
        assert sp.sched_dispatches == sp.device_calls >= 1
        assert eng.sched_units_score == sp.sched_dispatches
    finally:
        faults.disarm()
        await sp.stop()
        await eng.stop()


# --- throughput: coalescing from dispatch counts -----------------------


async def test_scoring_batched_throughput_vs_serial():
    """The acceptance ratio, from counters: with one batch plugging
    the only dispatch slot, 24 queued requests coalesce into 2 more
    device calls — requests/device_calls >= 3 (serial would be 1.0)
    with a formed batch >= 8 rows."""
    stub = _ScoreStub()
    sp = ScorePath(stub, max_batch=16, max_wait_ms=5.0, max_inflight=1)
    await sp.start()
    try:
        stub.gate.clear()
        plug = asyncio.create_task(sp.submit(np.zeros(4)))
        await _wait_for(lambda: sp.device_calls >= 1)
        n = 24
        tasks = [
            asyncio.create_task(sp.submit(np.full(4, float(i))))
            for i in range(n)
        ]
        await _wait_for(lambda: sp.requests >= n + 1)
        stub.gate.set()
        results = await asyncio.gather(plug, *tasks)
        assert sp.device_calls == 1 + math.ceil(n / 16)
        assert sp.requests / sp.device_calls >= 3.0
        assert max(stub.batch_sizes) >= 8
        assert [r[0] for r in results[1:]] == [
            str(float(i)) for i in range(n)
        ]
    finally:
        await sp.stop()


# --- one scheduler: score units interleave, nobody starves -------------


async def test_score_units_interleave_with_decode(gpt_params):
    """Score units dispatch BETWEEN decode chunks of a live lane:
    every scoring batch rides the unit queue (sched_dispatches ==
    device_calls), decode units keep dispatching after score units
    (trace order — generation not starved), and the scoring results
    resolve while the lane is still producing (scoring not starved)."""
    eng = _engine(_model(), gpt_params, sched_max_batches=2)
    sp = ScorePath(
        _ScoreStub(), model_id="clf", max_wait_ms=0.0,
        sched_source=lambda: eng.sched,
    )
    await eng.start()
    await sp.start()
    try:
        faults.arm("decode:every=1:delay=0.02")
        r = await eng.submit(_SHORT[0], max_new_tokens=24, stream=True)
        await _wait_for(lambda: eng.sched_batches_live == 1)
        decode_before = eng.sched_units_decode
        for i in range(5):
            label, prob = await sp.submit(np.full(4, float(i)))
            assert label == str(float(i))
        # All five resolved while the delayed lane was still live —
        # the lane never starved scoring out.
        assert eng.sched_batches_live == 1
        toks, err = await _collect(r)
        assert err is None and len(toks) == 24
        assert sp.sched_dispatches == sp.device_calls == 5
        assert eng.sched_units_score == 5
        # ... and scoring never starved decode: decode units kept
        # dispatching after the first score unit.
        kinds = [k for _, k in eng.sched.trace]
        first_score = kinds.index("score")
        assert "decode" in kinds[first_score + 1:]
        assert eng.sched_units_decode > decode_before
    finally:
        faults.disarm()
        await sp.stop()
        await eng.stop()


# --- quota pin: defer the tenant, never evict the peer -----------------


async def test_tenant_quota_defers_not_evicts(gpt_params):
    """Tenant A at its page quota: A's second group defers (counted
    on the engine AND in A's ledger row) while A's first lane streams
    on and tenant B's stream starts and completes untouched. The
    deferral is the QUOTA's (the pool-wide gate never fired), and the
    deferred group runs to completion after A's release — pages move
    by lane retirement, never by evicting B."""
    eng = _engine(_model(), gpt_params, sched_max_batches=3)
    await eng.start()
    try:
        faults.arm("decode:every=1:delay=0.02")
        ra1 = await eng.submit(
            _SHORT[0], max_new_tokens=_SHORT[1], stream=True, tenant="a"
        )
        await _wait_for(lambda: eng.sched_batches_live == 1)
        held = eng.sched._lanes[0].tenant_pages["a"]
        assert held > 0
        # Quota = exactly what A already holds: any growth is over.
        led = TenantLedger(quota_pages={"a": held})
        eng.tenants = led
        ra2 = await eng.submit(
            _LONG[0], max_new_tokens=_LONG[1], stream=True, tenant="a"
        )
        await _wait_for(lambda: eng.sched_tenant_pages_deferred >= 1)
        assert led.deferrals("a") >= 1
        # B starts as a second lane while A's group waits: three lane
        # slots, so ONLY the quota is what defers A.
        rb = await eng.submit(
            "y" * 100, max_new_tokens=8, stream=True, tenant="b"
        )
        tb, eb = await _collect(rb)
        assert eb is None and len(tb) == 8
        assert led.deferrals("b") == 0
        # The pool itself never said no — the distinction the per-
        # tenant counter exists for.
        assert eng.sched_pages_deferred == 0
        faults.disarm()
        (t1, e1), (t2, e2) = await asyncio.gather(
            _collect(ra1), _collect(ra2)
        )
        assert e1 is None and e2 is None
        assert len(t1) == _SHORT[1] and len(t2) == _LONG[1]
        await _wait_for(lambda: eng.kv_pages_in_use == 0)
    finally:
        faults.disarm()
        await eng.stop()


# --- tenant brownout engages before the fleet ladder -------------------


async def test_tenant_brownout_before_fleet(gpt_params):
    """One hot tenant's live depth clamps ITS oversized budget while
    the fleet-wide brownout ladder reads rung 0 and an idle tenant
    keeps its full budget — the tenant degrades itself before it
    degrades anyone."""
    eng = _engine(_model(), gpt_params, max_queue=8)
    led = TenantLedger()
    eng.tenants = led
    await eng.start()
    try:
        # Manufacture tenant depth (2 * 4 >= max_queue 8) with the
        # queue itself empty — exactly the split the rung order is
        # about: tenant pressure without fleet pressure.
        led.enter("a")
        led.enter("a")
        ra = await eng.submit(_SHORT[0], max_new_tokens=64, tenant="a")
        assert ra.n_new == eng.default_max_new_tokens
        assert eng.brownout_tenant_clamped == 1
        assert led.brownouts("a") == 1
        assert eng._brownout_level() == 0   # fleet ladder untouched
        assert eng.brownout_tokens_clamped == 0
        toks, err = await _collect(ra)
        assert err is None
        assert len(toks) == eng.default_max_new_tokens
        # The idle tenant at the same instant: full budget.
        rb = await eng.submit(_SHORT[0], max_new_tokens=40, tenant="b")
        assert rb.n_new == 40
        tb, eb = await _collect(rb)
        assert eb is None and len(tb) == 40
        assert led.brownouts("b") == 0
    finally:
        await eng.stop()


# --- the app surface: routes, healthz, metric families -----------------


async def test_app_multi_model_routes_metrics_healthz(gpt_params):
    """One app over a two-entry registry: per-model routes answer,
    /healthz advertises the model map (what the router's candidate
    filter polls), scoring requests ride the generative scheduler,
    and /metrics grows the model.<id>.* and tenant.<t>.* families."""
    from mlapi_tpu.serving.app import build_app

    gen = _engine(_model(), gpt_params)
    clf = _TabStub()
    models = ModelRegistry({"default": gen, "clf": clf})
    led = TenantLedger(quota_pages={"acme": 64})
    app = build_app(models=models, tenants=led)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as client:
            hz = (await client.get("/healthz")).json()
            assert hz["models"] == {
                "clf": {"kind": "tabular", "default": False},
                "default": {"kind": "generative", "default": True},
            }
            r = await client.post(
                "/models/clf/predict",
                json={"f0": 1.0, "f1": 0.0, "f2": 0.0, "f3": 0.0},
            )
            assert r.status_code == 200
            body = r.json()
            assert body["prediction"] == "pos"
            assert body["probability"] == 0.75
            for path in ("/generate", "/models/default/generate"):
                r = await client.post(
                    path,
                    json={"text": "hi", "max_new_tokens": 4,
                          "tenant": "acme"},
                )
                assert r.status_code == 200
                assert len(r.json()["token_ids"]) == 4
            # Exercise the tenant export path directly: live traffic
            # above balanced its depth back to zero (enter/exit), and
            # the snapshot only lists tenants WITH history.
            led.note_deferral("acme")
            m = (await client.get("/metrics")).json()
            c, g = m["counters"], m["gauges"]
            assert c["model.default.requests"] == 2
            assert c["model.clf.requests"] == 1
            assert c["model.clf.device_calls"] >= 1
            # The one-scheduler claim, end to end through the app:
            # every clf dispatch rode default's unit queue.
            assert (
                c["model.clf.sched_dispatches"]
                == c["model.clf.device_calls"]
                == c["model.default.sched_units_score"]
            )
            assert g["model.default.queue_depth"] == 0
            assert c["tenant.acme.deferrals"] == 1
            assert g["tenant.acme.depth"] == 0
    finally:
        await app.shutdown()


async def test_single_model_surface_unchanged(gpt_params):
    """A one-entry registry is bit-identical to r21: no per-model
    routes, no models block in /healthz, no model.*/tenant.* metric
    families."""
    from mlapi_tpu.serving.app import build_app

    app = build_app(_engine(_model(), gpt_params))
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as client:
            hz = (await client.get("/healthz")).json()
            assert "models" not in hz
            r = await client.post(
                "/models/default/generate",
                json={"text": "hi", "max_new_tokens": 2},
            )
            assert r.status_code == 404
            m = (await client.get("/metrics")).json()
            keys = set(m["counters"]) | set(m.get("gauges", {}))
            assert not any(
                k.startswith(("model.", "tenant.")) for k in keys
            )
    finally:
        await app.shutdown()


# --- soak: sustained mixed traffic (demoted from the tier-1 window) ----


@pytest.mark.slow
@pytest.mark.heavy
async def test_mixed_soak_generation_with_scoring(gpt_params):
    """Sustained mixed rounds — generation waves with scoring bursts
    riding the same unit queue — complete exactly, with every scoring
    dispatch on the scheduler backend and the ledger balanced back to
    zero depth. Duplicates the functional coverage above at iteration
    count (hence slow-marked, outside the 870 s window)."""
    eng = _engine(_model(), gpt_params, sched_max_batches=2)
    led = TenantLedger(weights={"a": 2.0})
    eng.tenants = led
    sp = ScorePath(
        _ScoreStub(), model_id="clf", max_wait_ms=0.0,
        sched_source=lambda: eng.sched,
    )
    await eng.start()
    await sp.start()
    try:
        rounds, per_round = 6, 20
        for rnd in range(rounds):
            ra = await eng.submit(
                _SHORT[0], max_new_tokens=12, stream=True, tenant="a"
            )
            rb = await eng.submit(
                _LONG[0], max_new_tokens=6, stream=True, tenant="b"
            )
            scores = await asyncio.gather(
                *[
                    sp.submit(np.full(4, float(i)))
                    for i in range(per_round)
                ]
            )
            assert [s[0] for s in scores] == [
                str(float(i)) for i in range(per_round)
            ]
            (ta, ea), (tb, eb) = await asyncio.gather(
                _collect(ra), _collect(rb)
            )
            assert ea is None and eb is None
            assert len(ta) == 12 and len(tb) == 6
        assert sp.requests == rounds * per_round
        assert sp.sched_dispatches == sp.device_calls
        assert eng.sched_units_score == sp.sched_dispatches
        assert led.depth("a") == 0 and led.depth("b") == 0
        await _wait_for(lambda: eng.kv_pages_in_use == 0)
    finally:
        await sp.stop()
        await eng.stop()
