"""Batched speculative decoding (`ops/speculative.py` batched path):
per-row cache write positions let a WHOLE BATCH of greedy streams
speculate in lockstep rounds while each row advances by its own
acceptance length — the layout change the scalar-``pos`` design
deliberately deferred (rowpos support in `models/gpt.py`'s
`cached_attend` / mask helpers).

The pin is the same as single-row speculation: every row's emitted
stream is byte-identical to its SOLO plain greedy stream, for any
draft quality — desynchronized rows must not leak into each other's
cache or mask."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.speculative import (
    speculative_generate,
    speculative_generate_batched,
)

T_CFG = dict(
    vocab_size=260, hidden_size=48, num_layers=3, num_heads=4,
    max_positions=160, compute_dtype="float32",
)
D_CFG = dict(
    vocab_size=260, hidden_size=24, num_layers=1, num_heads=2,
    max_positions=160, compute_dtype="float32",
)


def _solo_refs(model, params, prompts, n):
    return [
        np.asarray(
            model.generate(
                params, jnp.asarray(p[None]), max_new_tokens=n
            )
        )[0].tolist()
        for p in prompts
    ]


@pytest.mark.parametrize("k", [1, 3])
def test_every_row_matches_its_solo_greedy_stream(k):
    """Random draft + random target, 3 different prompts: rows accept
    different lengths each round (desync from round one) and every
    stream must still equal its solo run exactly."""
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    prompts = np.stack([
        (np.arange(9, dtype=np.int32) % 200) + 3,
        (np.arange(9, dtype=np.int32)[::-1] % 180) + 7,
        (np.full(9, 42, dtype=np.int32)),
    ])
    n = 22
    refs = _solo_refs(target, tp, prompts, n)
    got, stats = speculative_generate_batched(
        target, tp, draft, dp, prompts, max_new_tokens=n, k=k,
    )
    assert got == refs, (k, stats)
    assert all(len(g) == n for g in got)


def test_batched_matches_single_row_library():
    """The batched path and the single-row library emit identical
    streams for the same row (same round algebra, different cache
    layout)."""
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(2))
    dp = draft.init(jax.random.key(3))
    prompt = (np.arange(8, dtype=np.int32) % 150) + 5
    solo, _ = speculative_generate(
        target, tp, draft, dp, prompt[None], max_new_tokens=18, k=3,
    )
    batched, _ = speculative_generate_batched(
        target, tp, draft, dp, prompt[None], max_new_tokens=18, k=3,
    )
    assert batched[0] == solo


def test_draft_equals_target_full_acceptance_batched():
    target = get_model("gpt_lm", **T_CFG)
    tp = target.init(jax.random.key(0))
    prompts = np.stack([
        (np.arange(7, dtype=np.int32) % 150) + 5,
        (np.arange(7, dtype=np.int32) % 90) + 11,
    ])
    n = 21
    refs = _solo_refs(target, tp, prompts, n)
    got, stats = speculative_generate_batched(
        target, tp, target, tp, prompts, max_new_tokens=n, k=4,
    )
    assert got == refs
    assert stats.acceptance_rate == 1.0, stats


def test_llama_family_batched():
    cfg = dict(T_CFG, hidden_size=32, num_layers=2)
    cfg.pop("num_heads")
    target = get_model("llama_lm", **cfg, num_heads=4, num_kv_heads=2)
    tp = target.init(jax.random.key(0))
    prompts = np.stack([
        (np.arange(6, dtype=np.int32) % 120) + 3,
        (np.arange(6, dtype=np.int32) % 77) + 9,
    ])
    n = 12
    refs = _solo_refs(target, tp, prompts, n)
    got, stats = speculative_generate_batched(
        target, tp, target, tp, prompts, max_new_tokens=n, k=2,
    )
    assert got == refs
    assert stats.acceptance_rate == 1.0


def test_window_headroom_validated():
    cfg = dict(T_CFG, max_positions=32)
    target = get_model("gpt_lm", **cfg)
    tp = target.init(jax.random.key(0))
    prompts = (np.arange(8, dtype=np.int32) % 100)[None] + 3
    with pytest.raises(ValueError, match="cache slots"):
        speculative_generate_batched(
            target, tp, target, tp, prompts, max_new_tokens=24, k=4,
        )


def test_sampled_batched_rows_match_fused_solo():
    """Batched SAMPLED speculation: every row is byte-identical to
    its solo fused-sampled run (same tagged-stream discipline, same
    usable=0 budget-capped rounds) — per-row seeds, desynchronized
    positions and all."""
    from mlapi_tpu.ops.speculative import (
        speculative_sample_batched,
        speculative_sample_fused,
    )

    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    prompts = np.stack([
        (np.arange(8, dtype=np.int32) % 200) + 3,
        (np.arange(8, dtype=np.int32)[::-1] % 150) + 7,
        (np.full(8, 31, dtype=np.int32)),
    ])
    n, k, temp, seeds = 17, 3, 0.9, [5, 11, 42]
    refs = [
        speculative_sample_fused(
            target, tp, draft, dp, prompts[i][None],
            max_new_tokens=n, k=k, temperature=temp, seed=seeds[i],
        )[0]
        for i in range(3)
    ]
    got, stats = speculative_sample_batched(
        target, tp, draft, dp, prompts,
        max_new_tokens=n, k=k, temperature=temp, seeds=seeds,
    )
    assert got == refs, stats
    assert stats.rounds > 0


def test_sampled_batched_draft_equals_target_accepts_all():
    from mlapi_tpu.ops.speculative import speculative_sample_batched

    target = get_model("gpt_lm", **T_CFG)
    tp = target.init(jax.random.key(2))
    prompts = np.stack([
        (np.arange(6, dtype=np.int32) % 150) + 5,
        (np.arange(6, dtype=np.int32) % 90) + 11,
    ])
    got, stats = speculative_sample_batched(
        target, tp, target, tp, prompts,
        max_new_tokens=16, k=4, temperature=0.8,
        top_k=12, top_p=0.9, seeds=[1, 2],
    )
    assert all(len(g) == 16 for g in got)
    assert stats.acceptance_rate == 1.0, stats


def test_sampled_batched_greedy_delegates():
    from mlapi_tpu.ops.speculative import speculative_sample_batched

    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    prompts = (np.arange(7, dtype=np.int32) % 150)[None] + 5
    ref, _ = speculative_generate_batched(
        target, tp, draft, dp, prompts, max_new_tokens=12, k=3,
    )
    got, _ = speculative_sample_batched(
        target, tp, draft, dp, prompts,
        max_new_tokens=12, k=3, temperature=0.0,
    )
    assert got == ref


def test_uneven_finish_rows_ride_as_dummies():
    """All rows share max_new_tokens, but acceptance differences make
    rows REACH the budget at different rounds; late rows must finish
    correctly after early rows froze."""
    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(5))
    dp = draft.init(jax.random.key(6))
    prompts = np.stack([
        (np.arange(10, dtype=np.int32) % 200) + 3,
        (np.arange(10, dtype=np.int32) * 7 % 190) + 4,
        (np.arange(10, dtype=np.int32) * 3 % 170) + 6,
        (np.full(10, 99, dtype=np.int32)),
    ])
    n = 33  # not a multiple of k+1: forces budget-capped last rounds
    refs = _solo_refs(target, tp, prompts, n)
    got, stats = speculative_generate_batched(
        target, tp, draft, dp, prompts, max_new_tokens=n, k=4,
    )
    assert got == refs, stats


def test_fused_batched_rows_match_solo_fused():
    """The FULLY-FUSED batched program (one dispatch for the whole
    batched speculation) emits, per row, exactly the solo fused
    stream — greedy and sampled, with per-row budgets, pads, and
    seeds (the last cell of the fused matrix)."""
    from mlapi_tpu.ops.speculative import (
        fused_spec_batched_fn,
        speculative_generate_fused,
        speculative_sample_fused,
    )

    target = get_model("gpt_lm", **T_CFG)
    draft = get_model("gpt_lm", **D_CFG)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    bucket, k, tier, b = 12, 4, 24, 4
    rows = np.zeros((b, bucket), np.int32)
    # Two distinct prompt lengths (not four): each DISTINCT length
    # compiles its own solo fused reference program, and the per-row
    # variety this test pins — pads, budgets, seeds — is already
    # covered by the length pair + the budget spread below.
    lens = [12, 5, 12, 5]
    for i, ln in enumerate(lens):
        rows[i, bucket - ln:] = (np.arange(ln) * (i + 3)) % 200 + 4
    n_pad = np.asarray([bucket - ln for ln in lens], np.int32)
    budgets = np.asarray([24, 7, 16, 1], np.int32)
    kd = np.stack([
        np.asarray(jax.random.key_data(jax.random.key(s)))
        for s in range(b)
    ])
    zt = jnp.zeros((b,), jnp.float32)
    zk = jnp.zeros((b,), jnp.int32)
    op = jnp.ones((b,), jnp.float32)

    packed = np.asarray(
        fused_spec_batched_fn(target, draft, bucket, tier, k, False)(
            tp, dp, jnp.asarray(rows), jnp.asarray(kd), zt, zk, op,
            jnp.asarray(n_pad), jnp.asarray(budgets),
        )
    )
    for i in range(b):
        n = int(budgets[i])
        # Solo fused takes the unpadded prompt (library convention);
        # bucket-invariance makes the padded batch row equivalent.
        solo = rows[i, bucket - lens[i]:][None]
        want, _ = speculative_generate_fused(
            target, tp, draft, dp, solo, max_new_tokens=n, k=k,
        )
        assert packed[i, :n].tolist() == want, i
    assert packed[0, tier] > 0            # rounds ran
    assert int(packed[:, tier + 2].sum()) > 0

    temps = jnp.full((b,), 0.8, jnp.float32)
    packed = np.asarray(
        fused_spec_batched_fn(target, draft, bucket, tier, k, True)(
            tp, dp, jnp.asarray(rows), jnp.asarray(kd), temps, zk, op,
            jnp.asarray(n_pad), jnp.asarray(budgets),
        )
    )
    for i in range(b):
        n = int(budgets[i])
        solo = rows[i, bucket - lens[i]:][None]
        want, _ = speculative_sample_fused(
            target, tp, draft, dp, solo, max_new_tokens=n, k=k,
            temperature=0.8, seed=i,
        )
        assert packed[i, :n].tolist() == want, i
