"""Hierarchical KV tier: host-RAM (and disk) spill of evicted prefix
page sets under the page pool (``serving/kv_tier.py``, the spill seam
in ``PagePool._spill_and_release``, the restore seams in
``PrefixCache.entry`` / ``paged_entry``, ``--kv-tier-bytes``).

The contract, layer by layer:

- **Tier bookkeeping**: exact byte accounting from dtype/shape
  arithmetic (``payload_bytes`` == the ``kv_tree_bytes`` closed form),
  LRU eviction under the bytes budget, replace-on-respill, disk-backed
  payloads round-tripping byte-identically with the index dropping
  unreadable files as misses.
- **The serving stack**: a prefix evicted under pool pressure (the
  same ``evict_idle`` lever brownout pulls) or off the prefix dict's
  own LRU restores on re-arrival with ZERO prefill FLOPs — pinned by
  the ``PrefixCache.builds`` counter, never wall-clock — and the
  restored greedy stream is TOKEN-IDENTICAL to the never-evicted run
  across {gpt-MHA, llama-GQA} x {none, int8}, paged and contiguous.
- **Failure discipline**: a fault at ``tier_spill`` degrades to the
  pre-tier discard; a fault at ``tier_restore`` falls back to the
  cold path (re-adopt or prefill) — both counted, both conserving
  ``kv_pages_in_use``; pool pressure during a restore rejects loudly
  with nothing half-installed; geometry drift drops the blob instead
  of ever restoring wrong bytes.

Engines here reuse ``test_paged_kv``'s tiny-model CFG so the jitted
program factories (lru-cached on the frozen model config) are shared
across the two files instead of compiled twice.
"""

import os

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.quant import kv_page_bytes
from mlapi_tpu.serving import faults
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.serving.kv_tier import (
    KVTier,
    payload_bytes,
    payload_from_contiguous,
)
from mlapi_tpu.serving.paged_pool import PagePoolExhausted
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)


def _model(kind="gpt_lm", kv_quant="none"):
    kw = dict(CFG, kv_quant=kv_quant)
    if kind == "llama_lm":
        kw["num_kv_heads"] = 2  # GQA: 4 query heads over 2 KV heads
    return get_model(kind, **kw)


@pytest.fixture(scope="module")
def gpt_params():
    return _model().init(jax.random.key(0))


@pytest.fixture(scope="module")
def llama_params():
    return _model("llama_lm").init(jax.random.key(0))


def _engine(model, params, **kw):
    kw.setdefault("chunk", 2)
    kw.setdefault("fused_single", False)
    return TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), **kw
    )


def _tiered(model, params, **kw):
    kw.setdefault("kv_page_size", 8)
    kw.setdefault("kv_tier_bytes", 1 << 24)
    return _engine(model, params, **kw)


def _payload(n_pages, page=8, heads=4, hd=8, seed=0):
    """Synthetic page-shaped blob payload (one bf-free f32 layer)."""
    rng = np.random.default_rng(seed)
    return {
        "layer_0": {
            "k": rng.standard_normal(
                (n_pages, page, heads, hd)
            ).astype(np.float32),
            "v": rng.standard_normal(
                (n_pages, page, heads, hd)
            ).astype(np.float32),
        }
    }


# --- tier bookkeeping (no engine) --------------------------------------


def test_payload_bytes_is_exact_arithmetic():
    p = _payload(3)
    assert payload_bytes(p) == 2 * 3 * 8 * 4 * 8 * 4  # leaves*shape*f32
    with pytest.raises(ValueError, match="kv_tier_bytes"):
        KVTier(0)


def test_lru_bytes_budget_evicts_coldest():
    one = payload_bytes(_payload(2))
    tier = KVTier(2 * one)  # budget fits exactly two blobs
    for fp in ("a", "b"):
        tier.spill(fp, _payload(2), 8)
    tier.lookup("a")  # touch: b is now coldest
    tier.spill("c", _payload(2), 8)
    assert tier.evictions == 1
    assert tier.lookup("b") is None          # evicted
    assert tier.lookup("a") is not None
    assert tier.bytes_in_use == 2 * one <= tier.max_bytes
    assert tier.restore_misses == 1
    # A blob that can NEVER fit is refused (counted), not thrashed in.
    big = KVTier(one // 2)
    big.spill("x", _payload(2), 8)
    assert big.entries == 0 and big.evictions == 1


def test_respill_replaces_and_drop_forgets():
    tier = KVTier(1 << 20)
    tier.spill("fp", _payload(2), 8)
    b1 = tier.bytes_in_use
    tier.spill("fp", _payload(2, seed=1), 8)  # replace, not accumulate
    assert tier.bytes_in_use == b1 and tier.entries == 1
    assert tier.spill_count == 2
    tier.drop("fp")
    assert tier.entries == 0 and tier.bytes_in_use == 0
    tier.drop("fp")  # idempotent


def test_disk_tier_roundtrip(tmp_path):
    d = str(tmp_path / "tier")
    tier = KVTier(1 << 20, disk_dir=d)
    pay = _payload(2)
    tier.spill("fp", pay, 8)
    files = os.listdir(d)
    assert len(files) == 1           # payload on disk, index in RAM
    blob = tier.lookup("fp")
    for ln, layer in pay.items():
        for name, a in layer.items():
            np.testing.assert_array_equal(blob.payload[ln][name], a)
    assert blob.nbytes == payload_bytes(pay)
    # Eviction unlinks; a vanished file is a miss, never a crash.
    tier.drop("fp")
    assert os.listdir(d) == []
    tier.spill("fp2", pay, 8)
    os.unlink(os.path.join(d, os.listdir(d)[0]))
    assert tier.lookup("fp2") is None
    assert tier.entries == 0         # dead index entry swept


def test_disk_stale_sweep(tmp_path):
    """Blob files whose owner pid is dead are swept at tier init
    (restart loops must not accumulate dead bytes); files owned by
    live pids (sibling --workers sharing the dir) and foreign files
    are left alone."""
    import subprocess

    d = str(tmp_path / "tier")
    os.makedirs(d)
    proc = subprocess.Popen(["true"])
    proc.wait()
    dead = os.path.join(d, f"kvtier-{proc.pid}-0.npz")
    alive = os.path.join(d, "kvtier-1-0.npz")      # pid 1: EPERM, kept
    foreign = os.path.join(d, "notes.txt")
    for p in (dead, alive, foreign):
        open(p, "wb").close()
    KVTier(1 << 20, disk_dir=d)
    assert not os.path.exists(dead)
    assert os.path.exists(alive)
    assert os.path.exists(foreign)


def test_contiguous_payload_page_shape():
    # [1, 20] cache at page 8 -> 3 pages, zero-padded past 20; bytes
    # follow the padded page shape (what a pool spill would hold).
    kv = {
        "layer_0": {
            "k": np.arange(20 * 4 * 8, dtype=np.float32).reshape(
                1, 20, 4, 8
            )
        }
    }
    pay = payload_from_contiguous(kv, 8)
    a = pay["layer_0"]["k"]
    assert a.shape == (3, 8, 4, 8)
    flat = a.reshape(1, 24, 4, 8)
    np.testing.assert_array_equal(flat[:, :20], kv["layer_0"]["k"])
    assert not flat[:, 20:].any()


# --- evict -> restore: stream identity, zero prefill FLOPs -------------


@pytest.mark.parametrize("fmt", ["none", "int8"])
@pytest.mark.parametrize("kind", ["gpt_lm", "llama_lm"])
def test_evict_restore_stream_identity(
    kind, fmt, gpt_params, llama_params
):
    """The acceptance pin: {evict -> restore} is token-identical to
    {never evicted} at both restore seams, restore does zero prefill
    FLOPs (``builds`` flat), and spill/restore bytes equal the
    ``kv_page_bytes`` closed form — both cache formats, MHA and GQA."""
    params = gpt_params if kind == "gpt_lm" else llama_params
    model = _model(kind, fmt)
    eng = _tiered(model, params)
    tier = eng.kv_tier
    pre = "You are a helpful bot."
    ref = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    n_pages = len(eng.pool.entry_pages(pre))
    blob_bytes = n_pages * kv_page_bytes(model, eng.pool.page)
    assert eng.prefix.builds == 1

    # Seam 1 — pool pressure (the brownout evict_idle lever): pages
    # spill, the entry survives, re-arrival restores pages from the
    # blob instead of re-adopting.
    assert eng.pool.evict_idle(1) == 1
    assert tier.spill_count == 1 and tier.spill_bytes == blob_bytes
    assert eng.pool.entry_pages(pre) is None
    out = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    assert out["token_ids"] == ref["token_ids"]
    assert tier.restore_hits == 1 and tier.restore_bytes == blob_bytes
    assert eng.prefix.builds == 1  # no prefill ran

    # Seam 2 — the prefix dict's own LRU: the whole entry (contiguous
    # KV included) is discarded; re-arrival rebuilds it from the blob
    # AND restores its pool pages — still zero prefills.
    eng.prefix.max_entries = 1
    eng.generate_text(" q", max_new_tokens=4, prefix="other prefix")
    assert eng.pool.entry_pages(pre) is None
    builds = eng.prefix.builds          # the other prefix's cold build
    hits = tier.restore_hits
    out2 = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    assert out2["token_ids"] == ref["token_ids"]
    assert eng.prefix.builds == builds  # entry rebuilt, not prefilled
    assert tier.restore_hits == hits + 2  # entry rebuild + page restore
    assert tier.restore_bytes == tier.restore_hits * blob_bytes
    assert eng.kv_pages_in_use == n_pages  # only the entry's own holds


def test_restored_stream_matches_untiered_engine(gpt_params):
    """Cross-engine anchor for the identity matrix above: the tiered
    engine's post-restore stream equals a tier-less engine's stream
    (which the paged suite pins against contiguous), so restore
    identity chains back to the r09 baseline."""
    model = _model()
    plain = _engine(model, gpt_params, kv_page_size=8)
    pre = "sys prompt"
    ref = plain.generate_text(" ask", max_new_tokens=6, prefix=pre)
    eng = _tiered(model, gpt_params)
    eng.generate_text(" ask", max_new_tokens=6, prefix=pre)
    eng.pool.evict_idle(1)
    out = eng.generate_text(" ask", max_new_tokens=6, prefix=pre)
    assert out["token_ids"] == ref["token_ids"]
    assert eng.kv_tier.restore_hits == 1
    # Disabled-tier engines carry no tier state at all.
    assert plain.kv_tier is None
    assert plain.kv_prefix_restore_hits == 0
    assert plain.kv_prefix_spill_count == 0


def test_contiguous_engine_entry_spill_restore(gpt_params):
    """No pool at all: the prefix dict's LRU spill/restore works on
    contiguous engines too (blobs are one bucket-wide page)."""
    model = _model()
    eng = _engine(model, gpt_params, kv_tier_bytes=1 << 24)
    eng.prefix.max_entries = 1
    pre = "sys A"
    ref = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    assert eng.prefix.builds == 1
    eng.generate_text(" q", max_new_tokens=4, prefix="sys B")  # evicts A
    assert eng.kv_tier.spill_count == 1
    out = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    assert out["token_ids"] == ref["token_ids"]
    assert eng.kv_tier.restore_hits == 1
    assert eng.prefix.builds == 2  # only B's build; A was restored


def test_disk_tier_serving_roundtrip(gpt_params, tmp_path):
    model = _model()
    eng = _tiered(
        model, gpt_params, kv_tier_disk_dir=str(tmp_path / "t")
    )
    pre = "sys prompt"
    ref = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    eng.pool.evict_idle(1)
    assert len(os.listdir(tmp_path / "t")) == 1
    out = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    assert out["token_ids"] == ref["token_ids"]
    assert eng.kv_tier.restore_hits == 1 and eng.prefix.builds == 1


# --- failure discipline ------------------------------------------------


def test_spill_fault_degrades_to_discard(gpt_params):
    """An injected ``tier_spill`` raise: the eviction still completes
    (pages freed, pool consistent), the tier stays untouched, the
    failure is counted, and the re-arrival pays the pre-tier cold
    path — the fault can never strand pages or corrupt the tier."""
    model = _model()
    eng = _tiered(model, gpt_params)
    ref = eng.generate_text(" q1", max_new_tokens=6, prefix="sys")
    with faults.active("tier_spill:raise"):
        assert eng.pool.evict_idle(1) == 1
    assert eng.kv_tier.spill_count == 0
    assert eng.kv_tier.spill_failures == 1
    assert eng.kv_tier.entries == 0
    assert eng.pool.entry_pages("sys") is None
    out = eng.generate_text(" q1", max_new_tokens=6, prefix="sys")
    assert out["token_ids"] == ref["token_ids"]  # cold re-adopt
    assert eng.kv_tier.restore_hits == 0
    assert eng.prefix.builds == 1  # entry survived; only pages re-adopt


def test_restore_fault_falls_back_cold(gpt_params):
    """An injected ``tier_restore`` raise: the restore's freshly
    allocated pages are handed back (``kv_pages_in_use`` conserved),
    the blob survives for the next attempt, the failure is counted,
    and the request is served by the cold path, token-identical."""
    model = _model()
    eng = _tiered(model, gpt_params)
    ref = eng.generate_text(" q1", max_new_tokens=6, prefix="sys")
    eng.pool.evict_idle(1)
    with faults.active("tier_restore:raise"):
        out = eng.generate_text(" q1", max_new_tokens=6, prefix="sys")
    assert out["token_ids"] == ref["token_ids"]
    assert eng.kv_tier.restore_failures == 1
    assert eng.kv_tier.restore_hits == 0
    assert eng.kv_tier.entries == 1      # blob retained
    n_pages = len(eng.pool.entry_pages("sys"))
    assert eng.kv_pages_in_use == n_pages  # fallback adopt, no leak
    # Unfaulted retry restores for real.
    eng.pool.evict_idle(1)
    out2 = eng.generate_text(" q1", max_new_tokens=6, prefix="sys")
    assert out2["token_ids"] == ref["token_ids"]
    assert eng.kv_tier.restore_hits == 1


def test_restore_fault_on_entry_rebuild_goes_cold(gpt_params):
    """Same fault at the OTHER restore seam (entry rebuild after a
    full dict eviction): falls back to a normal cold prefill, counted
    — the satellite's restore-failure pin."""
    model = _model()
    eng = _tiered(model, gpt_params)
    eng.prefix.max_entries = 1
    ref = eng.generate_text(" q1", max_new_tokens=6, prefix="sys A")
    eng.generate_text(" q", max_new_tokens=4, prefix="sys B")
    builds = eng.prefix.builds
    with faults.active("tier_restore:raise"):
        out = eng.generate_text(" q1", max_new_tokens=6, prefix="sys A")
    assert out["token_ids"] == ref["token_ids"]
    assert eng.prefix.builds == builds + 1  # the cold prefill ran
    assert eng.kv_tier.restore_failures >= 1


def test_restore_under_pool_pressure_rejects_loudly(gpt_params):
    """Pool pressure DURING a restore: the restore allocates first,
    so exhaustion propagates as the same loud PagePoolExhausted with
    nothing half-installed — no poisoned pool, and the stream serves
    once pressure lifts."""
    model = _model()
    eng = _tiered(model, gpt_params)
    pre = "sys prompt"
    ref = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    eng.pool.evict_idle(1)
    n_pages = eng.kv_tier.lookup(pre).num_pages
    # Occupy the pool down to FEWER free pages than the blob needs, so
    # the restore's own allocation is the one that fails — before any
    # device write or registration.
    free = eng.kv_pages_total - eng.kv_pages_in_use
    hold = eng.pool.alloc(free - (n_pages - 1))
    with pytest.raises(PagePoolExhausted):
        eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    assert eng.kv_pages_in_use == len(hold)  # nothing installed
    assert eng.pool.entry_pages(pre) is None
    assert eng.kv_tier.entries == 1          # blob intact
    assert eng.kv_tier.restore_hits == 0
    # Pressure that clears only AFTER the entry pages are restored
    # (the suffix alloc fails): the restored entry set stays resident
    # with its own hold — page-accounted, evictable, not a leak.
    eng.pool.release(hold)
    hold = eng.pool.alloc(
        eng.kv_pages_total - eng.kv_pages_in_use - n_pages
    )
    with pytest.raises(PagePoolExhausted):
        eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    pages = eng.pool.entry_pages(pre)
    assert pages is not None and len(pages) == n_pages
    assert np.all(eng.pool.ref[pages] == 1)  # row holds all released
    assert eng.kv_pages_in_use == len(hold) + n_pages
    eng.pool.release(hold)
    out = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    assert out["token_ids"] == ref["token_ids"]
    assert eng.kv_tier.restore_hits >= 1


def test_concurrent_alloc_waits_for_inflight_eviction(gpt_params):
    """Eviction spills outside the pool lock; an alloc that finds no
    free pages AND no victim mid-spill must WAIT for the in-flight
    eviction's release instead of raising a spurious
    PagePoolExhausted for capacity that is moments from free."""
    import threading
    import time

    model = _model()
    eng = _tiered(model, gpt_params)
    pool = eng.pool
    e = pool.alloc(2)
    pool.put_entry_pages("victim", e)         # the only idle victim
    hold = pool.alloc(pool.pages_total - pool.pages_in_use)
    started = threading.Event()
    real_spill = eng.kv_tier.spill

    def slow_spill(*a, **kw):
        started.set()
        time.sleep(0.3)
        return real_spill(*a, **kw)

    eng.kv_tier.spill = slow_spill
    done = {}
    t = threading.Thread(target=lambda: done.update(
        n=pool.evict_idle(1)
    ))
    t.start()
    assert started.wait(5)
    pages = pool.alloc(1)   # mid-spill: must wait, not shed
    t.join()
    assert done["n"] == 1 and len(pages) == 1
    pool.release(pages)
    pool.release(hold)
    assert pool.pages_in_use == 0


def test_geometry_drift_drops_blob(gpt_params):
    model = _model()
    eng = _tiered(model, gpt_params)
    pre = "sys prompt"
    ref = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    eng.pool.evict_idle(1)
    # A blob whose page size does not match the live pool (e.g. a
    # stale disk tier from a differently-configured run) must be
    # dropped at restore time, never applied.
    blob = eng.kv_tier.lookup(pre)
    eng.kv_tier.spill(
        pre,
        {
            ln: {
                n: a.reshape((-1, 4) + a.shape[2:])
                for n, a in layer.items()
            }
            for ln, layer in blob.payload.items()
        },
        4,
    )
    out = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    assert out["token_ids"] == ref["token_ids"]  # cold re-adopt
    assert eng.kv_tier.entries == 0              # inapplicable: dropped
    # Entry-rebuild drift: tamper the recorded bucket; the rebuild
    # declines, drops, and the cold build serves.
    eng.prefix.max_entries = 1
    eng.pool.evict_idle(1)   # respill with good geometry
    eng.generate_text(" q", max_new_tokens=4, prefix="other")
    with eng.kv_tier._lock:
        eng.kv_tier._blobs[pre].bucket = 999
    builds = eng.prefix.builds
    out2 = eng.generate_text(" q1", max_new_tokens=6, prefix=pre)
    assert out2["token_ids"] == ref["token_ids"]
    assert eng.prefix.builds == builds + 1


# --- observability -----------------------------------------------------


async def test_metrics_exports_tier_block(gpt_params):
    import httpx

    from mlapi_tpu.serving import build_app

    eng = _tiered(_model(), gpt_params)
    eng.generate_text(" q1", max_new_tokens=4, prefix="sys")
    eng.pool.evict_idle(1)
    eng.generate_text(" q1", max_new_tokens=4, prefix="sys")
    app = build_app(eng)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as cl:
            snap = (await cl.get("/metrics")).json()
        c, g = snap["counters"], snap["gauges"]
        assert c["generate.kv_prefix_spill_count"] == 1
        assert c["generate.kv_prefix_restore_hits"] == 1
        assert (
            c["generate.kv_prefix_restore_bytes"]
            == c["generate.kv_prefix_spill_bytes"]
            > 0
        )
        assert c["generate.kv_entry_evictions"] == 1
        assert c["generate.kv_tier_evictions"] == 0
        assert g["generate.kv_tier_entries"] == 1
        assert (
            g["generate.kv_tier_bytes_in_use"]
            == c["generate.kv_prefix_spill_bytes"]
        )
    finally:
        await app.shutdown()


def test_disk_dir_without_budget_is_loud(gpt_params, tmp_path):
    """A disk dir with no bytes budget would silently store nothing:
    refused at construction, mirroring the kv_pages-without-page-size
    validation."""
    with pytest.raises(ValueError, match="kv_tier_disk_dir"):
        _engine(_model(), gpt_params, kv_tier_disk_dir=str(tmp_path))


# --- soak: evict/restore churn (heavy) ---------------------------------


@pytest.mark.heavy
@pytest.mark.slow  # 4.8 s measured call — r16 tier-1 buyback (conftest);
# the spill/restore/eviction seams stay covered by the per-seam tests.
def test_tier_churn_soak(gpt_params):
    """Alternate spill seams, restores, budget evictions, and plain
    traffic for a while: every stream stays identical to its first
    run, page refcounts return to entry-only holds, and tier bytes
    accounting never drifts from the closed form."""
    model = _model()
    eng = _tiered(model, gpt_params, kv_tier_bytes=1 << 18)
    prefixes = ["sys one", "sys two prompt", "sys three!"]
    refs = {
        p: eng.generate_text(" q", max_new_tokens=5, prefix=p)[
            "token_ids"
        ]
        for p in prefixes
    }
    for i in range(4):
        eng.pool.evict_idle(2)
        for p in prefixes:
            out = eng.generate_text(" q", max_new_tokens=5, prefix=p)
            assert out["token_ids"] == refs[p], (i, p)
        eng.generate_text(f"plain {i}", max_new_tokens=5)
    t = eng.kv_tier
    assert t.restore_hits > 0 and t.spill_count > 0
    with t._lock:
        assert t._bytes == sum(s.nbytes for s in t._blobs.values())
        assert t._bytes <= t.max_bytes
    # Only entry holds remain on the pool.
    held = sum(
        len(eng.pool.entry_pages(p))
        for p in prefixes
        if eng.pool.entry_pages(p) is not None
    )
    assert eng.kv_pages_in_use == held
