"""mlapi-lint as a tier-1 gate (tools/lint/, r16).

Three layers, mirroring what the linter promises:

- **Clean tree.** ``run_rules`` over the real repo config reports
  zero unsuppressed findings — the invariants the rules encode
  (donation discipline, lock discipline, seam ordering/coverage,
  router purity, metrics consistency, test hygiene, and the r19
  concurrency layer: lock-order acyclicity, thread-context
  placement, terminal-frame wait discipline) HOLD on the current
  tree, and any PR that breaks one fails here with a ``file:line``.
  The MLA007 artifact (``tools/lint/lockorder.json``) is
  additionally pinned byte-for-byte — the runtime witness loads it,
  so staleness would enforce a stale order.
- **Fixtures.** Each rule is negative-tested against a minimal repro
  of the historical bug it mechanizes (``tests/lint_fixtures/``,
  one module per rule). The contract is exact: the finding set must
  EQUAL the ``# EXPECT(MLA0xx)`` marker set — every marked line
  flagged, nothing else flagged — so both missed detections and
  false positives fail.
- **Machinery.** Inline suppressions and the baseline file require
  justifications, stale baseline entries fail loudly, the CLI exits
  0/1/2, ``--format=github`` emits Actions annotations, and the
  whole run never imports jax (pure AST — the property that keeps it
  <15 s and CI-anywhere).

The lint fixtures are EXCLUDED from the clean-tree scan (they are
deliberate violations) and are not collected by pytest (no ``test_``
file prefix).
"""

from __future__ import annotations

import re
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.lint import load_project, run_rules  # noqa: E402
from tools.lint.baseline import (  # noqa: E402
    SuppressionError,
    apply_suppressions,
)
from tools.lint.config import Config  # noqa: E402

FIXTURES = "tests/lint_fixtures/"

_EXPECT_RE = re.compile(r"EXPECT\((MLA\d{3}(?:\s*,\s*MLA\d{3})*)\)")


def fixture_config(**overrides) -> Config:
    base = dict(
        root=REPO_ROOT,
        py_globs=(f"{FIXTURES}**/*.py",),
        exclude_prefixes=(),
        faults_module=f"{FIXTURES}prod/fx_faults.py",
        latency_stats_module=f"{FIXTURES}prod/fx_app.py",
        production_prefix=f"{FIXTURES}prod/",
        serving_prefix=f"{FIXTURES}prod/",
        test_prefix=f"{FIXTURES}t/",
        bench_files=(),
        doc_files=(f"{FIXTURES}fx_docs.md",),
        async_pure_modules=(f"{FIXTURES}prod/fx_router.py",),
        baseline_file=f"{FIXTURES}fx_baseline.txt",
    )
    base.update(overrides)
    return Config(**base)


def expected_markers(proj) -> set[tuple[str, int, str]]:
    """(file, line, rule) for every EXPECT marker in the fixture
    set — python comments and doc-file lines alike."""
    out: set[tuple[str, int, str]] = set()
    for sf in proj.files:
        for line_no, comment in sf.comments.items():
            m = _EXPECT_RE.search(comment)
            if m:
                for rule in re.split(r"\s*,\s*", m.group(1)):
                    out.add((sf.path, line_no, rule))
    for path, text in proj.docs.items():
        for i, line in enumerate(text.splitlines(), 1):
            m = _EXPECT_RE.search(line)
            if m:
                for rule in re.split(r"\s*,\s*", m.group(1)):
                    out.add((path, i, rule))
    return out


# --- the gate: the real tree is clean ---------------------------------


def test_clean_tree_zero_findings():
    cfg = Config()
    proj = load_project(cfg)
    findings = run_rules(proj, cfg)
    reported, _ = apply_suppressions(proj, cfg, findings)
    assert reported == [], "\n" + "\n".join(
        f.render() for f in reported
    )


# --- fixtures: every rule catches its historical bug exactly ----------


def test_fixtures_flag_exactly_the_marked_lines():
    cfg = fixture_config()
    proj = load_project(cfg)
    assert len(proj.files) >= 7, "fixture set went missing"
    findings = run_rules(proj, cfg)
    reported, suppressed = apply_suppressions(proj, cfg, findings)
    # No duplicate reports: each violation is charged to exactly one
    # frame (the nested-closure double-report class).
    keys = [(f.rule, f.file, f.line, f.message) for f in reported]
    assert len(keys) == len(set(keys)), "duplicate findings"
    got = {(f.rule, f.file, f.line) for f in reported}
    want = {(r, f, ln) for (f, ln, r) in expected_markers(proj)}
    missed = want - got
    extra = got - want
    assert not missed, f"rules MISSED marked repros: {sorted(missed)}"
    assert not extra, f"rules over-flagged (false positives): {sorted(extra)}"
    # Every rule has at least one fixture repro.
    assert {r for (r, _, _) in got} == {
        "MLA001", "MLA002", "MLA003", "MLA004", "MLA005", "MLA006",
        "MLA007", "MLA008", "MLA009",
    }
    # Both suppression paths were exercised: the inline allow and the
    # baseline entry each swallowed one fx_locks violation.
    sup = {(f.rule, f.symbol) for f in suppressed}
    assert ("MLA002", "PagePool.allowed_bump") in sup
    assert ("MLA002", "PagePool.baselined_bump") in sup


def test_stale_baseline_entry_fails_loudly(tmp_path):
    stale = tmp_path / "baseline.txt"
    stale.write_text(
        "MLA002 tests/lint_fixtures/prod/fx_locks.py::PagePool.gone "
        "-- excuses code that no longer exists\n"
    )
    cfg = fixture_config(baseline_file=str(stale))
    proj = load_project(cfg)
    findings = run_rules(proj, cfg)
    try:
        apply_suppressions(proj, cfg, findings)
    except SuppressionError as e:
        assert "stale" in str(e)
    else:
        raise AssertionError("stale baseline entry was not rejected")


def test_baseline_requires_justification(tmp_path):
    bad = tmp_path / "baseline.txt"
    bad.write_text(
        "MLA002 tests/lint_fixtures/prod/fx_locks.py::PagePool.x --\n"
    )
    cfg = fixture_config(baseline_file=str(bad))
    proj = load_project(cfg)
    try:
        apply_suppressions(proj, cfg, run_rules(proj, cfg))
    except SuppressionError as e:
        assert "malformed" in str(e)
    else:
        raise AssertionError("justification-less entry was accepted")


# --- MLA007 artifact ---------------------------------------------------


def test_lockorder_artifact_roundtrip():
    """The committed tools/lint/lockorder.json IS the freshly
    recomputed graph — byte-for-byte. A PR that changes lock scopes
    without regenerating the artifact fails here (the runtime
    witness loads this file as the allowed order, so a stale file
    would enforce a stale order)."""
    from tools.lint.rules.lockorder import render_artifact

    cfg = Config()
    proj = load_project(cfg)
    committed = (REPO_ROOT / cfg.lockorder_artifact).read_text()
    assert committed == render_artifact(proj, cfg), (
        "lockorder.json is stale — regenerate: python -m tools.lint "
        "--lockorder-out tools/lint/lockorder.json"
    )


def test_lockorder_artifact_is_cycle_free_with_total_order():
    import json

    from tools.lint.rules.lockorder import find_cycles

    doc = json.loads(
        (REPO_ROOT / Config().lockorder_artifact).read_text()
    )
    edges = {(e["before"], e["after"]) for e in doc["edges"]}
    assert find_cycles(edges) == []
    # Acyclic graphs always emit a usable total order, and it must
    # respect every edge.
    order = doc["order"]
    assert order is not None
    pos = {n: i for i, n in enumerate(order)}
    for a, b in edges:
        assert pos[a] < pos[b], (a, b)
    # The edge the serving stack actually carries (drop_entry under
    # the prefix registry lock) is present — the graph is not
    # vacuously empty.
    assert ("PrefixCache", "PagePool") in edges


def test_find_cycles_unit():
    from tools.lint.rules.lockorder import find_cycles

    assert find_cycles({("A", "B"), ("B", "A")}) == [["A", "B"]]
    assert find_cycles({("A", "A")}) == [["A"]]
    assert find_cycles({("A", "B"), ("B", "C")}) == []


# --- CLI + purity ------------------------------------------------------


def test_cli_exit_codes_and_jax_purity(tmp_path):
    """The CI entry point: ``python -m tools.lint`` exits 0 on the
    clean tree, the ``--rule`` filter and ``--lockorder-out`` flag
    work, and the analysis never imports jax (pure AST — the <15 s
    CPU-only property). One subprocess checks all of it."""
    out = tmp_path / "lockorder.json"
    code = (
        "import sys\n"
        "from pathlib import Path\n"
        "from tools.lint.__main__ import main\n"
        "rc = main([])\n"
        "assert rc == 0, f'lint reported findings: rc={rc}'\n"
        "rc = main(['--rule', 'MLA007', '--lockorder-out', "
        f"{str(out)!r}])\n"
        "assert rc == 0, f'MLA007 reported findings: rc={rc}'\n"
        f"assert 'PrefixCache' in Path({str(out)!r}).read_text()\n"
        "assert main(['--rule', 'MLA999']) == 2\n"
        "assert 'jax' not in sys.modules, 'linter imported jax'\n"
        "print('LINT_OK')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "LINT_OK" in proc.stdout


def test_github_annotation_format():
    from tools.lint import Finding

    f = Finding(
        rule="MLA002", file="mlapi_tpu/serving/x.py", line=7,
        message="boom", symbol="C.m",
    )
    assert f.render_github() == (
        "::error file=mlapi_tpu/serving/x.py,line=7,title=MLA002::boom"
    )
    assert "x.py:7" in f.render()
