"""Config-5 readiness (VERDICT r03 "Next" #9): the --from-hf path that
fine-tunes a LOCAL HuggingFace BERT checkpoint (tested here with tiny
synthetic stand-ins — the real bert-base run needs only the weights on
disk), and the docs_clf real-data classification proxy."""

import json

import numpy as np
import pytest

from mlapi_tpu.datasets import get_dataset


def _tiny_hf_checkpoint(path):
    from transformers import BertConfig, BertForSequenceClassification

    cfg = BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=64, num_labels=2,
    )
    m = BertForSequenceClassification(cfg)
    m.save_pretrained(path)
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    vocab += [f"tok{i}" for i in range(64 - len(vocab))]
    (path / "vocab.txt").write_text("\n".join(vocab))
    return m


TINY_KW = dict(
    vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
    intermediate_size=64, max_positions=64, num_classes=2,
)


def test_from_hf_cli_initialises_from_torch_weights(tmp_path, monkeypatch):
    """--from-hf: tokenize with the dir's vocab.txt, convert the torch
    weights, fine-tune, save. With a near-zero LR the saved embedding
    must still BE the HF embedding — proof the init was used."""
    import yaml

    from mlapi_tpu.checkpoint import load_checkpoint
    from mlapi_tpu.train.__main__ import main

    monkeypatch.setenv("MLAPI_TPU_PLATFORM", "cpu")
    hf_dir = tmp_path / "hf"
    hf_dir.mkdir()
    tm = _tiny_hf_checkpoint(hf_dir)

    cfg = {
        "name": "tiny-hf-sst2",
        "model": "bert_classifier",
        "model_kwargs": TINY_KW,
        "dataset": "sst2",
        "dataset_kwargs": {"max_len": 32, "n_train": 64, "n_test": 16},
        "steps": 2,
        "batch_size": 16,
        "optimizer": "adamw",
        "learning_rate": 1e-9,
    }
    ycfg = tmp_path / "cfg.yaml"
    ycfg.write_text(yaml.safe_dump(cfg))
    out = tmp_path / "ck"
    main(["--config", str(ycfg), "--from-hf", str(hf_dir),
          "--out", str(out)])
    assert (out / "MANIFEST.json").exists()

    params, meta = load_checkpoint(out)
    want = tm.state_dict()["bert.embeddings.word_embeddings.weight"]
    np.testing.assert_allclose(
        np.asarray(params["embeddings"]["word"]),
        want.detach().numpy(), atol=1e-4,
    )
    # The checkpoint records the HF dir's WordPiece tokenizer, so
    # serving encodes exactly as training did.
    assert meta.config["tokenizer"]["kind"] == "wordpiece"


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_docs_clf_is_real_and_learnable():
    """The config-5 local proxy: real repo prose, real labels, and a
    tiny BERT must beat chance decisively on the held-out tail."""
    import jax

    from mlapi_tpu.models import get_model
    from mlapi_tpu.train import fit

    splits = get_dataset("docs_clf", seq_len=128)
    assert splits.source == "real"
    # The dataset must default to the commit-pinned snapshot: the
    # margin asserted below was measured against those exact bytes
    # and the live docs drift every round (r04: 0.19 -> 0.07 within
    # one round, silently).
    assert splits.extras["corpus"] == "frozen@012402d"
    n_classes = len(splits.vocab.labels)
    assert n_classes >= 2
    assert set(np.unique(splits.y_test)) == set(range(n_classes))
    # Stratified random split of NON-overlapping windows: no byte
    # appears in both splits.
    tr = {w.tobytes() for w in splits.x_train}
    assert not any(w.tobytes() in tr for w in splits.x_test)

    model = get_model(
        "bert_classifier", vocab_size=260, hidden_size=64, num_layers=2,
        num_heads=4, intermediate_size=128, max_positions=128,
        num_classes=n_classes,
    )
    r = fit(model, splits, steps=100, batch_size=64,
            learning_rate=2e-3, optimizer="adamw")
    chance = max(
        np.mean(splits.y_test == c) for c in range(n_classes)
    )
    # Frozen corpus (snapshot @012402d), so the margin is a fixed
    # property of the bytes, not of this round's doc growth:
    # measured 0.3913 held-out vs 0.3230 chance at this exact config
    # (100 steps, lr 2e-3), 0.4845 at the 300-step preset — the
    # BASELINE.json headline. Asserted with ~half the measured
    # margin as slack for BLAS/thread nondeterminism.
    from mlapi_tpu.train.loop import evaluate

    train_acc = evaluate(
        model.apply, r.params, splits.x_train[:256],
        splits.y_train[:256],
    )
    assert train_acc > chance + 0.25, (float(train_acc), float(chance))
    assert r.test_accuracy > chance + 0.035, (
        r.test_accuracy, float(chance)
    )


def test_docsclf_bert_preset_registered():
    from mlapi_tpu.config import get_preset

    cfg = get_preset("docsclf-bert")
    assert cfg.dataset == "docs_clf"
