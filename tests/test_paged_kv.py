"""Paged KV cache: block-granular page pool + page-table flash-decode
+ copy-on-write prefix sharing (``serving/paged_pool.py``, the paged
seams in ``ops/quant.py``, ``ops/pallas.paged_decode_attention``, the
paged ``BatchRun`` lifecycle, ``--kv-page-size``).

The contract these tests pin, layer by layer:

- **Host bookkeeping**: page alloc/free round-trips, refcounts,
  LRU eviction of prefix page sets under pressure, and the LOUD
  :class:`PagePoolExhausted` reject — never a silent spill.
- **Device seams**: a paged layer (pool + table) appends and reads
  byte-identically to the contiguous layout, both cache formats,
  scalar and per-row positions; the page-table kernel matches the
  contiguous kernel over gathered pages.
- **The serving stack**: greedy token streams are IDENTICAL between
  paged and contiguous allocation across {MHA, GQA} x {none, int8} x
  {einsum, flash} — solo, continuously-admitted, and behind shared
  prefixes (whose pages are ref-shared, diverging by COW, never
  copied per row).
- **The capacity model**: padding waste and slot capacity come from
  dtype/shape arithmetic (never wall-clock), matching what
  ``BENCH_GEN_PAGED`` publishes.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.quant import (
    init_kv_cache,
    kv_cache_append,
    kv_cache_kv,
    kv_cache_seq_len,
    kv_page_bytes,
    make_paged_pools,
    paged_cache_tree,
)
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.serving.paged_pool import PagePool, PagePoolExhausted
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)


def _model(kind="gpt_lm", kv_quant="none", impl="einsum"):
    kw = dict(CFG, kv_quant=kv_quant, decode_attn_impl=impl)
    if kind == "llama_lm":
        kw["num_kv_heads"] = 2  # GQA: 4 query heads over 2 KV heads
    return get_model(kind, **kw)


@pytest.fixture(scope="module")
def gpt_params():
    return _model().init(jax.random.key(0))


@pytest.fixture(scope="module")
def llama_params():
    return _model("llama_lm").init(jax.random.key(0))


def _engine(model, params, paged, **kw):
    kw.setdefault("chunk", 2)
    # Pin the chunked batch lifecycle: the fused fast paths build
    # their own transient in-program caches and never touch the pool.
    kw.setdefault("fused_single", False)
    if paged:
        kw.setdefault("kv_page_size", 8)
    return TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), **kw
    )


async def _collect(req) -> list[int]:
    out: list[int] = []
    while True:
        item = await req.queue.get()
        if item is None:
            return out
        if isinstance(item, Exception):
            raise item
        out.extend(item["token_ids"])


# --- host bookkeeping --------------------------------------------------


def test_pool_alloc_free_roundtrip():
    pool = PagePool(_model(), page_size=8, num_pages=9)
    assert pool.pages_total == 8  # page 0 is the null page, not capacity
    a = pool.alloc(3)
    assert 0 not in a and len(set(a.tolist())) == 3
    assert pool.pages_in_use == 3
    assert pool.pages_shared == 0
    pool.retain(a)  # second holder
    assert pool.pages_shared == 3  # ref > 1 counts, null excluded
    pool.release(a)  # first holder gone; still held
    assert pool.pages_in_use == 3
    assert pool.pages_shared == 0
    pool.release(a)
    assert pool.pages_in_use == 0
    # Freed pages are allocatable again; the whole pool round-trips.
    b = pool.alloc(8)
    assert pool.pages_in_use == 8
    with pytest.raises(PagePoolExhausted):
        pool.alloc(1)
    pool.release(b)
    assert pool.pages_in_use == 0


def test_pool_double_release_is_loud():
    pool = PagePool(_model(), page_size=8, num_pages=4)
    a = pool.alloc(1)
    pool.release(a)
    with pytest.raises(AssertionError, match="below zero"):
        pool.release(a)


def test_pool_pressure_evicts_lru_entry_pages():
    pool = PagePool(_model(), page_size=8, num_pages=7)
    e1, e2 = pool.alloc(2), pool.alloc(2)
    pool.put_entry_pages("sys-a", e1)
    pool.put_entry_pages("sys-b", e2)
    pool.entry_pages("sys-a")  # touch: b is now LRU... a is MRU
    # 2 free pages left; asking for 4 must evict entry sets — LRU
    # ("sys-b"? no: insertion a,b then touch a -> b older) first.
    got = pool.alloc(4)
    assert len(got) == 4
    assert pool.entry_evictions >= 1
    # A row-referenced entry set is NOT evictable: pin one and fill.
    pool2 = PagePool(_model(), page_size=8, num_pages=4)
    e = pool2.alloc(2)
    pool2.put_entry_pages("sys", e)
    pool2.retain(e)  # a live batch row shares these pages
    with pytest.raises(PagePoolExhausted):
        pool2.alloc(2)
    # Atomic lookup+holds: the row references ride the same lock as
    # the lookup (a bare lookup-then-retain would race drop_entry).
    pool3 = PagePool(_model(), page_size=8, num_pages=4)
    e3 = pool3.alloc(1)
    pool3.put_entry_pages("sys", e3)
    got = pool3.entry_pages("sys", holds=2)
    assert np.array_equal(got, e3)
    pool3.drop_entry("sys")  # entry hold gone; rows still hold 2
    assert pool3.pages_in_use == 1
    pool3.release(e3)
    pool3.release(e3)
    assert pool3.pages_in_use == 0


def test_pool_eviction_counter_exact_under_concurrency():
    """Regression pin for the r16 mlapi-lint MLA002 fix: evictions
    run concurrently from the decode thread (alloc pressure) and the
    event loop (brownout ``evict_idle``), and ``entry_evictions`` —
    scraped by /metrics as ``generate.kv_entry_evictions`` — was
    bumped OUTSIDE the pool lock, so concurrent evictions could lose
    updates. The counter must now be exact: every registered entry
    evicted exactly once, counted exactly once, whatever the thread
    interleaving."""
    import threading

    n_entries = 24
    pool = PagePool(_model(), page_size=8, num_pages=n_entries + 2)
    for i in range(n_entries):
        pool.put_entry_pages(f"sys-{i}", pool.alloc(1))
    assert pool.pages_in_use == n_entries

    def churn():
        while pool.evict_idle(3):
            pass

    threads = [threading.Thread(target=churn) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # The pop-is-the-claim protocol means each entry evicts once; the
    # COUNTER matching it exactly is what the lock fix guarantees.
    assert pool.entry_evictions == n_entries
    assert pool.pages_in_use == 0
    assert pool.exhaustions == 0


# --- device seams ------------------------------------------------------


@pytest.mark.parametrize("fmt", ["none", "int8"])
def test_paged_append_and_read_match_contiguous(fmt):
    page, npv, b = 8, 4, 2
    heads, hd = CFG["num_heads"], CFG["hidden_size"] // CFG["num_heads"]
    m = get_model("gpt_lm", **dict(CFG, kv_quant=fmt))
    pools = make_paged_pools(m, 10, page)
    tab = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.int32)
    lay_p = {**pools["layer_0"], "table": jnp.asarray(tab)}
    lay_c = init_kv_cache(b, npv * page, heads, hd, jnp.float32, fmt)
    k = jax.random.normal(jax.random.key(0), (b, 3, heads, hd))
    v = jax.random.normal(jax.random.key(1), (b, 3, heads, hd))
    # Scalar-pos block write (serving layout), spanning a page edge.
    lay_p = kv_cache_append(lay_p, k, v, jnp.int32(6), jnp.float32)
    lay_c = kv_cache_append(lay_c, k, v, jnp.int32(6), jnp.float32)
    # Per-row-pos single-token write (speculation layout).
    k1 = jax.random.normal(jax.random.key(2), (b, 1, heads, hd))
    v1 = jax.random.normal(jax.random.key(3), (b, 1, heads, hd))
    pv = jnp.asarray(np.array([9, 12], np.int32))
    lay_p = kv_cache_append(lay_p, k1, v1, pv, jnp.float32)
    lay_c = kv_cache_append(lay_c, k1, v1, pv, jnp.float32)
    kp, vp = kv_cache_kv(lay_p, jnp.float32)
    kc, vc = kv_cache_kv(lay_c, jnp.float32)
    np.testing.assert_array_equal(np.asarray(kp), np.asarray(kc))
    np.testing.assert_array_equal(np.asarray(vp), np.asarray(vc))
    assert kv_cache_seq_len({"layer_0": lay_p}) == npv * page


@pytest.mark.parametrize("fmt", ["none", "int8"])
def test_paged_kernel_matches_contiguous_kernel(fmt):
    from mlapi_tpu.ops.pallas import decode_attention, paged_decode_attention
    from mlapi_tpu.ops.quant import kv_quantize

    b, npv, page, kvh, d, pool_pages, h = 2, 4, 8, 2, 16, 12, 4
    q = jax.random.normal(jax.random.key(0), (b, 1, h, d), jnp.float32)
    pk = jax.random.normal(
        jax.random.key(1), (pool_pages, page, kvh, d), jnp.float32
    )
    pv = jax.random.normal(
        jax.random.key(2), (pool_pages, page, kvh, d), jnp.float32
    )
    # Non-contiguous, per-row-distinct page placement incl. the null
    # page on unallocated tail tiles.
    tab = jnp.asarray(np.array([[2, 5, 7, 0], [1, 3, 0, 0]], np.int32))
    L = npv * page
    mask = (
        jnp.arange(L)[None, :] <= jnp.asarray([[20], [10]])
    ).astype(jnp.float32)
    if fmt == "int8":
        kq, ks = kv_quantize(pk)
        vq, vs = kv_quantize(pv)
        k_op = {"q": kq, "scale": ks}
        v_op = {"q": vq, "scale": vs}
        kc = {
            "q": kq[tab].reshape(b, L, kvh, d),
            "scale": ks[tab].reshape(b, L, kvh, 1),
        }
        vc = {
            "q": vq[tab].reshape(b, L, kvh, d),
            "scale": vs[tab].reshape(b, L, kvh, 1),
        }
    else:
        k_op, v_op = pk, pv
        kc = pk[tab].reshape(b, L, kvh, d)
        vc = pv[tab].reshape(b, L, kvh, d)
    out = paged_decode_attention(q, k_op, v_op, tab, mask, interpret=True)
    ref = decode_attention(q, kc, vc, mask, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5
    )


# --- token-identical serving streams -----------------------------------


@pytest.mark.parametrize("impl", ["einsum", "flash"])
@pytest.mark.parametrize("fmt", ["none", "int8"])
@pytest.mark.parametrize("kind", ["gpt_lm", "llama_lm"])
def test_stream_token_identical_paged_vs_contiguous(
    kind, fmt, impl, gpt_params, llama_params
):
    params = gpt_params if kind == "gpt_lm" else llama_params
    model = _model(kind, fmt, impl)
    cont = _engine(model, params, paged=False)
    paged = _engine(model, params, paged=True)
    for prompt in ("hello world", "b" * 40):  # in-bucket + bucket-2
        a = cont.generate_text(prompt, max_new_tokens=8)
        b = paged.generate_text(prompt, max_new_tokens=8)
        assert a["token_ids"] == b["token_ids"], (kind, fmt, impl, prompt)
    # Every page went back: batches release their tables at the end.
    assert paged.kv_pages_in_use == 0


def test_long_prompt_chunked_prefill_paged():
    # A prompt past the largest bucket takes the page-native chunked
    # extend path (one paged_extend_fn program per fixed-width block):
    # 200 tokens round up to a [256]-wide prompt served as two
    # 128-wide extend blocks straight into pool pages.
    model = get_model("gpt_lm", **dict(CFG, max_positions=320))
    params = model.init(jax.random.key(1))
    cont = _engine(model, params, paged=False)
    paged = _engine(model, params, paged=True)
    prompt = "x" * 200
    a = cont.generate_text(prompt, max_new_tokens=8)
    b = paged.generate_text(prompt, max_new_tokens=8)
    assert a["token_ids"] == b["token_ids"]
    assert cont.prefill_chunks >= 2 and paged.prefill_chunks >= 2


# --- prefix sharing + copy-on-write ------------------------------------


def test_prefix_hit_shares_pages_not_copies(gpt_params):
    model = _model()
    paged = _engine(model, gpt_params, paged=True)  # page 8 | bucket 16
    pre = "You are a helpful bot."
    paged.generate_text(" q1", max_new_tokens=6, prefix=pre)
    # The entry's page set is pool-resident after the first batch...
    entry_pages = paged.pool.entry_pages(pre)
    assert entry_pages is not None and len(entry_pages) > 0
    in_use_after_first = paged.kv_pages_in_use
    paged.generate_text(" q2", max_new_tokens=6, prefix=pre)
    # ...and a second request re-POINTS at it: no new permanent pages,
    # no COW at an aligned prefix bucket (64 % 8 == 0), zero copies.
    assert paged.kv_pages_in_use == in_use_after_first
    assert paged.pool.cow_copies == 0
    assert paged.prefix_hits >= 1


def test_cow_divergence_after_shared_prefix(gpt_params):
    # Entries page-align their buckets at store time (r10), so COW
    # only arises when the model window cannot FIT the aligned bucket:
    # a 135-token prefix hits cap 143 (aligned would be 144), stays
    # unaligned, and the suffix's first tokens land mid-page — every
    # row must diverge the shared tail page by COPY-ON-WRITE, and the
    # shared pages must come out unscathed (the first suffix replays
    # identically afterwards).
    model = _model()
    cont = _engine(model, gpt_params, paged=False)
    paged = _engine(model, gpt_params, paged=True, kv_page_size=12)
    pre = "b" * 135
    outs = {}
    for sfx in (" alpha", " other beta"):
        a = cont.generate_text(sfx, max_new_tokens=8, prefix=pre)
        b = paged.generate_text(sfx, max_new_tokens=8, prefix=pre)
        assert a["token_ids"] == b["token_ids"], sfx
        outs[sfx] = b["token_ids"]
    assert paged.pool.cow_copies >= 2  # one divergence per batch
    # Divergence left the shared prefix pages intact: replay matches.
    again = paged.generate_text(" alpha", max_new_tokens=8, prefix=pre)
    assert again["token_ids"] == outs[" alpha"]


def test_prefix_entry_eviction_releases_pages(gpt_params):
    model = _model()
    paged = _engine(model, gpt_params, paged=True)
    paged.prefix.max_entries = 1
    paged.generate_text(" q", max_new_tokens=4, prefix="first prefix")
    held = paged.kv_pages_in_use
    assert held > 0
    # Registering a second prefix evicts the first entry — and its
    # page set's entry hold with it.
    paged.generate_text(" q", max_new_tokens=4, prefix="second prefix")
    assert paged.pool.entry_pages("first prefix") is None


# --- pool exhaustion ---------------------------------------------------


def test_oom_of_pages_loud_reject(gpt_params):
    model = _model()
    tiny = _engine(
        model, gpt_params, paged=True, kv_page_size=8, kv_pages=3
    )
    with pytest.raises(PagePoolExhausted, match="kv-pages"):
        tiny.generate_text("does not fit", max_new_tokens=16)
    # The reject left the pool consistent: nothing leaked, and a
    # request that FITS still serves.
    assert tiny.kv_pages_in_use == 0
    small = _engine(
        model, gpt_params, paged=True, kv_page_size=8, kv_pages=4
    )
    out = small.generate_text("hi", max_new_tokens=2)
    assert len(out["token_ids"]) == 2


# --- continuous batching on page tables --------------------------------


async def test_paged_admission_growth_compaction_parity(gpt_params):
    model = _model()
    outs = {}
    for paged in (False, True):
        eng = _engine(model, gpt_params, paged=paged, max_wait_ms=0.0)
        await eng.start()
        try:
            r1 = await eng.submit("the first long request",
                                  max_new_tokens=48, stream=True)
            # Wait for r1's FIRST chunk: its batch is then provably
            # running when the joiners arrive (admission, not a new
            # batch) — the counter assert below is deterministic.
            head = await r1.queue.get()
            assert not isinstance(head, Exception)
            r2 = await eng.submit("joiner", max_new_tokens=6)
            r3 = await eng.submit("another joiner arrives",
                                  max_new_tokens=6)
            outs[paged] = await asyncio.gather(
                _collect(r1), _collect(r2), _collect(r3)
            )
            outs[paged][0] = head["token_ids"] + outs[paged][0]
            if paged:
                # Growth and compaction ran as TABLE ops and the
                # batch returned every page. The release runs on the
                # DISPATCH thread after the terminal frames — wait on
                # the counter instead of racing it (the MLA006
                # discipline; this site flaked once the r18 family
                # reordering shifted its timing).
                assert eng.admitted >= 1
                deadline = asyncio.get_running_loop().time() + 60.0
                while eng.kv_pages_in_use != 0:
                    assert (
                        asyncio.get_running_loop().time() < deadline
                    ), eng.kv_pages_in_use
                    await asyncio.sleep(0.005)
        finally:
            await eng.stop()
    assert outs[True] == outs[False]


# --- TP shard_map wrapper (ROADMAP open item) --------------------------


def test_flash_decode_tp_shard_map_stream_parity(gpt_params):
    from mlapi_tpu.parallel import create_mesh

    model = _model("gpt_lm", "int8", "flash")
    solo = _engine(model, gpt_params, paged=True)
    mesh = create_mesh((1, 2), devices=jax.devices()[:2])
    tp = _engine(model, gpt_params, paged=True, mesh=mesh)
    # The engine pinned the mesh on the model, so cached_attend wraps
    # the kernel in shard_map over the model axis (4 query / 4 KV
    # heads split 2 ways) instead of leaving the opaque pallas_call
    # to GSPMD.
    assert tp.model.mesh is mesh
    for prompt in ("hello world", "sharded decode"):
        a = solo.generate_text(prompt, max_new_tokens=8)
        b = tp.generate_text(prompt, max_new_tokens=8)
        assert a["token_ids"] == b["token_ids"], prompt


def test_tp_wrapper_kernel_level_parity():
    from mlapi_tpu.ops.pallas import (
        decode_attention,
        decode_attention_tp,
        paged_decode_attention,
        paged_decode_attention_tp,
    )
    from mlapi_tpu.parallel import create_mesh

    mesh = create_mesh((1, 2), devices=jax.devices()[:2])
    b, npv, page, kvh, d, h = 2, 2, 8, 2, 16, 4
    q = jax.random.normal(jax.random.key(0), (b, 1, h, d), jnp.float32)
    pk = jax.random.normal(jax.random.key(1), (6, page, kvh, d))
    pv = jax.random.normal(jax.random.key(2), (6, page, kvh, d))
    tab = jnp.asarray(np.array([[1, 2], [3, 4]], np.int32))
    L = npv * page
    mask = (
        jnp.arange(L)[None, :] <= jnp.asarray([[12], [9]])
    ).astype(jnp.float32)
    plain = paged_decode_attention(q, pk, pv, tab, mask, interpret=True)
    tp = paged_decode_attention_tp(
        mesh, q, pk, pv, tab, mask, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(plain), np.asarray(tp), atol=1e-6
    )
    kc = pk[tab].reshape(b, L, kvh, d)
    vc = pv[tab].reshape(b, L, kvh, d)
    plain_c = decode_attention(q, kc, vc, mask, interpret=True)
    tp_c = decode_attention_tp(mesh, q, kc, vc, mask, interpret=True)
    np.testing.assert_allclose(
        np.asarray(plain_c), np.asarray(tp_c), atol=1e-6
    )


# --- observability + the capacity model --------------------------------


async def test_metrics_exports_page_pool_gauges(gpt_params):
    import httpx

    from mlapi_tpu.serving import build_app

    eng = _engine(_model(), gpt_params, paged=True)
    app = build_app(eng)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as c:
            snap = (await c.get("/metrics")).json()
        g = snap["gauges"]
        assert g["generate.kv_pages_total"] == eng.kv_pages_total > 0
        assert g["generate.kv_pages_in_use"] == 0
        assert g["generate.kv_pages_shared"] == 0
        assert g["generate.kv_page_utilization"] == 0.0
        assert g["generate.kv_page_bytes"] == eng.kv_page_bytes()
    finally:
        await app.shutdown()


def test_capacity_model_exact_arithmetic(gpt_params):
    """The BENCH_GEN_PAGED claim, pinned from shapes alone: pool bytes
    per token equal contiguous bytes per token (paging adds
    indirection, not byte overhead), so any sequence shorter than its
    tier strictly beats the contiguous slot — waste bounded by one
    page."""
    page = 8
    model = _model()
    eng = _engine(model, gpt_params, paged=True, kv_page_size=page)
    page_b = eng.kv_page_bytes()
    assert page_b == kv_page_bytes(model, page)
    for bucket in eng.prompt_buckets:
        total = eng._cache_len(bucket, eng.default_max_new_tokens)
        abstract = jax.eval_shape(lambda t=total: model.init_cache(1, t))
        slot_b = sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            for layer in abstract.values()
            for leaf in layer.values()
        )
        # Exact identity: page_bytes * (total / page) == slot bytes.
        assert page_b * total == slot_b * page
        # A typical half-full prompt + default budget wastes less than
        # one page under paging; the contiguous slot wastes the tier
        # remainder.
        used = bucket // 2 + eng.default_max_new_tokens
        paged_bytes = -(-used // page) * page_b
        waste_paged = paged_bytes - used * page_b // page
        assert waste_paged < page_b
        assert paged_bytes <= slot_b


# --- soak: page-table churn under sequential load (heavy) --------------


@pytest.mark.heavy
@pytest.mark.slow  # 5.5 s measured call — demoted from the tier-1
# window in the r16 wall-clock buyback (see conftest); leak coverage
# stays: every non-soak paged test asserts pages_in_use==0 teardown.
def test_paged_churn_no_leaks(gpt_params):
    """Soak the page lifecycle: many sequential batches across plain,
    prefix-shared, COW-diverging, and OOM-rejected traffic — the pool
    must end with only entry page sets held and a clean free list
    (every alloc matched by a release). The prefix is cap-clamped
    (135 tokens: aligned 144 > cap 143) so it stays UNALIGNED at page
    12 and every suffix batch still exercises the COW divergence —
    store-time alignment (r10) removes it for alignable entries."""
    model = _model()
    eng = _engine(model, gpt_params, paged=True, kv_page_size=12)
    pre = "b" * 135
    for i in range(6):
        eng.generate_text(f"plain {i}", max_new_tokens=10)
        eng.generate_text(f" sfx {i}", max_new_tokens=6, prefix=pre)
    entry_pages = eng.pool.entry_pages(pre)
    assert entry_pages is not None
    # Only the entry's own holds remain.
    assert eng.kv_pages_in_use == len(entry_pages)
    assert np.all(eng.pool.ref[entry_pages] == 1)
    assert eng.pool.cow_copies >= 6
