"""Generative serving end to end: byte-level GPT trained on a planted
pattern → checkpoint → TextGenerationEngine via from_checkpoint →
POST /generate through the ASGI app."""

import asyncio
import json

import httpx
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mlapi_tpu.checkpoint import save_checkpoint
from mlapi_tpu.models import get_model
from mlapi_tpu.serving import InferenceEngine, build_app
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio

CFG = dict(
    vocab_size=260,  # ByteTokenizer: 256 bytes + 4 specials
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=96,
    compute_dtype="float32",
)


@pytest.fixture
def anyio_backend():
    return "asyncio"


def _train_char_repeater(model):
    """Teach the LM to continue 'ababab...' patterns (byte-level)."""
    tok = ByteTokenizer()
    pattern = np.asarray(tok.token_ids("ab" * 24), np.int32)  # 48 ids
    seqs = np.tile(pattern, (64, 1))
    x, y = seqs[:, :-1], seqs[:, 1:]
    params = model.init(jax.random.key(0))
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    loss = None
    for _ in range(150):
        params, opt, loss = step(params, opt, x, y)
    assert float(loss) < 0.2, f"pattern not learned: {float(loss)}"
    return params


@pytest.fixture(scope="module")
def gpt_checkpoint(tmp_path_factory):
    model = get_model("gpt_lm", **CFG)
    params = _train_char_repeater(model)
    ck = tmp_path_factory.mktemp("gpt") / "ck"
    save_checkpoint(
        ck, params, step=150,
        config={
            "model": "gpt_lm",
            "model_kwargs": CFG,
            "tokenizer": ByteTokenizer().fingerprint(),
        },
    )
    return ck


def test_from_checkpoint_builds_generation_engine(gpt_checkpoint):
    engine = InferenceEngine.from_checkpoint(gpt_checkpoint)
    assert isinstance(engine, TextGenerationEngine)
    assert engine.kind == "generative"


def test_generate_text_continues_pattern(gpt_checkpoint):
    engine = InferenceEngine.from_checkpoint(gpt_checkpoint)
    out = engine.generate_text("abababab", max_new_tokens=6)
    assert out["text"].startswith("ab") or out["text"].startswith("ba")
    assert len(out["token_ids"]) == 6


async def test_generate_over_http(gpt_checkpoint):
    engine = InferenceEngine.from_checkpoint(gpt_checkpoint)
    app = build_app(engine)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as client:
            r = await client.post(
                "/generate",
                json={"text": "abababab", "max_new_tokens": 6},
            )
            assert r.status_code == 200, r.text
            body = r.json()
            assert set(body) == {"text", "token_ids", "prompt_tokens"}
            assert len(body["token_ids"]) == 6

            # Sampling with a fixed seed is reproducible.
            r1 = await client.post(
                "/generate",
                json={"text": "ab", "max_new_tokens": 5,
                      "temperature": 0.7, "seed": 3},
            )
            r2 = await client.post(
                "/generate",
                json={"text": "ab", "max_new_tokens": 5,
                      "temperature": 0.7, "seed": 3},
            )
            assert r1.json() == r2.json()

            # Validation: absurd token counts are a 422, not a crash.
            bad = await client.post(
                "/generate", json={"text": "x", "max_new_tokens": 10_000}
            )
            assert bad.status_code == 422

            # healthz/metrics exist on the generative app too.
            assert (await client.get("/healthz")).json()["status"] == "ok"
            assert "counters" in (await client.get("/metrics")).json()
    finally:
        await app.shutdown()


def test_bucket_invariant_outputs(gpt_checkpoint):
    """The pad prefix must not leak into the result: the same prompt
    decoded from different pad buckets produces identical tokens
    (regression for the left-pad masking bug — pads used to be
    attended and to shift positions)."""
    e_small = InferenceEngine.from_checkpoint(gpt_checkpoint)
    e_small.prompt_buckets = (16,)
    e_big = InferenceEngine.from_checkpoint(gpt_checkpoint)
    e_big.prompt_buckets = (48,)
    for temp, seed in ((0.0, 0), (0.9, 5)):
        a = e_small.generate_text(
            "abab", max_new_tokens=6, temperature=temp, seed=seed
        )
        b = e_big.generate_text(
            "abab", max_new_tokens=6, temperature=temp, seed=seed
        )
        assert a["token_ids"] == b["token_ids"], (temp, seed)


async def test_concurrent_requests_coalesce_and_match_single_stream(
    gpt_checkpoint,
):
    """N concurrent /generate requests share a decode batch (few
    batch_calls) and each row's output equals its single-stream
    answer — batching must be invisible except in throughput."""
    engine = InferenceEngine.from_checkpoint(gpt_checkpoint)
    app = build_app(engine)
    await app.startup()
    try:
        prompts = ["ab", "abab", "ababab", "ba", "aabb", "abba"]
        singles = [
            engine.generate_text(p, max_new_tokens=8, temperature=0.5, seed=i)
            for i, p in enumerate(prompts)
        ]
        base_batches = engine.batch_calls
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as client:
            outs = await asyncio.gather(
                *(
                    client.post(
                        "/generate",
                        json={"text": p, "max_new_tokens": 8,
                              "temperature": 0.5, "seed": i},
                    )
                    for i, p in enumerate(prompts)
                )
            )
        for single, r in zip(singles, outs):
            assert r.status_code == 200, r.text
            assert r.json()["token_ids"] == single["token_ids"]
        # 6 requests -> far fewer than 6 batches (some coalescing).
        assert engine.batch_calls - base_batches <= 3
    finally:
        await app.shutdown()


async def test_stream_terminator_not_starved_by_dispatch_chain(
    gpt_checkpoint,
):
    """A streaming consumer co-batched with a long plain request must
    receive its final chunk and terminator promptly (≤ one chunk of
    lag), NOT when the whole batch finishes — the chained-dispatch
    loop's ≤1-in-flight rule must keep applying to a stream row's
    LAST chunk after the row leaves the live set."""
    engine = InferenceEngine.from_checkpoint(gpt_checkpoint)
    engine.max_wait_s = 0.2  # make co-batching deterministic
    await engine.start()
    try:
        short_g = await engine.submit("ab", max_new_tokens=6,
                                      stream=True)
        long_g = await engine.submit("abab", max_new_tokens=72)
        got = []
        while True:
            item = await short_g.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item
            got.extend(item["token_ids"])
        assert len(got) == 6
        # The moment the stream completed, the co-batched long plain
        # request must still be decoding: its terminator cannot have
        # been delivered yet (72 tokens >> 6 at the same chunk
        # cadence). If the chain had parked the stream's final chunk,
        # both terminators would arrive together at batch end.
        leftovers = []
        while not long_g.queue.empty():
            leftovers.append(long_g.queue.get_nowait())
        assert None not in leftovers, (
            "long request finished before the stream's terminator "
            "was delivered — the chain starved the stream row"
        )
        long_ids = [
            t for item in leftovers
            if item is not None
            for t in item["token_ids"]
        ]
        while True:
            item = await long_g.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item
            long_ids.extend(item["token_ids"])
        assert len(long_ids) == 72
    finally:
        await engine.stop()


async def test_streaming_ndjson(gpt_checkpoint):
    """stream=true yields incremental NDJSON chunks whose tokens
    concatenate to the non-streamed answer, ending with a done line."""
    engine = InferenceEngine.from_checkpoint(gpt_checkpoint)
    app = build_app(engine)
    await app.startup()
    try:
        ref = engine.generate_text("abababab", max_new_tokens=10)
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as client:
            async with client.stream(
                "POST",
                "/generate",
                json={"text": "abababab", "max_new_tokens": 10,
                      "stream": True},
            ) as r:
                assert r.status_code == 200
                assert r.headers["content-type"] == "application/x-ndjson"
                lines = []
                async for line in r.aiter_lines():
                    if line:
                        lines.append(json.loads(line))
        assert len(lines) >= 3  # at least 2 token chunks + done
        done = lines[-1]
        assert done["done"] is True
        streamed = [t for ln in lines[:-1] for t in ln["token_ids"]]
        assert streamed == ref["token_ids"] == done["token_ids"]
        assert done["text"] == ref["text"]
    finally:
        await app.shutdown()


def test_mixed_length_batch_compacts_and_matches(gpt_checkpoint):
    """A short request batched with long ones must not cost the batch
    its row for the whole decode: once >=half the rows finish, the
    loop gathers live rows into the next-smaller power-of-two program
    (batch compaction). Outputs are row-independent, so compaction
    must be invisible in the tokens."""
    from mlapi_tpu.serving.engine import _SyncSink

    engine = InferenceEngine.from_checkpoint(gpt_checkpoint)
    # Compaction belongs to the CHUNKED path — the fused-batched fast
    # path would (correctly) serve this batch in one program with no
    # compaction to observe.
    engine.fused_single = False
    singles = [
        engine.generate_text("abab", max_new_tokens=n, temperature=t, seed=s)
        for n, t, s in ((4, 0.0, 0), (4, 0.7, 1), (4, 0.0, 2), (40, 0.7, 3))
    ]

    outs = [[] for _ in range(4)]
    sinks = []
    for (n, t, s), out in zip(
        ((4, 0.0, 0), (4, 0.7, 1), (4, 0.0, 2), (40, 0.7, 3)), outs
    ):
        req = engine._encode("abab", n, t, s, None)
        sinks.append(_SyncSink(req, out))
    engine._run_batch(sinks)
    for sink in sinks:
        assert sink.error is None

    # 3 of 4 rows finish after 4 tokens -> the batch compacts 4 -> 1
    # and keeps decoding only the 40-token row.
    assert engine.compactions >= 1
    for single, got in zip(singles, outs):
        assert got == single["token_ids"]


async def test_stop_sequences(gpt_checkpoint):
    """stop strings truncate the authoritative text at the first match
    and cancel the decode row early (both response modes)."""
    engine = InferenceEngine.from_checkpoint(gpt_checkpoint)
    # Pin the decode chunk: the auto-RTT choice could pick 16 on a
    # slow host, and the early-cancel assertion below needs the stop
    # to land before max_new_tokens tokens have been pushed.
    engine.chunk = 4
    app = build_app(engine)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as client:
            # The repeater model continues 'abab…'; stopping on "ba"
            # must cut at the first boundary: one 'a' of generated
            # text survives (prompt ends in 'b' → continuation
            # 'ababab…' hits "ba" at index 1).
            r = await client.post(
                "/generate",
                json={"text": "abababab", "max_new_tokens": 12,
                      "stop": "ba"},
            )
            assert r.status_code == 200, r.text
            body = r.json()
            assert body["stopped"] == "ba"
            assert body["text"] == "a"
            # Fewer than max tokens were emitted before the cut —
            # the row was cancelled, not decoded to 12.
            assert len(body["token_ids"]) < 12

            # List form + no match: runs to max_new_tokens, no
            # "stopped" key.
            r2 = await client.post(
                "/generate",
                json={"text": "abababab", "max_new_tokens": 6,
                      "stop": ["zz", "qq"]},
            )
            body2 = r2.json()
            assert "stopped" not in body2
            assert len(body2["token_ids"]) == 6

            # Streaming: the done frame carries the truncated text
            # and the stop reason.
            async with client.stream(
                "POST", "/generate",
                json={"text": "abababab", "max_new_tokens": 12,
                      "stop": ["ba"], "stream": True},
            ) as resp:
                lines = [
                    json.loads(l) async for l in resp.aiter_lines() if l
                ]
            done = lines[-1]
            assert done["done"] is True
            assert done["stopped"] == "ba"
            assert done["text"] == "a"

            # Validation: too many / empty stop strings are a 422.
            bad = await client.post(
                "/generate",
                json={"text": "x", "stop": ["a", "b", "c", "d", "e"]},
            )
            assert bad.status_code == 422
            bad2 = await client.post(
                "/generate", json={"text": "x", "stop": [""]}
            )
            assert bad2.status_code == 422
    finally:
        await app.shutdown()
