"""The batch-1 fused fast path: a solo non-streaming /generate runs as
ONE XLA program (``generate_tier_fn`` / ``fused_spec_fn``) and its
output is byte-identical to the chunked path it replaces.

This is the round-4 serving canary for the r03 library-only fused
programs (VERDICT "Next" #1): the engine must match the library fused
rate up to dispatch overhead, which on CPU reduces to "same tokens,
one device program instead of many".
"""

import asyncio

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)

D_CFG = dict(CFG, hidden_size=16, num_layers=1)


@pytest.fixture(scope="module")
def pair():
    target = get_model("gpt_lm", **CFG)
    draft = get_model("gpt_lm", **D_CFG)
    return (
        target, target.init(jax.random.key(0)),
        draft, draft.init(jax.random.key(1)),
    )


def _engine(pair, *, fused=True, draft=False, **kw):
    t, tp, d, dp = pair
    return TextGenerationEngine(
        t, tp, tokenizer=ByteTokenizer(), chunk=8,
        draft=(d, dp) if draft else None,
        fused_single=fused, **kw,
    )


PROMPT = "the quick brown fox"


def test_fused_path_engages_and_matches_chunked(pair):
    fused = _engine(pair)
    chunked = _engine(pair, fused=False)
    for kw in (
        dict(max_new_tokens=20),                      # greedy, off-tier n
        dict(max_new_tokens=8),                       # exactly one tier
        dict(max_new_tokens=1),                       # prefill-only
        dict(max_new_tokens=17, temperature=0.9, seed=5),
        dict(max_new_tokens=17, temperature=0.8, top_k=12, top_p=0.9,
             seed=3),
    ):
        a = fused.generate_text(PROMPT, **kw)
        b = chunked.generate_text(PROMPT, **kw)
        assert a["token_ids"] == b["token_ids"], kw
    assert fused.fused_calls == 5
    assert fused.chunk_calls == 0
    assert chunked.fused_calls == 0
    assert chunked.chunk_calls > 0


def test_fused_spec_greedy_matches_plain(pair):
    spec = _engine(pair, draft=True)
    plain = _engine(pair, fused=False)
    a = spec.generate_text(PROMPT, max_new_tokens=24)
    b = plain.generate_text(PROMPT, max_new_tokens=24)
    assert a["token_ids"] == b["token_ids"]
    assert spec.fused_spec_calls == 1
    assert spec.fused_calls == 0
    assert spec.spec_rounds > 0
    assert spec.spec_drafted > 0


def test_fused_spec_sampled_matches_library(pair):
    """A prompt that exactly fills its bucket (n_pad == 0) must emit
    the library ``speculative_sample_fused`` stream verbatim — the
    engine adds nothing but bucketing to the fused program."""
    from mlapi_tpu.ops.speculative import speculative_sample_fused

    t, tp, d, dp = pair
    eng = _engine(pair, draft=True, spec_sample=True)
    text = "x" * 16  # 16 one-byte tokens -> bucket 16, no pads
    got = eng.generate_text(
        text, max_new_tokens=16, temperature=0.7, seed=9,
    )["token_ids"]
    ids = np.asarray(
        ByteTokenizer().token_ids(text), np.int32
    )[None]
    want, _ = speculative_sample_fused(
        t, tp, d, dp, ids, max_new_tokens=16,
        k=eng.spec_k, temperature=0.7, seed=9,
    )
    assert got == want
    assert eng.fused_spec_calls == 1


def test_fused_respects_budget_cap_and_falls_back(pair):
    eng = _engine(pair, fused_max_new=16)
    out = eng.generate_text(PROMPT, max_new_tokens=32)
    assert len(out["token_ids"]) == 32
    assert eng.fused_calls == 0          # over the cap -> chunked
    assert eng.chunk_calls > 0
    out = eng.generate_text(PROMPT, max_new_tokens=16)
    assert eng.fused_calls == 1          # within the cap -> fused


def test_strict_mode_requires_warmed_fused_shape(pair):
    eng = _engine(pair)
    eng._strict_admit = True             # tunnel discipline, no warmup
    eng.generate_text(PROMPT, max_new_tokens=8)
    assert eng.fused_calls == 0          # unwarmed shape -> chunked
    eng._strict_admit = False
    eng.generate_text(PROMPT, max_new_tokens=8)
    assert eng.fused_calls == 1          # proves itself once allowed
    eng._strict_admit = True
    eng.generate_text(PROMPT, max_new_tokens=8)
    assert eng.fused_calls == 2          # now warmed -> fused in strict


def test_warmup_populates_fused_grid(pair):
    eng = _engine(pair, draft=True)
    eng.warmup(full=False)
    # Minimal warmup covers the smallest bucket at both fused tiers.
    assert any(k[2] == "plain" for k in eng.fused.warmed)
    assert any(k[2] == "spec" for k in eng.fused.warmed)
    eng._strict_admit = True
    eng.generate_text("ab", max_new_tokens=8)
    assert eng.fused_spec_calls == 1


@pytest.mark.anyio
async def test_formed_batch_runs_fused_and_matches_solo(pair):
    """A collector batch of plain non-stream requests runs as ONE
    program (per-row traced budgets); every row byte-identical to its
    solo run, mixed greedy/sampled/budgets included."""
    eng = _engine(pair, fused_batch=True)  # "auto" declines on CPU
    solo = _engine(pair)
    loop = asyncio.get_running_loop()
    specs = [
        ("the quick brown fox", dict(n=12, temp=0.0, seed=0)),
        ("jumps over", dict(n=20, temp=0.8, seed=3)),
        ("the lazy dog", dict(n=5, temp=0.0, seed=0)),
    ]
    reqs = [
        eng._encode(text, kw["n"], kw["temp"], kw["seed"], loop)
        for text, kw in specs
    ]
    await loop.run_in_executor(None, lambda: eng._run_batch(reqs, True))
    assert eng.fused_batch_calls == 1
    assert eng.chunk_calls == 0
    for (text, kw), r in zip(specs, reqs):
        got = []
        while True:
            item = await r.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item
            got.extend(item["token_ids"])
        ref = solo.generate_text(
            text, max_new_tokens=kw["n"], temperature=kw["temp"],
            seed=kw["seed"],
        )
        assert got == ref["token_ids"], text
        assert len(got) == kw["n"]


@pytest.mark.anyio
async def test_batched_fused_spec_matches_plain_greedy(pair):
    """The last cell of the fused matrix: an all-greedy batch on a
    draft engine runs the whole BATCHED SPECULATION as one program,
    every row byte-identical to plain greedy (argmax-exactness)."""
    loop = asyncio.get_running_loop()
    eng = _engine(pair, draft=True, fused_batch=True)
    plain = _engine(pair, fused=False)
    texts = ["the quick brown", "fox jumps", "over the lazy dog"]
    budgets = [16, 6, 11]
    reqs = [
        eng._encode(t, n, 0.0, 0, loop)
        for t, n in zip(texts, budgets)
    ]
    await loop.run_in_executor(None, lambda: eng._run_batch(reqs, True))
    assert eng.fused_batch_calls == 1
    assert eng.spec_rounds > 0 and eng.spec_drafted > 0
    for t, n, r in zip(texts, budgets, reqs):
        got = []
        while True:
            item = await r.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item
            got.extend(item["token_ids"])
        ref = plain.generate_text(t, max_new_tokens=n)
        assert got == ref["token_ids"], t


@pytest.mark.anyio
async def test_batched_fused_skipped_for_mixed_or_stream(pair):
    """A mixed greedy/sampled batch on a draft engine falls through
    (``sampled`` is static per program); a stream row keeps the whole
    batch chunked."""
    loop = asyncio.get_running_loop()
    spec_eng = _engine(pair, draft=True, fused_batch=True)
    reqs = [
        spec_eng._encode("abcab", 8, 0.0, 0, loop),
        spec_eng._encode("xyz", 8, 0.9, 3, loop),  # sampled row
    ]
    await loop.run_in_executor(
        None, lambda: spec_eng._run_batch(reqs, True)
    )
    assert spec_eng.fused_batch_calls == 0
    for r in reqs:
        while True:
            item = await r.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item

    eng = _engine(pair)
    reqs = [
        eng._encode("abcab", 8, 0.0, 0, loop, stream=True),
        eng._encode("xyz", 8, 0.0, 0, loop),
    ]
    await loop.run_in_executor(None, lambda: eng._run_batch(reqs, True))
    assert eng.fused_batch_calls == 0
    assert eng.chunk_calls > 0
    for r in reqs:
        while True:
            item = await r.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item


@pytest.mark.anyio
async def test_staged_joiners_suppress_fused_path(pair):
    """A collector batch (admit=True) with joiners already staged must
    NOT take the fused path — one uninterruptible fused program would
    strand the joiners for a whole generation. With the staging lists
    empty the same batch runs fused."""
    eng = _engine(pair)
    loop = asyncio.get_running_loop()
    req = eng._encode(PROMPT, 12, 0.0, 0, loop)
    camper = eng._encode("xy", 2, 0.0, 1, loop)
    with eng._alock:
        eng._admit.append(camper)
    await loop.run_in_executor(None, lambda: eng._run_batch([req], True))
    assert eng.fused_calls == 0          # fell back to chunked
    assert eng.chunk_calls > 0
    # The camper was actually admitted into the running batch (it is
    # compatible), so both got terminators.
    assert eng.admitted == 1
    for r in (req, camper):
        items = []
        while True:
            item = await r.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item
            items.append(item)
        assert items
    with eng._alock:
        assert not eng._admit
    req2 = eng._encode(PROMPT, 12, 0.0, 0, loop)
    await loop.run_in_executor(None, lambda: eng._run_batch([req2], True))
    assert eng.fused_calls == 1          # staging empty -> fused
    while await req2.queue.get() is not None:
        pass


@pytest.mark.anyio
async def test_streaming_requests_stay_chunked(pair):
    eng = _engine(pair)
    loop = asyncio.get_running_loop()
    req = eng._encode(PROMPT, 12, 0.0, 0, loop, stream=True)
    await loop.run_in_executor(None, eng._run_batch, [req])
    chunks = []
    while True:
        item = await req.queue.get()
        if item is None:
            break
        assert not isinstance(item, Exception), item
        chunks.append(item["token_ids"])
    assert eng.fused_calls == 0
    assert len(chunks) > 1               # incremental delivery kept
    ref = _engine(pair).generate_text(PROMPT, max_new_tokens=12)
    assert [t for c in chunks for t in c] == ref["token_ids"]


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.mark.anyio
async def test_fused_batch_holb_wait_is_bounded(pair):
    """The HOLB bound behind the ``fused_batch="auto"`` policy
    (fused_single.py try_run_batch), pinned with the policy FORCED on:
    a fused batch is ONE uninterruptible device program, so a stream
    arriving mid-program waits — but for at most THAT one program
    (the slowest row's budget) plus its own prefill, never two.
    Two structural facts deliver the bound: arrivals during a fused
    program stage in ``_admit``/the collector queue in FIFO order, so
    no later-arriving batch can fuse ahead of the waiting stream; and
    a group CONTAINING the stream can't take the fused path at all
    (try_run_batch declines streams), so the stream's own batch starts
    promptly once the in-flight program drains.

    The bound is asserted from ENGINE COUNTERS, not wall-clock (the
    2.5x-fused-time bound this replaces flaked on loaded CI boxes,
    ADVICE r05 #3): the unbounded failure modes — stream starved
    behind a second fused batch, or behind re-fused continuations —
    all require another fused program to run before the stream's
    first token, so ``fused_batch_calls`` still being at the
    in-flight program's count when the first token arrives IS the
    one-program bound, deterministically."""
    eng = _engine(pair, fused_batch=True)
    loop = asyncio.get_running_loop()
    N = 64  # the fused rows' budget == the bound's "slowest row"

    def batch_reqs():
        return [
            eng._encode("the quick brown fox", N, 0.0, 0, loop),
            eng._encode("jumps over", N, 0.0, 0, loop),
        ]

    async def drain(r):
        while True:
            item = await r.queue.get()
            if item is None:
                return
            assert not isinstance(item, Exception), item

    # Warm by execution: the fused 2-row program and the stream's
    # chunked programs must be compiled OUTSIDE the timed window.
    warm = batch_reqs()
    await loop.run_in_executor(None, lambda: eng._run_batch(warm, True))
    for r in warm:
        await drain(r)
    warm_s = eng._encode("xy", 8, 0.0, 0, loop, stream=True)
    await loop.run_in_executor(None, lambda: eng._run_batch([warm_s]))
    await drain(warm_s)
    assert eng.fused_batch_calls == 1

    # Reference: one more fused batch of the same shape, proving the
    # two-row fused program is the path this workload takes.
    ref = batch_reqs()
    await loop.run_in_executor(None, lambda: eng._run_batch(ref, True))
    for r in ref:
        await drain(r)
    assert eng.fused_batch_calls == 2

    # The race, through the real collector: the fused batch must be
    # IN FLIGHT (batch_calls ticks at _run_batch entry) before the
    # stream is submitted. If the stream sneaks into the staging list
    # first, try_run_batch declines and no HOLB occurs — retry.
    await eng.start()
    try:
        for _ in range(5):
            base_fused = eng.fused_batch_calls
            base_calls = eng.batch_calls
            a, b = [
                await eng.submit("the quick brown fox",
                                 max_new_tokens=N),
                await eng.submit("jumps over", max_new_tokens=N),
            ]
            for _ in range(2000):
                if eng.batch_calls > base_calls:
                    break
                await asyncio.sleep(0.001)
            s = await eng.submit("xy", max_new_tokens=8, stream=True)
            first = await s.queue.get()
            # Snapshot BEFORE draining: these are the programs that
            # ran up to the stream's first token.
            fused_at_first_token = eng.fused_batch_calls
            assert not isinstance(first, Exception), first
            await drain(s)
            await drain(a)
            await drain(b)
            if eng.fused_batch_calls > base_fused:
                break  # the race landed: stream waited on a fused batch
        else:
            pytest.skip("stream kept winning the staging race "
                        "(fused path never engaged mid-arrival)")
        # The one-program bound, from counters: exactly the fused
        # batch that was in flight when the stream arrived may run
        # before its first token — a second one means the stream got
        # starved behind later-arriving or re-fused work.
        assert fused_at_first_token == base_fused + 1, (
            f"{fused_at_first_token - base_fused - 1} extra fused "
            "batch(es) dispatched before the waiting stream's first "
            "token — HOLB wait is not bounded by one program"
        )
    finally:
        await eng.stop()


def test_fused_batch_requires_fused_single(pair):
    """fused_batch=True rides the solo path's warm grid and dispatch
    machinery, so combining it with fused_single=False would be
    silently inert — the constructor must reject the contradiction."""
    with pytest.raises(ValueError, match="fused_single"):
        _engine(pair, fused=False, fused_batch=True)


@pytest.mark.anyio
async def test_homogeneous_sampled_batch_degrades_to_plain_fused(pair):
    """With a draft attached but --spec-sample OFF, an all-sampled
    formed batch cannot speculate ('sampled' is static per program) —
    but it must still take the PLAIN fused-batched program like the
    solo path does, not fall back to chunked decode. Rows stay
    byte-identical to their solo runs."""
    eng = _engine(pair, draft=True, fused_batch=True)
    solo = _engine(pair)
    loop = asyncio.get_running_loop()
    specs = [
        ("the quick brown fox", dict(n=12, temp=0.9, seed=3)),
        ("jumps over", dict(n=9, temp=0.7, seed=5)),
    ]
    reqs = [
        eng._encode(text, kw["n"], kw["temp"], kw["seed"], loop)
        for text, kw in specs
    ]
    await loop.run_in_executor(None, lambda: eng._run_batch(reqs, True))
    assert eng.fused_batch_calls == 1   # plain fused-batched engaged
    assert eng.spec_rounds == 0         # no speculation without the flag
    assert eng.chunk_calls == 0
    for (text, kw), r in zip(specs, reqs):
        got = []
        while True:
            item = await r.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item
            got.extend(item["token_ids"])
        ref = solo.generate_text(
            text, max_new_tokens=kw["n"], temperature=kw["temp"],
            seed=kw["seed"],
        )
        assert got == ref["token_ids"], text
