"""Fused-chunk widths as typed units (r20).

Up to r15 a fused-eligible request ran as ONE uninterruptible XLA
program (``generate_tier_fn`` / ``fused_spec_fn``) behind per-path
decline gates (deadlines, streams, joiners, disagg). r20 folds the
dispatch saving into the one execution model: a fused-eligible batch
decodes TIER-WIDE chunks through the same ``decode_chunk_fn`` seam,
each fused chunk one schedulable ``"decode"`` unit — so deadlines,
admission, faults and drain apply to fused traffic with no parallel
path left to diverge. This module pins the fold's contract:

- byte-identity: fused widths change dispatch count, never tokens;
- engagement: ``fused_calls`` ticks once per batch that dispatched at
  least one fused-width chunk, and the fused engine pays strictly
  fewer ``chunk_calls`` than the plain-chunk engine;
- no declines: over-cap budgets ride the widest tier, deadlined
  requests ride fused chunks (both formerly fell back / declined);
- streams pin the plain chunk (incremental delivery), including a
  streaming JOINER admitted mid-generation into a fused lane;
- strict (tunnel) mode takes fused widths only for shapes the warm
  grid proved compiled, and the warm grid records at the dispatch
  site so the two can never disagree.

Same model CFG as the paged family (vocab 260 / h32 / 2L / 4H /
160 pos, f32) at page 8 / chunk 2 — the module shares that cache
window (conftest) and re-drives its compiled prefill/plain-decode
programs; only the fused-width chunk shapes are new.
"""

import asyncio

import jax
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)


@pytest.fixture(scope="module")
def gpt_params():
    return get_model("gpt_lm", **CFG).init(jax.random.key(0))


def _engine(params, *, fused=True, **kw):
    kw.setdefault("chunk", 2)
    kw.setdefault("kv_page_size", 8)
    kw.setdefault("max_wait_ms", 0.0)
    return TextGenerationEngine(
        get_model("gpt_lm", **CFG), params, tokenizer=ByteTokenizer(),
        fused_single=fused, **kw,
    )


async def _collect(req):
    out: list = []
    frames = 0
    while True:
        item = await req.queue.get()
        if item is None:
            return out, frames, None
        if isinstance(item, Exception):
            return out, frames, item
        out.extend(item["token_ids"])
        frames += 1


PROMPT = "the quick brown fox"  # 19 bytes -> bucket 32


def test_fused_widths_engage_and_match_chunked(gpt_params):
    fused = _engine(gpt_params)
    chunked = _engine(gpt_params, fused=False)
    for kw in (
        dict(max_new_tokens=20),                      # greedy, off-tier n
        dict(max_new_tokens=32),                      # exactly one tier
        dict(max_new_tokens=1),                       # prefill-only
        dict(max_new_tokens=17, temperature=0.9, seed=5),
        dict(max_new_tokens=17, temperature=0.8, top_k=12, top_p=0.9,
             seed=3),
    ):
        a = fused.generate_text(PROMPT, **kw)
        b = chunked.generate_text(PROMPT, **kw)
        assert a["token_ids"] == b["token_ids"], kw
    # n=1 never beats the plain chunk (width_at shrinks to the
    # remaining budget); the other four dispatched fused widths.
    assert fused.fused_calls == 4
    assert chunked.fused_calls == 0
    # The saving the fold keeps: tier-wide chunks are FEWER dispatches
    # of the same program family, not a separate program.
    assert 0 < fused.chunk_calls < chunked.chunk_calls


def test_over_cap_budget_rides_widest_tier(gpt_params):
    """fused_max_new caps the WIDTH ladder, not eligibility: a budget
    over the cap dispatches at the widest rung instead of silently
    falling back to the plain chunk (the r03 gate this replaces)."""
    fused = _engine(gpt_params)            # cap = fused_max_new = 64
    chunked = _engine(gpt_params, fused=False)
    a = fused.generate_text(PROMPT, max_new_tokens=100)
    assert len(a["token_ids"]) == 100
    assert fused.fused_calls == 1          # engaged, 64-wide chunks
    b = chunked.generate_text(PROMPT, max_new_tokens=100)
    assert a["token_ids"] == b["token_ids"]


def test_strict_mode_requires_warmed_fused_shape(gpt_params):
    eng = _engine(gpt_params)
    eng._strict_admit = True             # tunnel discipline, no warmup
    eng.generate_text(PROMPT, max_new_tokens=32)
    assert eng.fused_calls == 0          # unwarmed shape -> plain chunks
    eng._strict_admit = False
    eng.generate_text(PROMPT, max_new_tokens=32)
    assert eng.fused_calls == 1          # proves itself once allowed
    eng._strict_admit = True
    eng.generate_text(PROMPT, max_new_tokens=32)
    assert eng.fused_calls == 2          # now warmed -> fused in strict


def test_warmup_populates_fused_width_grid(gpt_params):
    """warm() drives REAL solo runs at ladder budgets, so the warmed
    set is populated at the dispatch site — strict mode then takes
    fused widths for exactly the shapes that actually compiled."""
    eng = _engine(gpt_params)
    eng.warmup(full=False)
    # Minimal warmup: first bucket at every ladder width up to the
    # default tier (4, 8, 16, 32 at chunk=2).
    assert len(eng.fused.warmed) >= 4
    eng._strict_admit = True
    eng.generate_text("ab", max_new_tokens=8)
    assert eng.fused_calls >= 1          # warmed shape fused in strict


async def test_formed_batch_rides_fused_widths(gpt_params):
    """A multi-row all-non-streaming batch dispatches fused widths
    exactly like a solo one (the r05 fused_batch flag is gone — width
    policy is per boundary, not per path); every row byte-identical
    to its solo run, mixed greedy/sampled/budgets included."""
    eng = _engine(gpt_params)
    solo = _engine(gpt_params)
    loop = asyncio.get_running_loop()
    specs = [
        ("the quick brown fox", dict(n=12, temp=0.0, seed=0)),
        ("jumps over", dict(n=20, temp=0.8, seed=3)),
        ("the lazy dog", dict(n=5, temp=0.0, seed=0)),
    ]
    reqs = [
        eng._encode(text, kw["n"], kw["temp"], kw["seed"], loop)
        for text, kw in specs
    ]
    await loop.run_in_executor(None, lambda: eng._run_batch(reqs, True))
    assert eng.fused_calls == 1
    assert eng.chunk_calls == 1  # one 32-wide chunk covered all rows
    for (text, kw), r in zip(specs, reqs):
        got, _, err = await _collect(r)
        assert err is None
        ref = solo.generate_text(
            text, max_new_tokens=kw["n"], temperature=kw["temp"],
            seed=kw["seed"],
        )
        assert got == ref["token_ids"], text
        assert len(got) == kw["n"]


async def test_deadlined_request_rides_fused_chunks(gpt_params):
    """Deadlines no longer decline the fused path: a deadlined
    fused-eligible request dispatches tier-wide chunks, and the r12
    expiry sweeps still run at every unit boundary (one seam)."""
    eng = _engine(gpt_params)
    await eng.start()
    try:
        r = await eng.submit(
            PROMPT, max_new_tokens=34, deadline_ms=60000.0,
        )
        toks, _, err = await _collect(r)
        assert err is None
        assert len(toks) == 34
        assert eng.fused_calls == 1      # fused despite the deadline
    finally:
        await eng.stop()
    ref = _engine(gpt_params, fused=False).generate_text(
        PROMPT, max_new_tokens=34
    )
    assert toks == ref["token_ids"]


async def test_streams_identical_across_execution_modes(gpt_params):
    """The identity matrix cell this module owns: fused default
    (scheduler on), fused serial (sched_max_batches=1) and plain
    chunked produce byte-identical streams for the same traffic."""
    engines = [
        _engine(gpt_params),                        # fused, scheduler on
        _engine(gpt_params, sched_max_batches=1),   # fused, serial
        _engine(gpt_params, fused=False),           # plain chunks
    ]
    outs = []
    for eng in engines:
        await eng.start()
        try:
            # Non-stream wave first: submitted together they may group
            # (or lane separately — identical bytes either way) and on
            # the fused engines they ride tier-wide chunks. The stream
            # goes AFTER the wave completes, or it would join the same
            # window and pin the plain width for everyone.
            reqs = [
                await eng.submit(PROMPT, max_new_tokens=20),
                await eng.submit("jumps over", max_new_tokens=17,
                                 temperature=0.9, seed=5),
            ]
            got = []
            for r in reqs:
                toks, _, err = await _collect(r)
                assert err is None
                got.append(toks)
            s = await eng.submit("the lazy dog", max_new_tokens=12,
                                 stream=True)
            toks, _, err = await _collect(s)
            assert err is None
            got.append(toks)
            outs.append(got)
        finally:
            await eng.stop()
    assert outs[0] == outs[1] == outs[2]
    assert engines[0].fused_calls >= 1
    assert engines[1].fused_calls >= 1
    assert engines[2].fused_calls == 0


async def test_streaming_rows_pin_plain_chunks(gpt_params):
    """Incremental delivery wins over width: a streaming request
    decodes at the plain chunk and its consumer sees >1 frames."""
    eng = _engine(gpt_params)
    await eng.start()
    try:
        r = await eng.submit(PROMPT, max_new_tokens=12, stream=True)
        toks, frames, err = await _collect(r)
        assert err is None
        assert frames > 1                # incremental delivery kept
        assert eng.fused_calls == 0
    finally:
        await eng.stop()
    ref = _engine(gpt_params).generate_text(PROMPT, max_new_tokens=12)
    assert toks == ref["token_ids"]


async def test_streaming_joiner_drops_width_mid_generation(gpt_params):
    """Continuous admission reaches fused traffic (the old
    one-program path stranded joiners for a whole generation): a
    streaming joiner installs at a fused-chunk boundary and the width
    drops to the plain chunk while it is live — the joiner streams
    incrementally and both rows stay byte-identical to solo runs."""
    from mlapi_tpu.serving import faults

    eng = _engine(gpt_params)
    await eng.start()
    try:
        # Host budget over the widest rung: 100 new tokens ride
        # 64-wide chunks, so there is a boundary after the first
        # fused chunk for the joiner to install at. The decode delay
        # keeps the window open without wall-clock assertions.
        faults.arm("decode:every=1:delay=0.05")
        host = await eng.submit("hello", max_new_tokens=100)
        # Wait for the first fused-width dispatch to be IN FLIGHT, so
        # the joiner cannot land before it and pin the plain width
        # from the start.
        deadline = asyncio.get_running_loop().time() + 60.0
        while eng.fused_calls < 1:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.002)
        joiner = await eng.submit("ab", max_new_tokens=4, stream=True)
        (ht, _, he), (jt, jframes, je) = await asyncio.gather(
            _collect(host), _collect(joiner)
        )
        assert he is None and je is None
        assert len(ht) == 100 and len(jt) == 4
        assert eng.admitted == 1         # joined the fused lane
        assert eng.fused_calls == 1      # fused before the joiner
        assert jframes > 1               # streamed at plain width
    finally:
        faults.disarm()
        await eng.stop()
    ref = _engine(gpt_params)
    assert ht == ref.generate_text("hello", max_new_tokens=100)["token_ids"]
    assert jt == ref.generate_text("ab", max_new_tokens=4)["token_ids"]
