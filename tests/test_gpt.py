"""GPT decoder: causal correctness, KV-cache decode parity with the
full forward (the silent killer in every decoder implementation), TP
sharding, and learnability on a planted sequence task."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mlapi_tpu.models import get_model

TINY = dict(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=64,
    compute_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    return get_model("gpt_lm", **TINY)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def test_forward_shapes(model, params):
    ids = np.ones((3, 10), np.int32)
    logits = jax.jit(model.apply)(params, ids)
    assert logits.shape == (3, 10, TINY["vocab_size"])


def test_causality(model, params):
    """Changing a future token must not change past logits."""
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (2, 16)).astype(np.int32)
    base = np.asarray(jax.jit(model.apply)(params, ids))
    ids2 = ids.copy()
    ids2[:, 10:] = (ids2[:, 10:] + 7) % 64
    out = np.asarray(jax.jit(model.apply)(params, ids2))
    np.testing.assert_allclose(out[:, :10], base[:, :10], atol=1e-5)
    assert not np.allclose(out[:, 10:], base[:, 10:], atol=1e-5)


def test_kv_cache_decode_matches_full_forward(model, params):
    """Token-by-token decode through the cache must produce the same
    next-token choices as re-running the full forward each step."""
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 64, (2, 8)).astype(np.int32)
    n_new = 6

    generated = np.asarray(
        model.generate(params, jnp.asarray(prompt), max_new_tokens=n_new)
    )

    # Reference: greedy decode by full re-forward (no cache).
    seq = prompt.copy()
    ref = []
    for _ in range(n_new):
        logits = np.asarray(jax.jit(model.apply)(params, seq))
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        ref.append(nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(generated, np.stack(ref, axis=1))


def test_sampled_generation_is_reproducible(model, params):
    prompt = np.ones((1, 4), np.int32)
    a = model.generate(
        params, jnp.asarray(prompt), max_new_tokens=5, temperature=0.8,
        rng=jax.random.key(7),
    )
    b = model.generate(
        params, jnp.asarray(prompt), max_new_tokens=5, temperature=0.8,
        rng=jax.random.key(7),
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_generate_rejects_overflow(model, params):
    with pytest.raises(ValueError, match="max_positions"):
        model.generate(
            params, jnp.ones((1, 60), jnp.int32), max_new_tokens=10
        )


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_learns_induction_copy_task(model):
    """Train on sequences where token t+1 = token t (constant-run
    sequences): a causal LM must drive loss near zero; a broken mask
    or cache can't."""
    rng = np.random.default_rng(2)
    starts = rng.integers(0, 64, (512, 1)).astype(np.int32)
    seqs = np.repeat(starts, 17, axis=1)  # [B, 17], constant runs
    x, y = seqs[:, :-1], seqs[:, 1:]

    params = model.init(jax.random.key(1))
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    loss = None
    for _ in range(120):
        params, opt, loss = step(params, opt, x, y)
    assert float(loss) < 0.1, f"copy task not learned, loss={float(loss)}"

    # And generation actually continues the pattern.
    out = model.generate(
        params, jnp.asarray([[5, 5, 5, 5]], jnp.int32), max_new_tokens=4
    )
    np.testing.assert_array_equal(np.asarray(out), [[5, 5, 5, 5]])


def test_tp_sharded_forward(model, params, mesh_2x4):
    from mlapi_tpu.parallel import params_for_model, shard_batch_for_mesh

    placed = params_for_model(model, params, mesh_2x4)
    assert tuple(placed["wte"].sharding.spec)[0] == "model"
    ids = shard_batch_for_mesh(np.ones((8, 12), np.int32), mesh_2x4)
    sharded = np.asarray(jax.jit(model.apply)(placed, ids))
    ref = np.asarray(jax.jit(model.apply)(params, np.ones((8, 12), np.int32)))
    np.testing.assert_allclose(sharded, ref, atol=1e-4)


def test_top_k_one_is_greedy():
    """top_k=1 collapses sampling to argmax regardless of
    temperature — and the filter params are traced, so this reuses
    the same compiled program as unfiltered sampling."""
    model = get_model("gpt_lm", **TINY)
    params = model.init(jax.random.key(0))
    prompt = np.tile(np.arange(10, 18, dtype=np.int32), (2, 1))
    greedy = model.generate(
        params, jnp.asarray(prompt), max_new_tokens=8, temperature=0.0
    )
    k1 = model.generate(
        params, jnp.asarray(prompt), max_new_tokens=8, temperature=1.5,
        top_k=1,
    )
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))
    # Tiny nucleus: only the argmax token survives the cumulative cut.
    p_tiny = model.generate(
        params, jnp.asarray(prompt), max_new_tokens=8, temperature=1.5,
        top_p=1e-6,
    )
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(p_tiny))


def test_top_k_restricts_support():
    """Sampled ids under top_k=k must always be among the k highest
    logits of the (recomputed) next-token distribution."""
    model = get_model("gpt_lm", **TINY)
    params = model.init(jax.random.key(1))
    prompt = np.tile(np.arange(5, 13, dtype=np.int32), (1, 1))
    k = 3
    out = model.generate(
        params, jnp.asarray(prompt), max_new_tokens=1, temperature=2.0,
        top_k=k, rng=jax.random.key(7),
    )
    logits = np.asarray(model.apply(params, jnp.asarray(prompt)))[0, -1]
    top = set(np.argsort(logits)[-k:].tolist())
    assert int(out[0, 0]) in top


@pytest.mark.parametrize("family", ["gpt_lm", "llama_lm"])
def test_extend_core_chunks_match_single_prefill(family):
    """Op-level pin of the chunked-prefill building block: running a
    left-padded prompt as sequential extend_core blocks over a fresh
    cache reproduces prefill_core's cache contents AND next-token
    logits exactly — including a fully-padded first chunk (per-row
    pad counts crossing chunk boundaries) and GQA kv caches."""
    kw = dict(vocab_size=120, hidden_size=32, num_layers=2,
              max_positions=96, compute_dtype="float32")
    if family == "llama_lm":
        m = get_model(family, **kw, num_heads=4, num_kv_heads=2)
    else:
        m = get_model(family, **kw, num_heads=4)
    p = m.init(jax.random.key(0))
    rng = np.random.default_rng(5)
    b, width, total = 3, 64, 80
    prompt = rng.integers(3, 119, size=(b, width)).astype(np.int32)
    n_pad = np.asarray([0, 7, 40], np.int32)  # row 2 pads past chunk 0
    for i in range(b):
        prompt[i, : n_pad[i]] = 0

    cache_ref, logits_ref = m.prefill_core(
        p, jnp.asarray(prompt), jnp.asarray(n_pad), total
    )

    cache = m.init_cache(b, total)
    logits = None
    for c0 in range(0, width, 32):
        cache, logits = m.extend_core(
            p, cache, jnp.asarray(prompt[:, c0:c0 + 32]),
            jnp.int32(c0), jnp.asarray(n_pad),
            jnp.int32(0), jnp.int32(0),
        )

    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(logits_ref), atol=2e-4, rtol=1e-4
    )
    # Cache K/V must agree at every VALID slot (pads hold garbage in
    # both paths and are masked, so compare only real-token slots).
    for layer in cache:
        for kv in ("k", "v"):
            got = np.asarray(cache[layer][kv], np.float32)
            ref = np.asarray(cache_ref[layer][kv], np.float32)
            for i in range(b):
                np.testing.assert_allclose(
                    got[i, n_pad[i]:width], ref[i, n_pad[i]:width],
                    atol=2e-4, rtol=1e-4,
                    err_msg=f"{layer}/{kv} row {i}",
                )
