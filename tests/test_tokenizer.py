"""Tokenizers: WordPiece correctness against BERT's scheme, hash
fallback determinism, fixed-length encoding contract."""

import numpy as np

from mlapi_tpu.text import HashTokenizer, WordPieceTokenizer

TOY_VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]",
    "the", "movie", "was", "great", "##ly", "un", "##believ", "##able",
    ",", "!",
]


def test_wordpiece_greedy_longest_match():
    tok = WordPieceTokenizer(TOY_VOCAB)
    ids = tok.token_ids("unbelievable")
    assert [TOY_VOCAB[i] for i in ids] == ["un", "##believ", "##able"]


def test_wordpiece_punctuation_and_case():
    tok = WordPieceTokenizer(TOY_VOCAB)
    ids = tok.token_ids("The movie, was GREAT!")
    assert [TOY_VOCAB[i] for i in ids] == [
        "the", "movie", ",", "was", "great", "!",
    ]


def test_wordpiece_unknown_word():
    tok = WordPieceTokenizer(TOY_VOCAB)
    assert tok.token_ids("zzz") == [tok.unk_id]


def test_encode_contract():
    tok = WordPieceTokenizer(TOY_VOCAB)
    ids, mask = tok.encode("the movie was great", max_len=8)
    assert ids.shape == (8,) and mask.shape == (8,)
    assert ids[0] == tok.cls_id
    assert ids[5] == tok.sep_id  # 4 tokens + CLS
    assert mask.tolist() == [1, 1, 1, 1, 1, 1, 0, 0]
    # Truncation keeps CLS/SEP.
    ids2, mask2 = tok.encode("the movie was great " * 10, max_len=6)
    assert ids2[0] == tok.cls_id and ids2[5] == tok.sep_id
    assert mask2.sum() == 6


def test_hash_tokenizer_deterministic_and_in_range():
    a, b = HashTokenizer(1000), HashTokenizer(1000)
    ta, tb = a.token_ids("some words here"), b.token_ids("some words here")
    assert ta == tb
    assert all(4 <= t < 1000 for t in ta)
    # Different words, different ids (overwhelmingly).
    assert a.token_ids("alpha") != a.token_ids("omega")
