"""Multipart parser unit tests (RFC 7578 shapes + malformed bodies)."""

import pytest

from mlapi_tpu.serving.multipart import (
    MultipartError,
    boundary_from_content_type,
    parse_multipart,
)


def encode(parts, boundary=b"BoUnDaRy123"):
    out = bytearray()
    for name, filename, ctype, data in parts:
        out += b"--" + boundary + b"\r\n"
        disp = f'Content-Disposition: form-data; name="{name}"'
        if filename is not None:
            disp += f'; filename="{filename}"'
        out += disp.encode() + b"\r\n"
        if ctype:
            out += f"Content-Type: {ctype}".encode() + b"\r\n"
        out += b"\r\n" + data + b"\r\n"
    out += b"--" + boundary + b"--\r\n"
    return bytes(out)


def test_fields_and_files():
    body = encode(
        [
            ("token", None, None, b"sekrit"),
            ("file", "iris.csv", "text/csv", b"a,b\r\n1,2\r\n"),
        ]
    )
    parts = parse_multipart(body, b"BoUnDaRy123")
    assert [p.name for p in parts] == ["token", "file"]
    assert parts[0].filename is None and parts[0].text() == "sekrit"
    assert parts[1].filename == "iris.csv"
    assert parts[1].content_type == "text/csv"
    assert parts[1].data == b"a,b\r\n1,2\r\n"


def test_binary_data_with_crlf_inside():
    payload = b"line1\r\nline2\r\n\r\nbinary\x00\xff"
    parts = parse_multipart(
        encode([("file", "x.bin", None, payload)]), b"BoUnDaRy123"
    )
    assert parts[0].data == payload


def test_boundary_extraction():
    assert (
        boundary_from_content_type('multipart/form-data; boundary="abc123"')
        == b"abc123"
    )
    assert (
        boundary_from_content_type("multipart/form-data; boundary=xyz") == b"xyz"
    )
    with pytest.raises(MultipartError):
        boundary_from_content_type("application/json")


def test_unterminated_body_rejected():
    body = encode([("a", None, None, b"1")])
    with pytest.raises(MultipartError, match="terminated"):
        parse_multipart(body[:-8], b"BoUnDaRy123")


def test_missing_name_rejected():
    boundary = b"B"
    body = (
        b"--B\r\nContent-Disposition: form-data\r\n\r\ndata\r\n--B--\r\n"
    )
    with pytest.raises(MultipartError, match="field name"):
        parse_multipart(body, boundary)


def test_wrong_boundary_rejected():
    body = encode([("a", None, None, b"1")])
    with pytest.raises(MultipartError, match="never appears"):
        parse_multipart(body, b"NotTheBoundary")
