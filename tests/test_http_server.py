"""The framework's own HTTP/1.1 server over real sockets: request
parsing, keep-alive, chunked bodies, protocol-error responses."""

import asyncio
import json

import httpx
import pytest

from mlapi_tpu.serving.asgi import App
from mlapi_tpu.serving.server import Server

pytestmark = pytest.mark.anyio


def make_app() -> App:
    app = App()

    @app.get("/ping")
    async def ping():
        return {"pong": True}

    @app.post("/echo")
    async def echo(request):
        return {"len": len(request.body), "body": request.body.decode("latin-1")}

    return app


@pytest.fixture()
async def server():
    srv = Server(make_app(), host="127.0.0.1", port=0)
    await srv.start()
    yield srv
    await srv.stop()


async def test_get_and_post_over_real_socket(server):
    async with httpx.AsyncClient(
        base_url=f"http://127.0.0.1:{server.port}"
    ) as client:
        r = await client.get("/ping")
        assert r.status_code == 200 and r.json() == {"pong": True}
        r = await client.post("/echo", content=b"hello")
        assert r.json() == {"len": 5, "body": "hello"}


async def test_keep_alive_reuses_connection(server):
    reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
    try:
        for i in range(3):
            writer.write(
                b"GET /ping HTTP/1.1\r\nhost: x\r\n\r\n"
            )
            await writer.drain()
            status = await reader.readline()
            assert b"200" in status
            headers = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            assert headers["connection"] == "keep-alive"
            body = await reader.readexactly(int(headers["content-length"]))
            assert json.loads(body) == {"pong": True}
    finally:
        writer.close()


async def test_chunked_request_body(server):
    reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
    try:
        writer.write(
            b"POST /echo HTTP/1.1\r\nhost: x\r\n"
            b"transfer-encoding: chunked\r\n\r\n"
            b"5\r\nhello\r\n6\r\n world\r\n0\r\n\r\n"
        )
        await writer.drain()
        raw = await reader.readuntil(b"\r\n\r\n")
        assert b"200" in raw.split(b"\r\n")[0]
        length = int(
            [l for l in raw.split(b"\r\n") if l.lower().startswith(b"content-length")][
                0
            ].split(b":")[1]
        )
        body = json.loads(await reader.readexactly(length))
        assert body == {"len": 11, "body": "hello world"}
    finally:
        writer.close()


async def test_malformed_request_line_400(server):
    reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
    try:
        writer.write(b"GARBAGE\r\n\r\n")
        await writer.drain()
        status = await reader.readline()
        assert b"400" in status
    finally:
        writer.close()


async def test_unsupported_protocol_501(server):
    reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
    try:
        writer.write(b"GET /ping SPDY/3\r\n\r\n")
        await writer.drain()
        assert b"501" in await reader.readline()
    finally:
        writer.close()


async def test_connection_close_honored(server):
    async with httpx.AsyncClient(
        base_url=f"http://127.0.0.1:{server.port}"
    ) as client:
        r = await client.get("/ping", headers={"connection": "close"})
        assert r.status_code == 200
        assert r.headers["connection"] == "close"


async def test_loadgen_against_server(server):
    from mlapi_tpu.serving.loadgen import run_load

    result = await run_load(
        "127.0.0.1", server.port, "/ping", concurrency=8, duration_s=0.5
    )
    assert result.errors == 0
    assert result.requests > 50
    assert result.quantile(0.5) < 50.0
