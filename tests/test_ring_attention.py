"""Ring attention vs full attention: exact same math, different
communication pattern — so outputs (and grads) must agree to float
tolerance on a multi-device mesh (SURVEY §4 "distributed without a
cluster": 8 virtual CPU devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.ops import full_attention, ring_self_attention
from mlapi_tpu.parallel import create_mesh

B, L, H, D = 2, 32, 4, 8


def _qkv(seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (B, L, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.fixture(scope="module")
def seq_mesh():
    return create_mesh((2, 4), axis_names=("data", "seq"))


@pytest.fixture(scope="module")
def seq_only_mesh():
    return create_mesh((1, 8), axis_names=("data", "seq"))


def test_matches_full_attention(seq_mesh):
    q, k, v = _qkv()
    out = ring_self_attention(seq_mesh, q, k, v)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_matches_with_ragged_padding_mask(seq_only_mesh):
    q, k, v = _qkv(seed=1)
    lengths = np.array([L - 5, 7])  # one nearly-full row, one short row
    mask = (np.arange(L)[None, :] < lengths[:, None]).astype(np.float32)
    out = ring_self_attention(seq_only_mesh, q, k, v, jnp.asarray(mask))
    ref = full_attention(q, k, v, jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_causal_matches(seq_mesh):
    q, k, v = _qkv(seed=2)
    out = ring_self_attention(seq_mesh, q, k, v, causal=True)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_fully_masked_block_is_nan_free(seq_only_mesh):
    """A whole device's key block masked out must not poison the
    online-softmax recurrence (the exp(NEG-NEG)==1 hazard)."""
    q, k, v = _qkv(seed=3)
    mask = np.ones((B, L), np.float32)
    mask[:, L // 2 :] = 0.0  # last 4 of 8 ring blocks fully masked
    out = ring_self_attention(seq_only_mesh, q, k, v, jnp.asarray(mask))
    assert np.isfinite(np.asarray(out)).all()
    ref = full_attention(q, k, v, jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_bfloat16_inputs_keep_dtype_and_accuracy(seq_mesh):
    q, k, v = _qkv(seed=4, dtype=jnp.bfloat16)
    out = ring_self_attention(seq_mesh, q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


def test_gradients_match(seq_mesh):
    q, k, v = _qkv(seed=5)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(seq_mesh, q, k, v) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_bert_ring_encoder_matches_full(seq_mesh):
    """Same params, full vs ring attention backend: logits must agree.
    Exercises the jit path (serving traces encode under jit) with a
    real padding mask and L sharded over the seq axis."""
    from mlapi_tpu.models import get_model

    cfg = dict(
        num_classes=2, vocab_size=128, hidden_size=32, num_layers=2,
        num_heads=4, intermediate_size=64, max_positions=32,
        compute_dtype="float32",
    )
    full = get_model("bert_classifier", **cfg)
    ring = get_model(
        "bert_classifier", **cfg, attention_impl="ring", mesh=seq_mesh
    )
    params = full.init(jax.random.key(0))
    ids = np.ones((2, L), np.int32)
    ids[0, 20:] = 0  # padding → masked keys
    ids[1, 9:] = 0

    ref = jax.jit(full.apply)(params, jnp.asarray(ids))
    out = jax.jit(ring.apply)(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_bert_rejects_ring_without_mesh():
    from mlapi_tpu.models import get_model

    with pytest.raises(ValueError, match="needs a mesh"):
        get_model("bert_classifier", attention_impl="ring")


def test_single_row_batch_falls_back_to_replicated(seq_mesh):
    """B=1 (the common serving case) on a data-axis-2 mesh must not
    crash — the batch spec falls back to replicated."""
    ks = jax.random.split(jax.random.key(6), 3)
    q, k, v = (jax.random.normal(kk, (1, L, H, D)) for kk in ks)
    out = ring_self_attention(seq_mesh, q, k, v)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_rejects_indivisible_sequence(seq_only_mesh):
    q, k, v = (jnp.ones((1, 12, 2, 4)),) * 3
    with pytest.raises(ValueError, match="not divisible"):
        ring_self_attention(seq_only_mesh, q, k, v)


def test_flash_blocks_match_einsum_blocks(seq_mesh):
    """block_impl='flash' (Pallas kernel per ring step + exact lse
    merge) must agree with the einsum path and the full reference —
    the SP x kernel composition, not just a claim."""
    q, k, v = _qkv(seed=11)
    ref = full_attention(q, k, v)
    out = ring_self_attention(seq_mesh, q, k, v, block_impl="flash")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_blocks_causal_with_mask(seq_only_mesh):
    """Causal flash-block ring: past blocks full, diagonal causal,
    future skipped — with a ragged padding mask on top."""
    q, k, v = _qkv(seed=12)
    lengths = np.array([L - 5, 7])
    mask = jnp.asarray(
        (np.arange(L)[None, :] < lengths[:, None]).astype(np.float32)
    )
    ref = full_attention(q, k, v, mask, causal=True)
    out = ring_self_attention(
        seq_only_mesh, q, k, v, mask, causal=True, block_impl="flash"
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_blocks_gradients_match(seq_mesh):
    """Training through ring x flash: grads flow through the Pallas
    VJP *and* the lse merge (the lse cotangent path)."""
    q, k, v = _qkv(seed=13)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_self_attention(
                seq_mesh, q, k, v, causal=True, block_impl="flash"
            )
            ** 2
        )

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_gpt_ring_matches_full(seq_mesh):
    """GptLM(attention_impl='ring') scores sequences identically to
    the full-attention model — long-context decoder training is
    reachable, not forbidden (VERDICT r1 weak #5)."""
    from mlapi_tpu.models import get_model

    cfg = dict(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_positions=64, compute_dtype="float32",
    )
    full = get_model("gpt_lm", **cfg)
    ring = get_model("gpt_lm", **cfg, attention_impl="ring", mesh=seq_mesh)
    params = full.init(jax.random.key(0))
    ids = np.random.default_rng(5).integers(0, 64, (2, 32)).astype(np.int32)
    ref = jax.jit(full.apply)(params, ids)
    out = jax.jit(ring.apply)(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    # Zigzag ring (flash blocks, load-balanced stripes) is a model
    # option too, and scores identically.
    zig = get_model(
        "gpt_lm", **cfg, attention_impl="ring", mesh=seq_mesh,
        ring_block_impl="flash", ring_zigzag=True,
    )
    ids64 = np.random.default_rng(6).integers(0, 64, (2, 64)).astype(np.int32)
    np.testing.assert_allclose(
        np.asarray(jax.jit(zig.apply)(params, ids64)),
        np.asarray(jax.jit(full.apply)(params, ids64)),
        atol=1e-4,
    )

    with pytest.raises(ValueError, match="requires a mesh"):
        get_model("gpt_lm", **cfg, attention_impl="ring")
    with pytest.raises(ValueError, match="zigzag"):
        get_model(
            "gpt_lm", **cfg, attention_impl="ring", mesh=seq_mesh,
            ring_zigzag=True,
        )


def test_zigzag_matches_full_attention():
    """Zigzag-layout causal ring attention (the load-balanced layout:
    device i holds stripes (i, 2n-1-i), every ring step costs two
    half-block flash units on every device) must be numerically
    identical to plain full attention — the permutation, the
    stripe-pair branch decomposition, and the lse merges are all
    exact."""
    rng = np.random.default_rng(11)
    B, L, H, D = 2, 64, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
        for _ in range(3)
    )
    seq_mesh = create_mesh((1, 8), axis_names=("data", "seq"))
    out = ring_self_attention(
        seq_mesh, q, k, v, causal=True, block_impl="flash", zigzag=True
    )
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5
    )


def test_zigzag_with_mask_and_grads():
    """Zigzag with a padding mask, through the gradient path."""
    rng = np.random.default_rng(12)
    B, L, H, D = 2, 64, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
        for _ in range(3)
    )
    lengths = np.array([L - 5, 39])
    mask = jnp.asarray(
        (np.arange(L)[None, :] < lengths[:, None]).astype(np.float32)
    )
    seq_mesh = create_mesh((1, 8), axis_names=("data", "seq"))

    def loss_zig(q, k, v):
        out = ring_self_attention(
            seq_mesh, q, k, v, mask, causal=True, block_impl="flash",
            zigzag=True,
        )
        return jnp.sum(out**2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, k, v, mask, causal=True) ** 2)

    np.testing.assert_allclose(
        float(loss_zig(q, k, v)), float(loss_ref(q, k, v)), rtol=1e-5
    )
    gz = jax.grad(loss_zig, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gz, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_zigzag_rejects_non_causal():
    from mlapi_tpu.ops.ring_attention import ring_attention

    with pytest.raises(ValueError, match="zigzag"):
        ring_attention(
            jnp.zeros((1, 8, 1, 4)), jnp.zeros((1, 8, 1, 4)),
            jnp.zeros((1, 8, 1, 4)), axis_name="seq", axis_size=2,
            causal=False, block_impl="flash", zigzag=True,
        )


@pytest.mark.requires_tpu
def test_ring_flash_real_kernel_on_tpu():
    """Ring x flash with the REAL Pallas kernels (interpret=False)
    under shard_map — the composition the CPU suite can only cover
    via the jnp fallback (the Pallas interpreter can't run inside a
    vma-checked shard_map, flash_attention.py:_jnp_flash). A 1-device
    seq mesh on the real chip exercises the vma plumbing, the kernel
    lowering, and the (out, lse) merge end to end."""
    q, k, v = _qkv(seed=7)
    mesh = create_mesh(
        (1, 1), axis_names=("data", "seq"), devices=jax.devices()[:1]
    )
    out = ring_self_attention(mesh, q, k, v, block_impl="flash")
    ref = full_attention(q, k, v)
    # MXU f32 dots run bf16 multiplies at default precision; the
    # online-softmax rescaling amplifies that to ~1e-3 (same reason
    # test_compiled_on_tpu_matches uses 3e-2 on bf16 inputs).
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=5e-3
    )


@pytest.mark.requires_tpu
def test_ring_flash_zigzag_grads_real_kernel_on_tpu():
    """Causal zigzag ring x flash, forward AND grads, real kernels
    (the custom joint-(out, lse) VJP lowered through Mosaic)."""
    rng = np.random.default_rng(21)
    Lz = 64
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, Lz, H, D)).astype(np.float32))
        for _ in range(3)
    )
    mesh = create_mesh(
        (1, 1), axis_names=("data", "seq"), devices=jax.devices()[:1]
    )

    def loss_zig(q, k, v):
        out = ring_self_attention(
            mesh, q, k, v, causal=True, block_impl="flash", zigzag=True
        )
        return jnp.sum(out**2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    # MXU default-precision tolerance — see the forward test above.
    np.testing.assert_allclose(
        float(loss_zig(q, k, v)), float(loss_ref(q, k, v)), rtol=1e-3
    )
    gz = jax.grad(loss_zig, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gz, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=6e-2)
