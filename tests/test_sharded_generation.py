"""Mesh-aware GENERATIVE serving (VERDICT r03 "Next" #2): a
TextGenerationEngine on a (data, model) mesh — params in the model's
declared Megatron TP layout, decode/fused programs partitioned by
GSPMD — must emit byte-identical streams to the single-device engine,
through the full HTTP stack, on 8 virtual CPU devices (SURVEY §4
"distributed without a cluster")."""

import asyncio

import httpx
import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving import build_app
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio

CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)
D_CFG = dict(CFG, hidden_size=16, num_layers=1)

PROMPT = "the quick brown fox"


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.fixture(scope="module")
def gpt_and_params():
    model = get_model("gpt_lm", **CFG)
    return model, model.init(jax.random.key(0))


def _engine(model, params, *, mesh=None, **kw):
    return TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), chunk=8, mesh=mesh, **kw
    )


def test_params_live_in_tp_layout(gpt_and_params, mesh_1x4):
    model, params = gpt_and_params
    eng = _engine(model, params, mesh=mesh_1x4)
    qkv = eng.params["layer_0"]["qkv"]["kernel"]
    assert "model" in tuple(qkv.sharding.spec), qkv.sharding
    wte = eng.params["wte"]
    assert "model" in tuple(wte.sharding.spec), wte.sharding


def test_sharded_streams_match_unsharded(gpt_and_params, mesh_1x4):
    model, params = gpt_and_params
    sharded = _engine(model, params, mesh=mesh_1x4)
    local = _engine(model, params)
    for kw in (
        dict(max_new_tokens=20),                       # fused greedy
        dict(max_new_tokens=17, temperature=0.8, top_k=12, seed=3),
    ):
        a = sharded.generate_text(PROMPT, **kw)
        b = local.generate_text(PROMPT, **kw)
        assert a["token_ids"] == b["token_ids"], kw
    assert sharded.fused_calls == 2   # fast path engages on the mesh
    # The chunked path too (streams stay chunked on a mesh).
    sharded_c = _engine(model, params, mesh=mesh_1x4, fused_single=False)
    c = sharded_c.generate_text(PROMPT, max_new_tokens=20)
    assert c["token_ids"] == local.generate_text(
        PROMPT, max_new_tokens=20
    )["token_ids"]
    assert sharded_c.chunk_calls > 0


def test_sharded_spec_with_draft_on_mesh(gpt_and_params, mesh_1x4):
    """The draft rides the same mesh: speculative rounds run with both
    param trees sharded and stay byte-identical to plain greedy."""
    model, params = gpt_and_params
    draft = get_model("gpt_lm", **D_CFG)
    dp = draft.init(jax.random.key(1))
    spec = _engine(model, params, mesh=mesh_1x4, draft=(draft, dp))
    assert spec.draft_params["wte"].sharding.mesh.shape == {
        "data": 1, "model": 4
    }
    plain = _engine(model, params)
    a = spec.generate_text(PROMPT, max_new_tokens=24)
    b = plain.generate_text(PROMPT, max_new_tokens=24)
    assert a["token_ids"] == b["token_ids"]
    assert spec.spec_rounds > 0 and spec.spec_drafted > 0


def test_llama_generates_on_mesh(mesh_1x4):
    model = get_model(
        "llama_lm", vocab_size=260, hidden_size=32, num_layers=2,
        num_heads=4, num_kv_heads=2, max_positions=160,
        compute_dtype="float32",
    )
    params = model.init(jax.random.key(0))
    sharded = _engine(model, params, mesh=mesh_1x4)
    local = _engine(model, params)
    a = sharded.generate_text(PROMPT, max_new_tokens=16)
    b = local.generate_text(PROMPT, max_new_tokens=16)
    assert a["token_ids"] == b["token_ids"]


async def test_generate_over_http_on_2x4_mesh(gpt_and_params, mesh_2x4):
    """The full HTTP stack over a (2, 4) mesh: non-stream (fused),
    stream (chunked, byte-equal), seeded sampling reproducible."""
    model, params = gpt_and_params
    engine = _engine(model, params, mesh=mesh_2x4)
    app = build_app(engine)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as client:
            r = await client.post(
                "/generate",
                json={"text": PROMPT, "max_new_tokens": 12},
            )
            assert r.status_code == 200, r.text
            ids = r.json()["token_ids"]
            assert len(ids) == 12
            local = _engine(model, params)
            assert ids == local.generate_text(
                PROMPT, max_new_tokens=12
            )["token_ids"]

            s = await client.post(
                "/generate",
                json={"text": PROMPT, "max_new_tokens": 12,
                      "stream": True},
            )
            assert s.status_code == 200
            import json as _json

            last = _json.loads(s.text.strip().splitlines()[-1])
            assert last["done"] is True
            assert last["token_ids"] == ids

            m = (await client.get("/metrics")).json()["counters"]
            assert m["generate.fused_calls"] >= 1
    finally:
        await app.shutdown()


def test_fused_batched_spec_on_mesh(gpt_and_params, mesh_1x4):
    """The apex program: an ENTIRE batched speculative generation —
    draft scan, verify, per-row acceptance, desynchronized cache
    algebra — as one GSPMD-partitioned XLA program on a TP mesh,
    byte-identical per row to the unsharded solo fused run."""
    import jax.numpy as jnp

    from mlapi_tpu.ops.speculative import (
        fused_spec_batched_fn,
        speculative_generate_fused,
    )
    from mlapi_tpu.parallel import params_for_model

    model, params = gpt_and_params
    draft = get_model("gpt_lm", **D_CFG)
    dp = draft.init(jax.random.key(1))
    tps = params_for_model(model, params, mesh_1x4)
    dps = params_for_model(draft, dp, mesh_1x4)
    B, P, tier, k = 2, 12, 16, 4
    rows = np.zeros((B, P), np.int32)
    rows[0, -6:] = np.arange(6) + 10
    rows[1, -9:] = (np.arange(9) * 7) % 200 + 4
    pads = np.asarray([6, 3], np.int32)
    kd = np.stack([
        np.asarray(jax.random.key_data(jax.random.key(s)))
        for s in range(B)
    ])
    budgets = np.asarray([10, 4], np.int32)
    packed = np.asarray(
        fused_spec_batched_fn(model, draft, P, tier, k, False)(
            tps, dps, jnp.asarray(rows), jnp.asarray(kd),
            jnp.zeros((B,), jnp.float32), jnp.zeros((B,), jnp.int32),
            jnp.ones((B,), jnp.float32), jnp.asarray(pads),
            jnp.asarray(budgets),
        )
    )
    for i in range(B):
        n = int(budgets[i])
        solo = rows[i, pads[i]:][None]
        want, _ = speculative_generate_fused(
            model, params, draft, dp, solo, max_new_tokens=n, k=k,
        )
        assert packed[i, :n].tolist() == want, i
