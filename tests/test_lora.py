"""LoRA fine-tuning (`models/lora.py`): adapter init, the
zero-at-start guarantee, frozen-base training through the standard
`fit` loop, and merged export serving through the unchanged engines."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.models.lora import LoraModel
from mlapi_tpu.text import ByteTokenizer

CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=96,
    compute_dtype="float32",
)


def test_init_adapts_every_projection_and_starts_at_identity():
    base_model = get_model("gpt_lm", **CFG)
    lm = LoraModel(base_model, rank=4)
    params = lm.init(jax.random.key(0))
    # 4 projections per layer x 2 layers for the GPT family.
    assert len(params["lora"]) == 8
    for ab in params["lora"].values():
        assert ab["a"].shape[1] == 4 and ab["b"].shape[0] == 4
        np.testing.assert_array_equal(np.asarray(ab["b"]), 0.0)
    # b == 0 → the adapted model IS the base model at step 0.
    ids = jnp.asarray(np.arange(16, dtype=np.int32)[None] % 200)
    ref = base_model.apply(params["base"], ids)
    got = lm.apply(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-6)


def test_init_is_deterministic_across_calls():
    lm = LoraModel(get_model("gpt_lm", **CFG), rank=2)
    p1 = lm.init(jax.random.key(3))
    p2 = lm.init(jax.random.key(3))
    for k in p1["lora"]:
        np.testing.assert_array_equal(
            np.asarray(p1["lora"][k]["a"]), np.asarray(p2["lora"][k]["a"])
        )


def test_llama_targets_found():
    cfg = dict(CFG)
    cfg.pop("num_heads")
    lm = LoraModel(
        get_model("llama_lm", **cfg, num_heads=4, num_kv_heads=2), rank=2
    )
    params = lm.init(jax.random.key(0))
    # 7 projections per layer (q,k,v,wo,gate,up,down) x 2 layers.
    assert len(params["lora"]) == 14


def test_masked_training_updates_only_adapters():
    """Through the REAL train step (make_train_step + optax.masked):
    the base tree is byte-identical after training; only a/b move —
    and the optimizer keeps no state for frozen leaves."""
    from mlapi_tpu.train.loop import make_train_step

    model = LoraModel(get_model("gpt_lm", **CFG), rank=4)
    params = model.init(jax.random.key(0))
    base_before = jax.tree.map(lambda a: np.asarray(a).copy(),
                               params["base"])
    tx = optax.masked(optax.adam(1e-2), model.trainable_mask(params))
    opt = tx.init(params)
    # Frozen leaves carry no adam moments (MaskedNode), adapters do.
    masked_leaves = jax.tree.leaves(opt)
    lora_param_leaves = jax.tree.leaves(
        {"lora": params["lora"]}
    )
    # mu + nu per trainable leaf only:
    assert (
        sum(1 for x in masked_leaves if hasattr(x, "shape"))
        <= 2 * len(lora_param_leaves) + 2  # (+count leaves)
    )

    tok = ByteTokenizer()
    seq = np.asarray(tok.token_ids("ab" * 20), np.int32)[None]
    seqs = np.tile(seq, (8, 1))
    step = make_train_step(model.apply, tx, task="lm")
    loss0 = None
    for _ in range(30):
        params, opt, loss = step(
            params, opt, jnp.asarray(seqs), jnp.asarray(seqs)
        )
        loss0 = loss0 if loss0 is not None else float(loss)
    assert float(loss) < loss0, "LoRA-only training did not learn"
    for p_new, p_old in zip(
        jax.tree.leaves(params["base"]), jax.tree.leaves(base_before)
    ):
        np.testing.assert_array_equal(np.asarray(p_new), p_old)
    moved = any(
        not np.array_equal(np.asarray(ab["b"]), 0.0)
        for ab in params["lora"].values()
    )
    assert moved, "no adapter moved"


def test_merge_export_serves_through_plain_engine(tmp_path):
    """merge_params folds the adaptation into a plain tree that
    checkpoints and serves with zero engine changes."""
    from mlapi_tpu.checkpoint import save_checkpoint
    from mlapi_tpu.serving import InferenceEngine

    inner = get_model("gpt_lm", **CFG)
    lm = LoraModel(inner, rank=4)
    params = lm.init(jax.random.key(0))
    # Give the adapters some nonzero content.
    params["lora"] = jax.tree.map(
        lambda a: a + 0.01, params["lora"]
    )
    merged = lm.merge_params(params)
    ids = jnp.asarray(np.arange(12, dtype=np.int32)[None] % 200)
    np.testing.assert_allclose(
        np.asarray(inner.apply(merged, ids)),
        np.asarray(lm.apply(params, ids)),
        atol=1e-5,
    )
    ck = tmp_path / "merged"
    save_checkpoint(
        ck, merged, step=1,
        config={
            "model": "gpt_lm", "model_kwargs": CFG,
            "tokenizer": ByteTokenizer().fingerprint(),
        },
    )
    eng = InferenceEngine.from_checkpoint(ck)
    out = eng.generate_text("ab", max_new_tokens=4)
    assert len(out["token_ids"]) == 4


def test_fit_integration_freezes_base():
    """End to end through `fit`: LoRA training on the LM task runs
    and leaves the base frozen."""
    from mlapi_tpu.datasets import SupervisedSplits
    from mlapi_tpu.train import fit
    from mlapi_tpu.utils.vocab import LabelVocab

    model = LoraModel(get_model("gpt_lm", **CFG), rank=4)
    tok = ByteTokenizer()
    seqs = np.tile(
        np.asarray(tok.token_ids("abcd " * 12), np.int32)[None][:, :48],
        (16, 1),
    )
    splits = SupervisedSplits(
        x_train=seqs[:12], y_train=seqs[:12],
        x_test=seqs[12:], y_test=seqs[12:],
        vocab=LabelVocab(("<lm>",)), source="synthetic",
        extras={"tokenizer": tok.fingerprint(), "task": "lm"},
    )
    base_before = jax.tree.map(
        lambda a: np.asarray(a).copy(),
        model.init(jax.random.key(0))["base"],
    )
    r = fit(model, splits, steps=10, learning_rate=1e-2,
            optimizer="adam", batch_size=8, seed=0)
    for p_new, p_old in zip(
        jax.tree.leaves(r.params["base"]), jax.tree.leaves(base_before)
    ):
        np.testing.assert_array_equal(np.asarray(p_new), p_old)


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_cli_lora_finetunes_from_pretrained_base(tmp_path):
    """--init-from + --lora-rank: the frozen base really is the
    pretrained checkpoint (not a fresh init), and the exported merged
    checkpoint serves."""
    from mlapi_tpu.serving import InferenceEngine
    from mlapi_tpu.train.__main__ import main as train_main

    base_ck = tmp_path / "base"
    lora_ck = tmp_path / "lora"
    train_main([
        "--preset", "docs-gpt", "--steps", "8", "--out", str(base_ck),
    ])
    train_main([
        "--preset", "docs-gpt", "--steps", "4", "--out", str(lora_ck),
        "--lora-rank", "4", "--init-from", str(base_ck),
    ])
    base_eng = InferenceEngine.from_checkpoint(base_ck)
    lora_eng = InferenceEngine.from_checkpoint(lora_ck)
    # The adapted model inherits the pretrained embeddings: wte must
    # be byte-identical (frozen), not a different random init.
    np.testing.assert_array_equal(
        np.asarray(base_eng.params["wte"]),
        np.asarray(lora_eng.params["wte"]),
    )
    out = lora_eng.generate_text("the", max_new_tokens=4)
    assert len(out["token_ids"]) == 4


def test_no_targets_is_loud():
    with pytest.raises(ValueError, match="no LoRA targets"):
        LoraModel(
            get_model("linear", num_features=4, num_classes=3), rank=2
        ).init(jax.random.key(0))
