"""Shared-prefix KV caching (`serving.prefix.PrefixCache`,
`models/gpt.py::prefix_prefill_fn`, the `prefix` field of /generate):
a prompt prefix named by many requests is prefilled ONCE, its KV
scattered into each batch's cache, and only the per-request suffix is
computed — time-to-first-token for system-prompt-heavy serving drops
from O(prefix + suffix) to O(suffix) forward work.

The load-bearing property is equivalence: serving (prefix=P, text=S)
must produce the same tokens as serving text=P+S through the plain
path — same effective positions, same attended keys, whichever
bucket/pad layout either path landed in. Requests whose suffix rivals
the prefix fall back to the plain concatenation path silently (same
output, better TTFT — the KV path computes the suffix serially).
"""

import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio

CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)

LONG_P = "abcdefgh" * 3   # 24 tokens: bucket 64, lo 40 (padded prefix)
ALIGNED_P = "abcdefgh" * 8  # 64 tokens: bucket 64, lo 0 (aligned prefix)


@pytest.fixture
def anyio_backend():
    return "asyncio"


def _engine(model_name="gpt_lm", **kw) -> TextGenerationEngine:
    cfg = dict(CFG)
    if model_name == "llama_lm":
        cfg.pop("num_heads")
        cfg.update(num_heads=4, num_kv_heads=2)
    model = get_model(model_name, **cfg)
    return TextGenerationEngine(
        model,
        model.init(jax.random.key(0)),
        tokenizer=ByteTokenizer(),
        chunk=4,
        **kw,
    )


async def _collect(gen) -> list[int]:
    out: list[int] = []
    while True:
        item = await gen.queue.get()
        if item is None:
            return out
        if isinstance(item, Exception):
            raise item
        out.extend(item["token_ids"])


@pytest.mark.parametrize("model_name", ["gpt_lm", "llama_lm"])
def test_prefix_path_matches_plain_concatenation(model_name):
    """Greedy tokens via (prefix, suffix) equal the plain path's for
    padded and bucket-aligned prefixes, on both decoder families."""
    eng = _engine(model_name)
    for prefix in (LONG_P, ALIGNED_P):
        for suffix in ("ij", "ijklmnop"):
            plain = eng.generate_text(prefix + suffix, max_new_tokens=8)
            via = eng.generate_text(
                suffix, max_new_tokens=8, prefix=prefix
            )
            assert via["token_ids"] == plain["token_ids"], (
                model_name, prefix, suffix,
            )
            assert via["prompt_tokens"] == plain["prompt_tokens"]
    assert eng.prefix_misses == 2
    assert eng.prefix_hits == 2  # second suffix per prefix reuses it
    assert eng.prefix_fallbacks == 0


def test_short_prefix_takes_kv_path_and_matches():
    """Since the suffix runs as one fused block forward, even a short
    prefix wins on the KV path — and stays byte-identical to the
    concatenated prompt."""
    eng = _engine()
    plain = eng.generate_text("xyzij", max_new_tokens=6)
    via = eng.generate_text("ij", max_new_tokens=6, prefix="xyz")
    assert via["token_ids"] == plain["token_ids"]
    assert via["prompt_tokens"] == plain["prompt_tokens"] == 5
    assert eng.prefix_misses == 1 and eng.prefix_fallbacks == 0


def test_prefix_sampled_stream_matches_plain():
    """Seeded sampling is part of the equivalence: the stream index
    and per-row PRNG stream do not depend on which path served the
    prompt."""
    eng = _engine()
    kw = dict(max_new_tokens=10, temperature=0.9, seed=12, top_k=50)
    plain = eng.generate_text(LONG_P + "ij", **kw)
    via = eng.generate_text("ij", prefix=LONG_P, **kw)
    assert via["token_ids"] == plain["token_ids"]
    assert eng.prefix_misses == 1  # really took the KV path


async def test_same_prefix_requests_batch_and_match():
    """Concurrent same-prefix requests with different suffix lengths
    coalesce into one batch and each stream equals its solo run."""
    eng = _engine()
    await eng.start()
    try:
        cases = [("ij", 6, 0.0, 0), ("klmn", 8, 0.8, 7), ("op", 4, 0.0, 0)]
        solos = [
            eng.generate_text(
                t, max_new_tokens=n, temperature=temp, seed=s,
                prefix=LONG_P,
            )["token_ids"]
            for t, n, temp, s in cases
        ]
        base = eng.batch_calls
        gens = [
            await eng.submit(
                t, max_new_tokens=n, temperature=temp, seed=s,
                prefix=LONG_P,
            )
            for t, n, temp, s in cases
        ]
        outs = [await _collect(g) for g in gens]
        assert outs == solos
        assert eng.batch_calls - base <= 2, "same-prefix didn't coalesce"
    finally:
        await eng.stop()


async def test_different_prefixes_share_a_batch_exactly():
    """Cross-batch prefix regions (tests/test_prefix_mixed.py has the
    full matrix): two requests naming DIFFERENT prefixes decode in
    one batch, each on the KV path, streams exact."""
    eng = _engine(max_wait_ms=50.0)
    ref1 = eng.generate_text("a" * 16 + "ij", max_new_tokens=4)
    ref2 = eng.generate_text("b" * 16 + "ij", max_new_tokens=4)
    # Register both prefixes first so the co-batch window isn't
    # racing the entries' first-use prefill.
    eng.prefix.entry("a" * 16)
    eng.prefix.entry("b" * 16)
    await eng.start()
    try:
        g1 = await eng.submit("ij", max_new_tokens=4, prefix="a" * 16)
        g2 = await eng.submit("ij", max_new_tokens=4, prefix="b" * 16)
        a, b = await _collect(g1), await _collect(g2)
        assert a == ref1["token_ids"]
        assert b == ref2["token_ids"]
        assert eng.prefix_misses == 2  # both on the KV path
    finally:
        await eng.stop()


async def test_prefix_request_defers_from_plain_running_batch():
    """A prefix request arriving while a PLAIN batch decodes is never
    admitted into it (the prefix region is a batch-wide layout); it
    completes correctly in its own batch."""
    eng = _engine()
    await eng.start()
    try:
        a = await eng.submit("abcd", max_new_tokens=24)
        await a.queue.get()
        b = await eng.submit("ij", max_new_tokens=4, prefix=LONG_P)
        got = await _collect(b)
        await _collect(a)
        solo = eng.generate_text("ij", max_new_tokens=4, prefix=LONG_P)
        assert got == solo["token_ids"]
        assert eng.admitted == 0
    finally:
        await eng.stop()


def test_prefix_lru_eviction():
    eng = _engine()
    eng.prefix.max_entries = 2
    for p in ("a" * 16, "b" * 16, "c" * 16):
        eng.generate_text("ij", max_new_tokens=2, prefix=p)
    assert len(eng.prefix) == 2
    assert "a" * 16 not in eng.prefix._entries  # LRU went first
    eng.generate_text("ij", max_new_tokens=2, prefix="c" * 16)
    assert eng.prefix_hits == 1


def test_oversized_prefix_rejected():
    eng = _engine()
    cap = eng.model.max_positions - eng.prompt_buckets[0] - 1
    with pytest.raises(ValueError, match="fit the model window"):
        eng.generate_text("ij", max_new_tokens=2, prefix="a" * (cap + 10))


def test_prefix_plus_request_window_accounting():
    """max_new_tokens that fits without the prefix but not with it is
    refused loudly, not truncated."""
    eng = _engine()
    with pytest.raises(ValueError, match="leaves no room"):
        eng.generate_text("ij", max_new_tokens=150, prefix="a" * 16)


def test_empty_text_with_prefix_serves_prefix_alone():
    """text="" must not condition on a fabricated pad placeholder
    behind the prefix: it falls back to the plain path and equals
    serving the prefix as the whole prompt (code-review regression)."""
    eng = _engine()
    plain = eng.generate_text(LONG_P, max_new_tokens=6)
    via = eng.generate_text("", max_new_tokens=6, prefix=LONG_P)
    assert via["token_ids"] == plain["token_ids"]
    assert via["prompt_tokens"] == plain["prompt_tokens"]
    assert eng.prefix_fallbacks == 1


def test_hit_path_does_not_retokenize_prefix():
    """After the entry exists, encoding consults the LRU before
    touching the prefix string (multi-KB system prompts must not be
    re-tokenized per request)."""
    eng = _engine()
    eng.generate_text("ij", max_new_tokens=2, prefix=LONG_P)
    calls = []
    orig = eng.tokenizer.token_ids

    def spy(s):
        calls.append(s)
        return orig(s)

    eng.tokenizer.token_ids = spy
    try:
        eng.generate_text("kl", max_new_tokens=2, prefix=LONG_P)
    finally:
        eng.tokenizer.token_ids = orig
    assert LONG_P not in calls, "hit path re-tokenized the prefix"


async def test_mixed_traffic_soak_every_stream_exact():
    """Plain, prefix-cached, and chunked-long-prompt requests
    staggered together: the collector must group compatibly (prefix
    batches never mix), admission must defer cross-layout joiners,
    and EVERY stream must equal its solo run — the strongest
    whole-engine interleaving check."""
    import asyncio

    cfg = dict(CFG, max_positions=320)
    model = get_model("gpt_lm", **cfg)
    eng = TextGenerationEngine(
        model, model.init(jax.random.key(0)),
        tokenizer=ByteTokenizer(), chunk=4, max_batch=4,
        prompt_buckets=(16, 64, 128),
    )
    rng = np.random.default_rng(7)
    cases = []
    for i in range(9):
        kind = i % 3
        temp = float(rng.choice([0.0, 0.8]))
        n = int(rng.integers(3, 16))
        if kind == 0:
            cases.append(dict(text="ab" * int(rng.integers(1, 9)),
                              max_new_tokens=n, temperature=temp,
                              seed=i))
        elif kind == 1:
            cases.append(dict(text="q" * int(rng.integers(2, 7)),
                              prefix=LONG_P, max_new_tokens=n,
                              temperature=temp, seed=i))
        else:
            cases.append(dict(text="xyz" * 55,  # 165 toks → chunked
                              max_new_tokens=n, temperature=temp,
                              seed=i))
    solos = [eng.generate_text(**c)["token_ids"] for c in cases]
    await eng.start()
    try:
        gens = []
        for c in cases:
            gens.append(await eng.submit(**c))
            await asyncio.sleep(float(rng.uniform(0, 0.03)))
        outs = [await _collect(g) for g in gens]
        assert outs == solos
        assert eng.prefill_chunks > 0  # the long prompts really chunked
        assert eng.prefix_misses == 1  # one shared prefix entry
    finally:
        await eng.stop()


def test_oversized_suffix_on_kv_path_refused():
    """On the KV path the plain path's silent left-truncation would
    drop SUFFIX tokens while keeping the whole prefix — different
    conditioning than the concatenated prompt, so it must error."""
    eng = _engine()
    with pytest.raises(ValueError, match="exceed the model window"):
        # prefix 128 → bucket 128; limit = 160-8-128 = 24; suffix 30
        # tokens with bucket 64 <= 128 stays on the KV path → loud.
        eng.generate_text("x" * 30, max_new_tokens=8, prefix="a" * 128)
