"""Sharded serving end to end: a Wide&Deep checkpoint served on a
(data=2, model=4) mesh through the full HTTP stack — params placed in
the model's declared layout (vocab-sharded tables), request batches
sharded over the data axis, all on 8 virtual CPU devices (SURVEY §4
"distributed without a cluster")."""

import asyncio

import httpx
import jax
import numpy as np
import pytest

from mlapi_tpu.checkpoint import save_checkpoint
from mlapi_tpu.datasets import get_dataset
from mlapi_tpu.models import get_model
from mlapi_tpu.serving import InferenceEngine, build_app
from mlapi_tpu.train import fit

pytestmark = pytest.mark.anyio

SMALL = dict(
    num_dense=4,
    vocab_sizes=[256] * 4,
    embed_dim=8,
    hidden_dims=[16],
    num_classes=2,
)


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.fixture(scope="module")
def sharded_engine(tmp_path_factory, mesh_2x4):
    data = get_dataset(
        "criteo", num_dense=4, num_categorical=4, vocab_size=256,
        n_train=2048, n_test=256,
    )
    model = get_model("wide_deep", **SMALL)
    result = fit(model, data, steps=60, batch_size=256, learning_rate=3e-3)
    ck = tmp_path_factory.mktemp("sharded") / "ck"
    save_checkpoint(
        ck, result.params, step=60,
        config={
            "model": "wide_deep",
            "model_kwargs": SMALL,
            "feature_names": list(data.feature_names),
        },
        vocab=data.vocab,
    )
    # Buckets must divide the data-axis size (2).
    return InferenceEngine.from_checkpoint(
        ck, mesh=mesh_2x4, buckets=(2, 4, 8, 16)
    ), data


def test_engine_params_live_sharded(sharded_engine):
    engine, _ = sharded_engine
    spec = tuple(engine.params["deep_tables"].sharding.spec)
    assert spec in ((None, "model", None), (None, "model"))


def test_sharded_predictions_match_unsharded(sharded_engine):
    engine, data = sharded_engine
    rows = np.asarray(data.x_test[:8], np.float32)
    labels, probs = engine.predict_labels(rows)

    unsharded = InferenceEngine(
        engine.model,
        jax.device_put(jax.tree.map(np.asarray, engine.params)),
        engine.vocab,
        engine.feature_names,
        buckets=(8,),
    )
    labels_ref, probs_ref = unsharded.predict_labels(rows)
    assert labels == labels_ref
    np.testing.assert_allclose(probs, probs_ref, atol=1e-5)


def test_engine_rejects_indivisible_buckets(sharded_engine, mesh_2x4):
    engine, _ = sharded_engine
    with pytest.raises(ValueError, match="not divisible"):
        InferenceEngine(
            engine.model, jax.tree.map(np.asarray, engine.params),
            engine.vocab, engine.feature_names,
            mesh=mesh_2x4, buckets=(1, 3),
        )


async def test_serves_over_http_on_mesh(sharded_engine):
    engine, data = sharded_engine
    app = build_app(engine)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as client:
            names = list(data.feature_names)
            row = np.asarray(data.x_test[0], np.float32)
            payload = {n: float(v) for n, v in zip(names, row)}
            rs = await asyncio.gather(
                *(client.post("/predict", json=payload) for _ in range(16))
            )
            assert all(r.status_code == 200 for r in rs)
            bodies = [r.json() for r in rs]
            assert all(b["prediction"] in ("click", "no-click") for b in bodies)
            assert len({b["prediction"] for b in bodies}) == 1  # deterministic
    finally:
        await app.shutdown()
