"""SO_REUSEPORT multi-worker serving: ``--workers N`` spawns N fresh
server processes sharing one listening port, kernel-balanced per
connection — the CPU-attach scale-out past the single asyncio loop's
~one-core ceiling (BASELINE.md known-limitations, built in r03).

Integration test: real subprocesses, real sockets, real HTTP.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from mlapi_tpu.checkpoint import save_checkpoint
from mlapi_tpu.datasets import load_iris
from mlapi_tpu.models import get_model
from mlapi_tpu.train import fit

ROW = {
    "sepal_length": 5.1,
    "sepal_width": 3.5,
    "petal_length": 1.4,
    "petal_width": 0.2,
}


@pytest.fixture(scope="module")
def iris_checkpoint(tmp_path_factory):
    iris = load_iris()
    model = get_model(
        "linear", num_features=iris.num_features,
        num_classes=iris.num_classes,
    )
    result = fit(model, iris, steps=200, learning_rate=0.1,
                 weight_decay=1e-3)
    path = tmp_path_factory.mktemp("ckpt") / "iris"
    save_checkpoint(
        path,
        result.params,
        step=result.steps,
        config={
            "model": "linear",
            "model_kwargs": {
                "num_features": iris.num_features,
                "num_classes": iris.num_classes,
            },
            "feature_names": list(iris.feature_names),
        },
        vocab=iris.vocab,
    )
    return path


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port: int, path: str, timeout: float = 5.0) -> dict:
    # One fresh connection per call — SO_REUSEPORT balances per
    # connection, so keep-alive pooling would pin us to one worker.
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return json.loads(r.read())


def _post(port: int, path: str, body: dict, timeout: float = 5.0) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"content-type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.heavy  # in-suite training/soak — fast profile: -m 'not heavy'
def test_two_workers_share_one_port(iris_checkpoint):
    port = _free_port()
    env = dict(
        os.environ,
        MLAPI_TPU_PLATFORM="cpu",
        MLAPI_TPU_WARMUP="minimal",
    )
    sup = subprocess.Popen(
        [
            sys.executable, "-m", "mlapi_tpu.serving",
            "--checkpoint", str(iris_checkpoint),
            "--port", str(port), "--workers", "2",
        ],
        env=env,
    )
    try:
        # Wait for at least one worker to come up (cold JAX import on
        # a shared 1-core box is slow).
        deadline = time.time() + 180
        up = False
        while time.time() < deadline:
            if sup.poll() is not None:
                pytest.fail(f"supervisor died rc={sup.returncode}")
            try:
                if _get(port, "/healthz", timeout=2)["status"] == "ok":
                    up = True
                    break
            except Exception:
                time.sleep(1.0)
        assert up, "no worker became healthy in time"

        # Distinct connections spread across BOTH worker processes.
        pids = set()
        for _ in range(120):
            try:
                pids.add(_get(port, "/healthz")["pid"])
            except Exception:
                time.sleep(0.2)  # second worker may still be booting
            if len(pids) >= 2:
                break
        assert len(pids) == 2, f"connections all landed on one worker: {pids}"
        assert sup.pid not in pids, "supervisor must not serve traffic"

        # The actual serving contract works through the shared port.
        out = _post(port, "/predict", ROW)
        assert set(out) == {"prediction", "probability"}
        assert out["prediction"].startswith("Iris-")
    finally:
        sup.send_signal(signal.SIGTERM)
        try:
            sup.wait(20)
        except subprocess.TimeoutExpired:
            sup.kill()
            sup.wait(10)
    # SIGTERM to the supervisor must also stop the WORKERS (its
    # handler runs the shutdown fan-out) — no orphans still bound to
    # the port.
    deadline = time.time() + 15
    while time.time() < deadline:
        alive = [p for p in pids if os.path.isdir(f"/proc/{p}")]
        if not alive:
            break
        time.sleep(0.5)
    assert not alive, f"workers {alive} orphaned after supervisor SIGTERM"


def test_worker_flag_requires_explicit_port(iris_checkpoint):
    r = subprocess.run(
        [
            sys.executable, "-m", "mlapi_tpu.serving",
            "--checkpoint", str(iris_checkpoint),
            "--port", "0", "--workers", "2",
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode != 0
    assert "explicit --port" in r.stderr
