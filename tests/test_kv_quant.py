"""Int8 KV-cache quantization (`ops/quant.py` kv helpers, the
``kv_quant`` model field, engine ``--kv-quant``).

Decode at generation scale is CACHE-bandwidth-bound: every token
re-reads every layer's [B, L, H, D] K/V from HBM, so storing the cache
as int8 payload + per-token-per-head f32 scales halves the per-token
decode HBM and doubles how many continuous-batching slots / prefix
entries / spec mirrors fit a chip. These tests pin the three claims:

- **Bytes, exactly**: deterministic per-slot cache bytes from
  ``addressable_shards[...].data.nbytes`` match closed-form arithmetic,
  and the bf16 gpt-small ratio clears the committed >= 1.9x.
- **Quality, measured**: teacher-forced greedy top-1 agreement vs the
  full-precision cache >= 0.99 over >= 256 tokens x 8 prompts.
- **The SERVING stack, not just the model**: prefix hit/widen
  round-trips, continuous admission, fused batched speculation, and
  composition with int8 weights + a (1, 1, 2)-style TP mesh all run
  on the quantized format and stay byte-identical where the bf16
  contract says they must.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.ops.quant import (
    kv_cache_seq_len,
    kv_greedy_agreement,
    kv_quantize,
    maybe_dequant_kv,
)
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer
from mlapi_tpu.train.bench import bytes_per_device

# Tiny fast config for path coverage (f32 compute: the cache baseline
# is f32, ratio ~3.2x at D=16).
CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=2,
    max_positions=160,
    compute_dtype="float32",
)
# "gpt-small" for the committed numbers: bf16 cache, head_dim 128 —
# the shape class where int8+f32-scales clears the >= 1.9x bf16 bar
# (2D / (D + 4) at D = 128 -> 1.94x).
SMALL = dict(
    vocab_size=260,
    hidden_size=256,
    num_layers=2,
    num_heads=2,
    max_positions=320,
    compute_dtype="bfloat16",
)

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


def _model(kv_quant="int8", **over):
    return get_model("gpt_lm", **{**CFG, **over}, kv_quant=kv_quant)


@pytest.fixture(scope="module")
def params():
    return _model().init(jax.random.key(0))


def _engine(params, kv_quant="int8", **kw):
    kw.setdefault("chunk", 2)
    kw.setdefault("fused_single", False)
    return TextGenerationEngine(
        _model(kv_quant), params, tokenizer=ByteTokenizer(), **kw
    )


async def _collect(gen) -> list[int]:
    out: list[int] = []
    while True:
        item = await gen.queue.get()
        if item is None:
            return out
        if isinstance(item, Exception):
            raise item
        out.extend(item["token_ids"])


# --- the quantization math --------------------------------------------


def test_kv_quantize_per_token_head_scales_bound_error():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 7, 3, 16)).astype(np.float32)
    q, s = kv_quantize(jnp.asarray(x))
    assert q.dtype == jnp.int8 and q.shape == x.shape
    assert s.shape == (2, 7, 3, 1)  # one scale per (token, head)
    back = np.asarray(q, np.float32) * np.asarray(s)
    assert np.all(np.abs(back - x) <= np.asarray(s) / 2 + 1e-8)
    # All-zero blocks stay exactly zero with a nonzero scale.
    q0, s0 = kv_quantize(jnp.zeros((1, 2, 1, 8)))
    assert np.all(np.asarray(q0) == 0) and np.all(np.asarray(s0) == 1.0)


def test_init_cache_format_and_exact_bytes():
    """Per-slot cache bytes, EXACT: addressable-shard bytes equal the
    closed-form int8-payload + f32-scale arithmetic, for both
    families (and GQA shrinks the llama cache by the group factor)."""
    m = _model()
    total = 64
    cache = m.init_cache(1, total)
    layer = cache["layer_0"]
    assert sorted(layer) == ["k_q", "k_scale", "v_q", "v_scale"]
    assert layer["k_q"].dtype == jnp.int8
    assert layer["k_scale"].dtype == jnp.float32
    assert kv_cache_seq_len(cache) == total
    h, d = m.num_heads, m.head_dim
    expect = m.num_layers * 2 * (total * h * d + total * h * 4)
    assert bytes_per_device(cache) == expect
    base = _model("none").init_cache(1, total)
    expect_base = m.num_layers * 2 * total * h * d * 4  # f32
    assert bytes_per_device(base) == expect_base

    lm = get_model(
        "llama_lm", vocab_size=64, hidden_size=32, num_layers=1,
        num_heads=4, num_kv_heads=2, max_positions=64,
        compute_dtype="float32", kv_quant="int8",
    )
    lc = lm.init_cache(2, 16)
    assert lc["layer_0"]["k_q"].shape == (2, 16, 2, 8)  # KVH, not H
    assert bytes_per_device(lc) == 2 * (2 * 16 * 2 * 8 + 2 * 16 * 2 * 4)


def test_gpt_small_bf16_slot_bytes_ratio_ge_1_9():
    """The committed byte claim at identical bucket/tier config:
    engine-reported per-slot KV bytes (addressable_shards nbytes)
    drop >= 1.9x vs the bf16 cache, and the number is deterministic
    across engines (it is what /metrics exports)."""
    model = get_model("gpt_lm", **SMALL)
    real = model.init(jax.random.key(0))
    tok = ByteTokenizer()
    eng_b = TextGenerationEngine(model, real, tokenizer=tok)
    qmodel = dataclasses.replace(model, kv_quant="int8")
    eng_q = TextGenerationEngine(qmodel, real, tokenizer=tok)
    b, q = eng_b.kv_cache_slot_bytes(), eng_q.kv_cache_slot_bytes()
    assert b >= 1.9 * q, (b, q)
    eng_q2 = TextGenerationEngine(qmodel, real, tokenizer=tok)
    assert eng_q2.kv_cache_slot_bytes() == q


async def test_metrics_exports_kv_slot_bytes(params):
    import httpx

    from mlapi_tpu.serving import build_app

    eng = _engine(params)
    app = build_app(eng)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as c:
            snap = (await c.get("/metrics")).json()
        assert (
            snap["gauges"]["generate.kv_cache_bytes_per_slot"]
            == eng.kv_cache_slot_bytes()
        )
    finally:
        await app.shutdown()


# --- decode quality ----------------------------------------------------


def test_greedy_agreement_gpt_small_256_tokens():
    """The measured decode-quality guard: teacher-forced greedy top-1
    agreement of the int8 cache vs the bf16 cache >= 0.99 over
    256 tokens x 8 prompts on bf16 gpt-small."""
    model = get_model("gpt_lm", **SMALL)
    params = model.init(jax.random.key(0))
    tok = ByteTokenizer()
    prompts = [
        "the quick brown fox", "serving engines batch",
        "checkpoints commit", "tpu programs compile",
        "the draft proposes", "sharding follows mesh",
        "decode reads the cache", "quantize the kv cache",
    ]
    width = max(len(tok.token_ids(p)) for p in prompts)
    rows = np.full((len(prompts), width), tok.pad_id, np.int32)
    pads = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        ids = tok.token_ids(p)
        rows[i, width - len(ids):] = ids
        pads[i] = width - len(ids)
    agr = kv_greedy_agreement(
        model, params, jnp.asarray(rows), 257, pad_lens=pads
    )
    assert agr >= 0.99, agr


def test_generate_stream_matches_full_precision(params):
    """At the tiny f32 config the quantized-cache greedy stream is
    token-identical to full precision end to end (engine path)."""
    a = _engine(params, "none").generate_text("hello", max_new_tokens=24)
    b = _engine(params, "int8").generate_text("hello", max_new_tokens=24)
    assert a["token_ids"] == b["token_ids"]


def test_llama_gqa_kv_quant_decodes():
    m = get_model(
        "llama_lm", vocab_size=260, hidden_size=32, num_layers=2,
        num_heads=4, num_kv_heads=2, max_positions=96,
        compute_dtype="float32", kv_quant="int8",
    )
    p = m.init(jax.random.key(2))
    out = np.asarray(m.generate(
        p, jnp.asarray(np.arange(6, dtype=np.int32)[None]),
        max_new_tokens=8,
    ))
    assert out.shape == (1, 8) and (out >= 0).all()


def test_bad_kv_quant_value_rejected():
    with pytest.raises(ValueError, match="kv_quant"):
        _model("int4")


def test_maybe_dequant_kv_boundary():
    q, s = kv_quantize(jnp.ones((1, 4, 2, 8)))
    out = maybe_dequant_kv({"q": q, "scale": s}, jnp.float32)
    np.testing.assert_allclose(np.asarray(out), 1.0, rtol=1e-2)
    arr = jnp.ones((2, 2))
    assert maybe_dequant_kv(arr) is arr
    with pytest.raises(TypeError, match="quantized pairs"):
        maybe_dequant_kv({"weird": arr})


def test_flash_and_ring_dequant_at_boundary():
    """The documented kernel-boundary policy: quantized K/V pairs fed
    to the full-sequence kernels dequantize at entry and match the
    same kernel on the dequantized arrays."""
    from mlapi_tpu.ops.pallas import flash_attention

    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(1, 16, 2, 8)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, 16, 2, 8)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, 16, 2, 8)).astype(np.float32))
    kq, ks = kv_quantize(k)
    vq, vs = kv_quantize(v)
    ref = flash_attention(
        q, kq.astype(jnp.float32) * ks, vq.astype(jnp.float32) * vs,
        causal=True, interpret=True,
    )
    got = flash_attention(
        q, {"q": kq, "scale": ks}, {"q": vq, "scale": vs},
        causal=True, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=1e-5
    )


# --- the serving stack on the quantized format -------------------------


def test_prefix_cache_int8_hit_and_widen(params):
    """Prefix KVs store, hit, and widen in int8: a prefix-cached
    request equals the inline concatenation, the entry's KV pytree is
    really int8 on device, and the cross-batch widen preserves the
    format and the right-aligned content."""
    eng = _engine(params)
    prefix = "the quick brown fox "
    via = eng.generate_text("tail", prefix=prefix, max_new_tokens=8)
    concat = eng.generate_text(prefix + "tail", max_new_tokens=8)
    assert via["token_ids"] == concat["token_ids"]
    assert eng.prefix_misses == 1
    entry = eng.prefix.entry(prefix)  # second use: a hit
    assert eng.prefix_hits >= 1
    leaf = entry.kv["layer_0"]
    assert leaf["k_q"].dtype == jnp.int8

    wide = eng.prefix.widen(entry.kv, entry.bucket, entry.bucket + 16)
    wlayer = wide["layer_0"]
    assert wlayer["k_q"].dtype == jnp.int8
    assert wlayer["k_q"].shape[1] == entry.bucket + 16
    np.testing.assert_array_equal(
        np.asarray(wlayer["k_q"])[:, 16:], np.asarray(leaf["k_q"])
    )
    np.testing.assert_array_equal(
        np.asarray(wlayer["k_scale"])[:, 16:],
        np.asarray(leaf["k_scale"]),
    )
    # And a repeat request (an entry HIT) still matches.
    again = eng.generate_text("tail", prefix=prefix, max_new_tokens=8)
    assert again["token_ids"] == concat["token_ids"]


async def test_streaming_int8_matches_sync(params):
    """A ``stream=True`` consumer (one chunk in flight, prompt token
    delivery) over the int8 cache gets the same stream as the sync
    path."""
    eng = _engine(params)
    await eng.start()
    try:
        ref = eng.generate_text("stream me", max_new_tokens=12)
        gen = await eng.submit("stream me", max_new_tokens=12,
                               stream=True)
        chunks = []
        while True:
            item = await gen.queue.get()
            if item is None:
                break
            assert not isinstance(item, Exception), item
            chunks.append(item["token_ids"])
        assert len(chunks) >= 2  # actually incremental
        assert sum(chunks, []) == ref["token_ids"]
    finally:
        await eng.stop()


async def test_continuous_admission_int8(params):
    """A request admitted into a RUNNING int8-cache batch produces
    byte-identical tokens to its solo run (the continuous-batching
    exactness contract, on the quantized format)."""
    eng = _engine(params)
    await eng.start()
    try:
        solo_a = eng.generate_text("abcdef", max_new_tokens=40, seed=1)
        solo_b = eng.generate_text(
            "xyz", max_new_tokens=6, temperature=0.9, seed=7, top_k=40
        )
        base_batches = eng.batch_calls
        a = await eng.submit("abcdef", max_new_tokens=40, seed=1)
        first = await a.queue.get()
        b = await eng.submit(
            "xyz", max_new_tokens=6, temperature=0.9, seed=7, top_k=40
        )
        got_b = await _collect(b)
        got_a = first["token_ids"] + await _collect(a)
        assert eng.admitted >= 1, "request was not admitted mid-batch"
        assert eng.batch_calls - base_batches == 1
        assert got_a == solo_a["token_ids"]
        assert got_b == solo_b["token_ids"]
    finally:
        await eng.stop()


def _spec_pair(kv_quant="int8"):
    t_cfg = dict(
        vocab_size=260, hidden_size=48, num_layers=2, num_heads=4,
        max_positions=256, compute_dtype="float32", kv_quant=kv_quant,
    )
    d_cfg = dict(
        vocab_size=260, hidden_size=24, num_layers=1, num_heads=2,
        max_positions=256, compute_dtype="float32", kv_quant=kv_quant,
    )
    target = get_model("gpt_lm", **t_cfg)
    draft = get_model("gpt_lm", **d_cfg)
    return target, target.init(jax.random.key(0)), draft, \
        draft.init(jax.random.key(1))


async def test_batched_spec_int8():
    """A formed all-greedy batch runs BATCHED SPECULATION rounds with
    BOTH caches (target and draft mirror) in int8, and each stream
    equals its draft-less solo run. (r20: the retired whole-batch
    fused-spec program is gone — the rounds run as typed ``spec``
    units through the one execution model.)"""
    target, tp, draft, dp = _spec_pair()
    tok = ByteTokenizer()
    plain = TextGenerationEngine(
        target, tp, tokenizer=tok, max_wait_ms=2000.0
    )
    eng = TextGenerationEngine(
        target, tp, tokenizer=tok, max_wait_ms=2000.0,
        draft=(draft, dp), spec_k=3,
    )
    assert eng.kv_quant == "int8"
    texts = ["the quick brown", "a serving engine"]
    solos = [
        plain.generate_text(t, max_new_tokens=12)["token_ids"]
        for t in texts
    ]
    await eng.start()
    try:
        gens = [
            await eng.submit(t, max_new_tokens=12) for t in texts
        ]
        outs = [await _collect(g) for g in gens]
        assert eng.spec_rounds > 0 and eng.spec_drafted > 0, (
            eng.spec_rounds, eng.batch_calls
        )
        assert outs == solos
    finally:
        await eng.stop()


async def test_host_spec_phase_int8():
    """The HOST spec phase (chunked path with a draft): solo greedy
    speculation on int8 caches emits the exact draft-less stream."""
    target, tp, draft, dp = _spec_pair()
    tok = ByteTokenizer()
    plain = TextGenerationEngine(target, tp, tokenizer=tok)
    eng = TextGenerationEngine(
        target, tp, tokenizer=tok, draft=(draft, dp), spec_k=3,
        fused_single=False,
    )
    ref = plain.generate_text("hello world", max_new_tokens=24)
    got = eng.generate_text("hello world", max_new_tokens=24)
    assert got["token_ids"] == ref["token_ids"]
    assert eng.spec_rounds > 0, "spec phase never engaged"


def test_composes_with_int8_weights_and_tp_mesh(tmp_path):
    """--quantize int8 + --kv-quant int8 + a (1, 1, 2)-style TP mesh:
    int8 weights serve from the TP layout, the cache quantizes per
    token, and the stream equals the unsharded full-precision one."""
    from mlapi_tpu.checkpoint import save_checkpoint
    from mlapi_tpu.models.quantized import QuantizedModel
    from mlapi_tpu.parallel import create_mesh
    from mlapi_tpu.serving import InferenceEngine

    cfg = dict(CFG)
    model = get_model("gpt_lm", **cfg)
    ck = tmp_path / "ck"
    save_checkpoint(
        ck, model.init(jax.random.key(1)), step=1,
        config={
            "model": "gpt_lm", "model_kwargs": cfg,
            "tokenizer": ByteTokenizer().fingerprint(),
        },
    )
    mesh = create_mesh((1, 1, 2), devices=jax.devices()[:2])
    eng = InferenceEngine.from_checkpoint(
        ck, quantize="int8", kv_quant="int8", mesh=mesh
    )
    assert isinstance(eng.model, QuantizedModel)
    assert eng.model.kv_quant == "int8"  # forwarded from the inner
    assert eng.meta["kv_quant"] == "int8"
    # Byte-identical to the SAME quantization config off the mesh
    # (the weights-only precedent: test_quantized_mesh_serving).
    ref = InferenceEngine.from_checkpoint(
        ck, quantize="int8", kv_quant="int8"
    )
    a = eng.generate_text("hello world", max_new_tokens=10)
    b = ref.generate_text("hello world", max_new_tokens=10)
    assert a["token_ids"] == b["token_ids"]


def test_kv_quant_rejected_for_non_generative(tmp_path):
    from mlapi_tpu.checkpoint import save_checkpoint
    from mlapi_tpu.datasets import load_iris
    from mlapi_tpu.serving import InferenceEngine
    from mlapi_tpu.train import fit

    iris = load_iris()
    model = get_model(
        "linear", num_features=iris.num_features,
        num_classes=iris.num_classes,
    )
    r = fit(model, iris, steps=50, learning_rate=0.1)
    ck = tmp_path / "ck"
    save_checkpoint(
        ck, r.params, step=50,
        config={
            "model": "linear",
            "model_kwargs": {
                "num_features": iris.num_features,
                "num_classes": iris.num_classes,
            },
        },
        vocab=iris.vocab,
    )
    with pytest.raises(ValueError, match="generative"):
        InferenceEngine.from_checkpoint(ck, kv_quant="int8")
