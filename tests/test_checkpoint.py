"""Checkpoint layer: versioned save/restore, mismatch detection,
sharded restore — replaces the reference's pickle handoff
(``main.py:19``) with something safe and resumable."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mlapi_tpu.checkpoint import (
    latest_step,
    load_checkpoint,
    save_checkpoint,
    tree_signature,
)
from mlapi_tpu.checkpoint.io import step_dir
from mlapi_tpu.models import get_model
from mlapi_tpu.utils.vocab import LabelVocab


@pytest.fixture()
def params():
    model = get_model("linear", num_features=4, num_classes=3)
    p = model.init(jax.random.key(0))
    return jax.tree.map(lambda a: a + np.random.default_rng(0).normal(size=a.shape).astype(a.dtype), p)


def test_restore_ignores_saving_topology(tmp_path, params):
    """A checkpoint must load on a DIFFERENT topology than it was
    saved on (train on the TPU, serve on a CPU box). The restore
    pins every unsharded leaf to a concrete local sharding, so orbax
    never consults the sharding recorded at save time — which names
    the saving machine's devices and raises on any other (the exact
    failure: 'sharding passed to deserialization should be
    specified... Got None'). Emulated here by corrupting the saved
    sharding record: a restore that reads it would fail or warn."""
    import warnings

    save_checkpoint(tmp_path / "ck", params, step=1)
    # Clobber the recorded shardings the way a foreign topology looks
    # to orbax: entries that resolve to no local device.
    for shard_file in (tmp_path / "ck").rglob("_sharding"):
        data = json.loads(shard_file.read_text())
        shard_file.write_text(
            json.dumps({k: "" for k in data})
        )
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params
    )
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the fallback path warns
        restored, _ = load_checkpoint(tmp_path / "ck", abstract)
    jax.tree.map(np.testing.assert_array_equal, restored, params)


def test_roundtrip_with_meta(tmp_path, params):
    vocab = LabelVocab(labels=("Iris-setosa", "Iris-versicolor", "Iris-virginica"))
    cfg = {"model": "linear", "num_features": 4, "num_classes": 3}
    save_checkpoint(tmp_path / "ck", params, step=42, config=cfg, vocab=vocab)

    abstract = jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
    restored, meta = load_checkpoint(tmp_path / "ck", abstract)

    jax.tree.map(np.testing.assert_array_equal, restored, params)
    assert meta.step == 42
    assert meta.vocab == vocab
    assert meta.config == cfg
    assert meta.tree_signature == tree_signature(params)


def test_mismatched_model_raises(tmp_path, params):
    save_checkpoint(tmp_path / "ck", params, step=1)
    wrong = get_model("linear", num_features=8, num_classes=3).init(jax.random.key(0))
    abstract = jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), wrong)
    with pytest.raises(ValueError, match="mismatch"):
        load_checkpoint(tmp_path / "ck", abstract)


def test_uncommitted_checkpoint_rejected(tmp_path, params):
    # Simulate a crash between params write and manifest commit.
    save_checkpoint(tmp_path / "ck", params, step=1)
    (tmp_path / "ck" / "MANIFEST.json").unlink()
    with pytest.raises(FileNotFoundError, match="not a committed checkpoint"):
        load_checkpoint(tmp_path / "ck")


def test_future_format_version_rejected(tmp_path, params):
    save_checkpoint(tmp_path / "ck", params, step=1)
    m = tmp_path / "ck" / "MANIFEST.json"
    obj = json.loads(m.read_text())
    obj["format_version"] = 999
    m.write_text(json.dumps(obj))
    with pytest.raises(ValueError, match="newer"):
        load_checkpoint(tmp_path / "ck")


def test_latest_step_resume_point(tmp_path, params):
    assert latest_step(tmp_path) is None
    for s in (100, 500, 300):
        save_checkpoint(step_dir(tmp_path, s), params, step=s)
    assert latest_step(tmp_path).name == "step_00000500"


def test_restore_sharded_onto_mesh(tmp_path, params, mesh8):
    """Restore directly onto the mesh: abstract params carry a
    NamedSharding, orbax places shards without a host gather."""
    save_checkpoint(tmp_path / "ck", params, step=1)
    sharding = NamedSharding(mesh8, P())
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sharding), params
    )
    restored, _ = load_checkpoint(tmp_path / "ck", abstract)
    assert restored["w"].sharding == sharding
    jax.tree.map(np.testing.assert_array_equal, restored, params)


def test_no_pickle_on_disk(tmp_path, params):
    """The artifact must contain no pickle payloads (the reference's
    security hole, main.py:19)."""
    save_checkpoint(tmp_path / "ck", params, step=1)
    files = [p for p in (tmp_path / "ck").rglob("*") if p.is_file()]
    assert files
    for f in files:
        assert not f.name.endswith((".pkl", ".pickle"))
        head = f.read_bytes()[:2]
        assert head != b"\x80\x04", f"pickle protocol header found in {f}"


def test_multihost_commit_barrier(tmp_path, params, monkeypatch):
    """Mocked multi-process save: non-zero processes barrier twice and
    do NOT write the manifest; process 0 writes it between the
    barriers; barrier keys are HOST-INVARIANT (derived from leaf name
    + step + config + tree structure, NOT the locally-resolved path —
    hosts mounting the shared filesystem at different points must
    derive identical keys or they deadlock)."""
    import mlapi_tpu.checkpoint.io as io_mod
    from jax.experimental import multihost_utils

    seen: list[str] = []
    monkeypatch.setattr(
        multihost_utils, "sync_global_devices", lambda key: seen.append(key)
    )
    monkeypatch.setattr(io_mod, "_process_count", lambda: 2)

    # Process 1: returns after the barriers without committing.
    monkeypatch.setattr(io_mod, "_process_index", lambda: 1)
    p1 = save_checkpoint(tmp_path / "a" / "step_1", params, step=1)
    assert not (p1 / "MANIFEST.json").exists()
    assert len(seen) == 2
    assert seen[0].startswith("ckpt_pre:") and seen[1].startswith("ckpt_post:")
    # Host-invariance: the locally-resolved path must NOT leak into
    # the key (different mount points would then derive different
    # keys and deadlock in sync_global_devices).
    assert str(tmp_path) not in seen[0]
    keys_a = list(seen)

    # Another "host" saving the same checkpoint under a different
    # mount prefix derives the SAME keys.
    seen.clear()
    mount_b = tmp_path / "mnt"
    mount_b.mkdir()
    (mount_b / "a").symlink_to(tmp_path / "a")
    save_checkpoint(mount_b / "a" / "step_1", params, step=1)
    assert seen == keys_a

    # Process 0: commits the manifest between the two barriers.
    seen.clear()
    monkeypatch.setattr(io_mod, "_process_index", lambda: 0)
    p0 = save_checkpoint(tmp_path / "b" / "step_2", params, step=2)
    assert (p0 / "MANIFEST.json").exists()
    assert [k.split(":")[0] for k in seen] == ["ckpt_pre", "ckpt_post"]
    # A different step must not cross-match the first save's barrier.
    assert seen[0] != keys_a[0]
