"""Rowwise AdaGrad + optimizer partitioning (the recommender-native
embedding update — see mlapi_tpu/train/optimizers.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.train.loop import _make_optimizer
from mlapi_tpu.train.optimizers import rowwise_adagrad

KW = dict(
    num_dense=3, vocab_sizes=[50, 40], embed_dim=4,
    hidden_dims=[8], num_classes=2,
)


def test_rowwise_adagrad_matches_manual_update():
    tx = rowwise_adagrad(0.5, initial_accumulator_value=0.1)
    p = {"t": jnp.ones((2, 3, 4))}
    g = {"t": jnp.arange(24, dtype=jnp.float32).reshape(2, 3, 4)}
    state = tx.init(p)
    assert state["t"].shape == (2, 3)  # one accumulator per ROW
    updates, state2 = tx.update(g, state)
    acc = 0.1 + np.mean(np.square(np.asarray(g["t"])), axis=-1)
    want = -0.5 * np.asarray(g["t"]) / np.sqrt(acc + 1e-10)[..., None]
    np.testing.assert_allclose(np.asarray(updates["t"]), want, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(state2["t"]), acc, rtol=1e-6)


def test_rowwise_adagrad_freezes_untouched_rows():
    tx = rowwise_adagrad(0.5)
    p = {"t": jnp.ones((1, 5, 4))}
    g = {"t": jnp.zeros((1, 5, 4)).at[0, 2].set(1.0)}
    state = tx.init(p)
    updates, state2 = tx.update(g, state)
    u = np.asarray(updates["t"])
    assert (u[0, [0, 1, 3, 4]] == 0).all()  # untouched rows: no update
    assert (u[0, 2] != 0).all()
    np.testing.assert_array_equal(
        np.asarray(state2["t"])[0, [0, 1, 3, 4]],
        np.asarray(state["t"])[0, [0, 1, 3, 4]],
    )


def test_recsys_optimizer_routes_tables_to_rowwise_adagrad():
    model = get_model("wide_deep", **KW)
    params = model.init(jax.random.key(0))
    tx = _make_optimizer("recsys-adamw", 1e-3, model=model, params=params)
    state = tx.init(params)
    grads = jax.tree.map(jnp.ones_like, params)
    updates, _ = tx.update(grads, state, params)
    # Tables moved by the adagrad rule; dense weights by adamw — both
    # nonzero, different magnitudes (adagrad's first step is lr-scale).
    assert np.abs(np.asarray(updates["deep_tables"])).max() > 1e-4
    assert np.abs(np.asarray(updates["wide_dense"])).max() > 1e-5


def test_recsys_requires_partition_aware_model():
    model = get_model("linear", num_features=4, num_classes=3)
    with pytest.raises(ValueError, match="optimizer_partitions"):
        _make_optimizer(
            "recsys-adamw", 1e-3, model=model, params=model.init(
                jax.random.key(0)
            ),
        )


def test_fit_with_recsys_optimizer_learns():
    from mlapi_tpu.datasets.criteo import load_criteo
    from mlapi_tpu.train import fit

    model = get_model("wide_deep", **KW)
    data = load_criteo(
        num_dense=3, num_categorical=2, vocab_size=50,
        n_train=512, n_test=128,
    )
    r = fit(
        model, data, steps=60, batch_size=128, learning_rate=1e-2,
        optimizer="recsys-adamw",
    )
    assert np.isfinite(r.final_loss)
    assert r.test_accuracy >= 0.5  # learns past chance on the synthetic stream


def test_recsys_optimizer_state_survives_checkpoint_resume(tmp_path):
    """multi_transform's namedtuple state must round-trip through
    save/resume — the top-level treedef (not a plain tuple) is part
    of the contract."""
    from mlapi_tpu.datasets.criteo import load_criteo
    from mlapi_tpu.train import fit

    data = load_criteo(
        num_dense=3, num_categorical=2, vocab_size=50,
        n_train=256, n_test=64,
    )
    kw = dict(
        batch_size=64, learning_rate=1e-2, optimizer="recsys-adamw",
        checkpoint_dir=str(tmp_path / "ck"), save_every=5,
    )
    m = get_model("wide_deep", **KW)
    fit(m, data, steps=10, **kw)
    # Second run extends the schedule; it must RESUME from step 10's
    # checkpoint (exercising the opt_state restore), not start over.
    r = fit(m, data, steps=15, **kw)
    assert np.isfinite(r.final_loss)
