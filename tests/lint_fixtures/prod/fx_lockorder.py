"""MLA007 fixture: a two-class lock-order cycle — the deadlock shape
the rule exists to refuse. ``KVTier.register`` holds the tier lock
and calls into the pool (tier-before-pool); ``PagePool.evict`` holds
the pool lock and calls back into the tier (pool-before-tier). Two
threads taking one path each deadlock under load. The rule emits ONE
finding per cycle, anchored at the first edge's first site — the
call under ``KVTier._lock``."""

import threading


class KVTier:
    def __init__(self):
        self._lock = threading.Lock()
        self.pool = PagePool()

    def register(self, fp):
        with self._lock:
            self.pool.drop_entry(fp)  # EXPECT(MLA007)


class PagePool:
    def __init__(self):
        self.lock = threading.Lock()
        self.tier = KVTier()

    def drop_entry(self, fp):
        with self.lock:
            pass

    def evict(self, fp):
        with self.lock:
            self.tier.register(fp)  # the reverse order: the cycle

    def safe_evict(self, fp):
        # The fix pattern: claim under the lock, call outside it.
        with self.lock:
            victim = fp
        self.tier.register(victim)  # no lock held: no edge, clean
