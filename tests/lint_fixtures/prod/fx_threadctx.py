"""MLA008 fixture: the r13 spill-under-brownout shape — blocking
device/disk work REACHABLE on the event loop through two sync hops
the single-function rules can't see — plus the direct blocking call,
the propagated jax fence, and every documented clean shape (the
executor hop, thread-target workers)."""

import asyncio
import threading
import time

import numpy as np


class SpillPool:
    """Sync on purpose: only CONTEXT makes its work wrong."""

    def evict_idle(self):
        self._spill()

    def _spill(self):
        # Blocked on the loop via submit() -> evict_idle() -> here.
        np.savez("/tmp/blob.npz", x=1)  # EXPECT(MLA008)


def fence(x):
    import jax

    jax.block_until_ready(x)  # EXPECT(MLA008): reached from metrics()


class Server:
    def __init__(self):
        self.spool = SpillPool()

    async def submit(self, text):
        time.sleep(0.01)  # EXPECT(MLA008): directly on the loop
        self.spool.evict_idle()  # seeds the chain flagged above
        # The documented hop: the SAME work through the executor is
        # clean (the callee is seeded worker, never loop-propagated).
        await asyncio.get_running_loop().run_in_executor(
            None, self.spool.evict_idle
        )

    async def metrics(self):
        return fence(None)


def worker_loop(spool):
    # Thread-target context: blocking off the loop is the job.
    time.sleep(0.1)
    spool.evict_idle()


def start(spool):
    threading.Thread(target=worker_loop, args=(spool,)).start()
