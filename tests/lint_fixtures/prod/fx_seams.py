"""MLA003 fixture seams: fire-before-mutation discipline plus the
unknown-point typo. ``KVTier`` is a registry class name on purpose —
its ``spill_count`` is guarded state the ordering check watches."""

import threading

from somewhere import faults  # parse-only


class KVTier:
    def __init__(self):
        self._lock = threading.Lock()
        self.spill_count = 0

    def spill_ok(self, blob):
        # The documented ordering: the seam fires FIRST, so an
        # injected raise leaves state untouched.
        faults.fire("alloc")
        with self._lock:
            self.spill_count += 1

    def spill_fires_too_late(self, blob):
        with self._lock:
            self.spill_count += 1
        faults.fire("undrilled")  # EXPECT(MLA003)

    def typo(self):
        faults.fire("allocc")  # EXPECT(MLA003)
