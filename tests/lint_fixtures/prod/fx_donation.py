"""MLA001 fixture: the r13 ``restore_entry`` poisoning shape, minimal.

Parsed by the linter, NEVER imported — the ``import jax`` below is
AST scenery. ``# EXPECT(MLA001)`` marks the exact line the rule must
flag; tests/test_static_analysis.py asserts the finding set equals
the marker set.
"""

import jax


def _restore_fn():
    def _run(pools, payload):
        return pools

    return jax.jit(_run, donate_argnums=(0,))


class Pool:
    def restore_poisoned(self, blob):
        # The historical bug: the donated dispatch consumes
        # self.layers, then a fallback path reads it.
        out = _restore_fn()(self.layers, blob)
        n = len(self.layers)  # EXPECT(MLA001)
        return out, n

    def restore_written_back(self, blob):
        # The documented discipline: same-statement write-back.
        self.layers = _restore_fn()(self.layers, blob)
        return self.layers

    def restore_rebound_later(self, blob):
        out = _restore_fn()(self.layers, blob)
        self.layers = out  # rebind before any read: clean
        return len(self.layers)


def local_jit_closure(params, opt_state, batch):
    # The make_train_step shape: a closure calls the enclosing
    # frame's jitted binding; the CALLER reassigns — reads in a
    # sibling frame must not be charged to this one.
    step = jax.jit(lambda p, o, b: (p, o), donate_argnums=(0, 1))

    def run(p, o, b):
        return step(p, o, b)

    return run(params, opt_state, batch)
