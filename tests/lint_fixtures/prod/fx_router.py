"""MLA004 fixture: the two router-purity violations (a jax import,
a blocking call on the event loop) next to the documented
run_in_executor escape hatch."""

import asyncio
import time

import jax  # EXPECT(MLA004)


async def handler():
    time.sleep(0.1)  # EXPECT(MLA004)
    return jax


def _poll_blocking():
    time.sleep(0.5)  # handed to run_in_executor below: clean
    return 1


async def ok_handler():
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, _poll_blocking)
