"""MLA002 fixture: the entry_evictions/cow_copies shapes r16's first
clean-tree run actually found — a registered attribute mutated
outside its lock, self-scoped and cross-module, plus every deliberate
exception (``_locked`` convention, ``__init__``, inline allow,
baseline entry)."""

import threading


class PagePool:
    def __init__(self):
        self.lock = threading.Lock()
        self._free = []
        self.entry_evictions = 0  # __init__ is exempt: construction

    def bad_free(self, page):
        self._free.append(page)  # EXPECT(MLA002)

    def bad_counter(self):
        self.entry_evictions += 1  # EXPECT(MLA002)

    def good_free(self, page):
        with self.lock:
            self._free.append(page)

    def _drop_locked(self, page):
        self._free.append(page)  # caller holds the lock: clean

    def allowed_bump(self):
        # lint: allow(MLA002): fixture — proves inline suppression syntax
        self.entry_evictions += 1

    def baselined_bump(self):
        self.entry_evictions += 1  # suppressed via fx_baseline.txt


def cross_module_bad(pool, n):
    pool.cow_copies += n  # EXPECT(MLA002)


def cross_module_good(pool, n):
    with pool.lock:
        pool.cow_copies += n
