"""MLA005 fixture export surface: the snapshot-store shapes the rule
extracts exported names from. Exports exactly ``generate.requests``,
``generate.queue_depth``, and ``generate.kv_pages_in_use`` (the
MLA009 fixture scrapes the last) — anything else scraped or
documented in the fixture set is drift."""


async def metrics():
    snap = {"counters": {}, "gauges": {}}
    snap["counters"]["generate.requests"] = 1
    snap["gauges"]["generate.queue_depth"] = 2
    snap["gauges"]["generate.kv_pages_in_use"] = 3
    return snap
