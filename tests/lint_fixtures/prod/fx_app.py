"""MLA005 fixture export surface: the snapshot-store shapes the rule
extracts exported names from. Exports exactly ``generate.requests``
and ``generate.queue_depth`` — anything else scraped or documented in
the fixture set is drift."""


async def metrics():
    snap = {"counters": {}, "gauges": {}}
    snap["counters"]["generate.requests"] = 1
    snap["gauges"]["generate.queue_depth"] = 2
    return snap
