"""MLA003 fixture faults module: three declared points —
``alloc`` is fired (fx_seams) and drilled (t/fx_scrape), ``ghost``
is never fired anywhere, ``undrilled`` fires but no test arms it."""

POINTS = (
    "alloc",
    "ghost",      # EXPECT(MLA003)
    "undrilled",  # EXPECT(MLA003)
)
