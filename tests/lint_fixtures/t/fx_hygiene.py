"""MLA006 fixture: the ADVICE r05 flake shape (elapsed-vs-constant
assert) in an unmarked test, the exempt soak, and the legal wait
bound."""

import time

import pytest


def test_fast_path_flaky():
    t0 = time.perf_counter()
    do_work()
    elapsed = time.perf_counter() - t0
    assert elapsed < 0.5  # EXPECT(MLA006)


def test_direct_clock_compare_flaky():
    t0 = time.perf_counter()
    do_work()
    assert time.perf_counter() - t0 < 1.0  # EXPECT(MLA006)


@pytest.mark.heavy
def test_soak_may_time_itself():
    t0 = time.perf_counter()
    do_work()
    assert time.perf_counter() - t0 < 60.0  # exempt: heavy


def test_wait_guard_is_legal():
    deadline = time.monotonic() + 10.0
    while still_busy():
        assert time.monotonic() < deadline  # clock-vs-clock: a wait
