"""MLA005/MLA003 fixture test-side: scrapes one exported name (clean)
and one that nothing exports (drift), and arms the first fault point
only — the other two declared points stay uncovered on purpose."""

FAULT_MATRIX = ["alloc:after=1:raise"]


def read_metrics(snap):
    good = snap["counters"]["generate.requests"]
    bad = snap["gauges"]["generate.queue_len"]  # EXPECT(MLA005)
    return good, bad
