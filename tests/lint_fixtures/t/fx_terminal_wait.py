"""MLA009 fixture: the r17/r18 flake shape — a release-settled
counter asserted straight after the stream's terminal read — next to
every blessed wait shape (the `_wait_for` condition wait, the inline
deadline poll, engine stop, and the sync drive where no race
exists)."""

import asyncio


async def _collect(req):
    out = []
    while True:
        item = await req.queue.get()
        if item is None:
            return out
        out.extend(item["token_ids"])


async def _wait_for(pred):
    while not pred():
        await asyncio.sleep(0.005)


async def test_flaky_assert_after_terminal(eng):
    r = await eng.submit("x", stream=True)
    await _collect(r)
    assert eng.kv_pages_in_use == 0  # EXPECT(MLA009)


async def test_flaky_metrics_scrape_after_gather(eng):
    a = await eng.submit("x", stream=True)
    b = await eng.submit("y", stream=True)
    outs = await asyncio.gather(_collect(a), _collect(b))
    g = eng.metrics()["gauges"]
    assert outs and g["generate.kv_pages_in_use"] == 0  # EXPECT(MLA009)


async def test_condition_wait_is_clean(eng):
    r = await eng.submit("x", stream=True)
    await _collect(r)
    await _wait_for(lambda: eng.kv_pages_in_use == 0)
    assert eng.kv_pages_in_use == 0


async def test_inline_poll_is_clean(eng):
    r = await eng.submit("x", stream=True)
    await _collect(r)
    while eng.kv_pages_in_use != 0:
        await asyncio.sleep(0.005)
    assert eng.kv_pages_in_use == 0


async def test_stop_joins_the_dispatch_thread(eng):
    r = await eng.submit("x", stream=True)
    await _collect(r)
    await eng.stop()
    assert eng.kv_pages_in_use == 0


def test_sync_drive_never_races(eng):
    # generate_text returns after cleanup: nothing to wait on.
    eng.generate_text("x")
    assert eng.kv_pages_in_use == 0
