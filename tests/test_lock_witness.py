"""Runtime lock-order witness (tools/lint/witness.py) — the dynamic
half of MLA007.

Three layers:

- **Mechanics** (pure stdlib, no jax): proxy-wrapped locks record
  per-thread acquisition stacks; a declared-order nesting passes, the
  INVERSION of a committed lockorder.json edge is recorded as a
  violation (the negative test proving the witness and the static
  rule enforce the SAME order), Condition waits split hold spans, and
  the opt-in hold budget flags a lock held past it.
- **Armed smoke** (the tier-1 leg): one paged+tier+scheduler engine
  churns real traffic — prefix registrations past the dict-LRU cap
  (the ``drop_entry``-under-``PrefixCache._lock`` edge), concurrent
  bucket-incompatible scheduler lanes — with every registered lock
  wrapped. Passes iff NO inversion was recorded and every observed
  (held, acquired) class pair is inside the static graph's closure:
  an edge the analyzer cannot see fails here until the analyzer (or
  the binding registry) is taught it. That is the static/dynamic
  cross-check the artifact exists for.
- Module sits in the conftest ``paged-family`` cache window (same
  tiny CFG as test_paged_kv/tier/scheduler) so its compiles are
  already paid.
"""

from __future__ import annotations

import os
import sys
import threading
import time

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

from tools.lint.witness import (  # noqa: E402
    LockWitness,
    WitnessLock,
    install,
    load_order,
    wrap_instance,
)

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


class _Toy:
    """Stand-in lock-bearing class for mechanics tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self.lock = threading.Lock()
        self._cond = threading.Condition(self._lock)


def _wrapped(witness, cls_name, lock_names=("_lock",)):
    t = _Toy()
    wrap_instance(witness, t, cls_name, lock_names)
    return t


# --- mechanics ---------------------------------------------------------


def test_declared_order_passes_and_is_observed():
    w = LockWitness({("PrefixCache", "PagePool")})
    a = _wrapped(w, "PrefixCache")
    b = _wrapped(w, "PagePool", ("lock",))
    with a._lock:
        with b.lock:
            pass
    assert w.violations == []
    assert ("PrefixCache", "PagePool") in w.observed_edges


def test_inversion_of_committed_order_is_flagged():
    """The deliberately-inverted nesting: the committed artifact
    orders PrefixCache before PagePool (the ``drop_entry`` edge), so
    taking PagePool first and PrefixCache inside it must fail — the
    runtime witness enforcing exactly what MLA007 proved statically."""
    order = load_order()
    assert ("PrefixCache", "PagePool") in order, (
        "committed lockorder.json lost its PrefixCache->PagePool "
        "edge; regenerate with python -m tools.lint --lockorder-out"
    )
    w = LockWitness(order)
    pool = _wrapped(w, "PagePool", ("lock",))
    prefix = _wrapped(w, "PrefixCache")
    with pool.lock:
        with prefix._lock:
            pass
    assert len(w.violations) == 1
    assert "order inversion" in w.violations[0]
    assert "PrefixCache" in w.violations[0]


def test_same_class_nesting_carries_no_order():
    """Two INSTANCES of one class nest without findings — class-level
    pairs carry no order (the witness cannot and does not invent
    one)."""
    w = LockWitness({("PrefixCache", "PagePool")})
    a = _wrapped(w, "PagePool", ("lock",))
    b = _wrapped(w, "PagePool", ("lock",))
    with a.lock:
        with b.lock:
            pass
    assert w.violations == []
    assert w.observed_edges == set()


def test_hold_budget_flags_convoy():
    w = LockWitness(set(), hold_budget_s=0.01)
    t = _wrapped(w, "KVTier")
    with t._lock:
        time.sleep(0.05)
    assert len(w.violations) == 1
    assert "hold-span budget" in w.violations[0]


def test_condition_wait_splits_hold_span():
    """Condition.wait releases the lock — the witness must not charge
    the wait to the hold span (the whole point of waits is NOT
    holding)."""
    w = LockWitness(set(), hold_budget_s=0.04)
    t = _Toy()
    wrap_instance(w, t, "PagePool", ("_lock", "_cond"))
    done = threading.Event()

    def waker():
        done.wait(5.0)
        with t._cond:
            t._cond.notify_all()

    thr = threading.Thread(target=waker, daemon=True)
    thr.start()
    with t._cond:
        done.set()
        t._cond.wait(timeout=1.0)  # released while waiting
    thr.join(5.0)
    assert w.violations == [], w.violations


def test_witness_tolerates_unseen_release():
    """A release the witness never saw acquired (the init-window
    mixed-Condition path) must not corrupt the stack."""
    w = LockWitness(set())
    t = _Toy()
    proxy = WitnessLock(w, "KVTier", t._lock)
    t._lock.acquire()      # raw acquire, unrecorded
    proxy.release()        # recorded release with no record: tolerated
    assert w.violations == []
    with proxy:
        pass
    assert w.violations == []


# --- armed smoke: static order vs dynamic order ------------------------


CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)


@pytest.fixture(scope="module")
def gpt_model():
    from mlapi_tpu.models import get_model

    return get_model("gpt_lm", **CFG)


@pytest.fixture(scope="module")
def gpt_params(gpt_model):
    return gpt_model.init(jax.random.key(0))


async def _collect(req):
    out = []
    while True:
        item = await req.queue.get()
        if isinstance(item, Exception):
            return out, item
        if item is None:
            return out, None
        out.extend(item["token_ids"])


async def test_armed_paged_tier_scheduler_smoke(gpt_model, gpt_params):
    """One churn over the full lock surface with the witness armed:
    prefix registrations past the LRU cap take the
    PrefixCache->PagePool edge for real, scheduler lanes and tier
    traffic take everything else. Zero inversions, and the OBSERVED
    edge set is a subset of the static closure — the two halves of
    MLA007 checking each other."""
    from mlapi_tpu.serving.engine import TextGenerationEngine
    from mlapi_tpu.text import ByteTokenizer

    w = LockWitness.from_artifact()
    uninstall = install(w)
    try:
        eng = TextGenerationEngine(
            gpt_model, gpt_params, tokenizer=ByteTokenizer(),
            chunk=2, fused_single=False, kv_page_size=8,
            kv_tier_bytes=1 << 24,
            sched_max_batches=2, max_wait_ms=0.0,
        )
        # Tight entry cap: the THIRD distinct prefix evicts the first
        # inside ``entry()``'s registration block — ``drop_entry``
        # (pool lock) under ``PrefixCache._lock``, the committed
        # static edge, taken live.
        eng.prefix.max_entries = 2
        prefixes = ["alpha " * 4, "bravo " * 4, "charlie " * 4]
        for p in prefixes:
            out = eng.generate_text("go", max_new_tokens=4, prefix=p)
            assert out["token_ids"]
        assert eng.prefix.builds == 3
        # Scheduler churn: two bucket-incompatible groups advance as
        # concurrent lanes on the dispatch thread while the event
        # loop streams — the cross-thread traffic the witness exists
        # to observe.
        await eng.start()
        try:
            r1 = await eng.submit(
                "hello world", max_new_tokens=24, stream=True
            )
            r2 = await eng.submit("y" * 70, max_new_tokens=6)
            outs = [await _collect(r1), await _collect(r2)]
            assert all(err is None for _, err in outs)
        finally:
            await eng.stop()
    finally:
        uninstall()
    assert w.violations == [], "\n".join(w.violations)
    static = load_order()
    unknown = w.observed_edges - static
    assert not unknown, (
        f"runtime took lock orders the static analyzer cannot see: "
        f"{sorted(unknown)} — teach tools/lint/rules/lockorder.py (or "
        f"the binding registry) and regenerate lockorder.json"
    )
    assert ("PrefixCache", "PagePool") in w.observed_edges, (
        "the smoke no longer exercises the committed "
        "PrefixCache->PagePool edge — it must, or the cross-check "
        "is vacuous"
    )


# Staleness of the committed artifact vs a fresh static build is
# pinned byte-for-byte in test_static_analysis.py
# (test_lockorder_artifact_roundtrip) — not re-checked here.
