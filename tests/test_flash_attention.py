"""Pallas flash-attention kernel vs the XLA baseline — interpret mode
on CPU (SURVEY §4: no TPU needed for correctness), compiled parity
behind ``requires_tpu``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.ops import full_attention
from mlapi_tpu.ops.pallas import flash_attention

B, L, H, D = 2, 64, 4, 16


def _qkv(seed=0, dtype=jnp.float32, l=L):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (B, l, H, D), dtype) for k in ks)


def test_matches_full_attention():
    q, k, v = _qkv()
    out = flash_attention(q, k, v, block_q=32, interpret=True)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_matches_with_padding_mask():
    q, k, v = _qkv(seed=1)
    lengths = np.array([L - 3, 9])
    mask = (np.arange(L)[None, :] < lengths[:, None]).astype(np.float32)
    out = flash_attention(q, k, v, jnp.asarray(mask), block_q=32, interpret=True)
    ref = full_attention(q, k, v, jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_causal_matches():
    q, k, v = _qkv(seed=2)
    out = flash_attention(q, k, v, causal=True, block_q=16, interpret=True)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_fully_masked_rows_are_zero_not_nan():
    q, k, v = _qkv(seed=3)
    mask = np.zeros((B, L), np.float32)  # nothing valid at all
    out = flash_attention(q, k, v, jnp.asarray(mask), block_q=32, interpret=True)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_block_q_larger_than_sequence_is_clamped():
    q, k, v = _qkv(seed=4, l=16)
    out = flash_attention(q, k, v, block_q=128, interpret=True)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_indivisible_block_degrades_to_dividing_halving():
    """A block that doesn't divide L halves until it does (32 → 16
    for L=48) instead of erroring — so growing the performance default
    can never turn a working length into a crash."""
    q, k, v = _qkv(seed=5, l=48)
    out = flash_attention(q, k, v, block_q=32, interpret=True)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_gradients_match_full_attention():
    """flash is differentiable (custom VJP: Pallas kernels in both
    directions) — grads must match the reference."""
    q, k, v = _qkv(seed=7)
    lengths = np.array([L - 6, 23])
    mask = jnp.asarray(
        (np.arange(L)[None, :] < lengths[:, None]).astype(np.float32)
    )

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, mask, block_q=32, interpret=True) ** 2
        )

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, mask) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_bert_flash_backend_matches_full():
    """attention_impl='flash' is logit-identical to 'full' (interpret
    mode here; the compiled path is covered by the TPU-marked test)."""
    from mlapi_tpu.models import get_model

    cfg = dict(
        num_classes=2, vocab_size=128, hidden_size=32, num_layers=2,
        num_heads=4, intermediate_size=64, max_positions=64,
        compute_dtype="float32",
    )
    full = get_model("bert_classifier", **cfg)
    flash = get_model("bert_classifier", **cfg, attention_impl="flash")
    params = full.init(jax.random.key(0))
    ids = np.ones((2, 64), np.int32)
    ids[0, 40:] = 0
    ids[1, 11:] = 0
    ref = jax.jit(full.apply)(params, jnp.asarray(ids))
    out = jax.jit(flash.apply)(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.requires_tpu
def test_compiled_on_tpu_matches():
    q, k, v = _qkv(seed=6, dtype=jnp.bfloat16, l=256)
    lengths = np.array([200, 117])
    mask = (np.arange(256)[None, :] < lengths[:, None]).astype(np.float32)
    out = flash_attention(q, k, v, jnp.asarray(mask))
    ref = full_attention(q, k, v, jnp.asarray(mask))
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


def test_gradients_match_with_k_tiling_and_causal():
    """Both backward kernels accumulate across tiles: exercise
    multiple q- AND k-tiles (4x4 grid) with causal + padding mask —
    the online-softmax recompute path, not a single-tile degenerate."""
    q, k, v = _qkv(seed=8)
    lengths = np.array([L - 6, 23])
    mask = jnp.asarray(
        (np.arange(L)[None, :] < lengths[:, None]).astype(np.float32)
    )

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, mask, causal=True, block_q=16, block_k=16,
            interpret=True,
        )
        return jnp.sum(out ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, mask, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_forward_lse_is_consistent_under_k_tiling():
    """Forward output must not depend on the k-tile size (the online
    carry is exact, not approximate)."""
    q, k, v = _qkv(seed=9)
    a = flash_attention(q, k, v, block_q=16, block_k=64, interpret=True)
    b = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.requires_tpu
def test_compiled_grad_has_no_quadratic_tensor():
    """VERDICT r1 done-criterion: the compiled grad path must not
    materialise an [L, L] score tensor in HBM — check the optimized
    HLO for any buffer with two trailing L-sized dims."""
    l = 512
    q, k, v = _qkv(seed=10, dtype=jnp.bfloat16, l=l)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    txt = (
        jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        .lower(q, k, v)
        .compile()
        .as_text()
    )
    import re

    quadratic = re.findall(rf"\[(?:\d+,)*{l},{l}\]", txt)
    assert not quadratic, f"found [L,L] buffers in HLO: {quadratic[:5]}"


def test_gqa_forward_matches_repeated_reference():
    """GQA-native kernel (kv BlockSpec indexes hi // group) must equal
    attention over explicitly repeated K/V heads."""
    ks = jax.random.split(jax.random.key(21), 3)
    q = jax.random.normal(ks[0], (2, 32, 4, 8))
    k = jax.random.normal(ks[1], (2, 32, 2, 8))  # 2 kv heads, group=2
    v = jax.random.normal(ks[2], (2, 32, 2, 8))
    lengths = np.array([30, 17])
    mask = jnp.asarray(
        (np.arange(32)[None, :] < lengths[:, None]).astype(np.float32)
    )
    out = flash_attention(q, k, v, mask, interpret=True)
    ref = full_attention(
        q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2), mask
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_gqa_gradients_fold_onto_shared_kv_heads():
    """d/dK, d/dV of the GQA kernel must equal the repeated-reference
    grads summed over each group (the VJP's fold-back)."""
    ks = jax.random.split(jax.random.key(22), 3)
    q = jax.random.normal(ks[0], (1, 32, 4, 8))
    k = jax.random.normal(ks[1], (1, 32, 2, 8))
    v = jax.random.normal(ks[2], (1, 32, 2, 8))

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, interpret=True) ** 2
        )

    def loss_ref(q, k, v):
        kf = jnp.repeat(k, 2, axis=2)
        vf = jnp.repeat(v, 2, axis=2)
        return jnp.sum(full_attention(q, kf, vf, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_gqa_rejects_indivisible_heads():
    q = jnp.zeros((1, 16, 4, 8))
    kv = jnp.zeros((1, 16, 3, 8))
    with pytest.raises(ValueError, match="multiple of"):
        flash_attention(q, kv, kv, interpret=True)


@pytest.mark.requires_tpu
def test_gqa_compiled_on_tpu_matches():
    """The hi // group BlockSpec must survive real Mosaic lowering."""
    ks = jax.random.split(jax.random.key(23), 3)
    q = jax.random.normal(ks[0], (2, 256, 4, 64), jnp.bfloat16)
    k = jax.random.normal(ks[1], (2, 256, 2, 64), jnp.bfloat16)
    v = jax.random.normal(ks[2], (2, 256, 2, 64), jnp.bfloat16)
    out = flash_attention(q, k, v)
    ref = full_attention(q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2))
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


def _windowed_reference(q, k, v, window, mask=None):
    """Oracle: full attention with an explicit sliding-window mask."""
    import mlapi_tpu.ops.attention as att

    lq, lk = q.shape[1], k.shape[1]
    dist = np.arange(lq)[:, None] - np.arange(lk)[None, :]
    win = (dist >= 0) & (dist < window)
    keep = np.broadcast_to(win, (q.shape[0],) + win.shape).astype(np.float32)
    if mask is not None:
        keep = keep * np.asarray(mask)[:, None, :]
    s = np.einsum(
        "bqhd,bkhd->bhqk", np.asarray(q, np.float32), np.asarray(k, np.float32)
    ) / q.shape[-1] ** 0.5
    s = s + (1.0 - keep[:, None]) * att.NEG
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p * keep[:, None]
    denom = np.maximum(p.sum(-1, keepdims=True), 1e-30)
    return np.einsum(
        "bhqk,bkhd->bqhd", p / denom, np.asarray(v, np.float32)
    )


def test_sliding_window_matches_masked_reference():
    q, k, v = _qkv(seed=31)
    out = flash_attention(
        q, k, v, causal=True, window=10, block_q=16, block_k=16,
        interpret=True,
    )
    ref = _windowed_reference(q, k, v, 10)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_sliding_window_with_padding_mask_and_grads():
    q, k, v = _qkv(seed=32)
    lengths = np.array([L - 4, 37])
    mask = jnp.asarray(
        (np.arange(L)[None, :] < lengths[:, None]).astype(np.float32)
    )
    out = flash_attention(
        q, k, v, mask, causal=True, window=12, block_q=16, block_k=16,
        interpret=True,
    )
    ref = _windowed_reference(q, k, v, 12, mask=mask)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    # Grads: keys outside every query's window must get ZERO gradient.
    def loss(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, window=8, block_q=16, block_k=16,
                interpret=True,
            )[:, -1]  # only the last query row contributes
            ** 2
        )

    dk = jax.grad(loss, argnums=1)(q, k, v)
    dk = np.asarray(dk)
    assert np.abs(dk[:, : L - 8]).max() == 0.0  # outside the last row's window
    assert np.abs(dk[:, L - 8 :]).max() > 0.0


def test_window_tile_skip_is_exact_at_tile_boundaries():
    """Window == block size: whole tiles drop; result still exact."""
    q, k, v = _qkv(seed=33)
    out = flash_attention(
        q, k, v, causal=True, window=16, block_q=16, block_k=16,
        interpret=True,
    )
    ref = _windowed_reference(q, k, v, 16)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_window_requires_causal():
    q, k, v = _qkv(seed=34)
    with pytest.raises(ValueError, match="window requires causal"):
        flash_attention(q, k, v, window=8, interpret=True)


def test_randomized_differential_sweep():
    """Fuzz the kernel against the einsum oracle across random
    (shape, mask, GQA group, window, blocks, causal) configs — one
    seeded float32 sweep, so failures reproduce exactly (bf16
    numerics are covered separately by the requires_tpu tests)."""
    rng = np.random.default_rng(2026)
    for trial in range(12):
        b = int(rng.integers(1, 3))
        lq = int(rng.choice([16, 32, 48, 64]))
        h = int(rng.choice([2, 4]))
        d = int(rng.choice([8, 16]))
        group = int(rng.choice([1, 2]))
        kvh = h // group
        causal = bool(rng.integers(0, 2))
        window = (
            int(rng.choice([8, 16])) if causal and rng.integers(0, 2) else None
        )
        bq = int(rng.choice([16, 32]))
        bk = int(rng.choice([16, 32]))  # mismatched blocks included
        ks = jax.random.split(jax.random.key(trial), 3)
        q = jax.random.normal(ks[0], (b, lq, h, d))
        k = jax.random.normal(ks[1], (b, lq, kvh, d))
        v = jax.random.normal(ks[2], (b, lq, kvh, d))
        lengths = rng.integers(1, lq + 1, size=b)
        mask = jnp.asarray(
            (np.arange(lq)[None, :] < lengths[:, None]).astype(np.float32)
        )
        out = flash_attention(
            q, k, v, mask, causal=causal, window=window,
            block_q=bq, block_k=bk, interpret=True,
        )
        # Oracle: the shared references (no third masking copy).
        kf = jnp.repeat(k, group, axis=2) if group > 1 else k
        vf = jnp.repeat(v, group, axis=2) if group > 1 else v
        if causal:
            ref = _windowed_reference(q, kf, vf, window or lq, mask=mask)
        else:
            ref = np.asarray(full_attention(q, kf, vf, mask))
        np.testing.assert_allclose(
            np.asarray(out), ref, atol=2e-5,
            err_msg=f"trial {trial}: b={b} l={lq} h={h} d={d} "
                    f"group={group} causal={causal} window={window} "
                    f"bq={bq} bk={bk}",
        )


def test_window_grid_covers_every_live_tile():
    """White-box: the shrunken k-grid's physical tiles must cover
    every tile containing an attendable key, for all q-tiles and a
    sweep of (window, block) combinations."""
    from mlapi_tpu.ops.pallas.flash_attention import _window_k_tile

    for bq, bk, window, l in [
        (16, 16, 8, 64), (16, 16, 16, 64), (32, 16, 24, 128),
        (16, 32, 40, 128), (32, 32, 32, 256), (16, 16, 50, 128),
    ]:
        from mlapi_tpu.ops.pallas.flash_attention import _live_k_tiles

        nk_full = l // bk
        nkw = min(nk_full, _live_k_tiles(bq, bk, window))
        for qi in range(l // bq):
            visited = {
                max(0, int(_window_k_tile(qi, ki, bq, bk, nkw)))
                for ki in range(nkw)
                if int(_window_k_tile(qi, ki, bq, bk, nkw)) >= 0
            }
            # Tiles that contain at least one key some query attends:
            need = set()
            for qp in range(qi * bq, (qi + 1) * bq):
                lo, hi = max(0, qp - window + 1), qp
                need |= {t for t in range(lo // bk, hi // bk + 1)}
            assert need <= visited, (
                f"bq={bq} bk={bk} window={window} qi={qi}: "
                f"missing tiles {sorted(need - visited)}"
            )


def test_window_with_mismatched_blocks_matches_reference():
    """The shrunken k-grid's diagonal-tile arithmetic differs per
    q-tile alignment when block_q != block_k — exercise both
    directions through the actual kernel."""
    q, k, v = _qkv(seed=35)
    for bq, bk in [(16, 32), (32, 16)]:
        out = flash_attention(
            q, k, v, causal=True, window=24, block_q=bq, block_k=bk,
            interpret=True,
        )
        ref = _windowed_reference(q, k, v, 24)
        np.testing.assert_allclose(
            np.asarray(out), ref, atol=1e-5, err_msg=f"bq={bq} bk={bk}"
        )
