"""Configs 2-3: MNIST softmax and Fashion-MNIST MLP, single-device
and data-parallel over the 8-device virtual mesh (SURVEY §7 step 5)."""

import numpy as np
import pytest

from mlapi_tpu.datasets import get_dataset
from mlapi_tpu.datasets.mnist import read_idx
from mlapi_tpu.models import get_model
from mlapi_tpu.train import fit


@pytest.fixture(scope="module")
def mnist():
    return get_dataset("mnist", synthetic_train=4096, synthetic_test=512)


@pytest.fixture(scope="module")
def fashion():
    return get_dataset("fashion_mnist", synthetic_train=4096, synthetic_test=512)


def test_synthetic_fallback_is_deterministic():
    a = get_dataset("mnist", synthetic_train=64, synthetic_test=16)
    b = get_dataset("mnist", synthetic_train=64, synthetic_test=16)
    assert a.source == "synthetic"
    np.testing.assert_array_equal(a.x_train, b.x_train)
    np.testing.assert_array_equal(a.y_train, b.y_train)


def test_shapes_and_vocab(mnist, fashion):
    assert mnist.x_train.shape[1] == 784
    assert mnist.num_classes == 10
    assert mnist.vocab.labels[0] == "0"
    assert fashion.vocab.labels[0] == "T-shirt/top"


def test_idx_parser_roundtrip(tmp_path):
    import struct

    imgs = np.random.default_rng(0).integers(0, 256, (7, 4, 4), dtype=np.uint8)
    raw = struct.pack(">I", 0x00000803 | 0) + struct.pack(">3I", 7, 4, 4) + imgs.tobytes()
    # magic for 3-dim uint8 idx is 0x00000803; low byte = ndim
    p = tmp_path / "imgs-idx3-ubyte"
    p.write_bytes(raw)
    out = read_idx(p)
    np.testing.assert_array_equal(out, imgs)


def test_mnist_softmax_trains(mnist):
    model = get_model("linear", num_features=784, num_classes=10)
    result = fit(
        model, mnist, steps=300, batch_size=256, learning_rate=1e-2,
        optimizer="adam",
    )
    # Synthetic templates are very separable for a linear model.
    assert result.test_accuracy > 0.9


def test_fashion_mlp_trains_data_parallel(fashion, mesh8):
    model = get_model(
        "mlp", num_features=784, num_classes=10, hidden_dims=(64, 32)
    )
    result = fit(
        model, fashion, steps=200, batch_size=256, learning_rate=1e-3,
        mesh=mesh8,
    )
    assert result.test_accuracy > 0.9


def test_mlp_params_are_bf16_compute_f32_store():
    import jax.numpy as jnp

    model = get_model("mlp", num_features=8, num_classes=3, hidden_dims=(4,))
    import jax

    params = model.init(jax.random.key(0))
    # Params stored f32 (master weights)...
    assert params["dense_0"]["kernel"].dtype == jnp.float32
    # ...logits come out f32 even though hidden compute is bf16.
    logits = model.apply(params, jnp.zeros((2, 8)))
    assert logits.dtype == jnp.float32
