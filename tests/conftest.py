"""Test harness config.

Tests run on CPU with 8 virtual XLA devices
(``--xla_force_host_platform_device_count=8``) so mesh/sharding/
collective behaviour is exercised without TPU hardware (SURVEY §4,
"distributed without a cluster"). Real-TPU runs use the
``requires_tpu`` marker and are skipped here.

Env vars must be set before the first ``import jax`` anywhere in the
test process, hence this header runs at conftest import time.
"""

import os

# Force CPU regardless of ambient JAX_PLATFORMS (the dev box tunnels a
# real TPU chip; unit tests must not depend on it — bench.py does).
# Set MLAPI_TPU_TESTS=1 to run on the attached TPU instead — this is
# how the ``requires_tpu``-marked tests execute for real:
#   MLAPI_TPU_TESTS=1 pytest tests/ -m requires_tpu
_ON_TPU = os.environ.get("MLAPI_TPU_TESTS") == "1"
# Generation warmup compiles (bucket x batch) shape grids — right for
# serving, wasteful for unit tests. Tests that specifically exercise
# the full warmup opt back in with warmup(full=True).
os.environ.setdefault("MLAPI_TPU_WARMUP", "minimal")
if not _ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# The dev image's sitecustomize registers the TPU plugin and overwrites
# the jax_platforms *config* (which beats the env var). Backends are
# lazy, so re-pinning the config here — before any computation — wins.
if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "requires_tpu: needs real TPU hardware; skipped on CPU"
    )
    config.addinivalue_line(
        "markers",
        "heavy: in-suite model training or soak-style test (tens of "
        "seconds each on this box). The fast dev profile deselects "
        "them: pytest -m 'not heavy' (~8 min serial vs ~10.5 full — "
        "measured times in README). CI and tier-1 run the full suite.",
    )
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 window by its time budget "
        "(-m 'not slow'); run explicitly with pytest -m slow. Two "
        "populations: multi-minute spawned-process drills (e.g. the "
        "--router SIGTERM/respawn topology test), and — since the "
        "r16 buyback — the five in-suite churn/long-tail soaks whose "
        "per-test measured call times (5.5 + 4.8 + 7.2 + 7.1 + "
        "12.6 s, noted at each demotion site) were pushing the suite "
        "against the 870 s window (r14/r15 both timed out there with "
        "zero failures). The soaks duplicate tier-1 functional "
        "coverage at larger iteration counts, so demoting them "
        "regains ~37 s (~31 s net of the new test_static_analysis "
        "module) without dropping any invariant from the window.",
    )


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() != "tpu":
        skip = pytest.mark.skip(reason="no TPU attached")
        for item in items:
            if "requires_tpu" in item.keywords:
                item.add_marker(skip)
    # Cluster each cache family at its first member's position so the
    # shared-window fixture shares even when a CLI file list or -k
    # selection breaks the default alphabetical adjacency the family
    # relies on. A no-op for default runs (the spec family is already
    # contiguous); stable within and across groups.
    first_seen: dict = {}
    for i, item in enumerate(items):
        g = _cache_group(item.module.__name__)
        first_seen.setdefault(g, i)
    items.sort(key=lambda it: first_seen[_cache_group(it.module.__name__)])


# Module families that share a model config compile IDENTICAL
# expensive programs (whole-generation fused loops, prefill/decode
# grids) — clearing the XLA cache between them just recompiles the
# same executables (r04 suite creep, VERDICT #8). Each family forms
# one cache window; every other module stays its own window, and
# collection is reordered so family members run consecutively.
_CACHE_FAMILIES = {
    # h48/3L target + h24/1L draft speculation pair (sampling's
    # synthetic-kernel tests add small v32 models on top of the same
    # pair). Only IDENTICAL-config families share a window: a
    # serving-family grouping (same arch, differing max_positions)
    # was measured at ~38s saved and rejected — partially-overlapping
    # program sets accumulate across the window and weaken the
    # segfault guard the clears exist for.
    "spec-family": frozenset({
        "test_speculative",
        "test_speculative_batched",
        "test_speculative_fused",
        "test_speculative_sampling",
        "test_spec_batched_serving",
    }),
    # Identical tiny-model CFG (vocab 260 / h32 / 2L / 4H / 160 pos,
    # f32) and the same {gpt, llama} x {none, int8} engine shapes at
    # page 8 / chunk 2: the tier module re-drives the SAME compiled
    # prefill/decode programs test_paged_kv built, plus only its own
    # restore scatter — sharing the window saves the whole 4-config
    # compile ladder a second time (~15 s).
    # + the scheduler module (r15): same CFG and engine shapes again —
    # scheduler-on drives the SAME compiled prefill/decode programs
    # (the unit generator changes dispatch ORDER, never shapes), so
    # sharing the window costs it only its own handful of tier
    # variants instead of the whole ladder.
    # + the kv_peer module (r17): identical CFG and the same
    # {gpt, llama} x {none, int8} engine shapes at page 8 / chunk 2 —
    # peer restores re-drive the programs the tier module compiled;
    # only the wire hop is new, and it compiles nothing.
    # + the kv_push module (r18): the same CFG again — disaggregated
    # prefill/decode drive the family's compiled programs at a
    # (16, 64) bucket ladder (a handful of extra shapes, paid once in
    # the shared window); the push wire hop compiles nothing.
    # + the lock-witness module (r19): identical CFG once more — the
    # armed smoke re-drives the family's compiled prefix/scheduler
    # programs with wrapped locks; wrapping compiles nothing.
    # + the fused-serving module (r20 fold): same CFG at page 8 /
    # chunk 2 — fused-width decode chunks are the family's
    # decode_chunk_fn at tier-wide sizes, so only the handful of
    # fused-width shapes are new; prefill and plain-chunk programs
    # come from the shared window.
    # + the lora-serving module (r21): same CFG and engine shapes at
    # page 8 / chunk 2 — adapter traffic reaches the family's
    # prefill/decode programs through the one decode_chunk_fn seam;
    # only the lora-augmented trace variants (grouped scalar-slot and
    # gathered rows) are new, and they compile once in the shared
    # window instead of re-paying the whole ladder.
    # + the multi-model module (r22): same CFG and engine shapes at
    # page 8 / chunk 2 — a registry's generative entries drive the
    # family's prefill/decode programs unchanged (score units change
    # dispatch ORDER, never shapes), and the scoring fast path's
    # padded-shape jit programs are tiny tabular predicts.
    "paged-family": frozenset({
        "test_serving_fused",
        "test_kv_peer",
        "test_kv_push",
        "test_lock_witness",
        "test_lora_serving",
        "test_multi_model",
        "test_paged_kv",
        "test_paged_kv_tier",
        "test_scheduler",
    }),
}
_last_cache_group = [None]


def _cache_group(module_name: str) -> str:
    name = module_name.rsplit(".", 1)[-1]
    for family, members in _CACHE_FAMILIES.items():
        if name in members:
            return family
    return name


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_module_groups(request):
    """Drop compiled executables when crossing a module-GROUP
    boundary. A full-suite run accumulates hundreds of XLA CPU
    programs in one process and eventually SEGFAULTS inside a later
    compile (reproduced twice at the same test with ~128 GB RAM
    free — compiler-internal state, not host memory). Clearing
    between groups keeps the process within whatever envelope the
    compiler needs, while the spec-family modules — which compile the
    SAME programs — share one window instead of paying the compiles
    per module. Serial runs visit the family consecutively
    (alphabetical collection); under xdist each worker tracks its own
    last-group, so the bound holds per process either way."""
    group = _cache_group(request.module.__name__)
    if _last_cache_group[0] is not None and group != _last_cache_group[0]:
        jax.clear_caches()
    _last_cache_group[0] = group
    yield


@pytest.fixture(scope="session")
def mesh8():
    """An 8-device (data=8, model=1) mesh on virtual CPU devices."""
    from mlapi_tpu.parallel import create_mesh

    return create_mesh((8, 1))


@pytest.fixture(scope="session")
def mesh_2x4():
    """A (data=2, model=4) mesh for sharded-param configs."""
    from mlapi_tpu.parallel import create_mesh

    return create_mesh((2, 4))


@pytest.fixture(scope="session")
def mesh_1x4():
    """A (data=1, model=4) mesh — pure TP, the generative-serving
    decode layout (batch stays whole; params split over `model`)."""
    import jax as _jax

    from mlapi_tpu.parallel import create_mesh

    return create_mesh((1, 4), devices=_jax.devices()[:4])


def _armed_witness():
    """One arming protocol for both witness fixtures: install the
    runtime lock-order witness (tools/lint/witness.py, the dynamic
    half of MLA007), yield it, uninstall, and FAIL on any recorded
    order inversion against the committed lockorder.json (or
    hold-budget breach when MLAPI_LOCK_WITNESS_BUDGET_S is set)."""
    import sys

    root = str(os.path.dirname(os.path.dirname(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools.lint.witness import LockWitness, install

    w = LockWitness.from_artifact()
    uninstall = install(w)
    try:
        yield w
    finally:
        uninstall()
    assert not w.violations, "\n".join(w.violations)


@pytest.fixture
def lock_witness():
    """Opt-in per-test witness: every registered serving lock
    constructed inside the fixture's scope records per-thread
    acquisition stacks; teardown fails the test on violations. Arm
    it suite-wide instead with MLAPI_LOCK_WITNESS=1."""
    yield from _armed_witness()


@pytest.fixture(scope="session", autouse=True)
def _lock_witness_env():
    """MLAPI_LOCK_WITNESS=1 arms the witness for the WHOLE session:
    every engine any test builds runs wrapped, and the session fails
    at teardown on any recorded violation. Off (the default), this
    fixture is a no-op — zero cost, nothing imported."""
    if os.environ.get("MLAPI_LOCK_WITNESS") != "1":
        yield
        return
    yield from _armed_witness()
