"""Test harness config.

Tests run on CPU with 8 virtual XLA devices
(``--xla_force_host_platform_device_count=8``) so mesh/sharding/
collective behaviour is exercised without TPU hardware (SURVEY §4,
"distributed without a cluster"). Real-TPU runs use the
``requires_tpu`` marker and are skipped here.

Env vars must be set before the first ``import jax`` anywhere in the
test process, hence this header runs at conftest import time.
"""

import os

# Force CPU regardless of ambient JAX_PLATFORMS (the dev box tunnels a
# real TPU chip; unit tests must not depend on it — bench.py does).
# Set MLAPI_TPU_TESTS=1 to run on the attached TPU instead — this is
# how the ``requires_tpu``-marked tests execute for real:
#   MLAPI_TPU_TESTS=1 pytest tests/ -m requires_tpu
_ON_TPU = os.environ.get("MLAPI_TPU_TESTS") == "1"
# Generation warmup compiles (bucket x batch) shape grids — right for
# serving, wasteful for unit tests. Tests that specifically exercise
# the full warmup opt back in with warmup(full=True).
os.environ.setdefault("MLAPI_TPU_WARMUP", "minimal")
if not _ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# The dev image's sitecustomize registers the TPU plugin and overwrites
# the jax_platforms *config* (which beats the env var). Backends are
# lazy, so re-pinning the config here — before any computation — wins.
if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "requires_tpu: needs real TPU hardware; skipped on CPU"
    )


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() != "tpu":
        skip = pytest.mark.skip(reason="no TPU attached")
        for item in items:
            if "requires_tpu" in item.keywords:
                item.add_marker(skip)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables after each test module. A full-suite
    run accumulates hundreds of XLA CPU programs in one process and
    eventually SEGFAULTS inside a later compile (reproduced twice at
    the same test with ~128 GB RAM free — compiler-internal state,
    not host memory). Clearing between modules keeps the process
    within whatever envelope the compiler needs; modules recompile
    their own shapes, which costs seconds and buys a deterministic
    green suite."""
    yield
    jax.clear_caches()


@pytest.fixture(scope="session")
def mesh8():
    """An 8-device (data=8, model=1) mesh on virtual CPU devices."""
    from mlapi_tpu.parallel import create_mesh

    return create_mesh((8, 1))


@pytest.fixture(scope="session")
def mesh_2x4():
    """A (data=2, model=4) mesh for sharded-param configs."""
    from mlapi_tpu.parallel import create_mesh

    return create_mesh((2, 4))


@pytest.fixture(scope="session")
def mesh_1x4():
    """A (data=1, model=4) mesh — pure TP, the generative-serving
    decode layout (batch stays whole; params split over `model`)."""
    import jax as _jax

    from mlapi_tpu.parallel import create_mesh

    return create_mesh((1, 4), devices=_jax.devices()[:4])
