"""Weight-only int8 quantized serving (`ops/quant.py`,
`models/quantized.py`): per-channel symmetric quantization math, the
transparent model wrapper, and the engine/CLI integration. Decode on
TPU is weight-bandwidth-bound, so int8 weights are ~2x decode HBM
traffic and exactly 2x parameter memory; these tests pin the
correctness side of that trade."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.checkpoint import save_checkpoint
from mlapi_tpu.models import get_model
from mlapi_tpu.models.quantized import QuantizedModel
from mlapi_tpu.ops.quant import (
    dequantize_tree,
    is_quantized,
    quantize_tree,
    quantized_bytes,
)
from mlapi_tpu.serving import InferenceEngine
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=96,
    compute_dtype="float32",
)


def test_roundtrip_error_bounded_by_half_scale():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 48)).astype(np.float32) * 0.1
    (q,) = jax.tree.leaves(
        quantize_tree({"w": w}, min_size=64),
        is_leaf=lambda x: isinstance(x, dict) and set(x) == {"q", "scale"},
    )
    assert q["q"].dtype == np.int8
    assert q["scale"].shape == (1, 48)  # per-output-channel
    back = np.asarray(q["q"], np.float32) * q["scale"]
    err = np.abs(back - w)
    # Symmetric rounding: error is at most half a quantization step.
    assert np.all(err <= q["scale"] / 2 + 1e-8)


def test_small_and_1d_leaves_pass_through():
    params = {
        "bias": np.zeros((32,), np.float32),
        "small_kernel": np.ones((4, 4), np.float32),
        "ids": np.zeros((128, 128), np.int32),  # non-float
        "big": np.ones((128, 128), np.float32),
    }
    qt = quantize_tree(params, min_size=1024)
    assert qt["bias"] is params["bias"]
    assert qt["small_kernel"] is params["small_kernel"]
    assert qt["ids"] is params["ids"]
    assert set(qt["big"]) == {"q", "scale"}
    assert is_quantized(qt) and not is_quantized(params)
    stored, full = quantized_bytes(qt)
    assert stored < full  # the int8 leaf actually shrank


def test_dequantize_is_identity_on_float_leaves():
    params = {"w": jnp.ones((8, 8))}
    out = dequantize_tree(params)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones((8, 8)))


def test_wrapper_logits_close_to_float_model():
    """Per-channel int8 weight error must stay a small perturbation of
    the logits: per-row cosine similarity vs the float model."""
    model = get_model("gpt_lm", **CFG)
    params = model.init(jax.random.key(0))
    qparams = quantize_tree(params, min_size=64)
    qmodel = QuantizedModel(model)
    ids = jnp.asarray(np.arange(24, dtype=np.int32)[None] % 200)
    ref = np.asarray(model.apply(params, ids))[0]
    got = np.asarray(qmodel.apply(qparams, ids))[0]
    cos = np.sum(ref * got, -1) / (
        np.linalg.norm(ref, axis=-1) * np.linalg.norm(got, axis=-1)
    )
    assert np.all(cos > 0.999), cos.min()


def test_wrapper_generates_deterministically():
    model = get_model("gpt_lm", **CFG)
    qmodel = QuantizedModel(model)
    qparams = quantize_tree(model.init(jax.random.key(0)), min_size=64)
    ids = jnp.asarray(np.arange(8, dtype=np.int32)[None])
    a = np.asarray(qmodel.generate(qparams, ids, max_new_tokens=6))
    b = np.asarray(qmodel.generate(qparams, ids, max_new_tokens=6))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (1, 6)


@pytest.fixture(scope="module")
def gpt_checkpoint(tmp_path_factory):
    model = get_model("gpt_lm", **CFG)
    ck = tmp_path_factory.mktemp("qgpt") / "ck"
    save_checkpoint(
        ck, model.init(jax.random.key(1)), step=1,
        config={
            "model": "gpt_lm",
            "model_kwargs": CFG,
            "tokenizer": ByteTokenizer().fingerprint(),
        },
    )
    return ck


def test_from_checkpoint_quantized_generative(gpt_checkpoint):
    eng = InferenceEngine.from_checkpoint(gpt_checkpoint, quantize="int8")
    assert isinstance(eng, TextGenerationEngine)
    assert isinstance(eng.model, QuantizedModel)
    assert eng.meta["quantized"] == "int8"
    # The big leaves really are int8 on device.
    wte = eng.params["wte"]
    assert set(wte) == {"q", "scale"} and wte["q"].dtype == jnp.int8
    out = eng.generate_text("hello", max_new_tokens=5)
    assert len(out["token_ids"]) == 5
    # Byte-identical to a fresh engine on the same checkpoint (the
    # whole pipeline is deterministic under greedy decoding).
    eng2 = InferenceEngine.from_checkpoint(gpt_checkpoint, quantize="int8")
    out2 = eng2.generate_text("hello", max_new_tokens=5)
    assert out["token_ids"] == out2["token_ids"]


def test_from_checkpoint_quantized_tabular_routes_correctly(tmp_path):
    """Engine dispatch must key off the INNER model: the quantized
    wrapper defines the decoder protocol unconditionally, and probing
    the wrapper routed every quantized checkpoint — classifiers
    included — to the generative engine (code-review regression)."""
    from mlapi_tpu.datasets import load_iris
    from mlapi_tpu.train import fit

    iris = load_iris()
    model = get_model(
        "linear", num_features=iris.num_features,
        num_classes=iris.num_classes,
    )
    r = fit(model, iris, steps=100, learning_rate=0.1, weight_decay=1e-3)
    ck = tmp_path / "ck"
    save_checkpoint(
        ck, r.params, step=100,
        config={
            "model": "linear",
            "model_kwargs": {
                "num_features": iris.num_features,
                "num_classes": iris.num_classes,
            },
            "feature_names": list(iris.feature_names),
        },
        vocab=iris.vocab,
    )
    eng = InferenceEngine.from_checkpoint(ck, quantize="int8")
    assert type(eng) is InferenceEngine, type(eng)
    labels, probs = eng.predict_labels(
        np.asarray([[5.1, 3.5, 1.4, 0.2]], np.float32)
    )
    assert labels[0].startswith("Iris-")


def test_quantized_mesh_serving(gpt_checkpoint, mesh_1x4):
    """--quantize int8 composes with --mesh-shape (r03 VERDICT missing
    #4): q leaves carry the float TP layout, per-channel scales ride
    the channel axis, and the streams are byte-identical to the
    single-chip quantized engine."""
    eng = InferenceEngine.from_checkpoint(
        gpt_checkpoint, quantize="int8", mesh=mesh_1x4
    )
    # Only leaves >= MIN_QUANT_SIZE quantize; at this tiny config that
    # is the embedding table. Its q carries the float vocab-sharded
    # spec; its per-channel scale (hidden axis, unsharded here) is
    # replicated.
    wte = eng.params["wte"]
    assert set(wte) == {"q", "scale"}
    assert "model" in tuple(wte["q"].sharding.spec), wte["q"].sharding
    assert all(s is None for s in tuple(wte["scale"].sharding.spec))
    local = InferenceEngine.from_checkpoint(gpt_checkpoint, quantize="int8")
    a = eng.generate_text("hello world", max_new_tokens=8)
    b = local.generate_text("hello world", max_new_tokens=8)
    assert a["token_ids"] == b["token_ids"]


def test_place_params_shards_channel_scale(mesh_1x4):
    """A column-sharded quantized kernel: q takes the float spec and
    the per-channel scale shards the SAME channel axis, so the
    dequantized product keeps the float TP layout."""
    from jax.sharding import PartitionSpec as P

    from mlapi_tpu.ops.quant import quantize_tree
    from mlapi_tpu.parallel.mesh import place_params

    tree = {"kernel": np.ones((64, 128), np.float32)}
    qt = quantize_tree(tree, min_size=1)
    placed = place_params(qt, mesh_1x4, {"kernel": P(None, "model")})
    k = placed["kernel"]
    assert tuple(k["q"].sharding.spec) == (None, "model")
    assert tuple(k["scale"].sharding.spec) == (None, "model")
    # Row-sharded: the channel axis is unsharded -> scale replicated.
    placed = place_params(qt, mesh_1x4, {"kernel": P("model", None)})
    k = placed["kernel"]
    assert tuple(k["q"].sharding.spec) == ("model", None)
    assert all(s is None for s in tuple(k["scale"].sharding.spec))


def test_quantized_mesh_refused_without_layout(mesh_1x4, tmp_path):
    """A model with no declared TP layout still refuses loudly."""
    from mlapi_tpu.models.quantized import QuantizedModel

    class NoLayout:
        pass

    with pytest.raises(NotImplementedError, match="param"):
        QuantizedModel(NoLayout()).param_shardings()


def test_bad_quantize_value_rejected(gpt_checkpoint):
    with pytest.raises(ValueError, match="unsupported quantize"):
        InferenceEngine.from_checkpoint(gpt_checkpoint, quantize="int4")
