"""Flash-extend: the U-token-query split-K kernels
(`ops/pallas/decode_attention.extend_attention` /
`paged_extend_attention`) and their routing through ``extend_core``.

The contracts these tests pin (ISSUE 6 acceptance):

- **Parity, cell by cell**: interpret-mode extend kernel output
  matches the einsum oracle across {MHA, GQA} x {f32, bf16} x
  {kv_quant none, int8} x {plain, ragged-pad, prefix-shift, paged}
  x U in {2, 7, block-multiple}, to <= 1e-5 (f32) / <= 2e-2 (bf16)
  max-abs — the causal intra-span mask rows included.
- **Streams, end to end**: greedy token streams are IDENTICAL
  einsum-vs-flash through every multi-token span the server runs —
  chunked long-prompt prefill (contiguous AND paged),
  admission-during-an-interleaved-window, and batched-speculation
  verify — for gpt-MHA and llama-GQA.
- **Bytes, exactly**: ``engine.extend_bytes_per_chunk()`` equals the
  closed-form dtype arithmetic for every (impl, format) pair — the
  int8 flash chunk read clears 2D/(D+4) (1.94x at bf16 D=128) below
  the full-precision read, from arithmetic, never timing — and
  exports on ``/metrics``.
- **The old guard is gone, loudly**: a multi-token q through
  ``decode_attention`` dispatches to the extend kernel when the mask
  carries the per-query-row structure, and raises (not silently
  mis-attends) when it cannot.
"""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.models.gpt import extend_positions_and_mask
from mlapi_tpu.ops.attention import NEG
from mlapi_tpu.ops.pallas import (
    decode_attention,
    extend_attention,
    paged_extend_attention,
)
from mlapi_tpu.ops.quant import kv_dequantize, kv_quantize
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

B, L, H, D = 2, 32, 4, 8
BLOCK_K = 8  # so U = 8 is the block-multiple cell
PAGE = 8


def _einsum_oracle(q, k, v, mask):
    """The extend einsum read (``gpt.cached_attend``'s math over a
    ``[B, U, L]`` mask), GQA broadcast included."""
    group = q.shape[2] // k.shape[2]
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    s = (
        jnp.einsum(
            "buhd,bkhd->bhuk", q, k, preferred_element_type=jnp.float32
        )
        / q.shape[-1] ** 0.5
    )
    s = jnp.where(mask[:, None, :, :], s, NEG)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum(
        "bhuk,bkhd->buhd", p, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def _rows(dtype, kvh, u):
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(B, u, H, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, L, kvh, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, L, kvh, D)), dtype)
    return q, k, v


def _mask(case, u):
    """One [B, U, L] extend mask per semantics cell, built with the
    REAL helper (`extend_positions_and_mask`) so the causal
    intra-span structure and the pad/prefix algebra are the
    production ones. All cells vary per row."""
    if case == "plain":
        _, m = extend_positions_and_mask(
            L, u, jnp.asarray([4, 10]), jnp.zeros((B,), jnp.int32)
        )
    elif case == "ragged_pad":
        # Rows at desynchronized offsets with different pad holes —
        # the batched-spec verify layout; row 0's first span
        # positions land inside its own pad hole (all-dead mask
        # rows, the einsum path's uniform-garbage cell).
        _, m = extend_positions_and_mask(
            L, u, jnp.asarray([2, 13]), jnp.asarray([5, 1], jnp.int32)
        )
    else:
        assert case in ("prefix_shift", "paged")
        # Shared prefix region [lo_b, 12) ahead of per-row pads.
        _, m = extend_positions_and_mask(
            L, u, jnp.asarray([14, 17]), jnp.asarray([2, 0], jnp.int32),
            prefix_len=jnp.int32(12), prefix_lo=jnp.asarray([0, 3]),
        )
    return m[:, 0]  # [B, U, L]


def _paged_layout(x):
    """Scatter a contiguous [B, L, kvh, D] array into a PERMUTED page
    pool + table (page 0 reserved null): the kernel must follow the
    table, not the contiguous order."""
    kvh, d = x.shape[2], x.shape[3]
    npv = L // PAGE
    perm = np.random.default_rng(3).permutation(B * npv)
    pool = np.zeros((B * npv + 1, PAGE, kvh, d), np.asarray(x).dtype)
    table = np.zeros((B, npv), np.int32)
    blocks = np.asarray(x).reshape(B, npv, PAGE, kvh, d)
    for b in range(B):
        for i in range(npv):
            pid = int(perm[b * npv + i]) + 1
            pool[pid] = blocks[b, i]
            table[b, i] = pid
    return jnp.asarray(pool), jnp.asarray(table)


@pytest.mark.parametrize("kvh", [H, H // 2], ids=["mha", "gqa"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("fmt", ["none", "int8"])
@pytest.mark.parametrize(
    "case", ["plain", "ragged_pad", "prefix_shift", "paged"]
)
def test_extend_kernel_matches_einsum_oracle(kvh, dtype, fmt, case):
    """The full parity grid; U values (2, 7, block-multiple) share
    one cell to bound the suite's compile count."""
    for u in (2, 7, BLOCK_K):
        q, k, v = _rows(dtype, kvh, u)
        mask = _mask(case, u)
        if fmt == "int8":
            kq, ks = kv_quantize(k)
            vq, vs = kv_quantize(v)
            # Oracle reads the SAME int8 values through
            # kv_dequantize — kernel math isolated from quant error.
            kk = {"q": kq, "scale": ks}
            vv = {"q": vq, "scale": vs}
            ref = _einsum_oracle(
                q, kv_dequantize(kq, ks, dtype),
                kv_dequantize(vq, vs, dtype), mask,
            )
        else:
            kk, vv = k, v
            ref = _einsum_oracle(q, k, v, mask)
        if case == "paged":
            if fmt == "int8":
                pk, table = _paged_layout(kk["q"])
                psk, _ = _paged_layout(
                    jnp.broadcast_to(kk["scale"], k.shape[:3] + (1,))
                )
                pv, _ = _paged_layout(vv["q"])
                psv, _ = _paged_layout(
                    jnp.broadcast_to(vv["scale"], v.shape[:3] + (1,))
                )
                got = paged_extend_attention(
                    q, {"q": pk, "scale": psk}, {"q": pv, "scale": psv},
                    table, mask.astype(jnp.float32), interpret=True,
                )
            else:
                pk, table = _paged_layout(k)
                pv, _ = _paged_layout(v)
                got = paged_extend_attention(
                    q, pk, pv, table, mask.astype(jnp.float32),
                    interpret=True,
                )
        else:
            got = extend_attention(
                q, kk, vv, mask.astype(jnp.float32), interpret=True,
                block_k=BLOCK_K,
            )
        tol = 1e-5 if dtype == jnp.float32 else 2e-2
        # All-dead span rows come out exactly 0 from the kernel and
        # as uniform-average garbage from the softmax oracle — both
        # are never read; compare only live rows.
        live = np.asarray(jnp.any(mask, axis=-1))  # [B, U]
        diff = np.abs(
            np.asarray(got, np.float32) - np.asarray(ref, np.float32)
        )[live].max()
        assert diff <= tol, (case, fmt, u, diff)


def test_multi_token_dispatch_and_loud_reject():
    """`decode_attention` with a U-token q dispatches to the extend
    kernel when the mask carries per-query-row structure — the old
    'block extends take the einsum path' guard is GONE — and raises
    loudly when it cannot (a [B, L] decode mask has no intra-span
    causality to tile)."""
    u = 4
    q, k, v = _rows(jnp.float32, H, u)
    mask = _mask("plain", u)
    ref = _einsum_oracle(q, k, v, mask)
    got = decode_attention(
        q, k, v, mask.astype(jnp.float32), interpret=True,
        block_k=BLOCK_K,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=1e-5
    )
    with pytest.raises(ValueError, match="per-query-row"):
        decode_attention(
            q, k, v, jnp.ones((B, L), jnp.float32), interpret=True
        )
    with pytest.raises(ValueError, match="must be"):
        extend_attention(
            q, k, v, jnp.ones((B, u, L - 1), jnp.float32),
            interpret=True,
        )


def test_extend_kernel_awkward_length_single_block_fallback():
    """Cache lengths that defeat power-of-two blocking fall back to
    one whole-L block and stay exact — the only 'cannot tile' case,
    handled inside `_fit_block`, never a silent einsum."""
    u = 3
    q, k, v = _rows(jnp.float32, H, u)
    lk = 29  # prime: no block divides it
    _, m = extend_positions_and_mask(
        lk, u, jnp.asarray([4, 10]), jnp.zeros((B,), jnp.int32)
    )
    mask = m[:, 0]
    ref = _einsum_oracle(q, k[:, :lk], v[:, :lk], mask)
    got = extend_attention(
        q, k[:, :lk], v[:, :lk], mask.astype(jnp.float32),
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=1e-5
    )


# --- end-to-end streams ------------------------------------------------

GPT_CFG = dict(
    vocab_size=260, hidden_size=32, num_layers=2, num_heads=4,
    max_positions=320, compute_dtype="float32",
)
LLAMA_CFG = dict(
    vocab_size=260, hidden_size=32, num_layers=2, num_heads=4,
    num_kv_heads=2, max_positions=320, compute_dtype="float32",
)


def _engine(model, params, **kw):
    kw.setdefault("chunk", 2)
    kw.setdefault("fused_single", False)
    return TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), **kw
    )


async def _collect(req) -> list[int]:
    out: list[int] = []
    while True:
        item = await req.queue.get()
        if item is None:
            return out
        if isinstance(item, Exception):
            raise item
        out.extend(item["token_ids"])


@pytest.fixture(scope="module")
def gpt_params():
    return get_model("gpt_lm", **GPT_CFG).init(jax.random.key(0))


@pytest.fixture(scope="module")
def llama_params():
    return get_model("llama_lm", **LLAMA_CFG).init(jax.random.key(0))


@pytest.mark.parametrize("kind,fmt", [
    ("gpt_lm", "int8"), ("llama_lm", "none"),
], ids=["gpt-int8", "llama-gqa"])
def test_chunked_prefill_and_prefix_stream_matches_einsum(
    kind, fmt, gpt_params, llama_params
):
    """A 100-token prompt (two 64-wide extend chunks) and a
    shared-prefix suffix prefill emit token-identical greedy streams
    einsum-vs-flash — the extend kernel rides `extend_core`'s mask
    semantics through the whole engine path. (Sized to the budget:
    cp = 64 via prompt_buckets keeps the compiled extend programs and
    interpret-mode tiles small.)"""
    cfg = GPT_CFG if kind == "gpt_lm" else LLAMA_CFG
    params = gpt_params if kind == "gpt_lm" else llama_params
    m = get_model(kind, **cfg, kv_quant=fmt)
    engs = {
        impl: _engine(
            dataclasses.replace(m, decode_attn_impl=impl), params,
            prompt_buckets=(16, 64), chunk=4,
        )
        for impl in ("einsum", "flash")
    }
    long_p = "x" * 100  # -> [128] bucket, two 64-token chunks
    a = engs["einsum"].generate_text(long_p, max_new_tokens=4)
    b = engs["flash"].generate_text(long_p, max_new_tokens=4)
    assert a["token_ids"] == b["token_ids"], (kind, fmt)
    assert engs["flash"].prefill_chunks >= 2  # it actually chunked
    prefix = "the quick brown fox "
    pa = engs["einsum"].generate_text(
        "tail", prefix=prefix, max_new_tokens=4
    )
    pb = engs["flash"].generate_text(
        "tail", prefix=prefix, max_new_tokens=4
    )
    assert pa["token_ids"] == pb["token_ids"], (kind, fmt)


def test_paged_chunked_prefill_stream_matches_einsum(gpt_params):
    """The page-native chunked prefill (`paged_extend_fn` →
    `extend_core`) under flash reads pool pages in place via the
    U-token page-table kernel — streams pinned to the paged einsum
    engine, every page returned."""
    m = get_model("gpt_lm", **GPT_CFG, kv_quant="int8")
    engs = {
        impl: _engine(
            dataclasses.replace(m, decode_attn_impl=impl), gpt_params,
            kv_page_size=8, prompt_buckets=(16, 64), chunk=4,
        )
        for impl in ("einsum", "flash")
    }
    long_p = "y" * 100
    a = engs["einsum"].generate_text(long_p, max_new_tokens=4)
    b = engs["flash"].generate_text(long_p, max_new_tokens=4)
    assert a["token_ids"] == b["token_ids"]
    assert engs["flash"].prefill_chunks >= 2
    assert engs["flash"].prefill_adopt_bytes == 0  # still page-native
    assert engs["flash"].kv_pages_in_use == 0


@pytest.mark.anyio
async def test_admission_during_window_stream_matches_einsum(
    gpt_params,
):
    """The interleaved-prefill window (long-prompt joiner's chunks =
    admission mini-prefills through `paged_extend_fn`) with a short
    one-shot admission DURING it: every stream identical
    einsum-vs-flash, the stall bound intact under the kernel. Sized
    to the budget: the running stream starts at bucket 64, so the
    activation catch-up (and with it the interpret-mode decode-step
    count) is half the joiner's prompt, not all of it."""
    m = get_model("gpt_lm", **GPT_CFG)
    outs = {}
    for impl in ("einsum", "flash"):
        eng = _engine(
            dataclasses.replace(m, decode_attn_impl=impl), gpt_params,
            kv_page_size=8, max_wait_ms=0.0,
            prompt_buckets=(16, 64), chunk=8,
        )
        await eng.start()
        try:
            r1 = await eng.submit(
                "h" * 60, max_new_tokens=80, stream=True
            )
            head = await r1.queue.get()
            assert not isinstance(head, Exception)
            r2 = await eng.submit("x" * 100, max_new_tokens=6)
            r3 = await eng.submit("yo", max_new_tokens=4)
            outs[impl] = await asyncio.gather(
                _collect(r1), _collect(r2), _collect(r3)
            )
            outs[impl][0] = head["token_ids"] + outs[impl][0]
            assert eng.interleaved_prefills == 1
            assert eng.interleave_max_stall == 1
            assert eng.admitted >= 2
        finally:
            await eng.stop()
    assert outs["flash"] == outs["einsum"]


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.mark.anyio
async def test_batched_spec_verify_stream_matches_einsum(gpt_params):
    """Batched speculation's verify spans (per-row desynchronized
    positions — `extend_core` with a [B] pos0 vector) run through the
    flash-extend kernel: streams identical to the einsum engine,
    rounds actually verified on both."""
    m = get_model("gpt_lm", **GPT_CFG)
    outs = {}
    rounds = {}
    for impl in ("einsum", "flash"):
        mi = dataclasses.replace(m, decode_attn_impl=impl)
        eng = _engine(
            mi, gpt_params, draft=(mi, gpt_params), spec_k=3,
            max_wait_ms=2000.0,
        )
        await eng.start()
        try:
            r1 = await eng.submit("aaaa", max_new_tokens=9)
            r2 = await eng.submit("bbbb", max_new_tokens=4)
            outs[impl] = await asyncio.gather(
                _collect(r1), _collect(r2)
            )
            rounds[impl] = eng.spec_rounds
        finally:
            await eng.stop()
    assert outs["flash"] == outs["einsum"]
    assert rounds["flash"] > 0 and rounds["einsum"] > 0


# --- the byte model ----------------------------------------------------


def test_extend_bytes_per_chunk_closed_form():
    """Every (impl, format) pair's modeled chunk read equals the
    dtype arithmetic — identical by construction to the per-step
    decode read (the operand/storage asymmetry doesn't depend on the
    query width), amortized per chunk — and the int8 flash chunk
    read clears 2D/(D+4) = 1.94x at bf16 D=128."""
    small = dict(
        vocab_size=260, hidden_size=256, num_layers=2, num_heads=2,
        max_positions=320, compute_dtype="bfloat16",
    )
    model = get_model("gpt_lm", **small)
    params = model.init(jax.random.key(0))
    tok = ByteTokenizer()

    def eng(impl, fmt):
        m = dataclasses.replace(
            model, kv_quant=fmt, decode_attn_impl=impl
        )
        return TextGenerationEngine(m, params, tokenizer=tok, chunk=8)

    layers, h, d = small["num_layers"], 2, 128
    total = 160  # bucket 128 + default tier 32
    bf16 = layers * 2 * total * h * d * 2
    int8 = layers * 2 * (total * h * d + total * h * 4)
    assert eng("flash", "none").extend_bytes_per_chunk() == bf16
    assert eng("flash", "int8").extend_bytes_per_chunk() == int8
    assert eng("einsum", "none").extend_bytes_per_chunk() == bf16
    assert eng("einsum", "int8").extend_bytes_per_chunk() == bf16 + int8
    assert bf16 / int8 == pytest.approx((2 * d) / (d + 4))
    assert bf16 / int8 >= 1.9
    # The documented identity: one extend chunk reads what one decode
    # step reads — paid once per U-token span instead of per token.
    e = eng("flash", "int8")
    assert e.extend_bytes_per_chunk() == e.decode_bytes_per_step()


@pytest.mark.anyio
async def test_metrics_exports_extend_bytes(gpt_params):
    import httpx

    from mlapi_tpu.serving import build_app

    m = get_model("gpt_lm", **GPT_CFG, kv_quant="int8")
    eng = _engine(
        dataclasses.replace(m, decode_attn_impl="flash"), gpt_params
    )
    app = build_app(eng)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as c:
            snap = (await c.get("/metrics")).json()
        assert (
            snap["gauges"]["generate.extend_bytes_per_chunk"]
            == eng.extend_bytes_per_chunk()
        )
    finally:
        await app.shutdown()
