"""Overload behavior: full queues shed immediately (503 +
``Retry-After`` over HTTP, :class:`OverloadedError` at the engine
seam) instead of queueing without bound, and abandoned streams stop
consuming device time.

The reference has no overload story at all — uvicorn's accept queue
is the only backpressure (SURVEY §2: single asyncio loop, blocking
handlers). Here shedding is explicit and observable via /metrics.
"""

import asyncio
import json

import httpx
import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving import InferenceEngine, build_app
from mlapi_tpu.serving.scoring import MicroBatcher, OverloadedError
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer
from mlapi_tpu.utils.vocab import LabelVocab

from tests.test_batcher import FakeEngine

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


IRIS_FEATURES = (
    "sepal_length", "sepal_width", "petal_length", "petal_width",
)


@pytest.fixture
def iris_engine():
    """Untrained linear engine — overload mechanics don't care about
    prediction quality, only about queue/shed behavior."""
    model = get_model("linear", num_features=4, num_classes=3)
    return InferenceEngine(
        model,
        model.init(jax.random.key(0)),
        LabelVocab(("Iris-setosa", "Iris-versicolor", "Iris-virginica")),
        IRIS_FEATURES,
    )


GPT_CFG = dict(
    vocab_size=260,
    hidden_size=16,
    num_layers=1,
    num_heads=2,
    max_positions=96,
    compute_dtype="float32",
)


@pytest.fixture
def gen_engine():
    model = get_model("gpt_lm", **GPT_CFG)
    return TextGenerationEngine(
        model,
        model.init(jax.random.key(0)),
        tokenizer=ByteTokenizer(),
    )


async def test_batcher_sheds_fast_when_queue_full():
    """With the device blocked and the queue at 2x capacity, the
    excess requests fail in milliseconds — not after a timeout."""
    eng = FakeEngine()
    eng.gate.clear()  # device "wedged": nothing completes
    b = MicroBatcher(
        eng, max_batch=4, max_wait_ms=0.0, max_queue=8, max_inflight=1
    )
    await b.start()
    row = np.zeros(4, np.float32)
    try:
        tasks = [asyncio.create_task(b.submit(row)) for _ in range(32)]
        await asyncio.sleep(0.05)  # let the collector drain what it can
        rejected = [
            t
            for t in tasks
            if t.done() and isinstance(t.exception(), OverloadedError)
        ]
        assert rejected, "no request was shed at 4x queue capacity"
        assert b.rejected == len(rejected)
        # Immediacy from task state, not wall-clock (mlapi-lint
        # MLA006, the ADVICE r05 flake class): the device is wedged,
        # so NOTHING can complete by being processed — every task
        # that finished inside the 50 ms window must be a shed, and
        # the device must not have returned a single batch. A
        # timeout-style shed path would leave all 32 tasks pending
        # here (rejected would be empty) instead of failing a clock
        # bound.
        assert all(
            isinstance(t.exception(), OverloadedError)
            for t in tasks if t.done()
        ), "a task completed by processing while the device was wedged"
        assert eng.batch_sizes == [], "the wedged device returned a batch"
        assert b.queue_depth <= 8
    finally:
        eng.gate.set()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        await b.stop()


async def test_predict_returns_503_with_retry_after(iris_engine):
    """HTTP contract: queue-full surfaces as 503 + Retry-After, and
    the rejection is visible in /metrics."""
    app = build_app(iris_engine, max_wait_ms=50.0, max_batch=1, max_queue=1)
    await app.startup()
    try:
        # Stall the collector so submissions pile onto the queue: the
        # batch window (50 ms) holds the first request in the
        # collector while the rest hit the 1-deep queue.
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as client:
            payload = {
                "sepal_length": 5.1,
                "sepal_width": 3.5,
                "petal_length": 1.4,
                "petal_width": 0.2,
            }
            rs = await asyncio.gather(
                *(client.post("/predict", json=payload) for _ in range(12))
            )
            codes = sorted(r.status_code for r in rs)
            assert 503 in codes, codes
            assert 200 in codes, codes  # admitted requests still served
            shed = next(r for r in rs if r.status_code == 503)
            assert "retry-after" in shed.headers
            assert int(shed.headers["retry-after"]) >= 1
            m = (await client.get("/metrics")).json()
            assert m["counters"]["batcher.rejected"] >= 1
            assert "batcher.queue_depth" in m["gauges"]
    finally:
        await app.shutdown()


async def test_generate_queue_bounded_503(gen_engine):
    """The generation queue is bounded too: floods of /generate get
    immediate 503s, not unbounded memory growth (VERDICT r2 #5: the
    old queue was unbounded)."""
    engine = gen_engine
    engine.max_queue = 2
    engine.max_wait_s = 0.2  # hold the collector so the queue fills
    app = build_app(engine)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://test"
        ) as client:
            rs = await asyncio.gather(
                *(
                    client.post(
                        "/generate",
                        json={"text": "ab", "max_new_tokens": 4},
                    )
                    for _ in range(10)
                )
            )
            codes = sorted(r.status_code for r in rs)
            assert 503 in codes, codes
            assert 200 in codes, codes
            m = (await client.get("/metrics")).json()
            assert m["counters"]["generate.rejected"] >= 1
            assert "generate.queue_depth" in m["gauges"]
    finally:
        await app.shutdown()


async def test_cancelled_request_stops_decode(gen_engine):
    """A cancelled request stops the decode loop before it burns
    device time on the remaining tokens (VERDICT r2 weak #4). The
    request is cancelled before the collector picks it up, so the
    batch must exit after prefill with ZERO chunk decodes —
    deterministic, no race against a fast model."""
    engine = gen_engine
    await engine.start()
    try:
        gen = await engine.submit("ab", max_new_tokens=64)
        gen.cancel()
        for _ in range(200):
            if engine.cancelled_batches:
                break
            await asyncio.sleep(0.02)
        assert engine.cancelled_batches == 1
        assert engine.chunk_calls == 0, (
            "decode ran chunks for a batch whose only consumer was gone"
        )
    finally:
        await engine.stop()


async def test_stream_disconnect_marks_request_cancelled(gen_engine):
    """Client walks away mid-NDJSON-stream → the app layer must
    cancel the underlying GenRequest (via the body iterator's
    finally, run by the server's aclose on disconnect)."""
    engine = gen_engine
    app = build_app(engine)
    await app.startup()
    captured = []
    orig_submit = engine.submit

    async def spying_submit(*a, **kw):
        gen = await orig_submit(*a, **kw)
        captured.append(gen)
        return gen

    engine.submit = spying_submit
    try:
        scope = {
            "type": "http",
            "method": "POST",
            "path": "/generate",
            "headers": [(b"content-type", b"application/json")],
            "query_string": b"",
            "extensions": {
                "mlapi_tpu.body": json.dumps(
                    {"text": "ab", "max_new_tokens": 64, "stream": True}
                ).encode()
            },
        }
        sent = []

        async def receive():
            return {"type": "http.disconnect"}

        async def send(message):
            sent.append(message)
            # Simulate the client vanishing after the first body chunk
            # lands — exactly what Server._dispatch's send raises.
            if message["type"] == "http.response.body" and message.get(
                "body"
            ):
                raise ConnectionResetError("client disconnected mid-stream")

        await app(scope, receive, send)
        assert captured, "handler never submitted a generation request"
        assert captured[0].cancelled, (
            "disconnect did not cancel the in-flight generation"
        )
    finally:
        engine.submit = orig_submit
        await app.shutdown()


async def test_collector_death_errors_queued_requests(gen_engine):
    """ADVICE r2: if the collector dies unexpectedly, requests still
    sitting in the queue must get the error sentinel, not hang."""
    engine = gen_engine
    engine.max_wait_s = 30.0  # collector holds its first batch open
    await engine.start()
    try:
        g1 = await engine.submit("ab", max_new_tokens=4)  # popped by collector
        await asyncio.sleep(0.01)
        g2 = await engine.submit("ba", max_new_tokens=4)  # still queued
        engine._task.cancel()
        item1 = await asyncio.wait_for(g1.queue.get(), 5)
        item2 = await asyncio.wait_for(g2.queue.get(), 5)
        assert isinstance(item1, Exception)
        assert isinstance(item2, Exception)
    finally:
        engine._task = None
        await engine.stop()
