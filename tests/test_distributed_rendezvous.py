"""The first REAL multi-host rendezvous test: two CPU processes join
``jax.distributed`` through the env-driven bootstrap
(``parallel/distributed.py::initialize_from_env`` — the code path
every entry point calls but CI never executed until now), agree on
``process_count() == 2``, and run one tiny cross-process collective.

Everything before this exercised multi-DEVICE behaviour on one
process (the 8 virtual CPU devices); this is the multi-PROCESS story:
a coordinator address, two ranks, a real barrier at
``jax.distributed.initialize``, and a gloo-backed ``process_allgather``
whose result proves bytes actually crossed the process boundary.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = textwrap.dedent("""
    import json, os, sys
    # gloo is the CPU cross-process collectives backend; set before
    # any jax device/backend touch.
    import jax
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        gloo = True
    except Exception:
        gloo = False

    from mlapi_tpu.parallel import initialize_from_env

    ok = initialize_from_env()
    import numpy as np

    out = {
        "rank": int(os.environ["MLAPI_TPU_PROCESS_ID"]),
        "initialized": bool(ok),
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        "local_device_count": jax.local_device_count(),
        "gloo": gloo,
    }
    if gloo:
        # One tiny collective: every process contributes rank + 1 and
        # must see BOTH contributions — data crossed processes.
        from jax.experimental import multihost_utils

        g = multihost_utils.process_allgather(
            np.asarray([out["rank"] + 1], np.int32)
        )
        out["allgather"] = np.asarray(g).ravel().tolist()
    print("RESULT " + json.dumps(out), flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_rendezvous_and_collective(tmp_path):
    port = _free_port()
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    procs = []
    for rank in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
            MLAPI_TPU_COORDINATOR=f"127.0.0.1:{port}",
            MLAPI_TPU_NUM_PROCESSES="2",
            MLAPI_TPU_PROCESS_ID=str(rank),
        )
        # One real CPU device per process: the point is processES, and
        # the virtual-device flag would only blur the device counts.
        env.pop("XLA_FLAGS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env, cwd=ROOT,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, f"rank failed:\n{err[-2000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert line, out
        r = json.loads(line[-1][len("RESULT "):])
        results[r["rank"]] = r

    assert set(results) == {0, 1}
    for r in results.values():
        assert r["initialized"] is True
        # The rendezvous really formed: both ranks see both processes
        # and the union of their devices.
        assert r["process_count"] == 2, r
        assert r["device_count"] == 2 * r["local_device_count"], r
    # The collective: each rank gathered BOTH contributions (1 and 2),
    # in rank order — bytes crossed the process boundary, not just the
    # coordination handshake. (gloo ships with this jax build; if a
    # future build drops it, the rendezvous asserts above still hold
    # and this block self-skips.)
    for r in results.values():
        if r["gloo"]:
            assert r["allgather"] == [1, 2], r
    assert any(r["gloo"] for r in results.values()), (
        "no CPU collectives backend available — collective never ran"
    )
