"""Continuous-batching scheduler v2 (``serving/scheduler.py``):
one typed-unit queue across concurrent BatchRuns — DEFAULT-ON since
r20. ``--no-scheduler`` was retired in r22; ``sched_max_batches=1``
is the serial mode, pinning the same machinery to ONE lane (the
``scheduler=`` parameter of the ``_engine`` helper below maps to
exactly that).

The contract these tests pin, layer by layer — all interleaving and
priority claims are asserted from DISPATCH COUNTERS and the bounded
unit trace, never wall-clock:

- **Concurrency**: two bucket-incompatible request groups submitted
  together run as two live lanes with their units interleaved
  (``sched_batches_live_max == 2``; the trace alternates lane ids).
- **Identity**: greedy streams are byte-identical concurrent
  (default) vs serial (``sched_max_batches=1``) across {gpt-MHA,
  llama-GQA} x {none, int8} x {einsum, flash} x {paged, contiguous} —
  the structural consequence of both modes draining the same
  ``BatchRun.units()`` generator.
- **Fused fold (r20)**: a fused-eligible batch's tier-wide decode
  chunks are ordinary units, so a concurrent lane's head-of-line
  stall behind fused traffic is at most ONE fused-chunk dispatch
  (``sched_lane_stall_max``, a counter).
- **SLO policy**: pending groups start in deadline-slack order (the
  r12 ``_carry[0]`` FIFO head-of-line fix), expired requests get
  their terminal frames at unit boundaries (``deadline_expired_*``
  keeps ticking — no unit dispatches after a passed deadline).
- **Faults**: the ``sched_unit`` seam (raise kills ONE lane with its
  pages conserved while the other lane streams on; delay slows but
  never breaks).
- **Arbitration**: a pending group whose worst-case page footprint
  does not fit beside live lanes waits (``sched_pages_deferred``) and
  runs after a release — never a mid-decode ``PagePoolExhausted``.
- **Drain**: the typed-unit queue (pending groups + live lanes) is
  covered by ``drain()`` exactly as ``_carry`` is — terminal frames
  for everything, pool back to baseline.

Same tiny-model CFG and engine shapes as ``test_paged_kv`` ON
PURPOSE: the module shares that family's jax-cache window
(conftest ``paged-family``), so the compile ladder is paid once.
"""

import asyncio

import jax
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving import faults
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.serving.requests import DeadlineExceeded, DrainCancelled
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


CFG = dict(
    vocab_size=260,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=160,
    compute_dtype="float32",
)


def _model(kind="gpt_lm", kv_quant="none", impl="einsum"):
    kw = dict(CFG, kv_quant=kv_quant, decode_attn_impl=impl)
    if kind == "llama_lm":
        kw["num_kv_heads"] = 2  # GQA: 4 query heads over 2 KV heads
    return get_model(kind, **kw)


@pytest.fixture(scope="module")
def gpt_params():
    return _model().init(jax.random.key(0))


@pytest.fixture(scope="module")
def llama_params():
    return _model("llama_lm").init(jax.random.key(0))


def _engine(model, params, paged=True, scheduler=True, **kw):
    kw.setdefault("chunk", 2)
    # Pin the chunked batch lifecycle (same as test_paged_kv): fused
    # fast paths never touch the pool and would collapse a lane to
    # one opaque unit.
    kw.setdefault("fused_single", False)
    # Window 0: formation is driven by queue order alone, so which
    # requests group together is deterministic.
    kw.setdefault("max_wait_ms", 0.0)
    if paged:
        kw.setdefault("kv_page_size", 8)
    # scheduler=False maps to the r22 serial mode: ONE lane on the
    # same machinery (--no-scheduler retired; sched_max_batches=1 IS
    # serial). Forced, not defaulted — the old kwarg clamped to one
    # lane no matter what the lane budget said, and the identity
    # matrix passes both together.
    if not scheduler:
        kw["sched_max_batches"] = 1
    return TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), **kw,
    )


async def _collect(req):
    """(tokens, terminal_error_or_None) — never hangs on a live
    engine; errors are in-band."""
    out: list[int] = []
    while True:
        item = await req.queue.get()
        if item is None:
            return out, None
        if isinstance(item, Exception):
            return out, item
        out.extend(item["token_ids"])


async def _wait_for(pred, timeout_s: float = 60.0,
                    interval_s: float = 0.005) -> None:
    """CONDITION-based wait (the MLA006 discipline): poll a counter/
    state predicate under a generous deadline instead of a tuned
    iteration budget. The old ``for _ in range(200): ...sleep(0.01)``
    shape was a hidden 2 s wall-clock assertion — on this drifting
    box (documented ±25-30% and worse) it flaked whenever the
    condition was merely LATE, not wrong. Raises loudly on timeout so
    a genuinely-stuck condition still fails."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s
    while not pred():
        if loop.time() >= deadline:
            raise AssertionError(
                f"condition never became true within {timeout_s}s"
            )
        await asyncio.sleep(interval_s)


# Two groups the collector can NEVER window together: max(bucket) +
# max(n_new) = 128 + 34 > 160 = max_positions, while each alone fits.
_SHORT = ("hello world", 34)      # 16-bucket, long budget (> 32
                                  # forces window incompatibility)
_LONG = ("x" * 100, 8)            # 128-bucket, short budget


async def _submit_pair(eng):
    ra = await eng.submit(_SHORT[0], max_new_tokens=_SHORT[1], stream=True)
    rb = await eng.submit(_LONG[0], max_new_tokens=_LONG[1], stream=True)
    return ra, rb


# --- concurrency + interleaving (counter-pinned) -----------------------


async def test_two_incompatible_groups_interleave(gpt_params):
    """The flagship concurrency pin PLUS the scheduler-off identity
    for the bucket-incompatible pair (one config pays the extra cache
    tier's compiles; the cross-config identity matrix below reuses
    the family's warm shapes instead)."""
    outs = []
    for scheduler in (True, False):
        eng = _engine(
            _model(), gpt_params, scheduler=scheduler,
            sched_max_batches=2,
        )
        await eng.start()
        try:
            ra, rb = await _submit_pair(eng)
            (ta, ea), (tb, eb) = await asyncio.gather(
                _collect(ra), _collect(rb)
            )
            assert ea is None and eb is None
            assert len(ta) == _SHORT[1] and len(tb) == _LONG[1]
            outs.append((ta, tb))
            if scheduler:
                # Both batches were LIVE at once, asserted from the
                # high-water counter, and their units interleaved:
                # the trace must switch lanes mid-stream (an A,B,A
                # pattern), not run serially.
                assert eng.sched_batches_live_max == 2
                lanes = [lane for lane, kind in eng.sched.trace]
                switches = sum(
                    1 for i in range(1, len(lanes))
                    if lanes[i] != lanes[i - 1]
                )
                assert switches >= 2, lanes
                # Unit counters moved for both types of work.
                assert eng.sched_units_decode >= (
                    _SHORT[1] // eng.chunk + _LONG[1] // eng.chunk
                ) - 2
                assert eng.sched_units_prefill >= 2  # one formation each
            # The lane's page release runs on the dispatch thread
            # AFTER the terminal frame is pushed — wait for the
            # condition instead of racing it (the flake this module
            # carried since r15).
            await _wait_for(lambda: eng.kv_pages_in_use == 0)
        finally:
            await eng.stop()
    # Greedy streams byte-identical, scheduler-on vs off.
    assert outs[0] == outs[1]


async def test_scheduler_queue_feeds_queue_depth(gpt_params):
    """Pending groups the collector handed to the scheduler stay
    visible to backpressure/healthz via engine.queue_depth (the
    typed-unit queue, not just the submit queue)."""
    eng = _engine(_model(), gpt_params, sched_max_batches=1)
    await eng.start()
    try:
        blocker = await eng.submit(
            _SHORT[0], max_new_tokens=30, stream=True
        )
        # Wait until the blocker is laned, then park a second group.
        await _wait_for(lambda: eng.sched_batches_live == 1)
        pend = await eng.submit(_LONG[0], max_new_tokens=8, stream=True)
        await _wait_for(lambda: eng.queue_depth >= 1)
        assert (await _collect(blocker))[1] is None
        assert (await _collect(pend))[1] is None
    finally:
        await eng.stop()


# --- identity: scheduler-on == scheduler-off ---------------------------


@pytest.mark.parametrize("paged", [True, False], ids=["paged", "contig"])
@pytest.mark.parametrize("impl", ["einsum", "flash"])
@pytest.mark.parametrize("fmt", ["none", "int8"])
@pytest.mark.parametrize("kind", ["gpt_lm", "llama_lm"])
async def test_streams_identical_scheduler_on_off(
    kind, fmt, impl, paged, gpt_params, llama_params
):
    """Scheduler-on vs off byte-identity across the full config
    matrix. The two requests are window-COMPATIBLE but submitted one
    at a time through a zero-width window — default mode may take the
    second via in-lane admission OR as its own lane depending on
    arrival timing, and the streams must be byte-identical either way
    — while every program shape (16-bucket prompts, default tier) is
    one the family window already compiled (test_paged_kv's identity
    matrix), keeping the 16 configs cheap. The bucket-incompatible
    pair's identity is pinned on the flagship config above."""
    params = gpt_params if kind == "gpt_lm" else llama_params
    model = _model(kind, kv_quant=fmt, impl=impl)
    outs = []
    for scheduler in (True, False):
        eng = _engine(
            model, params, paged=paged, scheduler=scheduler,
            sched_max_batches=2,
        )
        await eng.start()
        try:
            ra = await eng.submit("hello", max_new_tokens=12, stream=True)
            rb = await eng.submit(
                "world bb", max_new_tokens=6, stream=True, seed=3
            )
            (ta, ea), (tb, eb) = await asyncio.gather(
                _collect(ra), _collect(rb)
            )
            assert ea is None and eb is None
            assert len(ta) == 12 and len(tb) == 6
            outs.append((ta, tb))
            if not scheduler:
                # The serial escape hatch is the SAME machinery
                # pinned to one lane — not a separate code path.
                assert eng.sched is not None
                assert eng.sched_max_batches == 1
                assert eng.sched_batches_live_max <= 1
                assert eng.sched_units_decode >= 1
        finally:
            await eng.stop()
    assert outs[0] == outs[1]


# --- SLO policy: deadline slack ----------------------------------------


async def test_pending_groups_start_in_deadline_slack_order(gpt_params):
    """The r12 _carry[0] head-of-line fix: with one lane occupied, a
    later-arriving DEADLINED group outranks an earlier deadline-less
    one when the scheduler picks the next formation."""
    eng = _engine(_model(), gpt_params, sched_max_batches=1)
    await eng.start()
    try:
        order: list[str] = []

        async def tagged(req, tag):
            toks, err = await _collect(req)
            order.append(tag)
            return toks, err

        # Slow every decode chunk so the blocker provably outlives
        # both submissions — the ordering claim must not race the
        # blocker's completion (the counters stay the assert; the
        # delay only holds the lane slot open). 0.05 x 20 chunks = a
        # 1 s floor: the r17-documented flake was this floor sitting
        # at 0.4 s while a drifting box took longer than that just to
        # run the two submits' encode hops.
        faults.arm("decode:every=1:delay=0.05")
        blocker = await eng.submit("hold", max_new_tokens=40, stream=True)
        await _wait_for(lambda: eng.sched_batches_live == 1)
        # A first (loose deadline), then B (tighter deadline): pure
        # slack comparison, reservoir-independent — FIFO would run A
        # first, slack priority runs B. (A deadline-LESS group is
        # deliberately not pinned against a generous deadline: once it
        # has queued past ~2x the observed TTFT p95 the policy
        # promotes it — by design it may beat a 60s-slack deadline.)
        # Both incompatible with the blocker's window (128-bucket
        # prompts: 128 + 40 > 160) — a window-COMPATIBLE group would
        # instead be STAGED into the blocker's lane by r20's in-lane
        # admission and never reach the pending queue this test
        # orders. A is confirmed pending before B is submitted, so
        # the collector can never window-merge the two into one
        # group.
        ra = await eng.submit(
            "a" * 100, max_new_tokens=24, stream=True,
            deadline_ms=120000.0,
        )
        await _wait_for(lambda: eng.sched.backlog >= 1)
        rb = await eng.submit(
            _LONG[0], max_new_tokens=8, stream=True, deadline_ms=60000.0
        )
        # Both groups pending BEFORE the blocker's lane can free.
        await _wait_for(lambda: eng.sched.backlog >= 2)
        results = await asyncio.gather(
            _collect(blocker), tagged(ra, "A"), tagged(rb, "B")
        )
        assert results[0][1] is None
        assert order == ["B", "A"]
    finally:
        faults.disarm()
        await eng.stop()


async def test_deadline_expiry_at_unit_boundaries(gpt_params):
    """No unit dispatches after a passed deadline: with every decode
    chunk slowed, a tight-deadline stream ends with DeadlineExceeded
    at a decode boundary and the r12 counters keep ticking under the
    scheduler."""
    eng = _engine(_model(), gpt_params, sched_max_batches=2)
    await eng.start()
    try:
        faults.arm("decode:every=1:delay=0.03")
        req = await eng.submit(
            "slow one", max_new_tokens=60, stream=True, deadline_ms=150.0
        )
        toks, err = await _collect(req)
        assert isinstance(err, DeadlineExceeded)
        assert len(toks) < 60
        assert (
            eng.deadline_expired_decode
            + eng.deadline_expired_prefill
            + eng.deadline_expired_queued
        ) >= 1
        faults.disarm()
        # The lane died cleanly: pages conserved, engine serves on.
        await _wait_for(lambda: eng.sched.idle)
        assert eng.kv_pages_in_use == 0
        fresh = await eng.submit("after", max_new_tokens=4)
        toks, err = await _collect(fresh)
        assert err is None and len(toks) == 4
    finally:
        faults.disarm()
        await eng.stop()


# --- the sched_unit fault seam -----------------------------------------


async def test_sched_unit_raise_kills_one_lane_only(gpt_params):
    """The unit-dispatch seam matrix, raise leg: one lane dies with
    the injected error as its waiters' terminal frame and its pages
    released; the OTHER lane streams on token-identical to an
    unfaulted run; the engine serves fresh work after."""
    eng = _engine(_model(), gpt_params, sched_max_batches=2)
    await eng.start()
    try:
        # Unfaulted reference for the short group's stream.
        ra, rb = await _submit_pair(eng)
        (ref_a, ea), (ref_b, eb) = await asyncio.gather(
            _collect(ra), _collect(rb)
        )
        assert ea is None and eb is None
        # Same dispatch-thread release race as the flagship test:
        # wait for the condition, don't race it.
        await _wait_for(lambda: eng.kv_pages_in_use == 0)
        # Fault a mid-run unit: both lanes formed (units 1-2), the
        # raise lands on one lane's decode/admit unit.
        faults.arm("sched_unit:after=6:raise")
        ra, rb = await _submit_pair(eng)
        (ta, ea), (tb, eb) = await asyncio.gather(
            _collect(ra), _collect(rb)
        )
        errs = [e for e in (ea, eb) if e is not None]
        assert len(errs) == 1, (ea, eb)
        assert isinstance(errs[0], faults.InjectedFault)
        # The surviving lane's stream is byte-identical to unfaulted.
        if ea is None:
            assert ta == ref_a
        else:
            assert tb == ref_b
        faults.disarm()
        await _wait_for(lambda: eng.sched.idle)
        assert eng.kv_pages_in_use == 0  # refcounts conserved
        fresh = await eng.submit("after", max_new_tokens=4)
        toks, err = await _collect(fresh)
        assert err is None and len(toks) == 4
    finally:
        faults.disarm()
        await eng.stop()


async def test_sched_unit_raise_before_first_unit_conserves_pages(
    gpt_params,
):
    """after=1: call 1 is the formation's own fire, call 2 fires in
    the dispatch loop BEFORE the lane's first generator advance. A
    never-started generator's close() runs no ``finally``, so the
    scheduler must release the formation's pages directly — this was
    a real leak (pool shrank by one formation per early fault)."""
    eng = _engine(_model(), gpt_params, sched_max_batches=2)
    await eng.start()
    try:
        faults.arm("sched_unit:after=1:raise")
        req = await eng.submit("hello", max_new_tokens=8, stream=True)
        toks, err = await _collect(req)
        assert isinstance(err, faults.InjectedFault)
        faults.disarm()
        await _wait_for(lambda: eng.sched.idle)
        assert eng.kv_pages_in_use == 0  # the formation's pages back
        fresh = await eng.submit("after", max_new_tokens=4)
        toks, err = await _collect(fresh)
        assert err is None and len(toks) == 4
    finally:
        faults.disarm()
        await eng.stop()


async def test_sched_unit_delay_slows_never_breaks(gpt_params):
    eng = _engine(_model(), gpt_params, sched_max_batches=2)
    await eng.start()
    try:
        faults.arm("sched_unit:every=3:delay=0.01")
        ra, rb = await _submit_pair(eng)
        (ta, ea), (tb, eb) = await asyncio.gather(
            _collect(ra), _collect(rb)
        )
        assert ea is None and eb is None
        assert len(ta) == _SHORT[1] and len(tb) == _LONG[1]
        assert eng.faults_injected > 0
        await _wait_for(lambda: eng.kv_pages_in_use == 0)
    finally:
        faults.disarm()
        await eng.stop()


# --- page-budget arbitration -------------------------------------------


async def test_page_budget_defers_second_lane(gpt_params):
    """A group whose worst-case footprint does not fit beside the
    live lane WAITS (counted) instead of racing the pool into a
    mid-decode PagePoolExhausted — and still completes after the
    first lane releases."""
    # 15 usable pages: lane A (16-bucket + 32-tier cache = 48 slots
    # -> 6 pages) fits; group B (16 + 64 = 80 slots -> 10 pages) does
    # not fit beside it (15 - 6 = 9 free), but fits alone. Under r20
    # B first tries in-lane admission into A (window-compatible), is
    # deferred there (64 new tokens exceed A's 48-slot cache), and
    # re-dispatches as its own group — which is what the page gate
    # then defers. The slowed decode keeps A's lane provably alive
    # through that staging round-trip (30 tokens x 0.02 s/chunk-pair
    # = a 0.3 s floor).
    eng = _engine(
        _model(), gpt_params, sched_max_batches=2,
        kv_page_size=8, kv_pages=16,
    )
    await eng.start()
    try:
        faults.arm("decode:every=1:delay=0.02")
        ra = await eng.submit("hold", max_new_tokens=30, stream=True)
        await _wait_for(lambda: eng.sched_batches_live == 1)
        rb = await eng.submit("bbbb", max_new_tokens=64, stream=True)
        (ta, ea), (tb, eb) = await asyncio.gather(
            _collect(ra), _collect(rb)
        )
        assert ea is None and eb is None
        assert len(ta) == 30 and len(tb) == 64
        assert eng.sched_pages_deferred >= 1
        await _wait_for(lambda: eng.kv_pages_in_use == 0)
    finally:
        faults.disarm()
        await eng.stop()


# --- fused chunks stay preemptible across lanes ------------------------


async def test_fused_chunks_bound_cross_lane_stall(gpt_params):
    """A fused-width generation sharing the machine with a plain
    chunked lane never monopolises dispatch: fused chunks are typed
    units yielded at the same boundaries, so the longest same-lane
    dispatch streak while another lane is live stays <= 1 extra
    dispatch (the one fused chunk in flight when the peer arrives)."""
    eng = _engine(
        _model(), gpt_params, fused_single=True, sched_max_batches=2,
    )
    await eng.start()
    try:
        # Slow decode so the two lanes provably overlap.
        faults.arm("decode:every=1:delay=0.01")
        rb = await eng.submit(_LONG[0], max_new_tokens=8, stream=True)
        await _wait_for(lambda: eng.sched_batches_live == 1)
        # Solo non-stream request: fused widths apply (34 new tokens
        # -> one 64-wide fused decode unit per chunk boundary).
        ra = await eng.submit(_SHORT[0], max_new_tokens=34)
        (tb, eb), (ta, ea) = await asyncio.gather(
            _collect(rb), _collect(ra)
        )
        assert ea is None and eb is None
        assert len(ta) == 34 and len(tb) == 8
        assert eng.fused_calls >= 1  # the fused path really ran
        assert eng.sched_batches_live_max == 2  # lanes overlapped
        # Max same-lane streak with >1 lane live: one fused chunk.
        assert eng.sched_lane_stall_max <= 1
        await _wait_for(lambda: eng.kv_pages_in_use == 0)
    finally:
        faults.disarm()
        await eng.stop()


# --- drain covers the typed-unit queue ---------------------------------


async def test_drain_covers_scheduler_queue(gpt_params):
    """drain()'s idle check and budget-exhausted sweep cover pending
    groups and live lanes exactly as they cover _carry: every stream
    gets a proper terminal frame, pool back to baseline."""
    eng = _engine(_model(), gpt_params, sched_max_batches=1)
    await eng.start()
    try:
        # Slowed decode chunks keep the blocker's lane provably alive
        # past the drain budget — the sweep claim must not race its
        # natural completion (0.05 x 30 chunks = a 1.5 s floor; the
        # 0.02 floor flaked on this drifting box when the submits +
        # backlog wait ran past 0.6 s and the blocker finished first,
        # letting the pending group lane and complete naturally).
        faults.arm("decode:every=1:delay=0.05")
        blocker = await eng.submit(
            _SHORT[0], max_new_tokens=60, stream=True
        )
        await _wait_for(lambda: eng.sched_batches_live == 1)
        pend = await eng.submit(_LONG[0], max_new_tokens=8, stream=True)
        await _wait_for(lambda: eng.sched.backlog >= 1)
        gather = asyncio.gather(_collect(blocker), _collect(pend))
        await eng.drain(0.05)  # budget too small: sweep fires
        (tb, ebk), (tp, ep) = await gather
        # Every consumer TERMINATED: completion or DrainCancelled.
        assert ebk is None or isinstance(ebk, DrainCancelled)
        assert ep is None or isinstance(ep, DrainCancelled)
        # The pending group can never have been laned after the sweep.
        assert isinstance(ep, DrainCancelled)
        await _wait_for(lambda: eng.sched.idle)
        assert eng.sched.idle
        assert eng.kv_pages_in_use == 0
    finally:
        faults.disarm()
        await eng.stop()


# --- router backpressure feeds the estimate/brownout -------------------


async def test_router_backpressure_feeds_estimate_and_brownout(
    gpt_params,
):
    eng = _engine(_model(), gpt_params, scheduler=False, max_queue=8)
    # Warm the reservoirs so the estimate has a rate to multiply.
    eng.latency.record_first(100.0)
    eng.latency.record_gap(10.0)
    base = eng.admission_estimate_ms()
    eng.router_queue_depth = 40
    assert eng.admission_estimate_ms() > base
    # Brownout: fleet pressure alone engages the ladder (queue empty).
    assert eng._brownout_level() >= 1
    eng.router_queue_depth = 0
    assert eng._brownout_level() == 0


async def test_router_depth_header_sets_gauge_and_metrics(
    gpt_params, monkeypatch
):
    import httpx

    from mlapi_tpu.serving.app import build_app

    # The header is only trusted on router replicas (spawned ones
    # carry this env; arbitrary direct callers must not inject fleet
    # pressure into admission control).
    monkeypatch.setenv("MLAPI_TPU_REPLICA", "1")
    eng = _engine(_model(), gpt_params, sched_max_batches=2)
    app = build_app(eng, max_wait_ms=0.0)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://t"
        ) as c:
            r = await c.post(
                "/generate",
                json={"text": "hi", "max_new_tokens": 2},
                headers={"x-mlapi-router-depth": "7"},
            )
            assert r.status_code == 200
            assert eng.router_queue_depth == 7
            m = (await c.get("/metrics")).json()
            assert m["gauges"]["generate.router_queue_depth"] == 7
            # The sched observability block is exported.
            for k in (
                "sched_units_prefill", "sched_units_decode",
                "sched_units_spec", "sched_units_admit",
                "sched_units_compact", "sched_deadline_preempts",
                "sched_pages_deferred",
            ):
                assert f"generate.{k}" in m["counters"], k
            assert "generate.sched_queue_depth" in m["gauges"]
            assert "generate.sched_batches_live" in m["gauges"]
            assert m["counters"]["generate.sched_units_decode"] >= 1
            # A direct request (no header) clears the gauge — a stale
            # fleet spike must not keep shedding.
            r = await c.post(
                "/generate", json={"text": "hi", "max_new_tokens": 2}
            )
            assert r.status_code == 200
            assert eng.router_queue_depth == 0
    finally:
        await app.shutdown()


async def test_router_depth_header_ignored_off_replica(
    gpt_params, monkeypatch
):
    """A NON-replica server ignores x-mlapi-router-depth outright: a
    direct caller must not be able to spoof fleet pressure into the
    admission estimate / brownout ladder."""
    import httpx

    from mlapi_tpu.serving.app import build_app

    monkeypatch.delenv("MLAPI_TPU_REPLICA", raising=False)
    monkeypatch.delenv("MLAPI_TPU_REPLICAS", raising=False)
    eng = _engine(_model(), gpt_params, scheduler=False)
    app = build_app(eng, max_wait_ms=0.0)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://t"
        ) as c:
            r = await c.post(
                "/generate",
                json={"text": "hi", "max_new_tokens": 2},
                headers={"x-mlapi-router-depth": "999999"},
            )
            assert r.status_code == 200
            assert eng.router_queue_depth == 0
            assert eng._brownout_level() == 0
    finally:
        await app.shutdown()


# --- churn soak --------------------------------------------------------


@pytest.mark.heavy
@pytest.mark.slow  # 7.2 s measured call — r16 tier-1 buyback (conftest);
# the 16-config identity matrix and unit-counter tests keep tier-1
# scheduler coverage.
async def test_scheduler_churn_soak(gpt_params):
    """Mixed-shape churn through the scheduler: short/long prompts,
    mixed budgets, a few deadlines — every stream terminates properly
    and the pool returns to baseline each round."""
    eng = _engine(_model(), gpt_params, sched_max_batches=2, max_batch=4)
    await eng.start()
    try:
        for round_i in range(6):
            reqs = []
            for j in range(4):
                text = "x" * 100 if (round_i + j) % 3 == 0 else f"p{j}"
                n_new = (8, 24, 40, 12)[j]
                kw = {}
                if j == 3:
                    kw["deadline_ms"] = 30000.0
                reqs.append(await eng.submit(
                    text, max_new_tokens=n_new, stream=True,
                    seed=round_i * 7 + j, **kw,
                ))
            results = await asyncio.gather(*(_collect(r) for r in reqs))
            for toks, err in results:
                assert err is None, err
                assert toks
            await _wait_for(lambda: eng.sched.idle)
            await _wait_for(lambda: eng.kv_pages_in_use == 0)
        assert eng.sched_batches_live_max >= 2
    finally:
        await eng.stop()
