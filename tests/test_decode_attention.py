"""Flash-decode kernel (`ops/pallas/decode_attention`) and the
``decode_attn_impl`` model field.

The kernel is the serving hot path's bandwidth lever: split-K
single-query attention that reads the STORED cache tiles — int8
payload + scales dequantized per tile in registers — instead of the
einsum path's dequant-at-the-read-seam. These tests pin three claims:

- **Parity, cell by cell**: interpret-mode kernel output matches the
  einsum decode oracle across {MHA, GQA} x {f32, bf16} x {kv_quant
  none, int8} x {plain pos, ragged n_pad, prefix shift, windowed}
  mask rows, to <= 1e-5 (f32) / <= 2e-2 (bf16) max-abs.
- **Streams, end to end**: gpt AND llama-GQA generate token-identical
  greedy streams under ``decode_attn_impl="flash"`` through the model
  AND engine paths, both cache formats, pads included.
- **Bytes, exactly**: ``engine.decode_bytes_per_step()`` equals the
  closed-form dtype arithmetic for every (impl, format) pair, and the
  int8 flash read is the committed factor below the full-precision
  read — asserted from arithmetic, never from timing.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.models.gpt import decode_valid_and_shift
from mlapi_tpu.ops.attention import NEG
from mlapi_tpu.ops.pallas import decode_attention
from mlapi_tpu.ops.quant import kv_dequantize, kv_greedy_agreement, kv_quantize
from mlapi_tpu.serving.engine import TextGenerationEngine
from mlapi_tpu.text import ByteTokenizer

B, L, H, D = 3, 64, 4, 16


def _einsum_oracle(q, k, v, mask):
    """The decode einsum read (``gpt.cached_attend``'s math), GQA
    broadcast included — the reference the kernel answers to."""
    group = q.shape[2] // k.shape[2]
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    s = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
        / q.shape[-1] ** 0.5
    )
    s = jnp.where(mask[:, None, None, :], s, NEG)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def _rows(dtype, kvh):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, 1, H, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, L, kvh, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, L, kvh, D)), dtype)
    return q, k, v


def _mask(case):
    """One [B, L] decode mask per semantics cell, all with per-row
    variation (the kernel must not assume batch-uniform layouts)."""
    idx = jnp.arange(L)
    if case == "plain":
        pos = jnp.asarray([10, 40, L - 1])
        valid, _ = decode_valid_and_shift(
            L, pos, jnp.zeros((B,), jnp.int32)
        )
        return valid[:, 0, 0, :]
    if case == "ragged_n_pad":
        pos = jnp.asarray([20, 33, 50])
        n_pad = jnp.asarray([0, 7, 15])
        valid, _ = decode_valid_and_shift(L, pos, n_pad)
        return valid[:, 0, 0, :]
    if case == "prefix_shift":
        # Shared prefix region [lo, 16) ahead of per-row pad holes.
        pos = jnp.asarray([30, 40, 55])
        n_pad = jnp.asarray([2, 5, 0])
        valid, _ = decode_valid_and_shift(
            L, pos, n_pad, prefix_len=jnp.int32(16),
            prefix_lo=jnp.asarray([0, 4, 9]),
        )
        return valid[:, 0, 0, :]
    assert case == "windowed"
    # Sliding window: only the last 12 slots before pos attend —
    # whole leading tiles go dead, the split-K skip path.
    pos = jnp.asarray([15, 35, 60])
    valid, _ = decode_valid_and_shift(L, pos, jnp.zeros((B,), jnp.int32))
    win = (idx[None, :] > pos[:, None] - 12)
    return valid[:, 0, 0, :] & win


@pytest.mark.parametrize("kvh", [H, H // 2], ids=["mha", "gqa"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("fmt", ["none", "int8"])
@pytest.mark.parametrize(
    "case", ["plain", "ragged_n_pad", "prefix_shift", "windowed"]
)
def test_kernel_matches_einsum_oracle(kvh, dtype, fmt, case):
    q, k, v = _rows(dtype, kvh)
    mask = _mask(case)
    if fmt == "int8":
        kq, ks = kv_quantize(k)
        vq, vs = kv_quantize(v)
        # Oracle reads the SAME int8 values through kv_dequantize, so
        # the comparison isolates kernel math from quantization error.
        ref = _einsum_oracle(
            q, kv_dequantize(kq, ks, dtype), kv_dequantize(vq, vs, dtype),
            mask,
        )
        got = decode_attention(
            q, {"q": kq, "scale": ks}, {"q": vq, "scale": vs},
            mask.astype(jnp.float32), interpret=True, block_k=16,
        )
    else:
        ref = _einsum_oracle(q, k, v, mask)
        got = decode_attention(
            q, k, v, mask.astype(jnp.float32), interpret=True, block_k=16,
        )
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    diff = np.abs(
        np.asarray(got, np.float32) - np.asarray(ref, np.float32)
    ).max()
    assert diff <= tol, (case, fmt, diff)


def test_kernel_awkward_length_single_block_fallback():
    """Cache lengths that defeat power-of-two blocking (the
    ``p + n_steps + 1`` harness shapes) fall back to one whole-L
    block and stay exact."""
    q, k, v = _rows(jnp.float32, H)
    lk = 47  # prime-ish: no block divides it
    mask = _mask("plain")[:, :lk]
    ref = _einsum_oracle(q, k[:, :lk], v[:, :lk], mask)
    got = decode_attention(
        q, k[:, :lk], v[:, :lk], mask.astype(jnp.float32), interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=1e-5
    )


def test_kernel_rejects_bad_operands():
    q, k, v = _rows(jnp.float32, H)
    mask = jnp.ones((B, L), jnp.float32)
    # r11: a multi-token q no longer errors outright — it dispatches
    # to the flash-extend kernel — but a single-query [B, L] mask
    # cannot express the intra-span causality, so THAT stays loud
    # (the U-token parity grid lives in test_extend_attention.py).
    with pytest.raises(ValueError, match="per-query-row"):
        decode_attention(
            jnp.concatenate([q, q], axis=1), k, v, mask, interpret=True
        )
    with pytest.raises(ValueError, match="one cache format"):
        kq, ks = kv_quantize(k)
        decode_attention(
            q, {"q": kq, "scale": ks}, v, mask, interpret=True
        )
    with pytest.raises(TypeError, match="quantized pairs"):
        decode_attention(q, {"weird": k}, v, mask, interpret=True)


def test_bad_decode_attn_impl_rejected():
    with pytest.raises(ValueError, match="decode_attn_impl"):
        get_model(
            "gpt_lm", vocab_size=32, hidden_size=32, num_layers=1,
            num_heads=2, max_positions=32, decode_attn_impl="paged",
        )


# --- end-to-end streams ------------------------------------------------

GPT_CFG = dict(
    vocab_size=260, hidden_size=32, num_layers=2, num_heads=2,
    max_positions=160, compute_dtype="float32",
)
LLAMA_CFG = dict(
    vocab_size=260, hidden_size=32, num_layers=2, num_heads=4,
    num_kv_heads=2, max_positions=96, compute_dtype="float32",
)


@pytest.mark.parametrize("family,cfg", [
    ("gpt_lm", GPT_CFG), ("llama_lm", LLAMA_CFG),
], ids=["gpt", "llama-gqa"])
@pytest.mark.parametrize("fmt", ["none", "int8"])
def test_flash_decode_stream_matches_einsum(family, cfg, fmt):
    """Greedy streams are token-identical across decode impls for
    both families and both cache formats — left pads included (the
    bucket-invariance discipline rides the mask into the kernel)."""
    m = get_model(family, **cfg, kv_quant=fmt)
    p = m.init(jax.random.key(0))
    prompt = np.zeros((2, 12), np.int32)
    prompt[:, 4:] = np.random.default_rng(3).integers(1, 200, (2, 8))
    pads = np.asarray([4, 4], np.int32)
    ref = np.asarray(m.generate(
        p, jnp.asarray(prompt), max_new_tokens=10, pad_lens=pads
    ))
    mf = dataclasses.replace(m, decode_attn_impl="flash")
    got = np.asarray(mf.generate(
        p, jnp.asarray(prompt), max_new_tokens=10, pad_lens=pads
    ))
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("family,cfg,dtype", [
    ("gpt_lm", GPT_CFG, "float32"),
    ("gpt_lm", GPT_CFG, "bfloat16"),
    ("llama_lm", LLAMA_CFG, "float32"),
    ("llama_lm", LLAMA_CFG, "bfloat16"),
], ids=["gpt-f32", "gpt-bf16", "llama-f32", "llama-bf16"])
@pytest.mark.parametrize("fmt", ["none", "int8"])
def test_decode_step_logits_parity(family, cfg, dtype, fmt):
    """LOGITS-level parity through a real decode_step (prefill +
    one cached step, ragged pads): flash vs einsum <= 1e-5 (f32) /
    2e-2 (bf16) max-abs — the whole-model form of the kernel parity."""
    m = get_model(
        family, **{**cfg, "compute_dtype": dtype}, kv_quant=fmt
    )
    p = m.init(jax.random.key(0))
    prompt = np.zeros((2, 10), np.int32)
    prompt[:, 3:] = np.random.default_rng(5).integers(1, 200, (2, 7))
    n_pad = jnp.asarray([3, 3], jnp.int32)
    cache, _ = m.prefill_core(p, jnp.asarray(prompt), n_pad, 24)
    tok = jnp.asarray([[7], [9]], jnp.int32)

    def step(model):
        logits, _ = jax.jit(model.decode_step)(
            p, cache, tok, jnp.int32(10), n_pad
        )
        return np.asarray(logits, np.float32)

    ref = step(m)
    got = step(dataclasses.replace(m, decode_attn_impl="flash"))
    tol = 1e-5 if dtype == "float32" else 2e-2
    assert np.abs(got - ref).max() <= tol, (family, dtype, fmt)


def test_engine_flash_decode_and_prefix_matches_einsum():
    """The engine path (chunked decode, prefix KV cache) over the
    flash impl emits the exact einsum-engine stream — the kernel
    rides ``decode_step``'s mask semantics, prefix regions included."""
    model = get_model("gpt_lm", **GPT_CFG, kv_quant="int8")
    params = model.init(jax.random.key(0))
    tok = ByteTokenizer()

    def eng(impl):
        return TextGenerationEngine(
            dataclasses.replace(model, decode_attn_impl=impl), params,
            tokenizer=tok, chunk=2, fused_single=False,
        )

    a, b = eng("einsum"), eng("flash")
    assert b.decode_attn_impl == "flash"
    ref = a.generate_text("hello world", max_new_tokens=16)
    got = b.generate_text("hello world", max_new_tokens=16)
    assert got["token_ids"] == ref["token_ids"]
    prefix = "the quick brown fox "
    pref_ref = a.generate_text("tail", prefix=prefix, max_new_tokens=8)
    pref_got = b.generate_text("tail", prefix=prefix, max_new_tokens=8)
    assert pref_got["token_ids"] == pref_ref["token_ids"]


def test_from_checkpoint_flag_and_rejects(tmp_path):
    from mlapi_tpu.checkpoint import save_checkpoint
    from mlapi_tpu.serving import InferenceEngine

    tok = ByteTokenizer()
    model = get_model("gpt_lm", **GPT_CFG)
    ck = tmp_path / "ck"
    save_checkpoint(
        ck, model.init(jax.random.key(1)), step=1,
        config={"model": "gpt_lm", "model_kwargs": GPT_CFG,
                "tokenizer": tok.fingerprint()},
    )
    eng = InferenceEngine.from_checkpoint(
        ck, kv_quant="int8", decode_attn_impl="flash"
    )
    assert eng.model.decode_attn_impl == "flash"
    assert eng.meta["decode_attn_impl"] == "flash"
    with pytest.raises(ValueError, match="decode_attn_impl"):
        InferenceEngine.from_checkpoint(ck, decode_attn_impl="paged")


# --- the byte model ----------------------------------------------------


def test_decode_bytes_per_step_closed_form():
    """Every (impl, format) pair's modeled read equals the dtype
    arithmetic, and the int8 flash read clears the committed factor
    below the full-precision read — from arithmetic, not timing.
    bf16 gpt-small shapes (head_dim 128): 2D/(D+4) = 1.94x."""
    small = dict(
        vocab_size=260, hidden_size=256, num_layers=2, num_heads=2,
        max_positions=320, compute_dtype="bfloat16",
    )
    model = get_model("gpt_lm", **small)
    params = model.init(jax.random.key(0))
    tok = ByteTokenizer()

    def eng(impl, fmt):
        m = dataclasses.replace(
            model, kv_quant=fmt, decode_attn_impl=impl
        )
        return TextGenerationEngine(m, params, tokenizer=tok, chunk=8)

    layers, h, d = small["num_layers"], 2, 128
    total = 160  # bucket 128 + default tier 32
    bf16 = layers * 2 * total * h * d * 2
    int8 = layers * 2 * (total * h * d + total * h * 4)
    assert eng("flash", "none").decode_bytes_per_step() == bf16
    assert eng("flash", "int8").decode_bytes_per_step() == int8
    assert eng("einsum", "none").decode_bytes_per_step() == bf16
    assert eng("einsum", "int8").decode_bytes_per_step() == bf16 + int8
    # The read-side claim: exact ratio from dtype arithmetic —
    # per (token, head): 2D bf16 bytes vs D + 4 int8+scale bytes.
    assert bf16 / int8 == pytest.approx((2 * d) / (d + 4))
    assert bf16 / int8 >= 1.9
    # And the einsum path demonstrably does NOT realize it.
    assert eng("einsum", "int8").decode_bytes_per_step() > bf16

    # GQA: the einsum operand broadcasts KV heads to query heads
    # (_repeat_kv materializes), so the einsum step reads the stored
    # KVH-width cache (the broadcast's producer) PLUS the
    # query-head-width operand — flash reads the stored tiles once.
    lm = get_model("llama_lm", **LLAMA_CFG)  # heads 4, kv_heads 2
    lp = lm.init(jax.random.key(1))

    def leng(impl, fmt):
        m = dataclasses.replace(lm, kv_quant=fmt, decode_attn_impl=impl)
        return TextGenerationEngine(
            m, lp, tokenizer=tok, chunk=8
        ).decode_bytes_per_step()

    kvh, hd, layers_l = 2, 8, LLAMA_CFG["num_layers"]
    total_l = 96  # bucket 64 + default tier 32, clamped to window 96
    stored_f32 = layers_l * 2 * total_l * kvh * hd * 4
    stored_l = layers_l * 2 * (total_l * kvh * hd + total_l * kvh * 4)
    full_l = layers_l * 2 * total_l * (2 * kvh) * hd * 4  # f32, H heads
    assert leng("flash", "none") == stored_f32
    assert leng("einsum", "none") == stored_f32 + full_l  # group 2: 3x
    assert leng("flash", "int8") == stored_l
    assert leng("einsum", "int8") == stored_l + full_l


def test_metrics_exports_decode_bytes():
    import asyncio

    from mlapi_tpu.serving import build_app

    model = get_model("gpt_lm", **GPT_CFG, kv_quant="int8")
    model = dataclasses.replace(model, decode_attn_impl="flash")
    eng = TextGenerationEngine(
        model, model.init(jax.random.key(0)), tokenizer=ByteTokenizer(),
        chunk=2, fused_single=False,
    )

    async def scrape():
        import httpx

        app = build_app(eng)
        await app.startup()
        try:
            transport = httpx.ASGITransport(app=app)
            async with httpx.AsyncClient(
                transport=transport, base_url="http://test"
            ) as c:
                return (await c.get("/metrics")).json()
        finally:
            await app.shutdown()

    snap = asyncio.run(scrape())
    assert (
        snap["gauges"]["generate.decode_bytes_per_step"]
        == eng.decode_bytes_per_step()
    )
    assert (
        snap["gauges"]["generate.kv_cache_bytes_per_slot"]
        == eng.kv_cache_slot_bytes()
    )


# --- the agreement pin -------------------------------------------------


@pytest.mark.heavy  # in-suite soak — fast profile: -m 'not heavy'
@pytest.mark.slow  # 12.6 s measured call — r16 tier-1 buyback (conftest);
# the 64-token agreement pin runs in tier-1, this is the long tail.
def test_flash_int8_greedy_agreement_256_tokens():
    """The acceptance pin: teacher-forced greedy top-1 agreement of
    ``kv_quant="int8", decode_attn_impl="flash"`` vs the
    FULL-PRECISION EINSUM reference >= 0.99 over 256 tokens x 8
    prompts on bf16 gpt-small — kernel math and quantization error
    guarded together, on the exact shape class the byte claim uses."""
    small = dict(
        vocab_size=260, hidden_size=256, num_layers=2, num_heads=2,
        max_positions=320, compute_dtype="bfloat16",
    )
    model = get_model("gpt_lm", **small)  # einsum reference config
    params = model.init(jax.random.key(0))
    tok = ByteTokenizer()
    prompts = [
        "the quick brown fox", "serving engines batch",
        "checkpoints commit", "tpu programs compile",
        "the draft proposes", "sharding follows mesh",
        "decode reads the cache", "quantize the kv cache",
    ]
    width = max(len(tok.token_ids(p)) for p in prompts)
    rows = np.full((len(prompts), width), tok.pad_id, np.int32)
    pads = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        ids = tok.token_ids(p)
        rows[i, width - len(ids):] = ids
        pads[i] = width - len(ids)
    agr = kv_greedy_agreement(
        model, params, jnp.asarray(rows), 257, pad_lens=pads,
        quant_overrides={"decode_attn_impl": "flash"},
    )
    assert agr >= 0.99, agr
