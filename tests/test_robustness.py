"""Serving robustness (r12): deadlines, SLO admission control,
graceful drain, and the deterministic fault-injection harness.

The load-bearing properties:

- **Deadlines end cleanly at every lifecycle stage.** Expiry while
  queued / mid-prefill / mid-decode produces a terminal
  ``DeadlineExceeded`` frame (504 unary, a ``deadline_exceeded``-coded
  NDJSON frame on streams) through the SAME cancellation machinery
  client disconnects use — rows free, pages release, nothing hangs.
- **Infeasible deadlines shed at the door** with a computed
  retry-after, instead of occupying a slot and timing out later.
- **Drain is graceful**: in-flight streams finish inside the budget,
  new admissions shed 503, ``/healthz`` says ``draining``, and
  budget-overrunning streams get proper ``DrainCancelled`` frames.
- **Conservation under injected failure** (the fault matrix): after
  ANY armed fault at ANY registered point, page refcounts return to
  baseline, every stream ends in a well-formed terminal frame, and
  the engine serves fresh work.

Faults are armed via ``serving/faults.py`` (the ``MLAPI_FAULTS``
grammar) — deterministic call-count triggers, zero overhead disarmed.
"""

import asyncio
import time

import httpx
import jax
import numpy as np
import pytest

from mlapi_tpu.models import get_model
from mlapi_tpu.serving import build_app, faults
from mlapi_tpu.serving.scoring import MicroBatcher, OverloadedError
from mlapi_tpu.serving.engine import TextGenerationEngine, _SyncSink
from mlapi_tpu.serving.paged_pool import PagePoolExhausted
from mlapi_tpu.serving.requests import DeadlineExceeded, DrainCancelled
from mlapi_tpu.text import ByteTokenizer

pytestmark = pytest.mark.anyio


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No armed spec may outlive its test — a leaked fault would fail
    unrelated tests in ways that look like real lifecycle bugs."""
    yield
    faults.disarm()


CFG = dict(
    vocab_size=260,
    hidden_size=16,
    num_layers=1,
    num_heads=2,
    max_positions=96,
    compute_dtype="float32",
)

_MODEL = get_model("gpt_lm", **CFG)
_PARAMS = _MODEL.init(jax.random.key(0))


def _engine(**kw) -> TextGenerationEngine:
    kw.setdefault("chunk", 2)  # many dispatch boundaries per request
    kw.setdefault("fused_single", False)  # the chunked (checkable) path
    return TextGenerationEngine(
        _MODEL, _PARAMS, tokenizer=ByteTokenizer(), **kw
    )


async def _collect(gen, timeout=30.0):
    """Drain one stream to its terminal frame: (tokens, error|None).
    Every well-formed stream ends in a ``None`` sentinel or an
    exception — a timeout here IS the hang this file polices."""
    toks: list[int] = []
    while True:
        item = await asyncio.wait_for(gen.queue.get(), timeout)
        if isinstance(item, Exception):
            return toks, item
        if item is None:
            return toks, None
        toks.extend(item["token_ids"])


def _pool_baseline(eng) -> None:
    """The paged conservation invariant: every page back on the free
    list, no residual references (no orphan table rows hold any)."""
    assert eng.kv_pages_in_use == 0, eng.kv_pages_in_use
    ref = eng.pool.ref
    assert int(ref[1:].sum()) == 0, np.nonzero(ref[1:])


async def _settle(eng, timeout=5.0) -> None:
    """Wait for the decode thread to finish its current batch (page
    cleanup runs in the batch's finally)."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while eng._running is not None and loop.time() < deadline:
        await asyncio.sleep(0.02)


# ---------------------------------------------------------------- deadlines


def test_deadline_expires_queued_unary():
    """A deadline already past at formation never reaches the device:
    terminal DeadlineExceeded, stage counter 'queued'."""
    eng = _engine(kv_page_size=4)
    ref = eng.generate_text("hello", max_new_tokens=6)
    with pytest.raises(DeadlineExceeded):
        eng.generate_text("hello", max_new_tokens=6, deadline_ms=1e-4)
    assert eng.deadline_expired_queued == 1
    # The engine is unpoisoned: same request, same stream.
    _pool_baseline(eng)
    again = eng.generate_text("hello", max_new_tokens=6)
    assert again["token_ids"] == ref["token_ids"]


def test_default_deadline_applies_when_request_names_none():
    eng = _engine()
    eng.default_deadline_ms = 1e-4
    with pytest.raises(DeadlineExceeded):
        eng.generate_text("hello", max_new_tokens=4)
    # An explicit generous deadline overrides the default.
    out = eng.generate_text(
        "hello", max_new_tokens=4, deadline_ms=60_000
    )
    assert len(out["token_ids"]) == 4


def test_deadlined_request_declines_fused_fast_path():
    """One fused run is one uninterruptible device program with no
    boundary to check a deadline at — a deadlined solo request must
    decode CHUNKED (where every boundary enforces the budget), not
    return 200 with the full completion long after the budget passed.
    The emitted stream is byte-identical either way (pinned r04), so
    the decline is invisible in the response."""
    eng = _engine(fused_single=True)
    ref = eng.generate_text("hello", max_new_tokens=6)
    assert eng.fused_calls == 1  # deadline-less solo unary runs fused
    out = eng.generate_text(
        "hello", max_new_tokens=6, deadline_ms=60_000
    )
    assert eng.fused_calls == 1  # the deadlined twin declined it
    assert out["token_ids"] == ref["token_ids"]


async def test_deadline_expires_mid_decode_stream():
    """Deterministic mid-decode expiry: retract the deadline after
    the first chunk arrives — the next chunk boundary must end the
    stream with the terminal frame, free the row, release pages."""
    eng = _engine(kv_page_size=4)
    await eng.start()
    try:
        gen = await eng.submit("abc", max_new_tokens=60, stream=True)
        first = await gen.queue.get()
        assert first["token_ids"]
        gen.deadline = 1e-4  # in the past on the perf_counter clock
        _, err = await _collect(gen)
        assert isinstance(err, DeadlineExceeded), err
        assert eng.deadline_expired_decode >= 1
        await _settle(eng)
        _pool_baseline(eng)
        # The engine still serves.
        ok = await eng.submit("abc", max_new_tokens=3)
        toks, err = await _collect(ok)
        assert err is None and len(toks) == 3
    finally:
        await eng.stop()


async def test_deadline_expires_mid_interleaved_prefill():
    """Stage 'prefill': a long-prompt joiner whose deadline passes
    inside its interleaved chunked-prefill window aborts the window
    (private pages back) with a terminal frame, while the running
    stream is untouched."""
    eng = _engine(
        kv_page_size=4, max_batch=4, prompt_buckets=(4, 8),
    )
    # This test is about EXPIRY; the admission estimator would
    # (correctly) shed the joiner outright here, because an unwarmed
    # test engine's first TTFT samples include XLA compiles.
    eng.admission_control = False
    solo = _engine(prompt_buckets=(4, 8))
    long_p = "abcdefghijklmnopqrst"  # 20 tokens → bucket 24 = 3 chunks
    ref = solo.generate_text("run ab", max_new_tokens=40)
    await eng.start()
    try:
        # Each prefill chunk sleeps, so a ~3-chunk window far outlives
        # the joiner's budget — expiry lands INSIDE the window at a
        # _pf_step boundary, deterministically.
        faults.arm("prefill_chunk:every=1:delay=0.15")
        a = await eng.submit("run ab", max_new_tokens=40, stream=True)
        first = await a.queue.get()
        b = await eng.submit(long_p, max_new_tokens=3, deadline_ms=200)
        _, berr = await _collect(b)
        assert isinstance(berr, DeadlineExceeded), berr
        assert eng.deadline_expired_prefill >= 1
        assert eng.interleaved_prefills == 1  # the window did start
        toks, aerr = await _collect(a)
        assert aerr is None
        assert first["token_ids"] + toks == ref["token_ids"]
        await _settle(eng)
        _pool_baseline(eng)
    finally:
        await eng.stop()


async def test_deadline_http_unary_504_and_stream_frame():
    """HTTP shapes: unary expiry → 504; stream expiry → a terminal
    NDJSON frame carrying code=deadline_exceeded."""
    eng = _engine()
    app = build_app(eng)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://t"
        ) as client:
            r = await client.post(
                "/generate",
                json={"text": "hi", "max_new_tokens": 8,
                      "deadline_ms": 0.001},
            )
            assert r.status_code == 504, r.text
            assert "deadline" in r.json()["detail"]

            r = await client.post(
                "/generate",
                json={"text": "hi", "max_new_tokens": 8,
                      "deadline_ms": 0.001, "stream": True},
            )
            assert r.status_code == 200
            last = [l for l in r.text.splitlines() if l][-1]
            import json as _json

            frame = _json.loads(last)
            assert frame.get("code") == "deadline_exceeded", frame

            r = await client.post(
                "/generate",
                json={"text": "hi", "deadline_ms": -5},
            )
            assert r.status_code == 422
    finally:
        await app.shutdown()


# ------------------------------------------------- admission control


def _seed_latency(eng, ttft_ms=1000.0, itl_ms=50.0, n=40):
    for _ in range(n):
        eng.latency.record_first(ttft_ms)
        eng.latency.record_gap(itl_ms)


def test_admission_estimate_formula():
    """est = backlog/max_batch * (ttft_p95 + default_n * itl_p50)
    + ttft_p95 — and exactly 0 on a cold server (never shed on a
    guess)."""
    eng = _engine()
    assert eng.admission_estimate_ms() == 0.0
    _seed_latency(eng, ttft_ms=1000.0, itl_ms=50.0)
    # Empty queue: just the request's own p95 TTFT.
    assert eng.admission_estimate_ms() == pytest.approx(1000.0)
    with eng._alock:
        eng._deferred.extend(object() for _ in range(2 * eng.max_batch))
    try:
        batch_ms = 1000.0 + eng.default_max_new_tokens * 50.0
        assert eng.admission_estimate_ms() == pytest.approx(
            2 * batch_ms + 1000.0
        )
    finally:
        with eng._alock:
            eng._deferred.clear()


async def test_infeasible_deadline_sheds_with_retry_after():
    eng = _engine()
    _seed_latency(eng, ttft_ms=2000.0)
    await eng.start()
    try:
        with pytest.raises(OverloadedError) as ei:
            await eng.submit("hi", max_new_tokens=4, deadline_ms=100)
        assert eng.shed_deadline_infeasible == 1
        # retry-after ≈ (est - budget) = 1.9 s, floor 1 s.
        assert 1.0 <= ei.value.retry_after_s <= 3.0
        # No deadline → no estimate gate: the request proceeds.
        g = await eng.submit("hi", max_new_tokens=4)
        toks, err = await _collect(g)
        assert err is None and len(toks) == 4
        # --no-admission-control: deadlined requests aren't estimated
        # (the deadline itself still enforces downstream).
        eng.admission_control = False
        g = await eng.submit("hi", max_new_tokens=4, deadline_ms=100)
        await _collect(g)
        assert eng.shed_deadline_infeasible == 1
    finally:
        await eng.stop()


def test_brownout_level_thresholds():
    eng = _engine(max_queue=8)
    assert eng._brownout_level() == 0
    with eng._alock:
        eng._deferred.extend(object() for _ in range(4))
    assert eng._brownout_level() == 1  # >= 50%
    with eng._alock:
        eng._deferred.extend(object() for _ in range(2))
    assert eng._brownout_level() == 2  # >= 75%
    eng.admission_control = False
    assert eng._brownout_level() == 0  # ladder disabled
    with eng._alock:
        eng._deferred.clear()


async def test_brownout_clamps_tokens_and_suppresses_spec(monkeypatch):
    eng = _engine()
    await eng.start()
    try:
        monkeypatch.setattr(eng, "_brownout_level", lambda: 1)
        g = await eng.submit(
            "hi", max_new_tokens=2 * eng.default_max_new_tokens
        )
        toks, err = await _collect(g)
        assert err is None
        assert len(toks) == eng.default_max_new_tokens  # clamped
        assert eng.brownout_tokens_clamped == 1
        # The production spec lever (BatchRun._spec_brownout): blocks
        # under pressure, and its counter ticks at most ONCE per batch
        # run however many chunk boundaries re-confirm the block.
        from mlapi_tpu.serving.batch_run import BatchRun

        br = BatchRun.__new__(BatchRun)
        br.eng = eng
        br._spec_supp_counted = False
        before = eng.brownout_spec_suppressed
        assert br._spec_brownout() is True
        assert br._spec_brownout() is True
        assert eng.brownout_spec_suppressed == before + 1
    finally:
        await eng.stop()


# ------------------------------------------------------------- drain


async def test_drain_completes_inflight_then_sheds():
    """Graceful path: the in-flight stream runs to completion inside
    the budget while new admissions shed 503 + retry-after."""
    eng = _engine(kv_page_size=4)
    await eng.start()
    try:
        gen = await eng.submit("abcd", max_new_tokens=30, stream=True)
        first = await gen.queue.get()
        drain = asyncio.create_task(eng.drain(20.0))
        await asyncio.sleep(0.05)
        assert eng.draining
        with pytest.raises(OverloadedError):
            await eng.submit("x", max_new_tokens=2)
        assert eng.shed_draining == 1
        toks, err = await _collect(gen)
        assert err is None
        assert len(first["token_ids"]) + len(toks) == 30  # ran to the end
        await asyncio.wait_for(drain, 20)
        _pool_baseline(eng)
    finally:
        await eng.stop()


async def test_drain_timeout_cancels_with_terminal_frames():
    """A stream outliving the budget (a slow dispatch inside the
    drain window — injected delay) gets a proper DrainCancelled
    terminal frame; pages return to baseline."""
    eng = _engine(kv_page_size=4)
    await eng.start()
    try:
        faults.arm("decode:every=1:delay=0.05")
        gen = await eng.submit("ab", max_new_tokens=80, stream=True)
        await gen.queue.get()
        await eng.drain(0.3)
        toks, err = await _collect(gen)
        assert isinstance(err, DrainCancelled), (len(toks), err)
        assert eng.faults_injected > 0  # the slow window was real
        await _settle(eng)
        _pool_baseline(eng)
    finally:
        faults.disarm()
        await eng.stop()


async def test_drain_sees_collector_forming_window():
    """A request the collector has claimed off the queue but not yet
    handed to the decode thread (the straggler-collection window) is
    in neither the queue, the staging lists, nor ``_running`` — drain
    must still count it as in-flight work. A premature "idle" verdict
    here turns the claimed stream into an opaque engine-stopped 500
    when the e2e shutdown path stops the engine right after."""
    eng = _engine(max_wait_ms=250.0)  # long straggler window
    await eng.start()
    try:
        gen = await eng.submit("hi", max_new_tokens=4, stream=True)
        await asyncio.sleep(0.05)  # claimed, sitting in the window
        await asyncio.wait_for(eng.drain(20.0), 25)
        await eng.stop()  # what lifespan.shutdown does next
        toks, err = await _collect(gen)
        assert err is None, err
        assert len(toks) == 4
    finally:
        await eng.stop()


async def test_submit_sheds_when_drain_completes_mid_encode():
    """submit() passed the front-door draining check, then suspended
    in the encode executor while drain() completed (idle engine) and
    stop() flushed the queue — the late enqueue would land in a queue
    no collector will ever pop: a stream with no terminal frame. The
    post-encode re-check sheds it exactly like the front door."""
    from mlapi_tpu.serving.scoring import OverloadedError

    eng = _engine()
    await eng.start()
    try:
        real = eng._encode

        def slow_encode(*a, **kw):
            time.sleep(0.4)  # hold submit inside the executor await
            return real(*a, **kw)

        eng._encode = slow_encode
        task = asyncio.create_task(eng.submit("hi", max_new_tokens=2))
        await asyncio.sleep(0.1)  # submit is inside the executor
        await asyncio.wait_for(eng.drain(1.0), 10)  # idle: instant
        await eng.stop()
        with pytest.raises(OverloadedError):
            await asyncio.wait_for(task, 10)
        assert eng.shed_draining >= 1
    finally:
        await eng.stop()


async def test_drain_sweep_covers_collector_carry():
    """The collector's window-incompatible leftovers (``_carry``) are
    in neither the queue, the staging lists, nor a formed batch — the
    budget-exhausted sweep must deliver their DrainCancelled frames
    too, not leave them for a post-budget batch run (followed by an
    opaque engine-stopped 500 at stop())."""
    eng = _engine(kv_page_size=4)
    await eng.start()
    try:
        out: list = []
        sink = _SyncSink(eng._encode("abc", 3, 0.0, 0, None), out)
        eng._carry.append(sink)
        await asyncio.wait_for(eng.drain(0.0), 10)
        assert isinstance(sink.error, DrainCancelled), sink.error
        assert sink.cancelled
    finally:
        eng._carry.clear()
        await eng.stop()


async def test_microbatcher_drain_budget_sheds_queued_503():
    """Budget-exhausted drain sheds still-QUEUED entries with the
    documented OverloadedError (503 + retry-after) — not the opaque
    RuntimeError("batcher stopped") 500 that stop() would raise."""
    from tests.test_batcher import FakeEngine

    eng = FakeEngine()
    b = MicroBatcher(eng, max_batch=4, max_wait_ms=0.0, max_inflight=1)
    await b.start()
    try:
        row = np.zeros(4, np.float32)
        eng.gate.clear()  # wedge the device
        t_block = asyncio.create_task(b.submit(row))  # holds the slot
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 10.0
        while b.device_calls < 1:  # plug batch is in the executor
            assert loop.time() < deadline
            await asyncio.sleep(0.01)
        t_queued = asyncio.create_task(b.submit(row))  # stuck in queue
        await asyncio.sleep(0.02)
        await asyncio.wait_for(b.drain(0.2), 10)  # budget expires
        with pytest.raises(OverloadedError):
            await asyncio.wait_for(t_queued, 5)
        eng.gate.set()  # let the dispatched batch finish cleanly
        await asyncio.wait_for(t_block, 10)
    finally:
        eng.gate.set()
        await b.stop()


async def test_drain_e2e_healthz_and_shed_over_http():
    """End-to-end: lifespan shutdown flips /healthz to "draining",
    in-flight NDJSON streams finish with their done frame, and new
    /generate requests shed 503 with retry-after."""
    eng = _engine()
    app = build_app(eng, drain_timeout_s=20.0)
    await app.startup()
    transport = httpx.ASGITransport(app=app)
    client = httpx.AsyncClient(transport=transport, base_url="http://t")
    try:
        stream_task = asyncio.create_task(
            client.post(
                "/generate",
                json={"text": "abcd", "max_new_tokens": 40,
                      "stream": True},
            )
        )
        # Wait until the stream is actually decoding.
        while eng._running is None:
            await asyncio.sleep(0.01)
        shutdown = asyncio.create_task(app.shutdown())
        while not eng.draining:
            await asyncio.sleep(0.01)
        r = await client.get("/healthz")
        assert r.json()["status"] == "draining"
        r = await client.post("/generate", json={"text": "x"})
        assert r.status_code == 503
        assert int(r.headers["retry-after"]) >= 1
        resp = await asyncio.wait_for(stream_task, 30)
        assert resp.status_code == 200
        import json as _json

        last = _json.loads(
            [l for l in resp.text.splitlines() if l][-1]
        )
        assert last.get("done") is True, last  # finished, not killed
        await asyncio.wait_for(shutdown, 30)
    finally:
        await client.aclose()


async def test_microbatcher_drain_and_deadline():
    from tests.test_batcher import FakeEngine

    eng = FakeEngine()
    b = MicroBatcher(eng, max_batch=4, max_wait_ms=0.0, max_inflight=1)
    await b.start()
    try:
        row = np.zeros(4, np.float32)
        # Deadline: block the device so the queue backs up past the
        # budget; the collector must fail the expired entry with
        # DeadlineExceeded (504), not serve it late.
        eng.gate.clear()
        t_block = asyncio.create_task(b.submit(row))  # occupies the slot
        await asyncio.sleep(0.05)
        t_late = asyncio.create_task(b.submit(row, deadline_ms=10))
        await asyncio.sleep(0.1)  # budget passes while queued
        eng.gate.set()
        with pytest.raises(DeadlineExceeded):
            await asyncio.wait_for(t_late, 10)
        assert b.deadline_expired == 1
        await asyncio.wait_for(t_block, 10)
        # Drain: sheds while draining.
        await b.drain(1.0)
        with pytest.raises(OverloadedError):
            await b.submit(row)
        assert b.shed_draining == 1
    finally:
        await b.stop()


# ---------------------------------- the mid-admission leak window (pinned)


async def test_admission_install_fault_spares_running_batch():
    """THE r12 leak-window pin: alloc-then-raise during the admission
    install leaves kv_pages_in_use at its pre-admission value, the
    rejected joiner gets a clean terminal error, and the running
    batch streams on token-identical to an unfaulted run."""
    # One 64-token page covers the runner's whole cache window, so
    # kv_pages_in_use is CONSTANT for the batch's lifetime — the
    # pre-admission value is deterministic, not a racing snapshot.
    solo = _engine(kv_page_size=64)
    ref = solo.generate_text("abcdef", max_new_tokens=24)
    eng = _engine(kv_page_size=64, max_batch=4)
    await eng.start()
    try:
        a = await eng.submit("abcdef", max_new_tokens=24, stream=True)
        first = await a.queue.get()
        pre = eng.kv_pages_in_use
        assert pre == 1  # the runner's single page
        # table_install raises AFTER the joiner's page allocation
        # (alloc-then-raise); the decode delay keeps the running
        # batch alive across the assertion window.
        faults.arm("table_install:raise,decode:every=1:delay=0.02")
        b = await eng.submit("xyz", max_new_tokens=4)
        _, berr = await _collect(b)
        assert isinstance(berr, faults.InjectedFault), berr
        # Pre-admission refcount restored WHILE the batch still runs
        # (the joiner's freshly-mapped page went back). The release
        # runs on the decode thread AFTER b's terminal frame — wait
        # on the counter instead of racing it (mlapi-lint MLA009,
        # caught r19: the same class as the r17/r18 hand-de-flakes).
        deadline = asyncio.get_running_loop().time() + 30.0
        while eng.kv_pages_in_use != pre:
            assert (
                asyncio.get_running_loop().time() < deadline
            ), eng.kv_pages_in_use
            await asyncio.sleep(0.005)
        assert eng.kv_pages_in_use == pre
        toks, aerr = await _collect(a)
        assert aerr is None
        assert first["token_ids"] + toks == ref["token_ids"]
        await _settle(eng)
        _pool_baseline(eng)
        faults.disarm()
        c = await eng.submit("xyz", max_new_tokens=4)
        toks, cerr = await _collect(c)
        assert cerr is None and len(toks) == 4
    finally:
        await eng.stop()


async def test_pool_exhausted_mid_admission_maps_to_503():
    """An injected PagePoolExhausted on the admission path reaches
    the client as 503 (capacity, not a 500) — via the in-band
    terminal-frame mapping."""
    eng = _engine(kv_page_size=4)
    app = build_app(eng)
    await app.startup()
    try:
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://t"
        ) as client:
            faults.arm("pool_alloc:raise")
            r = await client.post(
                "/generate", json={"text": "hi", "max_new_tokens": 4}
            )
            assert r.status_code == 503, r.text
            assert "retry-after" in r.headers
            faults.disarm()
            r = await client.post(
                "/generate", json={"text": "hi", "max_new_tokens": 4}
            )
            assert r.status_code == 200
    finally:
        await app.shutdown()


# ------------------------------------------------------- fault matrix


_SPEC_MODEL = get_model("gpt_lm", **CFG)
_SPEC_PARAMS = _SPEC_MODEL.init(jax.random.key(1))


def _matrix_engine() -> TextGenerationEngine:
    """One engine shape that exercises EVERY injection point: paged
    (pool_alloc / table_install), a draft (spec_verify), small
    prompt buckets so a 20-token prompt takes the chunked-prefill
    path (prefill_chunk), chunk=2 decode (decode), the async
    collector (collector_pop), streams (stream_push), and the host
    tier (tier_spill / tier_restore — the prefix evict/restore leg
    in ``_matrix_traffic``)."""
    return TextGenerationEngine(
        _SPEC_MODEL, _SPEC_PARAMS, tokenizer=ByteTokenizer(),
        chunk=2, fused_single=False, kv_page_size=4, max_batch=4,
        prompt_buckets=(4, 8), draft=(_SPEC_MODEL, _SPEC_PARAMS),
        spec_k=3, kv_tier_bytes=1 << 22,
    )


async def _submit_or_outcome(eng, *a, **kw):
    """submit() may itself fail terminally under an armed fault (a
    dead collector raises RuntimeError; shedding raises
    OverloadedError) — both are WELL-FORMED outcomes, not hangs."""
    try:
        return await eng.submit(*a, **kw), None
    except (RuntimeError, OverloadedError) as e:
        return None, ([], e)


async def _matrix_traffic(eng, tier_leg: bool = False) -> list:
    """Deterministic traffic hitting every seam; returns each
    stream's (tokens, terminal) — raising only on a HANG (wait_for),
    never on an in-band error frame. ``tier_leg`` adds the prefix
    evict/restore rounds that cross the tier_spill / tier_restore
    seams (enabled only for those points — the other 14 cases keep
    the r12 traffic and the r12 runtime)."""
    outcomes = []
    # Solo greedy → speculation engages (spec_verify); streams push.
    g1, out = await _submit_or_outcome(
        eng, "spec ab", max_new_tokens=10, stream=True
    )
    outcomes.append(out if g1 is None else await _collect(g1))
    # Long prompt → chunked prefill; a mid-batch joiner → admission
    # install (+ interleaved window when the long one runs).
    g2, out = await _submit_or_outcome(
        eng, "abcdefghijklmnopqrst", max_new_tokens=8, stream=True
    )
    if g2 is None:
        outcomes.append(out)
    else:
        first = await asyncio.wait_for(g2.queue.get(), 30)
        g3, out3 = await _submit_or_outcome(
            eng, "join", max_new_tokens=4
        )
        if isinstance(first, Exception) or first is None:
            # The stream's FIRST frame was already terminal.
            outcomes.append(
                ([], first if isinstance(first, Exception) else None)
            )
        else:
            t2, e2 = await _collect(g2)
            outcomes.append((first["token_ids"] + t2, e2))
        outcomes.append(
            out3 if g3 is None else await _collect(g3)
        )
    # Prefix evict/restore over the host tier: the entry's page set
    # spills on eviction (tier_spill) and the re-arrival restores it
    # from the blob (tier_restore); a raise at either point must
    # degrade to the pre-tier discard / cold path with the stream
    # still completing. The final evict returns the pool to the
    # page-conservation baseline (prefix entries hold pages by
    # design; a baseline sweep is not a leak).
    if tier_leg and eng.pool is not None and eng.kv_tier is not None:
        g4, out4 = await _submit_or_outcome(
            eng, " q", max_new_tokens=4, prefix="matrix sys"
        )
        outcomes.append(out4 if g4 is None else await _collect(g4))
        await _settle(eng, 10)
        eng.pool.evict_idle(1)           # spill seam
        g5, out5 = await _submit_or_outcome(
            eng, " q", max_new_tokens=4, prefix="matrix sys"
        )
        outcomes.append(out5 if g5 is None else await _collect(g5))
        await _settle(eng, 10)
        eng.pool.evict_idle(1)           # back to the page baseline
    return outcomes


# The engine-lifecycle seams: this matrix drives ENGINE traffic, so
# the router↔replica hop (`router_forward`, which only a router in
# front of replica servers crosses) has its own matrix —
# test_router.py pins raise-at-submit (single failover, no duplicate
# submit), raise-mid-stream (well-formed terminal frame), and delay
# (slowed, byte-complete); test_router_e2e.py pins page-refcount
# conservation on real paged replicas under the same faults. The
# unit-dispatch seam (`sched_unit`, crossed only with --scheduler on)
# likewise has its own matrix in test_scheduler.py: a raise kills one
# lane with pages conserved while the other lane streams on.
_ENGINE_POINTS = tuple(
    p for p in faults.POINTS
    if p not in (
        "router_forward", "sched_unit",
        # The peer-fetch hop (crossed only with --kv-peer-fetch on a
        # hinted replica) has its matrix in test_kv_peer.py: a raise
        # at either point degrades to the cold prefill with pages
        # conserved and streams completing.
        "peer_fetch", "peer_serve",
        # The disaggregation push seams (crossed only on role-split
        # replicas) have their matrix in test_kv_push.py: a raise at
        # either point fails the transfer and the decode replica
        # cold-prefills with kv_pages_in_use conserved on both ends.
        "kv_push_send", "kv_push_recv",
        # The adapter seams (crossed only with --adapter-slots > 0 and
        # a request naming a tenant) have their matrix in
        # test_lora_serving.py: a fetch raise is a counted miss / 404,
        # an install raise rejects the joiner with the pool intact.
        "adapter_fetch", "adapter_install",
    )
)


@pytest.mark.parametrize("action", ["raise", "delay=0.02"])
@pytest.mark.parametrize("point", _ENGINE_POINTS)
async def test_fault_matrix_conservation(point, action):
    """The tentpole invariant sweep: arm each registered point with
    each action, run traffic over every seam, and assert the
    conservation contract — streams TERMINATE (frame or sentinel,
    never a hang), pages/refcounts return to baseline, and the engine
    serves a fresh request afterwards."""
    eng = _matrix_engine()
    await eng.start()
    try:
        faults.arm(f"{point}:{action}")
        outcomes = await _matrix_traffic(
            eng, tier_leg=point.startswith("tier_")
        )
        if action == "delay=0.02":
            # Delays slow, never break: every stream must COMPLETE.
            for toks, err in outcomes:
                assert err is None, (point, err)
            assert eng.faults_injected > 0
        faults.disarm()
        await _settle(eng, 10)
        _pool_baseline(eng)
        # The engine accepts new work afterward (a dead collector —
        # the collector_pop kill — recovers via stop/start).
        if eng._task.done():
            await eng.stop()
            await eng.start()
        fresh = await eng.submit("after", max_new_tokens=4)
        toks, err = await _collect(fresh)
        assert err is None and len(toks) == 4, (point, action, err)
    finally:
        faults.disarm()
        await eng.stop()


@pytest.mark.heavy
async def test_faulted_admission_churn_soak():
    """Soak: repeated faulted admission churn (every 3rd page alloc
    raises) over many rounds must keep the pool conserved and the
    engine serving — the leak-window fix under sustained fire."""
    eng = _engine(kv_page_size=4, max_batch=4, prompt_buckets=(4, 8))
    await eng.start()
    try:
        for round_i in range(12):
            faults.arm("pool_alloc:every=3:times=2")
            gens = [
                await eng.submit(
                    f"soak {round_i} {i}", max_new_tokens=6,
                    stream=bool(i % 2),
                )
                for i in range(3)
            ]
            for g in gens:
                await _collect(g)  # frame or sentinel; hang = fail
            faults.disarm()
            await _settle(eng, 10)
            _pool_baseline(eng)
        out = await eng.submit("final", max_new_tokens=4)
        toks, err = await _collect(out)
        assert err is None and len(toks) == 4
    finally:
        faults.disarm()
        await eng.stop()


# ------------------------------------------------------- harness unit


def test_fault_spec_grammar():
    rules = faults.parse("pool_alloc:after=3:raise,decode:every=5:delay=0.05")
    assert rules["pool_alloc"].after == 3
    assert rules["pool_alloc"].times == 1  # raise defaults one-shot
    assert rules["decode"].every == 5
    assert rules["decode"].times is None  # delay defaults unlimited
    with pytest.raises(ValueError):
        faults.parse("nonsense:raise")
    with pytest.raises(ValueError):
        faults.parse("decode:bogus=1")
    with pytest.raises(ValueError):
        # after+every in one clause: due() honors a single trigger, so
        # silently preferring one would fire on a schedule the
        # operator did not write — loud instead.
        faults.parse("decode:after=10:every=5:delay=0.05")


def test_fault_triggers_are_call_counted():
    faults.arm("decode:after=2:raise")
    faults.fire("decode")
    faults.fire("decode")  # calls 1-2 pass
    with pytest.raises(faults.InjectedFault):
        faults.fire("decode")  # call 3 fires
    faults.fire("decode")  # one-shot: spent
    assert faults.injected_count() == 1
    faults.disarm()
    faults.fire("decode")  # disarmed: free


def test_disarmed_is_zero_cost_noop():
    faults.disarm()
    for p in faults.POINTS:
        faults.fire(p)
    assert faults.injected_count() == 0


def test_metrics_export_robustness_counters():
    """The /metrics names the dashboards key on exist from request
    zero (not only after the first failure)."""
    eng = _engine(kv_page_size=4)
    app = build_app(eng)

    async def scrape():
        await app.startup()
        try:
            transport = httpx.ASGITransport(app=app)
            async with httpx.AsyncClient(
                transport=transport, base_url="http://t"
            ) as client:
                return (await client.get("/metrics")).json()
        finally:
            await app.shutdown()

    snap = asyncio.run(scrape())
    for name in (
        "generate.shed_queue_full",
        "generate.shed_deadline_infeasible",
        "generate.shed_draining",
        "generate.deadline_expired_queued",
        "generate.deadline_expired_prefill",
        "generate.deadline_expired_decode",
        "generate.brownout_spec_suppressed",
        "generate.brownout_tokens_clamped",
        "generate.faults_injected",
    ):
        assert snap["counters"][name] == 0, name
    assert snap["gauges"]["generate.draining"] == 0


def test_streams_identical_with_faults_disarmed():
    """The acceptance guard in miniature: the robustness layer adds
    ZERO behavior with faults disarmed and no deadline set — greedy
    streams are byte-identical across paged × deadline-checking
    engines (the full {model} × {quant} × {impl} × {layout} identity
    rides the existing suites, which run on this same code)."""
    base = _engine()
    paged = _engine(kv_page_size=4)
    a = base.generate_text("identity", max_new_tokens=16)
    b = paged.generate_text("identity", max_new_tokens=16)
    assert a["token_ids"] == b["token_ids"]
    # A generous deadline changes nothing either.
    c = paged.generate_text(
        "identity", max_new_tokens=16, deadline_ms=600_000
    )
    assert c["token_ids"] == a["token_ids"]
