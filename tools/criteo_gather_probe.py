"""Decide the SURVEY §7 criteo Pallas-gather question with a
decomposed on-chip profile (VERDICT r04 "Next" #6).

The committed cost analysis says the criteo-widedeep step is
memory-bound (0.69 flops/byte; 2.0 ms HBM roofline vs 13.0 ms
measured on-chip in r04). The 6.5x gap has two candidate owners:

* the EMBEDDING GATHER — 26 tables of 100k x 16 rows read at
  scattered 64-byte granularity (plus the backward's scatter-add),
  which cannot stream at peak HBM bandwidth, or
* everything else (optimizer sweep over the 170 MB of tables, MLP,
  host input feed).

This probe separates them on the attached backend, synced by scalar
readback (never ``block_until_ready`` through the tunnel):

1. ``gather_random``     — the real access pattern: random ids into
                           [F, V, D] tables, forward gather only.
2. ``gather_sequential`` — iota ids (coalesced rows): the same
                           program with a streamable pattern; the
                           random-vs-sequential ratio IS the
                           scatter penalty.
3. ``gather_grad``       — forward + scatter-add backward, random
                           ids (training's actual embedding cost).
4. ``apply_fwd``         — the full model forward.
5. ``train_step_dense`` / ``train_step_sparse`` — the full jitted
                           step, dense-recsys control vs the preset's
                           TRUE-sparse embedding update
                           (train/sparse_embed.py), interleaved, on
                           THIS attach's topology (recorded with
                           device count; compare only same-topology
                           numbers).

Decision rule, recorded with the output: a Pallas gather kernel can
only help the portion of (3) above the streaming floor implied by
(2). If stages (2)+(3) are a small fraction of (5), the step is
bound elsewhere (tables optimizer sweep / MLP) and the kernel is
DECLINED with this profile as the evidence; if (3) dominates (5) and
sits far above (2)'s floor, the kernel is justified and this file's
numbers size its budget.

Runs in ~1 min on-chip; CPU runs exercise the harness only (the
ratios are meaningless off-TPU). Emits one JSON line per stage plus
a summary. Part of the alive-window harvest queue.
"""

from __future__ import annotations

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# Match the criteo-widedeep preset's operating point (batch_size
# 1024, config.py) so train_step re-measures the committed 13.0 ms
# basis rather than a 4x workload.
B, F, V, D = 1024, 26, 100_000, 16
REPS = 50  # sub-ms stages: amortize transport/dispatch overheads


def main() -> int:
    from bench import _choose_backend

    probe, note, env = _choose_backend()
    os.environ.update(env)
    from mlapi_tpu.utils.platform import apply_platform_override

    apply_platform_override()

    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    print(json.dumps({"stage": "backend", "backend": backend,
                      "batch": B, "note": note}), flush=True)

    key = jax.random.key(0)
    tables = jax.random.normal(key, (F, V, D), jnp.float32)
    ids_rand = jax.random.randint(jax.random.key(1), (B, F), 0, V,
                                  jnp.int32)
    ids_seq = (
        jnp.arange(B, dtype=jnp.int32)[:, None]
        + jnp.arange(F, dtype=jnp.int32)[None, :]
    ) % V
    feat = jnp.arange(F, dtype=jnp.int32)[None, :]

    @jax.jit
    def gather(t, ids):
        return t[feat, ids]  # [B, F, D]

    @jax.jit
    def gather_grad(t, ids):
        def loss(tt):
            return jnp.sum(tt[feat, ids] ** 2)

        return jax.grad(loss)(t)

    def rtt_of(readback) -> float:
        """Best-of-2 scalar-readback round trip on a pre-warmed
        value — the train bench's deduction pattern
        (train/bench.py): the final sync pays one transport RTT
        that must not be attributed to the device."""
        rtt = float("inf")
        for _ in range(2):
            t1 = time.perf_counter()
            float(readback())
            rtt = min(rtt, time.perf_counter() - t1)
        return rtt

    def timed(fn, *args, sync):
        fn(*args)  # compile + warm
        out = fn(*args)
        float(sync(out))  # settle
        rtt = rtt_of(lambda: sync(out))
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = fn(*args)
        # ONE scalar readback syncs the whole chain (dispatches
        # pipeline; the readback is the only true barrier through
        # the tunnel) — deduct its RTT from the window.
        float(sync(out))
        total = max(time.perf_counter() - t0 - rtt, 1e-9)
        return total / REPS

    res = {}
    sync = lambda o: o.ravel()[0]  # noqa: E731
    # Read+write byte models per stage: the gathers read B*F rows and
    # write a [B, F, D] output; the grad additionally materializes
    # the FULL dense [F, V, D] table cotangent (zero-init + scatter-
    # add writes) — the dominant traffic, ~25x the forward's.
    row_bytes = B * F * D * 4
    table_bytes = F * V * D * 4
    stage_bytes = {
        "gather_random": 2 * row_bytes,
        "gather_sequential": 2 * row_bytes,
        "gather_grad": 2 * row_bytes + 2 * table_bytes,
    }
    for stage, fn, ids in (
        ("gather_random", gather, ids_rand),
        ("gather_sequential", gather, ids_seq),
        ("gather_grad", gather_grad, ids_rand),
    ):
        dt = timed(fn, tables, ids, sync=sync)
        res[stage] = {
            "ms": round(dt * 1e3, 3),
            "bytes_model_gb": round(stage_bytes[stage] / 1e9, 3),
            "attained_gb_s": round(stage_bytes[stage] / 1e9 / dt, 2),
        }
        print(json.dumps({"stage": stage, **res[stage]}), flush=True)

    # Full model + train step via the bench's own machinery.
    from mlapi_tpu.config import get_preset
    from mlapi_tpu.datasets import get_dataset
    from mlapi_tpu.models import get_model

    cfg = get_preset("criteo-widedeep")
    model = get_model(cfg.model, **cfg.model_kwargs)
    splits = get_dataset(cfg.dataset, **cfg.dataset_kwargs)
    x = jnp.asarray(splits.x_train[:B], jnp.float32)
    y = jnp.asarray(splits.y_train[:B], jnp.int32)
    params = model.init(jax.random.key(2))

    apply_jit = jax.jit(model.apply)
    dt = timed(apply_jit, params, x, sync=lambda o: o.ravel()[0])
    res["apply_fwd"] = {"ms": round(dt * 1e3, 3)}
    print(json.dumps({"stage": "apply_fwd", **res["apply_fwd"]}),
          flush=True)

    from mlapi_tpu.train.loop import _make_optimizer, make_train_step
    from mlapi_tpu.train.sparse_embed import make_sparse_recsys_step

    def build_step(kind):
        p0 = model.init(jax.random.key(2))
        if kind == "sparse":
            base = _make_optimizer("adamw", cfg.learning_rate)
            init, step = make_sparse_recsys_step(
                model, base, cfg.learning_rate
            )
            return p0, init(p0), step
        tx = _make_optimizer("recsys-adamw", cfg.learning_rate,
                             model=model, params=p0)
        return p0, tx.init(p0), make_train_step(model.apply, tx)

    # Dense control vs the preset's TRUE-sparse step, INTERLEAVED
    # (this box's absolute throughput drifts; the ratio is the
    # result). params/opt_state are DONATED: chained runs, one
    # scalar sync per window.
    steps = {"train_step_dense": build_step("dense"),
             "train_step_sparse": build_step("sparse")}
    rtts = {}
    for k, (p0, s0, step) in steps.items():
        p, s, warm_loss = step(p0, s0, x, y)  # compile + warm
        float(warm_loss)  # settle: the warm step must NOT leak in
        rtts[k] = rtt_of(lambda: warm_loss + 0)
        steps[k] = (p, s, step)
    totals = {k: 0.0 for k in steps}
    executed = 4 * (REPS // 4)  # windows x steps actually run
    for _ in range(4):
        for k in steps:
            p, s, step = steps[k]
            t0 = time.perf_counter()
            loss = None
            for _ in range(REPS // 4):
                p, s, loss = step(p, s, x, y)
            float(loss)
            totals[k] += max(
                time.perf_counter() - t0 - rtts[k], 1e-9
            )
            steps[k] = (p, s, step)
    # Single-process topology: no mesh here — compare only against
    # same-topology numbers, never across (the committed bench basis
    # ran the bench's own topology).
    for k in totals:
        res[k] = {"ms": round(totals[k] / executed * 1e3, 3),
                  "devices": len(jax.devices()),
                  "mesh": None,
                  "rtt_deducted_ms": round(rtts[k] * 1e3, 2)}
        print(json.dumps({"stage": k, **res[k]}), flush=True)
    res["train_step"] = res["train_step_dense"]  # summary basis
    print(json.dumps({
        "stage": "sparse_speedup",
        "x": round(res["train_step_dense"]["ms"]
                   / res["train_step_sparse"]["ms"], 2),
    }), flush=True)

    embed_ms = res["gather_grad"]["ms"]
    step_ms = res["train_step"]["ms"]
    floor_ms = res["gather_sequential"]["ms"]
    verdict = {
        "embed_fraction_of_step": round(embed_ms / step_ms, 3)
        if step_ms else None,
        "scatter_penalty_vs_sequential": round(
            res["gather_random"]["ms"] / floor_ms, 2
        ) if floor_ms else None,
        "kernel_justified_if": "embed_fraction large AND penalty >> 1",
        "backend": backend,
    }
    print(json.dumps({"stage": "summary", **verdict}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
