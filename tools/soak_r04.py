"""Round-4 whole-system soak: every serving capability at once, with
byte-exactness checks.

Mixed traffic — plain greedy, sampled (seeded), prefix-cached, NDJSON
streams, a fraction cancelled mid-stream — against ONE engine with the
fast paths on (fused-chunk widths, solo fused default) so the soak
exercises fused-width decode chunks, plain chunked streams,
continuous admission, and the prefix KV path in the same run. Every
completed non-stream response and every completed stream's final ids
must be byte-identical to a solo reference run of the same request.

Run on CPU anywhere: ``python tools/soak_r04.py``; prints one JSON
summary line. Exit 0 = zero mismatches.
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from mlapi_tpu.models import get_model
    from mlapi_tpu.serving.engine import TextGenerationEngine
    from mlapi_tpu.text import ByteTokenizer

    cfg = dict(
        vocab_size=260, hidden_size=48, num_layers=2, num_heads=4,
        max_positions=192, compute_dtype="float32",
    )
    model = get_model("gpt_lm", **cfg)
    params = model.init(jax.random.key(0))
    eng = TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), chunk=4,
        max_batch=4,
    )
    ref = TextGenerationEngine(
        model, params, tokenizer=ByteTokenizer(), chunk=4,
        fused_single=False,
    )

    prefixes = ["the quick brown fox. ", "pack my box with jugs. "]
    rng = random.Random(11)
    specs = []
    for i in range(96):
        kind = rng.choice(["plain", "plain", "sampled", "prefix", "stream"])
        specs.append({
            "kind": kind,
            "text": rng.choice(["alpha bravo", "charlie delta",
                                "echo foxtrot golf", "hotel india"]),
            "n": rng.choice([4, 8, 12, 20]),
            "temp": 0.8 if kind == "sampled" else 0.0,
            "seed": i,
            "prefix": rng.choice(prefixes) if kind == "prefix" else None,
            "stream": kind == "stream",
            "cancel": kind == "stream" and rng.random() < 0.15,
        })

    await eng.start()
    mismatches = 0
    cancelled = 0
    try:
        async def one(s):
            nonlocal mismatches, cancelled
            gen = await eng.submit(
                s["text"], max_new_tokens=s["n"], temperature=s["temp"],
                seed=s["seed"], prefix=s["prefix"], stream=s["stream"],
            )
            got: list[int] = []
            n_items = 0
            while True:
                item = await gen.queue.get()
                if item is None:
                    break
                if isinstance(item, Exception):
                    raise item
                got.extend(item["token_ids"])
                n_items += 1
                if s["cancel"] and n_items == 1:
                    gen.cancel()
                    cancelled += 1
                    return
            want = ref.generate_text(
                s["text"], max_new_tokens=s["n"], temperature=s["temp"],
                seed=s["seed"], prefix=s["prefix"],
            )["token_ids"]
            if got != want:
                mismatches += 1

        # Staggered waves so batches form at every size and admission
        # happens mid-flight.
        tasks = []
        for i, s in enumerate(specs):
            tasks.append(asyncio.create_task(one(s)))
            if i % 7 == 0:
                await asyncio.sleep(0.05)
        await asyncio.gather(*tasks)
    finally:
        await eng.stop()

    summary = {
        "requests": len(specs),
        "cancelled_midstream": cancelled,
        "mismatches": mismatches,
        "batch_calls": eng.batch_calls,
        "fused_calls": eng.fused_calls,
        "chunk_calls": eng.chunk_calls,
        "admitted": eng.admitted,
        "compactions": eng.compactions,
        "prefix_hits": eng.prefix_hits,
        "prefix_misses": eng.prefix_misses,
    }
    print(json.dumps(summary))
    return 0 if mismatches == 0 else 1


if __name__ == "__main__":
    raise SystemExit(asyncio.run(main()))
