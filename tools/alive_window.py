"""Harvest every queued on-TPU measurement the moment the chip wakes.

The tunneled v5e wedges for hours between alive windows (~4 h blocks,
BENCH_DIAG.json records each probe), so hardware measurements must be
harvested greedily and in priority order the moment a probe succeeds —
waiting costs a round (the r01/r02 lesson). This driver runs the
round-4 measurement queue:

1. ``bench.py``            — /predict north star (refreshes the record)
2. ``bench.py --spec``     — single-stream ladder: the engine's fused
                             fast path is the round-4 headline
3. ``bench.py --generate`` — HTTP /generate (non-stream rides fused now)
4. MFU sweep               — sst2-bert b32/b128, flash preset
                             (+ roofline block per run)
5. criteo roofline         — attained-vs-peak HBM bandwidth: the
                             committed basis for the Pallas-gather call
6. ``requires_tpu`` tests  — kernels on real Mosaic lowering

Each stage runs in a subprocess with a hard timeout (a mid-window
wedge must not strand the rest) and appends its JSON to
``ALIVE_r04.jsonl``; on-TPU bench results also persist to
``TPU_RESULTS.json`` via bench.finish/record_tpu_result.

Usage:  python tools/alive_window.py [--skip-probe]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "ALIVE_r05.jsonl")


def log(stage: str, payload) -> None:
    rec = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "stage": stage,
        "result": payload,
    }
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(f"[{rec['ts']}] {stage}: "
          f"{json.dumps(payload)[:200]}", flush=True)


class StageTimeout(Exception):
    pass


def run(stage: str, cmd: list[str], timeout_s: float, env=None) -> bool:
    """One stage in its own PROCESS GROUP with a hard timeout.
    ``subprocess.run`` would SIGKILL only the direct child: bench.py
    Popens an HTTP server with inherited stdio, so a wedged grandchild
    would keep the capture pipes open (communicate() blocks forever)
    AND keep the single chip attached — killpg reaps the whole tree.
    Raises :class:`StageTimeout` so the caller can re-probe instead of
    marching the rest of the queue into guaranteed timeouts."""
    import signal

    t0 = time.time()
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=ROOT, env=dict(os.environ, **(env or {})),
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()
        log(stage, {"error": f"timeout >{timeout_s}s (wedged mid-window?)"})
        raise StageTimeout(stage) from None
    dur = round(time.time() - t0, 1)
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    payload: dict = {"rc": proc.returncode, "duration_s": dur}
    for ln in reversed(lines):
        try:
            payload["json"] = json.loads(ln)
            break
        except ValueError:
            continue
    if "json" not in payload and lines:
        payload["tail"] = lines[-3:]
    if proc.returncode != 0:
        payload["stderr_tail"] = stderr[-500:]
    log(stage, payload)
    return proc.returncode == 0


def main() -> int:
    sys.path.insert(0, ROOT)
    from bench import probe_device

    if "--skip-probe" not in sys.argv:
        probe, diag = probe_device(retries=1, timeout_s=90)
        if probe is None or probe.get("backend") != "tpu":
            print("chip not alive; nothing to harvest", flush=True)
            return 1
        log("probe", probe)

    py = sys.executable
    # Priority order; generous-but-hard timeouts, and after ANY stage
    # timeout a cheap 90s re-probe decides whether the window is over
    # — a dead chip must not burn the remaining stages' full timeouts
    # (~3h of a ~4h window).
    stages = [
        ("predict_north_star", [py, "bench.py"], 900, None),
        ("spec_ladder", [py, "bench.py", "--spec"], 1800, None),
        ("generate_http", [py, "bench.py", "--generate"], 1200, None),
        *[
            (f"mfu_sst2_bert_b{b}_flash",
             [py, "-m", "mlapi_tpu.train", "--bench", "--preset",
              "sst2-bert", "--bench-steps", "20",
              "--bench-batch", str(b)],
             1800, None)
            for b in (32, 128)
        ],
        # Full-attention control at b128: is the kernel the MFU lever?
        ("mfu_sst2_bert_b128_full",
         [py, "-m", "mlapi_tpu.train", "--bench", "--preset",
          "sst2-bert", "--bench-steps", "20", "--bench-batch", "128",
          "--bench-attn", "full"],
         1800, None),
        # The preset now defaults to the TRUE-sparse embedding update
        # (8.9x step time on CPU, exact-equivalence-tested); the
        # dense-control config is the same run with recsys-adamw so
        # the on-HBM ratio is measured, not inferred.
        ("criteo_roofline",
         [py, "-m", "mlapi_tpu.train", "--bench", "--preset",
          "criteo-widedeep", "--bench-steps", "30"],
         1200, None),
        ("criteo_roofline_dense_control",
         [py, "-m", "mlapi_tpu.train", "--bench", "--config",
          "tools/criteo_dense_control.yaml", "--bench-steps", "30"],
         1200, None),
        # r05: the decomposed gather profile that DECIDES the SURVEY
        # §7 Pallas-gather question (embed fraction of step, random-
        # vs-sequential scatter penalty, attained GB/s per stage).
        ("criteo_gather_probe",
         [py, "tools/criteo_gather_probe.py"], 900, None),
        # r05: the sharp-target speculation pair, served on the chip —
        # the attach where one-dispatch economics actually pay (CPU
        # canary is loop-overhead-bound at this model size). Trains
        # the 700-step pair on-TPU (minutes), then measures fused
        # plain vs fused spec through the engine.
        ("spec_sharp_target",
         [py, "tools/spec_sharp_target.py",
          "--workdir", "/tmp/spec_sharp_tpu"],
         3600, None),
        ("requires_tpu_tests",
         [py, "-m", "pytest", "tests/", "-m", "requires_tpu", "-q"],
         1800, {"MLAPI_TPU_TESTS": "1"}),
    ]
    for stage, cmd, timeout_s, env in stages:
        try:
            run(stage, cmd, timeout_s, env)
        except StageTimeout:
            probe, _ = probe_device(retries=1, timeout_s=90)
            if probe is None or probe.get("backend") != "tpu":
                log("abort", {
                    "reason": "chip wedged mid-window; remaining "
                              "stages skipped",
                })
                return 1
            # Chip still answers: the stage itself misbehaved — keep
            # harvesting the rest.
    print("window harvest complete; see", OUT, flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
