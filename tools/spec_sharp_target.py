"""Prove speculation against the repo's SHARPEST target (VERDICT r04
"Next" #2).

The r04 acceptance matrix showed the flagship numbers (0.73-0.78
sampled, 0.83 served) ride an undertrained 300-step target; against
the better 700-step target the same-capacity draft collapsed to
0.37-0.47. This experiment does what the matrix's own capacity rule
("the draft must scale WITH the target") prescribes, end to end:

1. Train the best target the corpus supports: docs-llama at 700 steps
   (the r04 quality anchor — 0.478 next-token on the then-live
   corpus; re-anchored here on the FROZEN snapshot).
2. Train capacity-scaled llama drafts DISTILLED from that target
   (T=1, mostly-teacher alpha — the matrix's best sampled-acceptance
   recipe), at increasing capacity until sampled acceptance >= 0.6.
3. Measure library-level acceptance STATISTICALLY (VERDICT r05
   "Next" #3 hardening): 25 prompts x 256 tokens per rung, k=4,
   greedy `speculative_generate` and sampled `speculative_sample` at
   T=0.8 — per-prompt acceptance fractions reduced to mean ± 95% CI,
   so a frontier delta smaller than the error bar can't be read as a
   capacity signal (the r05 5x64 numbers had no bars at all).
4. MEASURE the draft/target per-step cost ratio c — interleaved
   A/B within one window (this box's absolute wall-clock drifts
   ±25-30% across days; only interleaved ratios compare) — instead
   of assuming the parameter-count ratio. c is what the break-even
   acceptance depends on: a k-round costs ~(1 + k*c) target-steps
   and emits (1 + expected accepts), so the measured c decides
   whether a given acceptance PAYS.
5. Measure the served economics on this attach: engine fused plain
   vs fused speculative single-stream wall-clock, interleaved.

Usage:  python tools/spec_sharp_target.py [--workdir DIR] [--quick]
Emits one JSON line per stage; the final line is the summary
BASELINE.json `spec_sharp_target` republishes (with error bars).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# >= 25 prompts x >= 256 tokens per rung (VERDICT r05 "Next" #3): the
# old 5 x 64 frontier moved by more than its own (unreported) noise
# between recipes. Domain-flavoured prompts, like the corpus.
PROMPTS = [
    "The serving engine batches requests",
    "Checkpoints are committed when",
    "TPU programs compile once per",
    "Sharding follows the mesh",
    "The draft proposes tokens and",
    "The KV cache stores keys",
    "Decode reads the cache every",
    "A prefix entry is reused",
    "The collector forms a batch",
    "Admission happens at chunk",
    "The mesh axes name data",
    "Gradients reduce over the",
    "A fused program runs the",
    "The tokenizer maps bytes",
    "Training writes a manifest",
    "The warmup compiles every",
    "Quantized weights read as",
    "The flash kernel tiles the",
    "Ring attention rotates key",
    "Speculation verifies a block",
    "The optimizer state shards",
    "A bucket pads the prompt",
    "Metrics export counter and",
    "The scheduler drains the",
    "Positions shift by the pad",
]
N_TOKENS = 256
SPEC_K = 4

TARGET_KW = dict(
    vocab_size=260, hidden_size=128, num_layers=2, num_heads=4,
    # 320 positions: the longest prompt (~35 byte-tokens) plus the
    # 256-token measurement window (rotary positions extrapolate; the
    # model still trains on seq_len 128 windows like r05's).
    num_kv_heads=2, max_positions=320, compute_dtype="float32",
)
# Capacity x recipe ladder for the draft: params scale ~hidden^2 at
# fixed depth; h48/1L is the r04 flat-target draft (~1/10 params).
# Measured r05 frontier on the frozen corpus (greedy/sampled T=0.8):
#   h64 a=0.1@700: 0.413/0.322   h96 a=0.1@700: 0.439/0.420
#   h64 a=0.0@1400: 0.446/0.417  h96 a=0.0@1400: 0.288/0.204
# — capacity AND recipe saturate ~0.45; pure-KL over-distillation at
# h96 overfits teacher-forced train contexts and collapses on-policy.
DRAFT_LADDER = (
    dict(hidden_size=64, num_layers=2, distill_alpha=0.1, steps_x=1),
    dict(hidden_size=96, num_layers=2, distill_alpha=0.1, steps_x=1),
    dict(hidden_size=64, num_layers=2, distill_alpha=0.0, steps_x=2),
    dict(hidden_size=96, num_layers=2, distill_alpha=0.0, steps_x=2),
)


def log(stage: str, payload: dict) -> None:
    print(json.dumps({"stage": stage, **payload}), flush=True)


def train(name: str, out: str, *, steps: int, model: str, kw: dict,
          lr: float, distill_from: str | None = None,
          distill_alpha: float = 0.1) -> dict:
    """One training run through the product CLI (same path a user
    takes), on the frozen docs corpus (the dataset default)."""
    import yaml

    cfg = {
        "name": name, "model": model, "model_kwargs": kw,
        "dataset": "docs_text", "dataset_kwargs": {"seq_len": 128},
        "steps": steps, "batch_size": 64, "optimizer": "adamw",
        "learning_rate": lr, "eval_every": max(100, steps // 4),
    }
    if distill_from:
        cfg["distill_temperature"] = 1.0
        cfg["distill_alpha"] = distill_alpha
    ypath = os.path.join(os.path.dirname(out), f"{name}.yaml")
    with open(ypath, "w") as f:
        yaml.safe_dump(cfg, f)
    cmd = [sys.executable, "-m", "mlapi_tpu.train", "--config", ypath,
           "--out", out]
    if distill_from:
        cmd += ["--distill-from", distill_from]
    t0 = time.time()
    r = subprocess.run(cmd, capture_output=True, text=True, cwd=ROOT,
                       env=dict(os.environ), timeout=3600)
    if r.returncode != 0:
        raise RuntimeError(f"{name} failed: {r.stderr[-800:]}")
    line = [ln for ln in r.stdout.splitlines() if "test_accuracy" in ln]
    acc = None
    for ln in reversed(r.stdout.splitlines()):
        try:
            acc = json.loads(ln).get("test_accuracy")
            if acc is not None:
                break
        except ValueError:
            continue
    return {"seconds": round(time.time() - t0, 1),
            "next_token_acc": acc, "stdout_acc_line": line[-1:] or None}


def _mean_ci(xs) -> dict:
    """Mean ± 95% CI (normal approx over per-prompt fractions)."""
    import numpy as np

    xs = np.asarray(xs, np.float64)
    n = len(xs)
    mean = float(xs.mean())
    sem = float(xs.std(ddof=1) / np.sqrt(n)) if n > 1 else 0.0
    return {
        "mean": round(mean, 4),
        "ci95": round(1.96 * sem, 4),
        "n": n,
    }


def measure_acceptance(target_ck: str, draft_ck: str,
                       n_tokens: int = N_TOKENS) -> dict:
    """The matrix methodology, hardened: greedy + sampled(T=0.8)
    acceptance over ``len(PROMPTS)`` prompts x ``n_tokens`` tokens,
    k=4, library level — PER-PROMPT acceptance fractions reduced to
    mean ± 95% CI, plus the pooled rate (total accepted / drafted)
    the old tool reported."""
    import numpy as np

    from mlapi_tpu.checkpoint import load_checkpoint
    from mlapi_tpu.models import get_model
    from mlapi_tpu.ops.speculative import (
        speculative_generate, speculative_sample,
    )
    from mlapi_tpu.text import ByteTokenizer

    tok = ByteTokenizer()
    tp, tmeta = load_checkpoint(target_ck)
    dp, dmeta = load_checkpoint(draft_ck)
    target = get_model(tmeta.config["model"],
                       **tmeta.config["model_kwargs"])
    draft = get_model(dmeta.config["model"],
                      **dmeta.config["model_kwargs"])

    out = {}
    for mode in ("greedy", "sampled"):
        fracs = []
        acc_n = acc_d = 0
        for p in PROMPTS:
            ids = np.asarray(tok.token_ids(p), np.int32)[None, :]
            if mode == "greedy":
                _, stats = speculative_generate(
                    target, tp, draft, dp, ids,
                    max_new_tokens=n_tokens, k=SPEC_K,
                )
            else:
                _, stats = speculative_sample(
                    target, tp, draft, dp, ids,
                    max_new_tokens=n_tokens, k=SPEC_K,
                    temperature=0.8, seed=0,
                )
            acc_n += stats.accepted
            acc_d += stats.drafted
            if stats.drafted:
                fracs.append(stats.accepted / stats.drafted)
        out[mode] = {
            **_mean_ci(fracs),
            "pooled": round(acc_n / acc_d, 4) if acc_d else 0.0,
            "tokens_per_prompt": n_tokens,
        }
    return out


def measure_cost_ratio(target_ck: str, draft_ck: str,
                       reps: int = 7, steps: int = 32) -> dict:
    """MEASURE the draft/target per-decode-step cost ratio c —
    interleaved A/B within one window (absolute wall-clock on this
    box drifts ±25-30% across days; interleaved ratios compare) —
    instead of assuming the parameter-count ratio. Each rep times
    ``steps`` chained single-token decode dispatches per model
    against a warmed cache; c = draft_s / target_s per rep, reduced
    to mean ± 95% CI. Also reports the naive parameter ratio the old
    conclusion assumed, so the two are directly comparable."""
    import numpy as np

    from mlapi_tpu.checkpoint import load_checkpoint
    from mlapi_tpu.models import get_model
    from mlapi_tpu.models.gpt import decode_chunk_fn, prefill_fn
    from mlapi_tpu.text import ByteTokenizer

    import jax
    import jax.numpy as jnp

    tok = ByteTokenizer()
    built = {}
    for name, ck in (("target", target_ck), ("draft", draft_ck)):
        params, meta = load_checkpoint(ck)
        model = get_model(meta.config["model"],
                          **meta.config["model_kwargs"])
        bucket, total = 32, 32 + steps + 1
        row = np.full((1, bucket), tok.pad_id, np.int32)
        row[0, -4:] = [97, 98, 97, 98]
        kd = jnp.asarray(np.asarray(
            jax.random.key_data(jax.random.key(0)))[None])
        zt = jnp.zeros((1,), jnp.float32)
        z0 = jnp.zeros((1,), jnp.int32)
        o1 = jnp.ones((1,), jnp.float32)
        npj = jnp.asarray(np.asarray([bucket - 4], np.int32))
        _, cache = prefill_fn(model, total)(
            params, jnp.asarray(row), kd, zt, npj, z0, o1,
        )
        step_fn = decode_chunk_fn(model, 1)

        def run(model=model, params=params, cache=cache, npj=npj,
                kd=kd, zt=zt, z0=z0, o1=o1, step_fn=step_fn,
                bucket=bucket):
            # Donated-cache chained steps — the serving decode shape.
            c = jax.tree.map(lambda a: a + 0, cache)  # keep original
            tok_d = jnp.zeros((1,), jnp.int32)
            for i in range(steps):
                toks, c, tok_d = step_fn(
                    params, c, tok_d, jnp.int32(bucket + i), npj, zt,
                    kd, jnp.int32(0), z0, o1, jnp.int32(0),
                    jnp.int32(0),
                )
            jax.block_until_ready(toks)

        n_params = sum(
            int(np.prod(a.shape)) for a in jax.tree.leaves(params)
        )
        built[name] = (run, n_params)

    for run, _ in built.values():
        run()  # compile + warm off the clock
    ratios = []
    for _ in range(reps):
        t0 = time.perf_counter()
        built["target"][0]()
        t_t = time.perf_counter() - t0
        t0 = time.perf_counter()
        built["draft"][0]()
        t_d = time.perf_counter() - t0
        ratios.append(t_d / t_t)
    return {
        "c_measured": _mean_ci(ratios),
        "param_ratio": round(
            built["draft"][1] / built["target"][1], 4
        ),
        "steps_per_rep": steps,
        "note": "c = draft/target per-decode-step wall-clock, "
                "interleaved A/B reps; a k-round costs ~(1 + k*c) "
                "target-steps",
    }


SERVED_PROMPTS = PROMPTS[:5]
SERVED_TOKENS = 64  # comparable to the r04/r05 served rows


def measure_served(target_ck: str, draft_ck: str) -> dict:
    """Engine-level single-stream wall-clock: fused plain vs fused
    speculative (the serving quantity the acceptance number is a
    proxy for), plus served greedy acceptance from the engine's own
    counters. Kept at the r05 shape (5 prompts x 64 tokens) so the
    served rows stay comparable round over round; the RATIO is the
    result (interleaved reps)."""
    from mlapi_tpu.checkpoint import load_checkpoint
    from mlapi_tpu.models import get_model
    from mlapi_tpu.serving.engine import TextGenerationEngine
    from mlapi_tpu.text import ByteTokenizer

    def build(with_draft: bool) -> TextGenerationEngine:
        tp, tmeta = load_checkpoint(target_ck)
        kw = dict(
            tokenizer=ByteTokenizer(), fused_single=True,
            default_max_new_tokens=SERVED_TOKENS,
        )
        if with_draft:
            dp, dmeta = load_checkpoint(draft_ck)
            kw["draft"] = (
                get_model(dmeta.config["model"],
                          **dmeta.config["model_kwargs"]), dp,
            )
        target = get_model(tmeta.config["model"],
                           **tmeta.config["model_kwargs"])
        return TextGenerationEngine(target, tp, **kw)

    engines = {"fused_plain": build(False), "fused_spec": build(True)}
    for eng in engines.values():  # warm every bucket/tier off the clock
        for p in SERVED_PROMPTS:
            eng.generate_text(p, max_new_tokens=SERVED_TOKENS)
    # INTERLEAVED A/B reps: this box's absolute throughput drifts
    # (frequency/thread scheduling), so plain-vs-spec must be sampled
    # alternately within one window — the RATIO is the result.
    times = {k: 0.0 for k in engines}
    toks = {k: 0 for k in engines}
    for _ in range(3):
        for label, eng in engines.items():
            t0 = time.perf_counter()
            for p in SERVED_PROMPTS:
                r = eng.generate_text(p, max_new_tokens=SERVED_TOKENS)
                toks[label] += len(r["token_ids"])
            times[label] += time.perf_counter() - t0
    out = {}
    for label, eng in engines.items():
        out[label] = {
            "tokens_per_s": round(toks[label] / times[label], 1),
            # Which path actually served: the comparison is only
            # meaningful fused-vs-fused (one dispatch each).
            "fused_calls": eng.fused_calls,
            "fused_spec_calls": getattr(eng, "fused_spec_calls", 0),
            "chunk_calls": eng.chunk_calls,
        }
    eng = engines["fused_spec"]
    out["fused_spec"]["served_acceptance"] = round(
        eng.spec_accepted / eng.spec_drafted, 4
    ) if getattr(eng, "spec_drafted", 0) else None
    out["spec_speedup"] = round(
        out["fused_spec"]["tokens_per_s"]
        / out["fused_plain"]["tokens_per_s"], 3,
    )
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--quick", action="store_true",
                    help="100-step trainings (smoke-test the tool)")
    ap.add_argument("--target-steps", type=int, default=700)
    ap.add_argument("--draft-steps", type=int, default=700)
    args = ap.parse_args()

    # Pin the backend BEFORE any jax work or training subprocess: the
    # ambient platform here is the tunneled chip, which wedges for
    # hours — an unpinned run hangs at first dispatch with 0% CPU
    # (the documented trap). bench.py's probe decides chip-vs-CPU
    # with a hard timeout and hands back the env to propagate.
    from bench import _choose_backend

    probe, note, env = _choose_backend()
    os.environ.update(env)
    from mlapi_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    log("backend", {"backend": (probe or {}).get("backend", "cpu"),
                    "note": note})

    workdir = args.workdir or tempfile.mkdtemp(prefix="spec_sharp_")
    os.makedirs(workdir, exist_ok=True)
    tsteps = 100 if args.quick else args.target_steps
    dsteps = 100 if args.quick else args.draft_steps

    def cache_valid(ck: str, steps: int, kw: dict) -> bool:
        """Is the committed checkpoint the run we'd train now? Both
        the step count AND the model kwargs must match: a prior
        --quick run would masquerade as the 700-step target, and an
        r05 workdir holds max_positions=256 checkpoints that cannot
        serve this tool's 256-token measurement window."""
        mf = os.path.join(ck, "MANIFEST.json")
        if not os.path.exists(mf):
            return False
        try:
            meta = json.load(open(mf))
        except (ValueError, OSError):
            return False
        return (
            int(meta.get("step", -1)) == steps
            and meta.get("config", {}).get("model_kwargs") == kw
        )

    target_ck = os.path.join(workdir, "target")
    if not cache_valid(target_ck, tsteps, TARGET_KW):
        info = train("docs-llama-sharp", target_ck, steps=tsteps,
                     model="llama_lm", kw=TARGET_KW, lr=3e-4)
        log("target", info)
    else:
        log("target", {"cached": target_ck, "step": tsteps})

    n_tok = 64 if args.quick else N_TOKENS
    best = None
    frontier = {}
    for rung in DRAFT_LADDER:
        alpha = rung["distill_alpha"]
        steps = dsteps * rung["steps_x"]
        kw = dict(TARGET_KW, hidden_size=rung["hidden_size"],
                  num_layers=rung["num_layers"])
        name = (f"draft-h{rung['hidden_size']}L{rung['num_layers']}"
                + ("-pure" if alpha == 0.0 else ""))
        ck = os.path.join(workdir, name)
        if not cache_valid(ck, steps, kw):
            info = train(name, ck, steps=steps, model="llama_lm",
                         kw=kw, lr=1e-3, distill_from=target_ck,
                         distill_alpha=alpha)
            log(name, info)
        acc = measure_acceptance(target_ck, ck, n_tokens=n_tok)
        cost = measure_cost_ratio(target_ck, ck)
        log(f"{name}_acceptance", {**acc, "cost_ratio": cost})
        frontier[name] = {**acc, "cost_ratio": cost}
        best = {"draft": name, "ck": ck, **acc, "cost_ratio": cost}
        if acc["sampled"]["mean"] >= 0.6:
            break

    served = measure_served(target_ck, best["ck"])
    log("served", served)
    log("summary", {
        "target": f"docs-llama {tsteps}-step (frozen corpus)",
        "prompts": len(PROMPTS), "tokens_per_prompt": n_tok,
        **{k: v for k, v in best.items() if k != "ck"},
        "frontier": frontier,
        "served": served,
        "goal_sampled_ge_0.6": best["sampled"]["mean"] >= 0.6,
    })
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
