"""Prove speculation against the repo's SHARPEST target (VERDICT r04
"Next" #2).

The r04 acceptance matrix showed the flagship numbers (0.73-0.78
sampled, 0.83 served) ride an undertrained 300-step target; against
the better 700-step target the same-capacity draft collapsed to
0.37-0.47. This experiment does what the matrix's own capacity rule
("the draft must scale WITH the target") prescribes, end to end:

1. Train the best target the corpus supports: docs-llama at 700 steps
   (the r04 quality anchor — 0.478 next-token on the then-live
   corpus; re-anchored here on the FROZEN snapshot).
2. Train capacity-scaled llama drafts DISTILLED from that target
   (T=1, mostly-teacher alpha — the matrix's best sampled-acceptance
   recipe), at increasing capacity until sampled acceptance >= 0.6.
3. Measure library-level acceptance exactly like the matrix
   (5 prompts x 64 tokens, k=4): greedy `speculative_generate` and
   sampled `speculative_sample` at T=0.8.
4. Measure the served economics on this attach: engine fused plain
   vs fused speculative single-stream wall-clock (the quantity that
   decides whether speculation PAYS).

Usage:  python tools/spec_sharp_target.py [--workdir DIR] [--quick]
Emits one JSON line per stage; the final line is the summary the
BASELINE.md table quotes.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

PROMPTS = [
    "The serving engine batches requests",
    "Checkpoints are committed when",
    "TPU programs compile once per",
    "Sharding follows the mesh",
    "The draft proposes tokens and",
]
N_TOKENS = 64
SPEC_K = 4

TARGET_KW = dict(
    vocab_size=260, hidden_size=128, num_layers=2, num_heads=4,
    num_kv_heads=2, max_positions=256, compute_dtype="float32",
)
# Capacity x recipe ladder for the draft: params scale ~hidden^2 at
# fixed depth; h48/1L is the r04 flat-target draft (~1/10 params).
# Measured r05 frontier on the frozen corpus (greedy/sampled T=0.8):
#   h64 a=0.1@700: 0.413/0.322   h96 a=0.1@700: 0.439/0.420
#   h64 a=0.0@1400: 0.446/0.417  h96 a=0.0@1400: 0.288/0.204
# — capacity AND recipe saturate ~0.45; pure-KL over-distillation at
# h96 overfits teacher-forced train contexts and collapses on-policy.
DRAFT_LADDER = (
    dict(hidden_size=64, num_layers=2, distill_alpha=0.1, steps_x=1),
    dict(hidden_size=96, num_layers=2, distill_alpha=0.1, steps_x=1),
    dict(hidden_size=64, num_layers=2, distill_alpha=0.0, steps_x=2),
    dict(hidden_size=96, num_layers=2, distill_alpha=0.0, steps_x=2),
)


def log(stage: str, payload: dict) -> None:
    print(json.dumps({"stage": stage, **payload}), flush=True)


def train(name: str, out: str, *, steps: int, model: str, kw: dict,
          lr: float, distill_from: str | None = None,
          distill_alpha: float = 0.1) -> dict:
    """One training run through the product CLI (same path a user
    takes), on the frozen docs corpus (the dataset default)."""
    import yaml

    cfg = {
        "name": name, "model": model, "model_kwargs": kw,
        "dataset": "docs_text", "dataset_kwargs": {"seq_len": 128},
        "steps": steps, "batch_size": 64, "optimizer": "adamw",
        "learning_rate": lr, "eval_every": max(100, steps // 4),
    }
    if distill_from:
        cfg["distill_temperature"] = 1.0
        cfg["distill_alpha"] = distill_alpha
    ypath = os.path.join(os.path.dirname(out), f"{name}.yaml")
    with open(ypath, "w") as f:
        yaml.safe_dump(cfg, f)
    cmd = [sys.executable, "-m", "mlapi_tpu.train", "--config", ypath,
           "--out", out]
    if distill_from:
        cmd += ["--distill-from", distill_from]
    t0 = time.time()
    r = subprocess.run(cmd, capture_output=True, text=True, cwd=ROOT,
                       env=dict(os.environ), timeout=3600)
    if r.returncode != 0:
        raise RuntimeError(f"{name} failed: {r.stderr[-800:]}")
    line = [ln for ln in r.stdout.splitlines() if "test_accuracy" in ln]
    acc = None
    for ln in reversed(r.stdout.splitlines()):
        try:
            acc = json.loads(ln).get("test_accuracy")
            if acc is not None:
                break
        except ValueError:
            continue
    return {"seconds": round(time.time() - t0, 1),
            "next_token_acc": acc, "stdout_acc_line": line[-1:] or None}


def measure_acceptance(target_ck: str, draft_ck: str) -> dict:
    """The matrix methodology: greedy + sampled(T=0.8) acceptance,
    5 prompts x 64 tokens, k=4, library level."""
    import numpy as np

    from mlapi_tpu.checkpoint import load_checkpoint
    from mlapi_tpu.models import get_model
    from mlapi_tpu.ops.speculative import (
        speculative_generate, speculative_sample,
    )
    from mlapi_tpu.text import ByteTokenizer

    tok = ByteTokenizer()
    tp, tmeta = load_checkpoint(target_ck)
    dp, dmeta = load_checkpoint(draft_ck)
    target = get_model(tmeta.config["model"],
                       **tmeta.config["model_kwargs"])
    draft = get_model(dmeta.config["model"],
                      **dmeta.config["model_kwargs"])

    out = {}
    for mode in ("greedy", "sampled"):
        acc_n = acc_d = 0
        for p in PROMPTS:
            ids = np.asarray(tok.token_ids(p), np.int32)[None, :]
            if mode == "greedy":
                _, stats = speculative_generate(
                    target, tp, draft, dp, ids,
                    max_new_tokens=N_TOKENS, k=SPEC_K,
                )
            else:
                _, stats = speculative_sample(
                    target, tp, draft, dp, ids,
                    max_new_tokens=N_TOKENS, k=SPEC_K,
                    temperature=0.8, seed=0,
                )
            acc_n += stats.accepted
            acc_d += stats.drafted
        out[mode] = round(acc_n / acc_d, 4) if acc_d else 0.0
    return out


def measure_served(target_ck: str, draft_ck: str) -> dict:
    """Engine-level single-stream wall-clock: fused plain vs fused
    speculative (the serving quantity the acceptance number is a
    proxy for), plus served greedy acceptance from the engine's own
    counters."""
    from mlapi_tpu.checkpoint import load_checkpoint
    from mlapi_tpu.models import get_model
    from mlapi_tpu.serving.engine import TextGenerationEngine
    from mlapi_tpu.text import ByteTokenizer

    def build(with_draft: bool) -> TextGenerationEngine:
        tp, tmeta = load_checkpoint(target_ck)
        kw = dict(
            tokenizer=ByteTokenizer(), fused_single=True,
            default_max_new_tokens=N_TOKENS,
        )
        if with_draft:
            dp, dmeta = load_checkpoint(draft_ck)
            kw["draft"] = (
                get_model(dmeta.config["model"],
                          **dmeta.config["model_kwargs"]), dp,
            )
        target = get_model(tmeta.config["model"],
                           **tmeta.config["model_kwargs"])
        return TextGenerationEngine(target, tp, **kw)

    engines = {"fused_plain": build(False), "fused_spec": build(True)}
    for eng in engines.values():  # warm every bucket/tier off the clock
        for p in PROMPTS:
            eng.generate_text(p, max_new_tokens=N_TOKENS)
    # INTERLEAVED A/B reps: this box's absolute throughput drifts
    # (frequency/thread scheduling), so plain-vs-spec must be sampled
    # alternately within one window — the RATIO is the result.
    times = {k: 0.0 for k in engines}
    toks = {k: 0 for k in engines}
    for _ in range(3):
        for label, eng in engines.items():
            t0 = time.perf_counter()
            for p in PROMPTS:
                r = eng.generate_text(p, max_new_tokens=N_TOKENS)
                toks[label] += len(r["token_ids"])
            times[label] += time.perf_counter() - t0
    out = {}
    for label, eng in engines.items():
        out[label] = {
            "tokens_per_s": round(toks[label] / times[label], 1),
            # Which path actually served: the comparison is only
            # meaningful fused-vs-fused (one dispatch each).
            "fused_calls": eng.fused_calls,
            "fused_spec_calls": getattr(eng, "fused_spec_calls", 0),
            "chunk_calls": eng.chunk_calls,
        }
    eng = engines["fused_spec"]
    out["fused_spec"]["served_acceptance"] = round(
        eng.spec_accepted / eng.spec_drafted, 4
    ) if getattr(eng, "spec_drafted", 0) else None
    out["spec_speedup"] = round(
        out["fused_spec"]["tokens_per_s"]
        / out["fused_plain"]["tokens_per_s"], 3,
    )
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--quick", action="store_true",
                    help="100-step trainings (smoke-test the tool)")
    ap.add_argument("--target-steps", type=int, default=700)
    ap.add_argument("--draft-steps", type=int, default=700)
    args = ap.parse_args()

    # Pin the backend BEFORE any jax work or training subprocess: the
    # ambient platform here is the tunneled chip, which wedges for
    # hours — an unpinned run hangs at first dispatch with 0% CPU
    # (the documented trap). bench.py's probe decides chip-vs-CPU
    # with a hard timeout and hands back the env to propagate.
    from bench import _choose_backend

    probe, note, env = _choose_backend()
    os.environ.update(env)
    from mlapi_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    log("backend", {"backend": (probe or {}).get("backend", "cpu"),
                    "note": note})

    workdir = args.workdir or tempfile.mkdtemp(prefix="spec_sharp_")
    os.makedirs(workdir, exist_ok=True)
    tsteps = 100 if args.quick else args.target_steps
    dsteps = 100 if args.quick else args.draft_steps

    def cached_steps(ck: str) -> int | None:
        """The committed checkpoint's training step, or None. Cache
        hits must validate this: a prior --quick run in the same
        workdir would otherwise masquerade as the 700-step target."""
        mf = os.path.join(ck, "MANIFEST.json")
        if not os.path.exists(mf):
            return None
        try:
            return int(json.load(open(mf)).get("step", -1))
        except (ValueError, OSError):
            return None

    target_ck = os.path.join(workdir, "target")
    if cached_steps(target_ck) != tsteps:
        info = train("docs-llama-sharp", target_ck, steps=tsteps,
                     model="llama_lm", kw=TARGET_KW, lr=3e-4)
        log("target", info)
    else:
        log("target", {"cached": target_ck, "step": tsteps})

    best = None
    for rung in DRAFT_LADDER:
        alpha = rung["distill_alpha"]
        steps = dsteps * rung["steps_x"]
        kw = dict(TARGET_KW, hidden_size=rung["hidden_size"],
                  num_layers=rung["num_layers"])
        name = (f"draft-h{rung['hidden_size']}L{rung['num_layers']}"
                + ("-pure" if alpha == 0.0 else ""))
        ck = os.path.join(workdir, name)
        if cached_steps(ck) != steps:
            info = train(name, ck, steps=steps, model="llama_lm",
                         kw=kw, lr=1e-3, distill_from=target_ck,
                         distill_alpha=alpha)
            log(name, info)
        acc = measure_acceptance(target_ck, ck)
        log(f"{name}_acceptance", acc)
        best = {"draft": name, "ck": ck, **acc}
        if acc["sampled"] >= 0.6:
            break

    served = measure_served(target_ck, best["ck"])
    log("served", served)
    log("summary", {
        "target": f"docs-llama {tsteps}-step (frozen corpus)",
        **best, "served": served,
        "goal_sampled_ge_0.6": best["sampled"] >= 0.6,
    })
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
