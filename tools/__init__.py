"""Repo tooling (not shipped with the mlapi_tpu package)."""
