"""Runtime lock-order witness: the dynamic half of MLA007.

``tools/lint/lockorder.json`` is the STATIC partial order — lock
acquisitions the AST can see. This module checks the order the
process ACTUALLY takes: every registered lock is wrapped in a proxy
that records per-thread acquisition stacks, and

- acquiring lock B while holding lock A when the static order says
  ``B before A`` is an ORDER INVERSION — the exact half of a
  deadlock the static rule proved cannot come from the other side;
- every observed (held, acquired) class pair is recorded, so a test
  can assert the dynamic graph is a SUBSET of the static one — an
  observed edge the analyzer cannot see means the analyzer (or the
  binding registry) has a hole, and the smoke test fails until it is
  taught;
- a lock held longer than ``hold_budget_s`` (opt-in) is a convoy
  violation — the r13 spill-under-lock class, caught while it
  happens instead of in review.

Deterministic and pure stdlib: the witness adds one thread-local
list append/pop per acquisition when armed and EXISTS only when
armed — production code never imports this module; tests opt in via
the ``lock_witness`` fixture (``tests/conftest.py``) or
``MLAPI_LOCK_WITNESS=1``.

Wrapping preserves the UNDERLYING primitive: ``WitnessLock``
delegates to the same ``threading.Lock`` object the class built, and
``Condition`` attributes that shared the class lock are rebuilt
around the proxy — mutual exclusion is untouched, only observation
is added. Violations are RECORDED, not raised: raising inside
``acquire`` would corrupt the very engine state under test; the
fixture asserts the list is empty at teardown (and a negative test
asserts it is not).
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from pathlib import Path

_ARTIFACT = Path(__file__).resolve().parent / "lockorder.json"

# Registered class -> "module:Class" for install(); lock NAMES come
# from tools.lint.config.LOCK_REGISTRY (one source of truth). The
# metrics trio (MetricsRegistry/Counter/Histogram) is deliberately
# absent: leaf locks with no outgoing edges, wrapped cost on every
# counter bump for nothing the order can say.
_INSTALL_TARGETS = {
    "PagePool": "mlapi_tpu.serving.paged_pool",
    "KVTier": "mlapi_tpu.serving.kv_tier",
    "PrefixCache": "mlapi_tpu.serving.prefix",
    "KVPeer": "mlapi_tpu.serving.kv_peer",
    "KVPush": "mlapi_tpu.serving.kv_peer",
    "UnitScheduler": "mlapi_tpu.serving.scheduler",
    "LatencyStats": "mlapi_tpu.serving.requests",
}


def load_order(path=None) -> set[tuple[str, str]]:
    """The static edges ``{(before, after), ...}`` from the MLA007
    artifact, expanded to their transitive closure."""
    doc = json.loads(Path(path or _ARTIFACT).read_text())
    edges = {(e["before"], e["after"]) for e in doc.get("edges", [])}
    # Tiny graph: closure by iteration.
    changed = True
    while changed:
        changed = False
        for a, b in list(edges):
            for c, d in list(edges):
                if b == c and (a, d) not in edges:
                    edges.add((a, d))
                    changed = True
    return edges


class LockWitness:
    """Shared recorder: per-thread held stacks, observed class-pair
    edges, and the violation log."""

    def __init__(self, order: set[tuple[str, str]] | None = None,
                 hold_budget_s: float | None = None):
        self.order = set(order or ())
        self.hold_budget_s = hold_budget_s
        self.violations: list[str] = []
        self.observed_edges: set[tuple[str, str]] = set()
        self._tls = threading.local()
        self._vlock = threading.Lock()

    @classmethod
    def from_artifact(cls, path=None, hold_budget_s=None):
        if hold_budget_s is None:
            env = os.environ.get("MLAPI_LOCK_WITNESS_BUDGET_S")
            hold_budget_s = float(env) if env else None
        return cls(load_order(path), hold_budget_s=hold_budget_s)

    # -- recording (called by WitnessLock) -----------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_acquire(self, cls_name: str, token: int) -> None:
        st = self._stack()
        for held_cls, held_token, _ in st:
            if held_cls == cls_name:
                continue  # instance-level pairs carry no class order
            self.observed_edges.add((held_cls, cls_name))
            if (cls_name, held_cls) in self.order:
                self._violate(
                    f"order inversion: acquired {cls_name} while "
                    f"holding {held_cls}, but lockorder.json orders "
                    f"{cls_name} before {held_cls} (thread "
                    f"{threading.current_thread().name})"
                )
        st.append((cls_name, token, time.perf_counter()))

    def note_release(self, cls_name: str, token: int) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][1] == token and st[i][0] == cls_name:
                _, _, t0 = st.pop(i)
                if (
                    self.hold_budget_s is not None
                    and time.perf_counter() - t0 > self.hold_budget_s
                ):
                    self._violate(
                        f"hold-span budget exceeded: {cls_name} held "
                        f"{time.perf_counter() - t0:.3f}s > "
                        f"{self.hold_budget_s}s (thread "
                        f"{threading.current_thread().name})"
                    )
                return
        # A release the witness never saw acquired (the init-window
        # old-Condition path): tolerated — observation only.

    def _violate(self, msg: str) -> None:
        with self._vlock:
            self.violations.append(msg)


class WitnessLock:
    """Proxy around the class's OWN lock object: same mutual
    exclusion, plus acquisition recording. Duck-compatible with
    ``threading.Condition(lock=...)`` (acquire/release only — the
    Condition falls back to its portable ``_is_owned`` /
    ``_release_save`` paths, which route through this proxy)."""

    def __init__(self, witness: LockWitness, cls_name: str, inner):
        self._witness = witness
        self._cls = cls_name
        self._inner = inner
        self._token = id(self)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness.note_acquire(self._cls, self._token)
        return got

    def release(self):
        self._witness.note_release(self._cls, self._token)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def wrap_instance(witness: LockWitness, obj, cls_name: str,
                  lock_names) -> None:
    """Swap ``obj``'s registered lock attributes for witness proxies.
    Lock attrs wrap in place (same primitive underneath); Condition
    attrs are rebuilt around the proxy of their shared base lock.
    Runs at construction time (no waiters exist yet)."""
    conds: list[tuple[str, threading.Condition]] = []
    proxies: dict[int, WitnessLock] = {}
    for name in lock_names:
        lk = getattr(obj, name, None)
        if lk is None:
            continue
        if isinstance(lk, threading.Condition):
            conds.append((name, lk))
        else:
            proxy = WitnessLock(witness, cls_name, lk)
            proxies[id(lk)] = proxy
            setattr(obj, name, proxy)
    for name, cond in conds:
        base = cond._lock
        proxy = proxies.get(id(base))
        if proxy is None:
            proxy = WitnessLock(witness, cls_name, base)
            proxies[id(base)] = proxy
        setattr(obj, name, threading.Condition(proxy))


def install(witness: LockWitness, targets=None):
    """Patch the registered serving classes so every instance
    constructed while armed is witness-wrapped; returns the
    uninstall callable. Lock names come from the MLA002 registry —
    the static and dynamic checks share one contract."""
    import importlib

    from tools.lint.config import LOCK_REGISTRY

    originals = []
    for cls_name, mod_name in (targets or _INSTALL_TARGETS).items():
        spec = LOCK_REGISTRY.get(cls_name)
        if spec is None:
            continue
        mod = importlib.import_module(mod_name)
        cls = getattr(mod, cls_name)
        orig = cls.__init__

        def patched(self, *a, __orig=orig, __cls=cls_name,
                    __locks=tuple(spec.locks), **k):
            # Threads started DURING construction (UnitScheduler
            # spawns its dispatch thread at the end of __init__) must
            # not observe the pre-wrap locks: defer every start until
            # after the swap. Test-only machinery — constructions in
            # the suite are sequential, so the global patch window is
            # effectively private.
            deferred: list = []
            real_start = threading.Thread.start
            threading.Thread.start = lambda t: deferred.append(t)
            try:
                __orig(self, *a, **k)
                wrap_instance(witness, self, __cls, __locks)
            finally:
                # Restore AND replay in the finally: a raising
                # __init__ must still start any unrelated thread the
                # process-wide patch swallowed, or its owner hangs.
                threading.Thread.start = real_start
                for t in deferred:
                    real_start(t)

        cls.__init__ = functools.wraps(orig)(patched)
        originals.append((cls, orig))

    def uninstall():
        for cls, orig in originals:
            cls.__init__ = orig

    return uninstall
