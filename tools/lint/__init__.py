"""mlapi-lint: invariant-aware static analysis for this repo.

Three consecutive PRs (r12, r13, r15) each shipped — and only caught
in review — the same bug families: reading a donation-consumed jax
buffer after a donated dispatch, mutating shared pool/tier/scheduler
state outside its lock, or placing a fault-injection point after the
state mutation it is supposed to guard. Those invariants are
load-bearing across ~20 serving modules but lived only in reviewers'
heads and DESIGN.md prose. This package mechanizes them as named,
fixture-tested AST rules so each review-caught class is a CI failure
instead (DESIGN.md §22 maps every rule to the incident it encodes).

Design constraints, in order:

- **Pure AST.** ``import jax`` is forbidden here (asserted by the
  tier-1 test): the linter must run anywhere, instantly, with no
  device, no XLA, no compile. Everything is ``ast`` + ``tokenize``
  over source text.
- **Repo-specific on purpose.** The rules encode THIS codebase's
  contracts (``tools/lint/config.py`` is the registry: which
  attributes are lock-guarded, which factories donate, which module
  must stay async-pure). A generic linter cannot know that
  ``PagePool._free`` is decode-thread-shared; this one does.
- **Heuristic, lexical, and honest about it.** The analyses are
  intraprocedural and lexical (no dataflow across calls, no loop
  back-edges). That is exactly the shape of every historical
  incident this package encodes — and anything it cannot see, it
  must stay silent about rather than cry wolf. False positives are
  handled by inline suppressions or the baseline file, each with a
  mandatory written justification.

Run as ``python -m tools.lint`` (CI: ``--format=github``); the tier-1
suite runs the same entry point in ``tests/test_static_analysis.py``.
"""

from __future__ import annotations

import ast
import io
import tokenize
from dataclasses import dataclass, field
from pathlib import Path


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to an exact ``file:line``.

    ``symbol`` is the dotted enclosing scope (``Class.method`` or
    ``""`` for module level) — the line-drift-stable anchor baseline
    entries match on.
    """

    rule: str
    file: str  # repo-relative posix path
    line: int
    message: str
    symbol: str = ""

    def render(self) -> str:
        where = f"{self.file}:{self.line}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.rule} {where}{sym}: {self.message}"

    def render_github(self) -> str:
        """GitHub Actions annotation format (future CI mode)."""
        return (
            f"::error file={self.file},line={self.line},"
            f"title={self.rule}::{self.message}"
        )


class SourceFile:
    """One parsed python file: AST + per-line comments + raw lines.

    Parsed once, shared by every rule. Comments come from
    ``tokenize`` (the AST drops them) because the inline-suppression
    syntax lives in comments.
    """

    def __init__(self, root: Path, path: Path):
        self.abspath = path
        self.path = path.relative_to(root).as_posix()
        self.text = path.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        try:
            self.tree: ast.Module | None = ast.parse(self.text)
        except SyntaxError:
            self.tree = None
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(self.text).readline
            ):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, SyntaxError):
            # Unparseable files (WIP syntax errors) degrade to
            # comment-less, same as the ast.parse fallback above —
            # the linter must never crash on the tree it scans.
            pass
        self._scopes: list[tuple[int, int, str]] | None = None
        self._parents: dict | None = None

    def parents(self) -> dict:
        """Lazy child->parent map over the whole tree, computed once
        per file (several rules need ancestry walks; rebuilding the
        map per rule would re-walk every AST per rule)."""
        if self._parents is None:
            self._parents = {}
            if self.tree is not None:
                import ast as _ast

                for node in _ast.walk(self.tree):
                    for child in _ast.iter_child_nodes(node):
                        self._parents[child] = node
        return self._parents

    def symbol_at(self, line: int) -> str:
        """Deepest enclosing ``Class.method`` scope containing
        ``line`` (innermost span wins)."""
        if self._scopes is None:
            self._scopes = []
            if self.tree is not None:
                self._walk_scopes(self.tree, ())
        best = ""
        best_span = None
        for lo, hi, name in self._scopes:
            if lo <= line <= hi:
                span = hi - lo
                if best_span is None or span <= best_span:
                    best, best_span = name, span
        return best

    def _walk_scopes(self, node: ast.AST, prefix: tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                qual = prefix + (child.name,)
                self._scopes.append(
                    (child.lineno, child.end_lineno or child.lineno,
                     ".".join(qual))
                )
                self._walk_scopes(child, qual)
            else:
                self._walk_scopes(child, prefix)


@dataclass
class Project:
    """The scanned tree: parsed python files plus raw doc texts."""

    root: Path
    files: list[SourceFile] = field(default_factory=list)
    docs: dict[str, str] = field(default_factory=dict)  # path -> text

    def get(self, relpath: str) -> SourceFile | None:
        for f in self.files:
            if f.path == relpath:
                return f
        return None

    def matching(self, prefix: str) -> list[SourceFile]:
        """Files whose repo-relative path starts with ``prefix``."""
        return [f for f in self.files if f.path.startswith(prefix)]


def load_project(cfg) -> Project:
    """Collect + parse every file the config names (each file parsed
    exactly once even when globs overlap)."""
    proj = Project(root=cfg.root)
    seen: set[str] = set()
    for pattern in cfg.py_globs:
        for path in sorted(cfg.root.glob(pattern)):
            rel = path.relative_to(cfg.root).as_posix()
            if rel in seen or not path.is_file():
                continue
            if any(rel.startswith(ex) for ex in cfg.exclude_prefixes):
                continue
            seen.add(rel)
            proj.files.append(SourceFile(cfg.root, path))
    for doc in cfg.doc_files:
        p = cfg.root / doc
        if p.is_file():
            proj.docs[doc] = p.read_text(encoding="utf-8")
    return proj


def run_rules(proj: Project, cfg, rule_ids: set[str] | None = None):
    """Run every (selected) rule; returns raw findings, pre-
    suppression, sorted by location."""
    from tools.lint.rules import ALL_RULES

    findings: list[Finding] = []
    for rule in ALL_RULES:
        if rule_ids is not None and rule.id not in rule_ids:
            continue
        findings.extend(rule.run(proj, cfg))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings
