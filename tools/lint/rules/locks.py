"""MLA002 — lock discipline over registered shared state.

The serving stack's shared-mutable surfaces — PagePool counters and
freelists, KVTier byte/entry accounting, UnitScheduler queue/forming
slots, the latency reservoirs — are mutated from at least two threads
(decode/dispatch thread, event loop, registration threads). Their
classes document one lock each; a mutation that slips outside it is a
lost update or a torn container at load, invisible in single-threaded
tests. This rule makes "mutations of registered attributes happen
inside ``with self.<lock>``" checkable.

Two detection modes, both over ``tools/lint/config.py``'s registry:

- **Self-scoped.** Inside methods of a registered class, every
  mutation of ``self.<attr>`` for a registered attr must be lexically
  inside ``with self.<lock>`` for one of the class's registered lock
  names (Condition wrappers like ``_work``/``_evict_cond`` that share
  the lock are registered alongside it).
- **Cross-module.** For the handful of DISTINCTIVE attribute names
  (``cow_copies``, ``_free``, ``_blobs``, ...), a mutation of
  ``<base>.<attr>`` anywhere in production code must sit inside
  ``with <base>.<lock>`` for the SAME base expression — this is what
  catches ``self.eng.pool.cow_copies += n`` from another module.

Deliberate exceptions, encoded rather than suppressed ad hoc:

- ``__init__`` bodies (construction precedes sharing);
- methods whose name ends in ``_locked`` (the repo's documented
  caller-holds-the-lock convention, e.g. ``_release_locked``);
- the claim-under-lock/spill-outside pattern is already shaped this
  way in the registry: the spill path's tier/counter work happens on
  popped (invisible) state, and the counters it does touch are
  registered so the rule FORCES them back under the lock — that is
  rule-driven fix r16 shipped, not a false positive.

Anything genuinely single-writer stays OUT of the registry (see the
config's comment) instead of being suppressed at every site.
"""

from __future__ import annotations

import ast

from tools.lint import Finding
from tools.lint.rules import common


class LockRule:
    id = "MLA002"
    title = "registered shared state must be mutated under its lock"

    def run(self, proj, cfg):
        findings: list[Finding] = []
        for sf in proj.files:
            if not sf.path.startswith(cfg.production_prefix):
                continue
            if sf.tree is None:
                continue
            parents = sf.parents()
            findings.extend(self._self_scoped(sf, cfg, parents))
            findings.extend(self._cross_module(sf, cfg, parents))
        return findings

    # -- mode 1: methods of registered classes -------------------------

    def _self_scoped(self, sf, cfg, parents):
        findings = []
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            spec = cfg.lock_registry.get(cls.name)
            if spec is None:
                continue
            for meth in cls.body:
                if not isinstance(
                    meth, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if meth.name == "__init__" or meth.name.endswith(
                    "_locked"
                ):
                    continue
                for site in common.find_mutations(meth, spec.attrs):
                    if site.base_fp != "self":
                        continue
                    if common.inside_with_lock(
                        site.node, parents, "self", spec.locks
                    ):
                        continue
                    findings.append(Finding(
                        rule=self.id,
                        file=sf.path,
                        line=site.line,
                        message=(
                            f"`self.{site.attr}` ({site.how}) mutated "
                            f"outside `with self."
                            f"{min(spec.locks, key=lambda n: (len(n), n))}` in "
                            f"{cls.name}.{meth.name} — registered "
                            f"shared state (see tools/lint/config.py)"
                        ),
                        symbol=sf.symbol_at(site.line),
                    ))
        return findings

    # -- mode 2: distinctive attrs anywhere ----------------------------

    def _cross_module(self, sf, cfg, parents):
        findings = []
        attrs = frozenset(cfg.distinctive_attrs)
        for func in ast.walk(sf.tree):
            if not isinstance(
                func, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if func.name == "__init__" or func.name.endswith("_locked"):
                continue
            # shallow: ast.walk above visits nested defs as their own
            # functions, so a deep scan here would report a closure's
            # mutation twice (once for each enclosing frame).
            for site in common.find_mutations(func, attrs, shallow=True):
                if site.base_fp == "self":
                    # self-scoped mode owns these (registry class) —
                    # or the attr lives on an unregistered class,
                    # where `self.<distinctive>` would double-report.
                    continue
                locks = cfg.distinctive_attrs[site.attr]
                if common.inside_with_lock(
                    site.node, parents, site.base_fp, locks
                ):
                    continue
                findings.append(Finding(
                    rule=self.id,
                    file=sf.path,
                    line=site.line,
                    message=(
                        f"`{site.base_fp}.{site.attr}` ({site.how}) "
                        f"mutated outside `with {site.base_fp}."
                        f"{sorted(locks)[0]}` — cross-module access "
                        f"to registered shared state"
                    ),
                    symbol=sf.symbol_at(site.line),
                ))
        return findings
