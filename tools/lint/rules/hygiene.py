"""MLA006 — tier-1 test hygiene: no wall-clock assertions.

The ADVICE r05 flake class: a test that asserts on ELAPSED TIME
(``assert elapsed < 1.0``) encodes the speed of one machine into a
correctness suite that runs on a drifting shared box — the r14/r15
tier-1 runs brushed the 870 s window for exactly that kind of
environmental reason. The repo's documented alternative is counter-
based asserts (engine/scheduler counters, fault counts, trace
contents), which are deterministic at any machine speed.

Flags, in tier-1 test files (functions NOT marked ``slow`` or
``heavy`` — soak tests may time themselves) and in bench assert
paths:

- an ``assert`` whose comparison reads a wall-clock source directly
  (``time.time()``, ``time.perf_counter()``, ``time.monotonic()``,
  ``loop.time()``), or
- an ``assert`` whose comparison reads a variable assigned from an
  expression containing such a call (one-level lexical taint — the
  ``t0 = perf_counter(); ...; assert loop.time() - t0 < X`` shape
  and its named-elapsed variants).

Wait bounds stay legal: bounding how long a test WAITS is fine,
asserting how long the code TOOK is the flake. Lexically, a wait
bound compares a clock against a clock-derived deadline
(``assert loop.time() < deadline`` where ``deadline = loop.time() +
10``) — BOTH sides clock-tainted — while the flake shape compares a
clock-derived elapsed against a plain constant (``assert elapsed <
1.0``). Only the mixed comparison is flagged.
"""

from __future__ import annotations

import ast

from tools.lint import Finding
from tools.lint.rules import common

_CLOCK_ATTRS = frozenset({"time", "perf_counter", "monotonic",
                          "process_time"})
_EXEMPT_MARKS = ("slow", "heavy")


def _is_clock_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _CLOCK_ATTRS:
        return True
    if isinstance(f, ast.Name) and f.id in _CLOCK_ATTRS:
        return True  # from time import perf_counter
    return False


def _module_exempt(tree) -> bool:
    """Module-level ``pytestmark`` includes slow/heavy."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "pytestmark":
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Attribute) and (
                            sub.attr in _EXEMPT_MARKS
                        ):
                            return True
    return False


class TestHygieneRule:
    id = "MLA006"
    title = "no wall-clock assertions outside slow/heavy tests"

    def run(self, proj, cfg):
        findings: list[Finding] = []
        for sf in proj.files:
            is_test = sf.path.startswith(cfg.test_prefix)
            is_bench = sf.path in cfg.bench_files
            if not (is_test or is_bench) or sf.tree is None:
                continue
            if is_test and _module_exempt(sf.tree):
                continue
            for func in sf.tree.body:
                if not isinstance(
                    func, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                marks = common.decorator_names(func)
                if any(
                    m.endswith(f"mark.{x}")
                    for m in marks for x in _EXEMPT_MARKS
                ):
                    continue
                findings.extend(self._check_function(sf, func))
        return findings

    def _check_function(self, sf, func):
        tainted: set[str] = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and any(
                _is_clock_call(sub) for sub in ast.walk(node.value)
            ):
                for t in node.targets:
                    els = t.elts if isinstance(t, ast.Tuple) else [t]
                    for el in els:
                        if isinstance(el, ast.Name):
                            tainted.add(el.id)
        findings = []
        for node in ast.walk(func):
            if not isinstance(node, ast.Assert):
                continue
            hit = self._wallclock_compare(node.test, tainted)
            if hit:
                findings.append(Finding(
                    rule=self.id, file=sf.path, line=node.lineno,
                    message=(
                        f"wall-clock assertion ({hit}) in a tier-1 "
                        f"test — encodes one machine's speed; assert "
                        f"on engine/scheduler counters instead, or "
                        f"mark the test slow/heavy (ADVICE r05 flake "
                        f"class)"
                    ),
                    symbol=sf.symbol_at(node.lineno),
                ))
        return findings

    @staticmethod
    def _wallclock_compare(test, tainted) -> str | None:
        def side_taint(expr) -> str | None:
            for sub in ast.walk(expr):
                if _is_clock_call(sub):
                    return "a clock read"
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return f"`{sub.id}` (assigned from a clock)"
            return None

        for node in ast.walk(test):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left, *node.comparators]
            taints = [side_taint(s) for s in sides]
            hits = [t for t in taints if t is not None]
            # All-sides-tainted = a wait bound (clock vs clock-derived
            # deadline): legal. Mixed = elapsed-vs-constant: the flake.
            if hits and len(hits) < len(sides):
                return f"compares {hits[0]} against a plain bound"
        return None
