"""MLA009 — terminal-frame wait discipline in async tests.

The exact flake class de-flaked by hand in r17 and r18 (four tests,
all failing on the unmodified r18 seed): a stream's TERMINAL frame
reaches the awaiting test strictly BEFORE the dispatch thread runs
the batch's cleanup, so an assert on release-settled state — page
refcounts back to zero, ``kv_pages_in_use == 0`` — placed lexically
right after the stream read races a thread that has not released
yet. It passes on a fast box, flakes on a loaded one, and every
occurrence was "fixed" once already before someone wrote the
condition wait.

The rule, lexical like every incident it encodes — in test files,
inside ``async def`` functions (sync tests drive ``generate_text``
inline, where cleanup completes before the call returns: no race):

- a **terminal read** is an ``await`` of a call whose name contains
  a ``config.TERMINAL_READ_HINTS`` token (``_collect``,
  ``asyncio.gather`` of collectors — the shapes this suite consumes
  streams with);
- a **settle event** is an ``await`` of a call whose name contains a
  ``config.SETTLE_WAIT_HINTS`` token (``_wait_for``, ``stop``,
  ``drain`` — condition waits and dispatch-thread joins), or a
  ``while`` loop that reads the settled counter (the inline
  deadline-poll shape);
- an ``assert`` reading a ``config.SETTLE_AFTER_TERMINAL`` counter
  (attribute access or a ``"...kv_pages_in_use"`` metrics key) is
  flagged when the nearest preceding terminal read has no settle
  event between it and the assert.

``slow``/``heavy`` tests are NOT exempt here (unlike MLA006): the
race is a correctness hole at any speed, not a machine-speed
encoding.
"""

from __future__ import annotations

import ast

from tools.lint import Finding
from tools.lint.rules import common


class TerminalWaitRule:
    id = "MLA009"
    title = "settled-state asserts need a condition wait after stream end"

    def run(self, proj, cfg):
        findings: list[Finding] = []
        for sf in proj.files:
            if not sf.path.startswith(cfg.test_prefix):
                continue
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.AsyncFunctionDef):
                    findings.extend(self._check(sf, node, cfg))
        return findings

    def _check(self, sf, func, cfg):
        # (line, kind) events in lexical order; shallow walk so a
        # nested helper def's internals are not this frame's events
        # (lambdas handed to _wait_for stay invisible for free).
        events: list[tuple[int, str, ast.AST]] = []
        for node in common.walk_shallow(func):
            if isinstance(node, ast.Await) and isinstance(
                node.value, ast.Call
            ):
                kind = self._await_kind(node.value, cfg)
                if kind:
                    events.append((node.lineno, kind, node))
            elif isinstance(node, ast.While):
                # Only the CONDITION counts — a loop polling the
                # counter is a wait; a loop that merely mentions it
                # in its body (an assert message, say) is not.
                if self._reads_counter(node.test, cfg):
                    events.append((node.lineno, "settle", node))
            elif isinstance(node, ast.Assert):
                name = self._reads_counter(node.test, cfg)
                if name:
                    events.append((node.lineno, f"assert:{name}", node))
        events.sort(key=lambda e: e[0])
        findings = []
        last_terminal: int | None = None
        for line, kind, node in events:
            if kind == "terminal":
                last_terminal = line
            elif kind == "settle":
                last_terminal = None
            elif kind.startswith("assert:") and last_terminal is not None:
                name = kind.split(":", 1)[1]
                findings.append(Finding(
                    rule=self.id, file=sf.path, line=line,
                    message=(
                        f"`{name}` asserted after the stream-terminal "
                        f"read at line {last_terminal} with no "
                        f"condition wait in between — the release "
                        f"runs on the dispatch thread AFTER the "
                        f"terminal frame; wait on the counter "
                        f"(`_wait_for`-style) first (the r17/r18 "
                        f"flake class)"
                    ),
                    symbol=sf.symbol_at(line),
                ))
                # One finding per unsettled terminal read: the fix (one
                # wait) settles every later assert in the run too.
                last_terminal = None
        return findings

    @staticmethod
    def _await_kind(call: ast.Call, cfg) -> str | None:
        chain = common.attr_chain(call.func)
        if not chain:
            return None
        name = chain[-1].lower()
        if any(h in name for h in cfg.settle_wait_hints):
            return "settle"
        if any(h in name for h in cfg.terminal_read_hints):
            return "terminal"
        return None

    @staticmethod
    def _reads_counter(expr, cfg) -> str | None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Attribute) and (
                sub.attr in cfg.settle_counters
            ):
                return sub.attr
            if isinstance(sub, ast.Constant) and isinstance(
                sub.value, str
            ):
                for c in cfg.settle_counters:
                    if c in sub.value:
                        return c
        return None
