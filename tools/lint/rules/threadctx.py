"""MLA008 — thread-context inference: blocking work must not reach
the event loop, device dispatch must not reach it either.

The serving process runs in (at least) five thread contexts — the
asyncio event loop, the scheduler's dispatch thread, encode/app
executor workers, the KVPush sender thread, prefix registration
threads — and the repo's worst bug class is work landing on the
WRONG one: the r13 spill (device gather + npz write) reachable on
the event loop through brownout's ``evict_idle``, the r17 prefix
hashing serializing encode threads behind the peer lock. MLA004
pins the router module; this rule infers contexts for everything
else.

**Seeding.** Per-function context sets start from what the AST shows
directly:

- every ``async def`` in a serving module runs ON the event loop;
- ``config.DISPATCH_SEEDS`` (``BatchRun.units``, the scheduler's
  ``_advance``/``_loop``) run on the dispatch thread;
- ``run_in_executor(...)``/``Thread(target=...)`` callees run on a
  worker thread (and the executor call is the HOP: it never
  propagates the caller's event-loop context into its argument).

**Propagation.** Contexts flow through the resolved call graph
(``rules/graph.py``: same-class methods, bound-class methods,
same-module functions) to a fixed point. A function reachable from
both a worker and the loop keeps both — blocking on the loop is the
bug regardless of who else calls it.

**Flagging.** In any function carrying the event-loop context:

- a call matching ``config.EVENT_LOOP_BLOCKING_PREFIXES``
  (``time.sleep``, sync socket/subprocess I/O, npz writes) — one
  blocked loop freezes every stream, timer, and health poll at once;
- a call whose attribute is in ``EVENT_LOOP_BLOCKING_ATTRS``
  (``block_until_ready``, ``device_put``, ``device_get``) — jax
  dispatch belongs to the dispatch thread or an executor worker,
  never the loop.

Calls inside nested sync defs/lambdas handed to ``run_in_executor``
are exempt (the documented hop), as are the async-pure modules
(MLA004's domain, no double reports) and ``serving/faults.py`` (the
delay action IS ``time.sleep`` — by design, and only on armed
threads). Each finding names the seed path (``submit ->
PagePool.evict_idle -> ...``) so the fix — an executor hop at the
boundary — is visible from the message.
"""

from __future__ import annotations

import ast

from tools.lint import Finding
from tools.lint.rules import common
from tools.lint.rules.graph import functions_with_class, production_index

EVENT_LOOP = "event-loop"
DISPATCH = "dispatch"
WORKER = "worker"


def _is_executor_call(call: ast.Call) -> bool:
    f = call.func
    # attr check first: `asyncio.get_running_loop().run_in_executor`
    # has a Call in its receiver chain, which attr_chain refuses.
    if isinstance(f, ast.Attribute):
        return f.attr in ("run_in_executor", "to_thread")
    chain = common.attr_chain(f)
    return bool(chain) and chain[-1] in (
        "run_in_executor", "to_thread"
    )


def _thread_target(call: ast.Call):
    """The ``target=`` expression of a ``threading.Thread(...)``
    construction, else None."""
    chain = common.attr_chain(call.func)
    if not chain or chain[-1] != "Thread":
        return None
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return None


class ThreadContextRule:
    id = "MLA008"
    title = "blocking calls and jax dispatch must stay off the event loop"

    def run(self, proj, cfg):
        files, index = production_index(proj, cfg)
        if not files:
            return []
        sf_by_path = {f.path: f for f in files}
        # def node -> {context: provenance string}
        ctx: dict[ast.AST, dict[str, str]] = {}
        exempt = set(cfg.async_pure_modules) | {cfg.faults_module}

        # -- seeds ----------------------------------------------------
        funcs: list[tuple[object, str | None, ast.AST]] = []
        for sf in files:
            for cls_name, func in functions_with_class(sf):
                funcs.append((sf, cls_name, func))
                label = (
                    f"{cls_name}.{func.name}" if cls_name
                    else func.name
                )
                if isinstance(func, ast.AsyncFunctionDef):
                    ctx.setdefault(func, {})[EVENT_LOOP] = (
                        f"async {label}"
                    )
                if (cls_name, func.name) in cfg.dispatch_seeds or (
                    (None, func.name) in cfg.dispatch_seeds
                ):
                    ctx.setdefault(func, {})[DISPATCH] = label
        # Executor / thread targets seed WORKER.
        for sf, cls_name, func in funcs:
            for node in common.walk_shallow(func):
                if not isinstance(node, ast.Call):
                    continue
                targets = []
                if _is_executor_call(node):
                    # run_in_executor(executor, fn, *args) carries the
                    # callee at [1]; asyncio.to_thread(fn, *args) at
                    # [0].
                    f = node.func
                    attr = (
                        f.attr if isinstance(f, ast.Attribute) else ""
                    )
                    idx = 0 if attr == "to_thread" else 1
                    targets = list(node.args[idx:idx + 1])
                t = _thread_target(node)
                if t is not None:
                    targets.append(t)
                for tgt in targets:
                    hit = self._resolve_expr(
                        tgt, index, cls_name, sf.path
                    )
                    if hit is not None:
                        ctx.setdefault(hit, {}).setdefault(
                            WORKER, "executor/thread target"
                        )

        # -- propagation to a fixed point -----------------------------
        changed = True
        while changed:
            changed = False
            for sf, cls_name, func in funcs:
                my = ctx.get(func)
                if not my:
                    continue
                for node in common.walk_shallow(func):
                    if not isinstance(node, ast.Call):
                        continue
                    if _is_executor_call(node):
                        continue  # the hop: callee already seeded worker
                    hit = index.resolve_call(node, cls_name, sf.path)
                    if hit is None:
                        continue
                    callee, callee_cls = hit
                    dst = ctx.setdefault(callee, {})
                    label = (
                        f"{callee_cls}.{callee.name}" if callee_cls
                        else callee.name
                    )
                    for c, prov in my.items():
                        if c not in dst:
                            dst[c] = f"{prov} -> {label}"
                            changed = True

        # -- flagging -------------------------------------------------
        findings: list[Finding] = []
        for sf, cls_name, func in funcs:
            if sf.path in exempt:
                continue
            my = ctx.get(func)
            if not my or EVENT_LOOP not in my:
                continue
            # Calls inside lambdas / nested defs are invisible to the
            # shallow walk by construction — the run_in_executor
            # lambda shape is exempt for free (a nested def is its
            # own function with its own contexts).
            for node in common.walk_shallow(func):
                if not isinstance(node, ast.Call):
                    continue
                label = self._blocking(node, cfg)
                if label is None:
                    continue
                findings.append(Finding(
                    rule=self.id, file=sf.path, line=node.lineno,
                    message=(
                        f"`{label}` is reachable on the event loop "
                        f"(context: {my[EVENT_LOOP]}) — blocking/"
                        f"device work freezes every stream and timer; "
                        f"hop through run_in_executor at the async "
                        f"boundary"
                    ),
                    symbol=sf.symbol_at(node.lineno),
                ))
        return findings

    # -- helpers -------------------------------------------------------

    @staticmethod
    def _resolve_expr(expr, index, cls_name, path):
        """A run_in_executor/Thread callee EXPRESSION -> its def node
        (name, self-method, or bound method), else None."""
        chain = common.attr_chain(expr)
        if not chain:
            return None
        fake = ast.Call(
            func=expr, args=[], keywords=[],
        )
        hit = index.resolve_call(fake, cls_name, path)
        return hit[0] if hit is not None else None

    @staticmethod
    def _blocking(node: ast.Call, cfg) -> str | None:
        chain = common.attr_chain(node.func)
        if not chain:
            return None
        if chain[-1] in cfg.blocking_attrs:
            return ".".join(chain[-2:]) if len(chain) > 1 else chain[-1]
        dotted = ".".join(chain)
        for pref in cfg.blocking_prefixes:
            # Match at a trailing boundary: `time.sleep` matches
            # `time.sleep` and `x.time.sleep`, never `mytime.sleeper`.
            if dotted == pref or dotted.endswith("." + pref):
                return pref
            head, _, last = pref.rpartition(".")
            if head and chain[-1].startswith(last) and (
                dotted.startswith(pref)
                or ("." + pref.rsplit(".", 1)[0] + ".") in "." + dotted + "."
            ):
                if ".".join(chain[:-1]).endswith(head):
                    return dotted
        return None

