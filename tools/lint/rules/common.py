"""Shared AST plumbing for the rules: expression fingerprints,
parent/scope maps, lock-enclosure and mutation detection.

Everything here is lexical and intraprocedural on purpose — see the
package docstring. The helpers return LINE-ANCHORED facts; the rules
turn them into findings.
"""

from __future__ import annotations

import ast


def fingerprint(node: ast.AST) -> str:
    """Structural identity of an expression, ignoring Load/Store
    context — ``self.layers`` as a read and as an assignment target
    fingerprint identically."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{fingerprint(node.value)}.{node.attr}"
    if isinstance(node, ast.Subscript):
        return f"{fingerprint(node.value)}[]"
    if isinstance(node, ast.Call):
        return f"{fingerprint(node.func)}()"
    if isinstance(node, ast.Constant):
        return repr(node.value)
    return ast.dump(node, annotate_fields=False, include_attributes=False)


def ancestors(node: ast.AST, parents: dict) -> list[ast.AST]:
    out = []
    while node in parents:
        node = parents[node]
        out.append(node)
    return out


def enclosing_function(node: ast.AST, parents: dict):
    for anc in ancestors(node, parents):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def attr_chain(node: ast.AST) -> list[str] | None:
    """``self.eng.pool.lock`` -> ["self","eng","pool","lock"]; None
    for anything that is not a pure Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def inside_with_lock(node: ast.AST, parents: dict, base_fp: str,
                     lock_names: frozenset[str]) -> bool:
    """Is ``node`` lexically inside ``with <base>.<lock>`` (or
    ``with <base>.<lock>:``-condition) where ``<base>`` fingerprints
    to ``base_fp`` and ``<lock>`` is a registered lock name?"""
    for anc in ancestors(node, parents):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                ctx = item.context_expr
                if (
                    isinstance(ctx, ast.Attribute)
                    and ctx.attr in lock_names
                    and fingerprint(ctx.value) == base_fp
                ):
                    return True
    return False


class MutationSite:
    """One mutation of ``<base>.<attr>``: line + the base expression's
    fingerprint + the mutated node (for enclosure walks)."""

    __slots__ = ("node", "line", "base_fp", "attr", "how")

    def __init__(self, node, line, base_fp, attr, how):
        self.node = node
        self.line = line
        self.base_fp = base_fp
        self.attr = attr
        self.how = how  # "assign" | "augassign" | "call" | "np-at" | "subscript"


def _attr_target(node: ast.AST):
    """(base_node, attr) if node is Attribute; descend one Subscript
    level so ``self.ref[pages] = 1`` mutates ``self.ref``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.value, node.attr
    return None


def find_mutations(func: ast.AST, attrs: frozenset[str],
                   shallow: bool = False):
    """Every mutation of ``<anything>.<attr>`` for ``attr`` in
    ``attrs`` within ``func``: assignments (incl. one subscript
    level), aug-assignments, mutating container-method calls, and
    ``np.add.at/np.subtract.at`` on the attribute. ``shallow`` skips
    nested function bodies — for callers that iterate every function
    (nested included) and must charge each mutation to its INNERMOST
    frame exactly once."""
    sites: list[MutationSite] = []

    def note(node, tgt, how):
        hit = _attr_target(tgt)
        if hit is None:
            return
        base, attr = hit
        if attr in attrs:
            sites.append(MutationSite(
                node, node.lineno, fingerprint(base), attr, how
            ))

    for node in (walk_shallow(func) if shallow else ast.walk(func)):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                    note(node, el, "assign")
        elif isinstance(node, (ast.AugAssign,)):
            note(node, node.target, "augassign")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                note(node, t, "assign")
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                from tools.lint.config import MUTATING_METHODS

                if f.attr in MUTATING_METHODS:
                    note(node, f.value, "call")
                # np.add.at(self.ref, ...) / np.subtract.at(...)
                elif f.attr == "at" and node.args:
                    note(node, node.args[0], "np-at")
    return sites


def walk_shallow(func: ast.AST):
    """Walk ``func``'s own nodes WITHOUT descending into nested
    function/lambda bodies — intraprocedural analyses must not see a
    sibling closure's reads as this frame's (the make_train_step
    false-positive shape)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def decorator_names(node) -> set[str]:
    """Flattened dotted names of a def's decorators
    (``pytest.mark.heavy`` -> "pytest.mark.heavy")."""
    out: set[str] = set()
    for dec in getattr(node, "decorator_list", ()):  # pragma: no branch
        target = dec.func if isinstance(dec, ast.Call) else dec
        chain = attr_chain(target)
        if chain:
            out.add(".".join(chain))
    return out
