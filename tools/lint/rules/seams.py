"""MLA003 — fault-seam ordering and coverage.

``serving/faults.py`` is the chaos-drill contract: every POINTS entry
is a named seam tests can arm, and the engine's recovery invariants
are only as strong as (a) the point actually firing at its seam,
(b) firing BEFORE the state mutation it guards (a fire placed after
the mutation "tests" a failure mode that leaves state already
corrupted — the r13 tier_spill review comment), and (c) at least one
test arming it. All three decay silently as code moves; this rule
pins them.

Checks:

1. **Known points only.** Every ``faults.fire("<p>")`` /
   ``_fire_async("<p>")`` argument must be a POINTS member (a typo'd
   point never fires and the drill silently tests nothing — the same
   loudness argument ``faults.parse`` makes for spec strings).
2. **Every point fires.** Each POINTS entry must have >= 1 fire site
   in production code.
3. **Every point is drilled.** Each POINTS entry must appear in >= 1
   test file's string constants (a ``faults.active`` spec, an
   ``MLAPI_FAULTS`` env, or a fault-matrix list).
4. **Fire-before-mutation.** At each fire site, no lexically earlier
   statement in the same function may have mutated a REGISTERED
   shared attribute (MLA002's registry — the state whose
   consistency the seam exists to drill). Lock state does not matter
   here: ordering is the property.
"""

from __future__ import annotations

import ast

from tools.lint import Finding
from tools.lint.rules import common


def _points(sf) -> dict[str, int]:
    """POINTS tuple -> {name: lineno} from the faults module."""
    out: dict[str, int] = {}
    if sf is None or sf.tree is None:
        return out
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "POINTS":
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        for el in node.value.elts:
                            if isinstance(el, ast.Constant) and isinstance(
                                el.value, str
                            ):
                                out[el.value] = el.lineno
    return out


def _fire_calls(sf):
    """(call_node, point_name|None, line) for every fire-family call."""
    if sf.tree is None:
        return []
    hits = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = common.attr_chain(node.func)
        if not chain:
            continue
        name = chain[-1]
        if name not in ("fire", "_fire_async"):
            continue
        if name == "fire" and not (
            len(chain) >= 2 and chain[-2] == "faults"
        ):
            continue
        point = None
        if node.args and isinstance(node.args[0], ast.Constant) and (
            isinstance(node.args[0].value, str)
        ):
            point = node.args[0].value
        hits.append((node, point, node.lineno))
    return hits


class SeamRule:
    id = "MLA003"
    title = "fault points: known, fired, drilled, fire-before-mutation"

    def run(self, proj, cfg):
        faults_sf = proj.get(cfg.faults_module)
        points = _points(faults_sf)
        if not points:
            return []  # no faults module in this scan set
        findings: list[Finding] = []

        prod = [
            f for f in proj.files
            if f.path.startswith(cfg.production_prefix)
        ]
        fired: dict[str, int] = {}
        guarded_attrs = frozenset().union(
            *(s.attrs for s in cfg.lock_registry.values())
        ) | frozenset(cfg.distinctive_attrs)

        for sf in prod:
            if sf.path == cfg.faults_module or sf.tree is None:
                continue
            parents = sf.parents()
            for node, point, line in _fire_calls(sf):
                if point is None:
                    continue
                if point not in points:
                    findings.append(Finding(
                        rule=self.id, file=sf.path, line=line,
                        message=(
                            f"faults.fire({point!r}): unknown point — "
                            f"not in serving/faults.py POINTS (a typo "
                            f"here never fires; the drill silently "
                            f"tests nothing)"
                        ),
                        symbol=sf.symbol_at(line),
                    ))
                    continue
                fired.setdefault(point, 0)
                fired[point] += 1
                findings.extend(self._ordering(
                    sf, node, point, line, parents, guarded_attrs
                ))

        # Coverage: every point fires somewhere...
        for point, decl_line in points.items():
            if not fired.get(point):
                findings.append(Finding(
                    rule=self.id, file=faults_sf.path, line=decl_line,
                    message=(
                        f"fault point {point!r} is declared but never "
                        f"fired from any seam in production code"
                    ),
                    symbol="POINTS",
                ))
        # ...and is ARMED by at least one test. Two recognized arming
        # shapes: (a) a literal clause — per the MLAPI_FAULTS grammar,
        # comma-separated clauses whose first ``:``-field is the point
        # (bare substring search would let a docstring merely
        # MENTIONING the point satisfy the check — that vacuousness
        # was itself a review catch); (b) the dynamic matrix — a test
        # file that reads ``faults.POINTS`` and calls ``faults.arm``/
        # ``faults.active`` arms every declared point by construction
        # (test_robustness's parametrized conservation sweep). Delete
        # the matrix and the POINTS reference disappears with it, so
        # the check bites again.
        armed: set[str] = set()
        for sf in proj.files:
            if not sf.path.startswith(cfg.test_prefix):
                continue
            if sf.tree is None:
                continue
            reads_points = False
            arms = False
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    for clause in node.value.split(","):
                        armed.add(clause.split(":")[0].strip())
                elif isinstance(node, ast.Attribute) and (
                    node.attr == "POINTS"
                ):
                    reads_points = True
                elif isinstance(node, ast.Call):
                    chain = common.attr_chain(node.func)
                    if chain and chain[-1] in ("arm", "active"):
                        arms = True
            if reads_points and arms:
                armed.update(points)
        for point, decl_line in points.items():
            if point not in armed:
                findings.append(Finding(
                    rule=self.id, file=faults_sf.path, line=decl_line,
                    message=(
                        f"fault point {point!r} is armed by no test "
                        f"(no spec-shaped string in {cfg.test_prefix} "
                        f"names it as a clause) — the seam is "
                        f"undrilled"
                    ),
                    symbol="POINTS",
                ))
        return findings

    def _ordering(self, sf, call, point, line, parents, guarded_attrs):
        func = common.enclosing_function(call, parents)
        if func is None:
            return []
        findings = []
        for site in common.find_mutations(func, guarded_attrs):
            if site.line < line:
                findings.append(Finding(
                    rule=self.id, file=sf.path, line=line,
                    message=(
                        f"faults.fire({point!r}) fires AFTER a "
                        f"mutation of guarded state "
                        f"`{site.base_fp}.{site.attr}` at line "
                        f"{site.line} in the same function — an "
                        f"injected failure here leaves the mutation "
                        f"already applied, so the drill exercises a "
                        f"corrupted-state path, not the seam"
                    ),
                    symbol=sf.symbol_at(line),
                ))
                break  # one finding per fire site
        return findings
