"""Shared cross-module resolution for the concurrency rules
(MLA007 lock order, MLA008 thread contexts).

Both rules need the same two facts the single-file rules never did:

- **Which class does this expression refer to?** ``self.eng.pool``
  means a :class:`PagePool` — knowable only through the repo's own
  wiring. Bindings are INFERRED from the one assignment shape the
  AST shows directly (``self.<attr> = <KnownClass>(...)`` anywhere in
  production code) and then overridden by the explicit
  ``config.INSTANCE_BINDINGS`` registry for the shapes it cannot see
  (constructor-arg back-references like ``self.eng = engine``, plain
  rebinds like ``pool.tier = self.kv_tier``). An attr name inferred
  to TWO different classes is dropped as ambiguous unless the config
  pins it — a wrong binding is worse than no binding.
- **Where is this callee's body?** Methods are indexed per class
  (class name -> method name -> def node), module functions per
  file. Resolution is name-based and honest about its limits: an
  unresolvable call is simply not followed, never guessed at.

Like everything in this package the analysis is lexical — no
instances, no inheritance walks (the serving classes are flat), no
dynamic dispatch. That is exactly the shape of the contracts it
feeds: the lock registry names concrete classes, and the thread
seeds name concrete functions.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.lint.rules import common


@dataclass
class ClassInfo:
    name: str
    file: str
    node: ast.ClassDef
    methods: dict[str, ast.AST] = field(default_factory=dict)
    properties: frozenset[str] = frozenset()


def production_index(proj, cfg):
    """``(files, ProjectIndex)`` over the production file set —
    built once per (project, config) and cached on the project, so
    MLA007, MLA008, and the ``--lockorder-out`` artifact render all
    share one repo-wide AST scan."""
    cached = getattr(proj, "_prod_index", None)
    if cached is not None and cached[0] is cfg:
        return cached[1], cached[2]
    files = [
        f for f in proj.files
        if f.path.startswith(cfg.production_prefix)
        and f.tree is not None
    ]
    index = ProjectIndex(files, cfg)
    proj._prod_index = (cfg, files, index)
    return files, index


class ProjectIndex:
    """Classes, methods, module functions, and instance-attr ->
    class bindings over the production file set. Built via
    :func:`production_index` (cached per run), shared by MLA007 and
    MLA008."""

    def __init__(self, files, cfg):
        self.classes: dict[str, ClassInfo] = {}
        # (file, func_name) -> def node, module level only.
        self.module_funcs: dict[tuple[str, str], ast.AST] = {}
        # def node -> (class_name | None, file)
        self.owner: dict[ast.AST, tuple[str | None, str]] = {}
        for sf in files:
            if sf.tree is None:
                continue
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    info = self.classes.setdefault(
                        node.name, ClassInfo(node.name, sf.path, node)
                    )
                    props = set(info.properties)
                    for meth in node.body:
                        if isinstance(
                            meth,
                            (ast.FunctionDef, ast.AsyncFunctionDef),
                        ):
                            info.methods.setdefault(meth.name, meth)
                            self.owner[meth] = (node.name, sf.path)
                            if "property" in common.decorator_names(
                                meth
                            ):
                                props.add(meth.name)
                    info.properties = frozenset(props)
                elif isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    self.module_funcs[(sf.path, node.name)] = node
                    self.owner[node] = (None, sf.path)
        self.bindings = self._infer_bindings(files, cfg)

    def _infer_bindings(self, files, cfg) -> dict[str, str]:
        inferred: dict[str, str | None] = {}
        for sf in files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                chain = common.attr_chain(node.value.func)
                if not chain or chain[-1] not in self.classes:
                    continue
                cls = chain[-1]
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                    ):
                        attr = t.attr
                        if inferred.get(attr, cls) != cls:
                            inferred[attr] = None  # ambiguous
                        else:
                            inferred[attr] = cls
        out = {a: c for a, c in inferred.items() if c is not None}
        out.update(cfg.instance_bindings)
        return out

    # -- resolution ----------------------------------------------------

    def resolve_receiver(self, chain: list[str],
                         enclosing_class: str | None) -> str | None:
        """Class name an attribute-chain RECEIVER refers to: the
        rightmost bound segment wins (``self.eng.pool`` -> the
        ``pool`` binding); a bare ``self`` is the enclosing class."""
        for seg in reversed(chain):
            if seg in self.bindings:
                return self.bindings[seg]
        if chain and chain[0] == "self" and len(chain) == 1:
            return enclosing_class
        return None

    def resolve_call(self, call: ast.Call, enclosing_class: str | None,
                     file: str):
        """``(def_node, class_name | None)`` for a call, or ``None``
        when the callee's body is not findable. ``self.m()`` binds to
        the enclosing class; ``<...>.bound.m()`` to the bound class;
        bare ``f()`` to the same module's top level."""
        chain = common.attr_chain(call.func)
        if not chain:
            return None
        name = chain[-1]
        if len(chain) == 1:
            node = self.module_funcs.get((file, name))
            return (node, None) if node is not None else None
        recv = chain[:-1]
        if recv == ["self"] and enclosing_class:
            cls = self.classes.get(enclosing_class)
        else:
            cname = self.resolve_receiver(recv, enclosing_class)
            cls = self.classes.get(cname) if cname else None
        if cls is None:
            return None
        node = cls.methods.get(name)
        return (node, cls.name) if node is not None else None


def functions_with_class(sf):
    """Every ``(enclosing_class | None, def)`` in a file — nested
    defs included, each visited once with the correct class. The ONE
    traversal both concurrency rules iterate, so they can never
    disagree on the function universe."""
    out = []

    def visit(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
            elif isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                out.append((cls, child))
                visit(child, cls)
            else:
                visit(child, cls)

    visit(sf.tree, None)
    return out


def lock_owner(ctx_expr: ast.AST, enclosing_class: str | None,
               index: ProjectIndex, lock_registry: dict):
    """``(class_name, lock_name)`` when ``with <recv>.<lock>:``
    acquires a REGISTERED class's registered lock, else ``None``."""
    if not isinstance(ctx_expr, ast.Attribute):
        return None
    chain = common.attr_chain(ctx_expr)
    if not chain or len(chain) < 2:
        return None
    lock_name = chain[-1]
    recv = chain[:-1]
    if recv == ["self"]:
        cname = enclosing_class
    else:
        cname = index.resolve_receiver(recv, enclosing_class)
    if cname is None:
        return None
    spec = lock_registry.get(cname)
    if spec is None or lock_name not in spec.locks:
        return None
    return cname, lock_name
