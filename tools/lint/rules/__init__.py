"""Rule registry: stable IDs -> implementations.

IDs are append-only (a baseline entry or suppression names them;
renumbering would orphan every written justification).
"""

from tools.lint.rules.donation import DonationRule
from tools.lint.rules.hygiene import TestHygieneRule
from tools.lint.rules.lockorder import LockOrderRule
from tools.lint.rules.locks import LockRule
from tools.lint.rules.metrics_consistency import MetricsRule
from tools.lint.rules.router_purity import RouterPurityRule
from tools.lint.rules.seams import SeamRule
from tools.lint.rules.terminal_wait import TerminalWaitRule
from tools.lint.rules.threadctx import ThreadContextRule

ALL_RULES = (
    DonationRule(),       # MLA001
    LockRule(),           # MLA002
    SeamRule(),           # MLA003
    RouterPurityRule(),   # MLA004
    MetricsRule(),        # MLA005
    TestHygieneRule(),    # MLA006
    LockOrderRule(),      # MLA007
    ThreadContextRule(),  # MLA008
    TerminalWaitRule(),   # MLA009
)
