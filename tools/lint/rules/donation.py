"""MLA001 — donation discipline (the r12/r13/r15 poisoning class).

A ``jax.jit(..., donate_argnums=...)`` program CONSUMES the buffers
bound at its donated positions: after the call those arrays are
deleted, and any later read dies on deleted buffers — at dispatch
time, far from the bug. Three PRs in a row shipped exactly this shape
(a fallback path reading ``pool.layers`` a failed donated restore had
consumed; a stale lane pytree written back over the live pool) and
each was only caught in review.

The rule, lexical and intraprocedural like every incident it encodes:

1. **Factory pass (whole tree).** A function whose body returns
   ``jax.jit(f, donate_argnums=(...))`` is a *donating factory*; its
   name maps to the donated positional indices of the returned
   callable. Local ``g = jax.jit(f, donate_argnums=...)`` bindings
   register the same way within their function and any nested
   closure (the ``make_train_step`` shape) — but each frame's
   read/rebind analysis never crosses a function boundary.
2. **Call-site pass.** ``factory(...)(a0, a1, ...)`` (or a local
   jitted name called directly) donates the argument expressions at
   the registered indices. For each donated Name/Attribute argument:

   - the call statement itself rebinding the expression
     (``x = fac()(x, ...)`` — tuple targets count) is the documented
     write-back: fine;
   - otherwise, a lexically later READ of the same expression in the
     same function, BEFORE a rebind event — a (re)assignment of the
     expression, a ``<base>.epoch`` bump, or a call to a
     ``*rebind*``/``*writeback*``/``*_paged_cleanup*`` helper — is a
     poisoning read: flagged at the read's line.

Control flow is ignored (no loop back-edges, no cross-function
dataflow) — the historical bugs were all lexically visible, and a
rule that guessed at more would need suppressing everywhere.
"""

from __future__ import annotations

import ast

from tools.lint import Finding
from tools.lint.rules import common

_REBIND_HINTS = ("rebind", "writeback", "write_back", "paged_cleanup")


def _donate_indices(call: ast.Call) -> tuple[int, ...] | None:
    """``jax.jit(f, donate_argnums=...)`` -> the donated indices."""
    chain = common.attr_chain(call.func)
    if chain is None or chain[-1] != "jit":
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Tuple):
                return tuple(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                )
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
    return None


def _collect_factories(files) -> dict[str, tuple[int, ...]]:
    factories: dict[str, tuple[int, ...]] = {}
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Return)
                    and isinstance(sub.value, ast.Call)
                ):
                    idx = _donate_indices(sub.value)
                    if idx:
                        prev = factories.get(node.name, ())
                        factories[node.name] = tuple(
                            sorted(set(prev) | set(idx))
                        )
    return factories


class DonationRule:
    id = "MLA001"
    title = "donated buffers must not be read after dispatch"

    def run(self, proj, cfg):
        files = [
            f for f in proj.files
            if f.path.startswith(cfg.production_prefix)
        ]
        factories = _collect_factories(files)
        findings: list[Finding] = []
        for sf in files:
            if sf.tree is None:
                continue
            self._visit_scope(sf, sf.tree, factories, {},
                              sf.parents(), findings)
        return findings

    # -- per-function analysis ----------------------------------------

    def _visit_scope(self, sf, scope, factories, inherited, parents,
                     findings):
        """Recurse function-by-function, carrying jit bindings down
        the closure chain (``jitted = jax.jit(...)`` in an enclosing
        function is callable from a nested one), while each frame's
        read/rebind analysis stays strictly intraprocedural
        (``walk_shallow``)."""
        local = dict(inherited)
        for node in common.walk_shallow(scope):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                idx = _donate_indices(node.value)
                if idx:
                    local[node.targets[0].id] = idx
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(
                self._check_function(sf, scope, factories, local,
                                     parents)
            )
        for node in common.walk_shallow(scope):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._visit_scope(sf, node, factories, local, parents,
                                  findings)

    def _check_function(self, sf, func, factories, local, parents):
        findings = []
        for node in common.walk_shallow(func):
            if not isinstance(node, ast.Call):
                continue
            idx = self._donating_call(node, factories, local)
            if idx is None:
                continue
            stmt = self._enclosing_stmt(node, parents)
            if stmt is None:
                continue
            for i in idx:
                if i >= len(node.args):
                    continue
                arg = node.args[i]
                if not isinstance(arg, (ast.Name, ast.Attribute)):
                    continue
                fp = common.fingerprint(arg)
                if self._rebound_in_stmt(stmt, fp):
                    continue
                hit = self._read_before_rebind(
                    func, stmt, fp
                )
                if hit is not None:
                    findings.append(Finding(
                        rule=self.id,
                        file=sf.path,
                        line=hit,
                        message=(
                            f"`{fp}` is read after being donated to a "
                            f"donate_argnums dispatch at line "
                            f"{stmt.lineno} with no write-back/epoch "
                            f"rebind in between — the buffer is "
                            f"consumed (r12/r13/r15 poisoning class)"
                        ),
                        symbol=sf.symbol_at(hit),
                    ))
        return findings

    @staticmethod
    def _donating_call(node: ast.Call, factories, local):
        # factory(...)(args) — outer call whose func is a call of a
        # known factory name.
        f = node.func
        if isinstance(f, ast.Call):
            chain = common.attr_chain(f.func)
            if chain and chain[-1] in factories:
                return factories[chain[-1]]
            return None
        # jitted-name(args) — local jax.jit binding called directly.
        if isinstance(f, ast.Name) and f.id in local:
            return local[f.id]
        return None

    @staticmethod
    def _enclosing_stmt(node, parents):
        for anc in [node] + common.ancestors(node, parents):
            if isinstance(anc, ast.stmt):
                return anc
        return None

    @staticmethod
    def _rebound_in_stmt(stmt, fp: str) -> bool:
        """The donating statement assigns the donated expression
        (directly or inside a tuple target): the documented same-
        statement write-back."""
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                if common.fingerprint(el) == fp:
                    return True
        return False

    @staticmethod
    def _read_before_rebind(func, stmt, fp: str) -> int | None:
        """First line > the donating statement that READS ``fp``
        before any rebind event; None when the first event is a
        rebind (or there are no events)."""
        start = stmt.end_lineno or stmt.lineno
        base = fp.rsplit(".", 1)[0] if "." in fp else fp
        events: list[tuple[int, str]] = []  # (line, "read"|"rebind")
        for node in common.walk_shallow(func):
            line = getattr(node, "lineno", None)
            if line is None or line <= start:
                continue
            # Rebind events -------------------------------------------------
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    els = t.elts if isinstance(t, ast.Tuple) else [t]
                    for el in els:
                        efp = common.fingerprint(el)
                        if efp == fp or efp == f"{base}.epoch":
                            events.append((line, "rebind"))
            if isinstance(node, ast.Call):
                chain = common.attr_chain(node.func)
                if chain and any(
                    h in chain[-1] for h in _REBIND_HINTS
                ):
                    events.append((line, "rebind"))
            # Read events ---------------------------------------------------
            if (
                isinstance(node, (ast.Name, ast.Attribute))
                and isinstance(getattr(node, "ctx", None), ast.Load)
                and common.fingerprint(node) == fp
            ):
                events.append((line, "read"))
        events.sort()
        for line, kind in events:
            if kind == "rebind":
                return None
            return line
        return None
