"""MLA007 — lock-order cycles across the registered locks.

The serving stack now guards shared state with seven registered
locks (PagePool, KVTier, UnitScheduler, LatencyStats, PrefixCache,
KVPeer, KVPush) acquired from at least five thread contexts. Any two
locks acquired in BOTH nesting orders by different threads is a
deadlock waiting for load — and the partial order lived nowhere: the
r13 review moved the pool spill outside the lock and the r17 review
moved prefix hashing outside the peer lock precisely because nobody
could see the whole graph. This rule builds it.

**The graph.** Nodes are the registered lock-bearing classes. An
edge ``A -> B`` means "A's lock is held while B's lock is acquired",
discovered lexically from every ``with <recv>.<lock>:`` whose
receiver resolves to a registered class (``tools/lint/config.py``'s
binding registry + inferred ``self.x = Class()`` assignments —
``rules/graph.py``):

- a ``with`` nested inside the body acquiring a DIFFERENT registered
  class's lock adds a direct edge;
- a call inside the body is resolved to its def (same-class method,
  bound-class method, or same-module function) and scanned
  recursively (bounded, cycle-safe): any registered lock THAT body
  acquires is an edge from the held class. A ``*_locked``-suffixed
  callee acquires nothing by the repo's convention — its caller
  already holds the lock, which the direct case above sees.
- an attribute read of a bound class's ``@property`` whose body
  acquires the class lock (``pool.pages_in_use`` under another lock)
  counts like a call.

**Findings.** Any cycle in the graph — including a self-edge
``A -> A``, which is a self-deadlock on this repo's non-reentrant
``threading.Lock``s — fails the run, with one finding per cycle
naming an acquisition site for every edge on it.

**The artifact.** The acyclic graph is emitted as
``tools/lint/lockorder.json`` (``--lockorder-out``; the tier-1 test
pins the committed file to the recomputed graph so it can never
drift silently). It is the machine-readable partial order future PRs
diff — and the contract ``tools/lint/witness.py`` enforces at
RUNTIME: the witness records per-thread acquisition stacks and fails
loudly on any inversion of an edge in this file, so the static order
and the dynamic order are checked against each other.
"""

from __future__ import annotations

import ast
import json

from tools.lint import Finding
from tools.lint.rules import common
from tools.lint.rules.graph import (
    functions_with_class,
    lock_owner,
    production_index,
)

_MAX_DEPTH = 6  # recursion bound for followed calls (cycle-safe anyway)


class _GraphBuilder:
    def __init__(self, proj, cfg):
        self.cfg = cfg
        self.files, self.index = production_index(proj, cfg)
        # (held_class, acquired_class) -> sorted set of "file:line".
        self.edges: dict[tuple[str, str], set[str]] = {}
        self._acquires_cache: dict[ast.AST, bool] = {}

    # -- public --------------------------------------------------------

    def build(self) -> dict[tuple[str, str], list[str]]:
        for sf in self.files:
            for cls_name, func in functions_with_class(sf):
                self._scan_function(sf, cls_name, func)
        return {
            k: sorted(v) for k, v in sorted(self.edges.items())
        }

    # -- traversal -----------------------------------------------------

    def _scan_function(self, sf, cls_name, func):
        for node in common.walk_shallow(func):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                owner = lock_owner(
                    item.context_expr, cls_name, self.index,
                    self.cfg.lock_registry,
                )
                if owner is None:
                    continue
                held_cls = owner[0]
                for stmt in node.body:
                    self._scan_held(
                        sf, cls_name, stmt, held_cls, _MAX_DEPTH,
                        frozenset(),
                    )

    def _scan_held(self, sf, cls_name, root, held_cls, depth,
                   visited):
        """Walk code executing while ``held_cls``'s lock is held;
        record every registered-lock acquisition as an edge."""
        for node in _walk_shallow_tree(root):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    owner = lock_owner(
                        item.context_expr, cls_name, self.index,
                        self.cfg.lock_registry,
                    )
                    if owner is not None:
                        self._edge(held_cls, owner[0], sf, node.lineno)
            elif isinstance(node, ast.Call):
                self._follow(sf, cls_name, node, held_cls, depth,
                             visited)
            elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                self._follow_property(sf, cls_name, node, held_cls)

    def _follow(self, sf, cls_name, call, held_cls, depth, visited):
        if depth <= 0:
            return
        chain = common.attr_chain(call.func)
        if chain and chain[-1].endswith("_locked"):
            return  # caller-holds-the-lock convention: acquires nothing
        hit = self.index.resolve_call(call, cls_name, sf.path)
        if hit is None:
            return
        callee, callee_cls = hit
        if callee in visited:
            return
        if self._acquires_any_lock(callee):
            # The callee's body runs entirely under held_cls's lock:
            # scan it with the SAME holder, charging edges to the
            # call site's file/line region (the callee's own nested
            # orders are charged when its own with-blocks are
            # scanned as holders).
            self._scan_callee(
                sf, call, callee, callee_cls, held_cls,
                depth - 1, visited | {callee},
            )

    def _scan_callee(self, call_sf, call, callee, callee_cls,
                     held_cls, depth, visited):
        sf = self._sf_of(callee)
        for node in common.walk_shallow(callee):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    owner = lock_owner(
                        item.context_expr, callee_cls, self.index,
                        self.cfg.lock_registry,
                    )
                    if owner is not None:
                        self._edge(
                            held_cls, owner[0], call_sf, call.lineno
                        )
                # Locks acquired INSIDE this with release before the
                # outer holder does — no need to recurse with a new
                # holder here (the callee's own scan covers it).
            elif isinstance(node, ast.Call):
                self._follow(sf or call_sf, callee_cls, node,
                             held_cls, depth, visited)
            elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                self._follow_property(
                    sf or call_sf, callee_cls, node, held_cls,
                    site=(call_sf, call.lineno),
                )

    def _follow_property(self, sf, cls_name, attr, held_cls,
                         site=None):
        chain = common.attr_chain(attr)
        if not chain or len(chain) < 2:
            return
        recv = chain[:-1]
        if recv == ["self"]:
            cname = cls_name
        else:
            cname = self.index.resolve_receiver(recv, cls_name)
        if cname is None or cname == held_cls:
            return
        cls = self.index.classes.get(cname)
        if cls is None or attr.attr not in cls.properties:
            return
        prop = cls.methods.get(attr.attr)
        if prop is not None and self._acquires_own_lock(prop, cname):
            where = site or (sf, attr.lineno)
            self._edge(held_cls, cname, where[0], where[1])

    # -- predicates ----------------------------------------------------

    def _acquires_any_lock(self, func) -> bool:
        """Does this def's body (nested defs excluded) contain ANY
        with-acquisition of a registered lock, or a call it might
        chain through? Cheap pre-filter: any With at all, or any
        Call — conservative, the real edge test runs in the scan."""
        cached = self._acquires_cache.get(func)
        if cached is None:
            cached = any(
                isinstance(n, (ast.With, ast.AsyncWith, ast.Call))
                for n in common.walk_shallow(func)
            )
            self._acquires_cache[func] = cached
        return cached

    def _acquires_own_lock(self, func, cls_name) -> bool:
        for node in common.walk_shallow(func):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    owner = lock_owner(
                        item.context_expr, cls_name, self.index,
                        self.cfg.lock_registry,
                    )
                    if owner is not None and owner[0] == cls_name:
                        return True
        return False

    def _sf_of(self, func):
        owner = self.index.owner.get(func)
        if owner is None:
            return None
        for sf in self.files:
            if sf.path == owner[1]:
                return sf
        return None

    def _edge(self, a, b, sf, line):
        self.edges.setdefault((a, b), set()).add(f"{sf.path}:{line}")


def _walk_shallow_tree(root):
    """walk_shallow over a statement (root included)."""
    yield root
    yield from common.walk_shallow(root)


def build_lock_graph(proj, cfg):
    """``{(held, acquired): [site, ...]}`` over production code —
    computed once per (project, config): the rule and the
    ``--lockorder-out`` render share the result."""
    cached = getattr(proj, "_lock_graph", None)
    if cached is not None and cached[0] is cfg:
        return cached[1]
    edges = _GraphBuilder(proj, cfg).build()
    proj._lock_graph = (cfg, edges)
    return edges


def find_cycles(edges) -> list[list[str]]:
    """Elementary cycles (as node lists, smallest-first start) via
    DFS — the graph has a handful of nodes, nothing fancier needed.
    Self-edges come out as ``[A]``."""
    adj: dict[str, set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    cycles: list[list[str]] = []
    seen_keys: set[tuple[str, ...]] = set()

    def dfs(start, node, path):
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                # Key = the path itself (it already starts at the
                # cycle's smallest node): two DISTINCT cycles over
                # the same node set (both orientations of a ring)
                # must each be reported.
                key = tuple(path)
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(list(path))
            elif nxt not in path and nxt > start:
                # Only explore nodes > start: each cycle is found
                # exactly once, from its smallest node.
                dfs(start, nxt, path + [nxt])

    for start in sorted(adj):
        dfs(start, start, [start])
    return cycles


def graph_as_json(edges, lock_registry) -> dict:
    """The machine-readable artifact: nodes, edges with example
    sites, and (when acyclic) one valid total order — deterministic,
    so the committed file diffs cleanly across PRs."""
    nodes = sorted(lock_registry)
    out_edges = [
        {"before": a, "after": b, "sites": sites}
        for (a, b), sites in sorted(edges.items())
    ]
    order = _topo_order(nodes, edges)
    return {
        "version": 1,
        "nodes": nodes,
        "edges": out_edges,
        "order": order,
    }


def _topo_order(nodes, edges) -> list[str] | None:
    indeg = {n: 0 for n in nodes}
    adj: dict[str, list[str]] = {n: [] for n in nodes}
    for a, b in edges:
        if a in indeg and b in indeg:
            adj[a].append(b)
            indeg[b] += 1
    ready = sorted(n for n, d in indeg.items() if d == 0)
    order: list[str] = []
    while ready:
        n = ready.pop(0)
        order.append(n)
        for m in sorted(adj[n]):
            indeg[m] -= 1
            if indeg[m] == 0:
                ready.append(m)
        ready.sort()
    return order if len(order) == len(nodes) else None


def render_artifact(proj, cfg) -> str:
    edges = build_lock_graph(proj, cfg)
    doc = graph_as_json(edges, cfg.lock_registry)
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"


class LockOrderRule:
    id = "MLA007"
    title = "registered locks must form a cycle-free acquisition order"

    def run(self, proj, cfg):
        edges = build_lock_graph(proj, cfg)
        findings: list[Finding] = []
        for cycle in find_cycles(edges):
            sites = []
            ring = cycle + [cycle[0]]
            for a, b in zip(ring, ring[1:]):
                es = edges.get((a, b))
                if es:
                    sites.append(f"{a}->{b} at {es[0]}")
            anchor_file, anchor_line = self._anchor(edges, cycle)
            if len(cycle) == 1:
                msg = (
                    f"lock self-deadlock: {cycle[0]}'s lock is "
                    f"acquired while already held "
                    f"({'; '.join(sites)}) — threading.Lock is not "
                    f"reentrant"
                )
            else:
                msg = (
                    f"lock-order cycle {' -> '.join(ring)}: two "
                    f"threads taking these in opposite order "
                    f"deadlock under load ({'; '.join(sites)}) — "
                    f"break one edge (move the call outside the "
                    f"lock, the claim-under-lock/work-outside "
                    f"pattern)"
                )
            findings.append(Finding(
                rule=self.id, file=anchor_file, line=anchor_line,
                message=msg,
            ))
        return findings

    @staticmethod
    def _anchor(edges, cycle):
        ring = cycle + [cycle[0]]
        for a, b in zip(ring, ring[1:]):
            es = edges.get((a, b))
            if es:
                f, _, ln = es[0].rpartition(":")
                try:
                    return f, int(ln)
                except ValueError:
                    continue
        return "tools/lint/config.py", 1
