"""MLA005 — metrics-registry consistency.

The ``/metrics`` block is the repo's observable contract: BENCH
blocks, the router's fleet sums, the README/DESIGN tables, and a
dozen tests all navigate by counter NAME. Names are plain strings
assembled in four different places (app.py's snapshot block, the
router's relabeler, registry.counter calls, the LatencyStats summary
loop), so a rename — or a test asserting a counter that was never
exported — compiles fine and fails only at scrape time, or worse,
silently scrapes a key that is always absent.

Sets computed per run:

- **Exported**: string keys stored into ``snap["counters"]``/
  ``snap["gauges"]``; constant args of ``registry.counter(...)`` /
  ``registry.histogram(...)``; every metric-shaped string constant
  inside a function named ``metrics`` (the endpoint builders); plus
  the dynamic families — ``generate.<k>`` for each LatencyStats
  summary key (the f-string export loop), and the configured
  dynamic prefixes (``replica.``/``router.``/``http.`` — relabeled
  or route-labeled at runtime).
- **Scraped**: metric-shaped strings in tests/ and bench.py.
- **Documented**: metric-shaped tokens in README.md / DESIGN.md.

Checks: every scraped and every documented name must be satisfied by
the exported set — exactly, as a prefix of an exported name (bench
filters on prefixes like ``generate.sched_``), or under a dynamic
prefix. Findings anchor at the scrape/doc line, because that is
where the drift is fixable.
"""

from __future__ import annotations

import ast
import re

from tools.lint import Finding
from tools.lint.config import DYNAMIC_METRIC_PREFIXES, METRIC_NAME_RE
from tools.lint.rules import common

_NAME_RE = re.compile(METRIC_NAME_RE)
# `batcher.py::_collect_loop` / `router.py` are file references that
# happen to share a metric family's prefix — never metric names.
_FILE_LOOKALIKE_RE = re.compile(r"^\w+\.py(?:\b|$)")


def _metric_tokens(text: str):
    for name in _NAME_RE.findall(text):
        if not _FILE_LOOKALIKE_RE.match(name):
            yield name


def _string_constants(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node


def _exported_names(serving_files, latency_sf) -> set[str]:
    names: set[str] = set()
    for sf in serving_files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            # snap["counters"]["generate.x"] = ...
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and isinstance(t.slice.value, str)
                        and isinstance(t.value, ast.Subscript)
                        and isinstance(t.value.slice, ast.Constant)
                        and t.value.slice.value in ("counters", "gauges")
                    ):
                        names.add(t.slice.value)
            # registry.counter("x") / registry.histogram("x")
            if isinstance(node, ast.Call):
                chain = common.attr_chain(node.func)
                if (
                    chain
                    and chain[-1] in ("counter", "histogram")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    names.add(node.args[0].value)
            # any metric-shaped constant inside a `metrics` builder
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.name == "metrics":
                for const in _string_constants(node):
                    names.update(_metric_tokens(const.value))
    # Dynamic family: the f"generate.{k}" LatencyStats export loop.
    if latency_sf is not None and latency_sf.tree is not None:
        for node in ast.walk(latency_sf.tree):
            if (
                isinstance(node, ast.ClassDef)
                and node.name == "LatencyStats"
            ):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Dict):
                        for k in sub.keys:
                            if isinstance(k, ast.Constant) and (
                                isinstance(k.value, str)
                            ):
                                names.add(f"generate.{k.value}")
    return names


def _satisfied(name: str, exported: set[str]) -> bool:
    if name.startswith(DYNAMIC_METRIC_PREFIXES):
        return True
    if name in exported:
        return True
    # A scraped/documented PREFIX (bench family filters, README's
    # `generate.shed_` rows, brace shorthand truncated at `{`) is
    # satisfied by an exported name under it — but only at a real
    # name boundary (`_`, `.`, or a digit, the brace-expansion
    # shapes). Without the boundary check, a typo'd scrape that is a
    # strict character prefix of a real name (`...restore_hit` for
    # `...restore_hits`) would silently pass.
    for e in exported:
        if e.startswith(name):
            nxt = e[len(name)]
            if (
                name.endswith(("_", "."))
                or nxt in "_."
                or nxt.isdigit()
            ):
                return True
    return False


class MetricsRule:
    id = "MLA005"
    title = "scraped/documented metric names must be exported"

    def run(self, proj, cfg):
        serving = proj.matching(cfg.serving_prefix)
        exported = _exported_names(
            serving, proj.get(cfg.latency_stats_module)
        )
        if not exported:
            return []  # nothing exports metrics in this scan set
        findings: list[Finding] = []

        # Scrapes: tests + bench.
        scrape_files = [
            f for f in proj.files
            if f.path.startswith(cfg.test_prefix)
            or f.path in cfg.bench_files
        ]
        for sf in scrape_files:
            if sf.tree is None:
                continue
            seen: set[tuple[str, int]] = set()
            for const in _string_constants(sf.tree):
                for name in _metric_tokens(const.value):
                    key = (name, const.lineno)
                    if key in seen or _satisfied(name, exported):
                        continue
                    seen.add(key)
                    findings.append(Finding(
                        rule=self.id, file=sf.path, line=const.lineno,
                        message=(
                            f"scraped metric {name!r} matches no "
                            f"exported counter/gauge (and no exported "
                            f"name extends it) — the scrape reads a "
                            f"key that will never exist"
                        ),
                        symbol=sf.symbol_at(const.lineno),
                    ))
        # Docs: README / DESIGN tables must not drift.
        for path, text in proj.docs.items():
            for i, line in enumerate(text.splitlines(), 1):
                for name in _metric_tokens(line):
                    if _satisfied(name, exported):
                        continue
                    findings.append(Finding(
                        rule=self.id, file=path, line=i,
                        message=(
                            f"documented metric {name!r} matches no "
                            f"exported counter/gauge — the doc table "
                            f"has drifted from the code"
                        ),
                    ))
        return findings
