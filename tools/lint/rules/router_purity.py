"""MLA004 — router async purity.

``serving/router.py`` runs ON the event loop and fronts the whole
fleet: one blocking call freezes every concurrent relay, the health
poll, and the drain path at once (and ``import jax`` would pull a
device runtime into a process whose whole point is having none —
its docstring promises both). The contract held by review so far;
this rule pins it.

Flags, in the configured async-pure modules:

- any ``import jax`` / ``from jax import ...`` (including inside
  functions — lazy imports count);
- any CALL of a blocking primitive (``time.sleep``, sync
  ``subprocess``/``socket``/``os.system``, builtin ``open``) unless
  the call sits inside a SYNC nested function handed to
  ``run_in_executor`` (the documented escape hatch —
  ``_fire_async`` passes ``faults.fire`` uncalled, which needs no
  exemption because there is no call node).
"""

from __future__ import annotations

import ast

from tools.lint import Finding
from tools.lint.config import BLOCKING_BUILTINS, BLOCKING_CALLS
from tools.lint.rules import common


def _executor_fn_names(tree) -> set[str]:
    """Names of functions/lambdas referenced as run_in_executor
    arguments (``loop.run_in_executor(None, fn, *args)``)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = common.attr_chain(node.func)
        if not chain or chain[-1] != "run_in_executor":
            continue
        for arg in node.args[1:]:
            c = common.attr_chain(arg)
            if c:
                out.add(c[-1])
    return out


class RouterPurityRule:
    id = "MLA004"
    title = "async-pure modules: no jax import, no blocking calls"

    def run(self, proj, cfg):
        findings: list[Finding] = []
        for rel in cfg.async_pure_modules:
            sf = proj.get(rel)
            if sf is None or sf.tree is None:
                continue
            parents = sf.parents()
            executor_fns = _executor_fn_names(sf.tree)
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        root = alias.name.split(".")[0]
                        if root == "jax":
                            findings.append(self._f(
                                sf, node.lineno,
                                "`import jax` in an async-pure module "
                                "— the router serves fleets with no "
                                "device runtime by contract",
                            ))
                elif isinstance(node, ast.ImportFrom):
                    root = (node.module or "").split(".")[0]
                    if root == "jax":
                        findings.append(self._f(
                            sf, node.lineno,
                            "`from jax import ...` in an async-pure "
                            "module",
                        ))
                elif isinstance(node, ast.Call):
                    label = self._blocking(node)
                    if label is None:
                        continue
                    if self._under_executor_fn(
                        node, parents, executor_fns
                    ):
                        continue
                    findings.append(self._f(
                        sf, node.lineno,
                        f"blocking call `{label}` on the event loop — "
                        f"wrap in run_in_executor (one blocked loop "
                        f"freezes every relay and the health poll)",
                    ))
        return findings

    @staticmethod
    def _blocking(node: ast.Call) -> str | None:
        f = node.func
        if isinstance(f, ast.Name) and f.id in BLOCKING_BUILTINS:
            return f.id
        chain = common.attr_chain(f)
        if chain and len(chain) >= 2:
            mod, attr = ".".join(chain[:-1]), chain[-1]
            if (mod, attr) in BLOCKING_CALLS or (
                (chain[-2], attr) in BLOCKING_CALLS
            ):
                return f"{mod}.{attr}"
        return None

    @staticmethod
    def _under_executor_fn(node, parents, executor_fns) -> bool:
        for anc in common.ancestors(node, parents):
            if isinstance(anc, ast.Lambda):
                return True  # lambdas only run when invoked elsewhere
            if isinstance(anc, ast.FunctionDef) and (
                anc.name in executor_fns
            ):
                return True
            if isinstance(anc, ast.AsyncFunctionDef):
                return False  # reached the event-loop frame: blocking
        return False

    def _f(self, sf, line, msg):
        return Finding(
            rule=self.id, file=sf.path, line=line, message=msg,
            symbol=sf.symbol_at(line),
        )
