"""Suppressions: inline comments and the baseline file.

Two mechanisms, both requiring a WRITTEN justification (a suppression
whose reason nobody recorded is indistinguishable from a bug nobody
fixed):

- **Inline**: ``# lint: allow(MLA002): <why>`` on the finding's line
  or the line directly above it. For deliberate single-site patterns
  (the claim-under-lock/spill-outside shape) where the justification
  belongs next to the code.
- **Baseline file** (``tools/lint/baseline.txt``): one entry per
  line, ``<RULE> <file>::<symbol> -- <why>``, matching findings by
  (rule, file, enclosing scope) so entries survive line drift. For
  whole-pattern false positives the rule cannot express.

Both are STRICT: an inline allow with an empty reason, a malformed
baseline line, or a baseline entry that matched nothing this run
(stale — the code it excused is gone) are themselves errors. The
baseline can only shrink honestly.
"""

from __future__ import annotations

import re

from tools.lint import Finding

_INLINE_RE = re.compile(
    r"#\s*lint:\s*allow\((?P<rules>[A-Z0-9, ]+)\)\s*:\s*(?P<why>\S.*)"
)
_BASELINE_RE = re.compile(
    r"^(?P<rule>MLA\d{3})\s+(?P<file>\S+)::(?P<symbol>\S*)\s+--\s+"
    r"(?P<why>\S.*)$"
)


class SuppressionError(Exception):
    """Malformed or stale suppression — exit code 2."""


def _inline_allows(sf, line: int) -> set[str]:
    """Rule IDs allowed at ``line`` by a well-formed inline comment on
    the line or the one above."""
    allowed: set[str] = set()
    for ln in (line, line - 1):
        comment = sf.comments.get(ln)
        if not comment:
            continue
        m = _INLINE_RE.search(comment)
        if m:
            allowed.update(
                r.strip() for r in m.group("rules").split(",")
            )
        elif "lint: allow" in comment:
            raise SuppressionError(
                f"{sf.path}:{ln}: malformed inline suppression "
                f"{comment!r} — want `# lint: allow(MLA0xx): reason` "
                f"with a non-empty reason"
            )
    return allowed


def load_baseline(path) -> list[dict]:
    entries = []
    if not path.is_file():
        return entries
    for i, raw in enumerate(path.read_text().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = _BASELINE_RE.match(line)
        if not m:
            raise SuppressionError(
                f"{path.name}:{i}: malformed baseline entry {line!r} "
                f"— want `MLA0xx path::Class.symbol -- justification`"
            )
        entries.append({**m.groupdict(), "line": i, "used": False})
    return entries


def apply_suppressions(proj, cfg, findings: list[Finding],
                       rule_ids: set[str] | None = None):
    """Split findings into (reported, suppressed); raises
    SuppressionError on malformed/stale suppressions. Staleness is
    only judged for entries whose rule actually RAN this invocation
    (``rule_ids``; None = all) — a ``--rules MLA001`` triage run must
    not condemn the MLA002 baseline as stale."""
    entries = load_baseline(proj.root / cfg.baseline_file)
    reported: list[Finding] = []
    suppressed: list[Finding] = []
    for f in findings:
        sf = proj.get(f.file)
        if sf is not None and f.rule in _inline_allows(sf, f.line):
            suppressed.append(f)
            continue
        hit = None
        for e in entries:
            if (
                e["rule"] == f.rule
                and e["file"] == f.file
                and e["symbol"] == f.symbol
            ):
                hit = e
                break
        if hit is not None:
            hit["used"] = True
            suppressed.append(f)
        else:
            reported.append(f)
    stale = [
        e for e in entries
        if not e["used"]
        and (rule_ids is None or e["rule"] in rule_ids)
    ]
    if stale:
        lines = ", ".join(
            f"{cfg.baseline_file}:{e['line']} ({e['rule']} "
            f"{e['file']}::{e['symbol']})"
            for e in stale
        )
        raise SuppressionError(
            f"stale baseline entr{'y' if len(stale) == 1 else 'ies'} "
            f"(matched no finding this run — delete, the excused code "
            f"is gone): {lines}"
        )
    return reported, suppressed
