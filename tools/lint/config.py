"""The repo-contract registry the rules check against.

Everything repo-specific lives HERE, not in the rule logic: which
attributes are lock-guarded and by which lock, which callables donate
their arguments, which module must stay async-pure, where the fault
points and metric exports live. A new shared structure (or a new
serving module) extends this file; the rules themselves stay generic
over the registry.

The registries are also what the tier-1 fixture tests parameterize:
``tests/test_static_analysis.py`` builds a Config pointed at
``tests/lint_fixtures/`` and asserts each rule flags its minimal
historical-bug repro at the exact ``file:line``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent


@dataclass(frozen=True)
class LockSpec:
    """One class's lock discipline: mutations of ``attrs`` (on
    ``self``) must happen lexically inside ``with self.<lock>`` for a
    lock named in ``locks``, or inside a method whose name ends in
    ``_locked`` (the documented caller-holds-the-lock convention)."""

    locks: frozenset[str]
    attrs: frozenset[str]


# -- MLA002: lock-guarded shared state -------------------------------------
#
# The shared-mutable registry. Deliberately NOT listed:
# - PagePool.layers / PagePool.epoch — single-dispatch-thread by
#   contract (the donation rule's domain, not the lock rule's).
# - UnitScheduler._pick_seq/_lane_seq/_summary_cache/_summary_seq and
#   the engine's sched_* counters — dispatch-thread-only by design
#   (DESIGN §21); registering them would force locks the one-writer
#   model does not need.
LOCK_REGISTRY: dict[str, LockSpec] = {
    "PagePool": LockSpec(
        locks=frozenset({"lock", "_evict_cond"}),
        attrs=frozenset({
            "ref", "_free", "_entries", "_evicting",
            # Counters: incremented from the decode thread AND the
            # event loop (brownout evict_idle, admission shed paths),
            # scraped by /metrics — a bare += is a lost update.
            "cow_copies", "entry_evictions", "exhaustions",
        }),
    ),
    "KVTier": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({
            "_blobs", "_bytes", "_seq", "_meta",
            "spill_count", "spill_bytes", "spill_failures",
            "restore_hits", "restore_misses", "restore_bytes",
            "restore_failures", "evictions",
        }),
    ),
    # Deliberately NOT listed: ``_last_was_score`` — the alternation
    # bit is read/written only on the dispatch thread (the same
    # one-writer contract as _pick_seq).
    "UnitScheduler": LockSpec(
        locks=frozenset({"_lock", "_work"}),  # _work wraps _lock
        attrs=frozenset({
            "_pending", "_lanes", "_forming_group", "_stopped",
            # r22 scoring fast path: formed batches enqueue from the
            # event loop (submit_score) while the dispatch thread
            # claims/drains — same cross-thread shape as _pending.
            "_score",
        }),
    ),
    # r22 multi-model/multi-tenant state. ModelRegistry's engine map
    # is frozen at build_app time; only the started-set mutates
    # (startup/shutdown hooks vs /healthz reads). TenantLedger is
    # crossed by the event loop (enter/brownout), the dispatch thread
    # (quota deferrals, terminal exits), and /metrics reads.
    "ModelRegistry": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({"_started"}),
    ),
    "TenantLedger": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({"_depth", "_deferrals", "_brownouts"}),
    ),
    # r17 peer-fetch state: hints arrive from the event loop, fetch
    # counters from encode executor threads, serve counters from the
    # app executor — all /metrics-scraped, all lost-update-prone.
    "KVPeer": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({
            "_hints", "_serve_cache",
            "fetch_hits", "fetch_misses", "fetch_bytes",
            "fetch_failures", "serve_count", "serve_bytes",
        }),
    ),
    # r18 disaggregation push state: chunk sends enqueue from the
    # dispatch thread, the sender thread posts and counts, receives
    # land on the app executor, applied/fallback counts come from the
    # dispatch thread AND encode executors — all /metrics-scraped,
    # all lost-update-prone.
    "KVPush": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({
            "_xfers", "_staged", "_staged_bytes", "_sendq", "_worker",
            "push_sent", "push_send_failures", "push_bytes_sent",
            "push_recv", "push_recv_failures", "push_bytes_recv",
            "push_applied", "push_bytes_applied", "push_fallbacks",
        }),
    ),
    # Prefix registry (r04, registered r19 for MLA007/MLA008's
    # whole-program view): entry lookups/registrations race across
    # encode executor threads; the counters are /metrics-scraped.
    # Deliberately NOT listed: ``_wide`` — the widened-stack cache is
    # mutated only at batch formation (the one dispatch thread at a
    # time), the same single-writer contract as ``PagePool.layers``.
    "PrefixCache": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({
            "_entries", "_building", "mix_warmed",
            "hits", "misses", "fallbacks", "builds",
        }),
    ),
    # r21 per-tenant adapter state. Deliberately NOT listed:
    # AdapterSlots.pools / AdapterSlots.rank — mutated only by
    # install()/_materialize() on the one dispatch thread (the same
    # single-writer contract as PagePool.layers); the donated scatter
    # could not tolerate a concurrent reader anyway.
    "AdapterStore": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({
            # Host LRU index + byte accounting: registrations arrive
            # from the event loop (register_adapter), fetch staging
            # from encode executor threads, spill/evict from either;
            # evictions is /metrics-scraped.
            "_blobs", "_bytes", "_seq", "evictions",
        }),
    ),
    "AdapterSlots": LockSpec(
        locks=frozenset({"lock"}),
        attrs=frozenset({
            # Slot map + holds: acquire/release cross from the
            # dispatch thread (batch formation/teardown) while
            # can_claim reads from the scheduler's advance; installs/
            # evictions are /metrics-scraped.
            "_slot_of", "_holds", "_free", "installs", "evictions",
        }),
    ),
    "AdapterPeer": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({
            # Warm-peer hints land from the event loop, fetch
            # counters from encode executor threads, serve counters
            # from the app executor — the KVPeer shape exactly.
            "_hints",
            "fetch_hits", "fetch_misses", "fetch_bytes",
            "fetch_failures", "serve_count", "serve_bytes",
        }),
    ),
    "LatencyStats": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({"_ttft_ms", "_itl_ms"}),
    ),
    "MetricsRegistry": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({"_counters", "_histograms"}),
    ),
    "Counter": LockSpec(
        locks=frozenset({"_lock"}), attrs=frozenset({"value"})
    ),
    "Histogram": LockSpec(
        locks=frozenset({"_lock"}),
        attrs=frozenset({"count", "total", "_reservoir"}),
    ),
}

# Attribute names distinctive enough to check OUTSIDE their class's
# own methods (e.g. ``self.eng.pool.cow_copies += n`` from
# batch_run): a mutation of ``<base>.<attr>`` for these must sit
# inside ``with <base>.lock``-family for the SAME base expression.
# Generic names (value, count, ref, total) stay self-scoped — the
# cross-module check would drown in unrelated matches.
DISTINCTIVE_ATTRS: dict[str, frozenset[str]] = {
    "cow_copies": frozenset({"lock"}),
    "entry_evictions": frozenset({"lock"}),
    "exhaustions": frozenset({"lock"}),
    "_free": frozenset({"lock"}),
    "_entries": frozenset({"lock"}),
    "_blobs": frozenset({"_lock"}),
    "spill_failures": frozenset({"_lock"}),
    "restore_failures": frozenset({"_lock"}),
    # r17/r18 additions, registered here r19 (they postdated the
    # registry and were cross-module-unchecked): the KVPush staging
    # store + its byte accounting and sender records, the KVPeer
    # warm-hint map and serve-side wire-image cache, and the
    # PrefixCache counters engine.py bumps from encode threads.
    "_staged": frozenset({"_lock"}),
    "_staged_bytes": frozenset({"_lock"}),
    "_xfers": frozenset({"_lock"}),
    "_hints": frozenset({"_lock"}),
    "_serve_cache": frozenset({"_lock"}),
    "builds": frozenset({"_lock"}),
    "fallbacks": frozenset({"_lock"}),
    "mix_warmed": frozenset({"_lock"}),
    # r21 adapter containers (batch_run holds/releases through the
    # AdapterSlots API today, but a future direct mutation of the
    # slot map or hold table from outside the class must still sit
    # under the instance's lock).
    "_slot_of": frozenset({"lock"}),
    "_holds": frozenset({"lock"}),
}

# Methods on guarded attributes that mutate the container. Reads
# (len, iteration, .get) stay free — the rule is MUTATION discipline.
MUTATING_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popitem",
    "popleft", "remove", "clear", "update", "add", "discard",
    "setdefault", "move_to_end", "sort",
})

# -- MLA007: lock-order graph ----------------------------------------------
# Attribute-name -> registered-class bindings the cross-module call
# resolver uses when the assignment shape (``self.pool =
# PagePool(...)``) is not visible in the AST (constructor args, plain
# name rebinds like ``pool.tier = self.kv_tier``). Inferred bindings
# (scanned from ``self.<attr> = <Class>(...)``) are merged first;
# entries here win on conflict.
INSTANCE_BINDINGS: dict[str, str] = {
    "pool": "PagePool",
    "tier": "KVTier",
    "kv_tier": "KVTier",
    "kv_peer": "KVPeer",
    "kv_push": "KVPush",
    "prefix": "PrefixCache",
    "sched": "UnitScheduler",
    "latency": "LatencyStats",
    "eng": "TextGenerationEngine",
    "engine": "TextGenerationEngine",
    "batcher": "ScorePath",
    "adapter_store": "AdapterStore",
    "adapters": "AdapterSlots",
    "adapter_peer": "AdapterPeer",
    "models": "ModelRegistry",
    "tenants": "TenantLedger",
    "led": "TenantLedger",
}
# Where the machine-readable partial order is committed (the rule
# recomputes it every run; the tier-1 test pins the committed file to
# the recomputed graph so the artifact can never drift silently, and
# the runtime witness loads it as the allowed order).
LOCKORDER_ARTIFACT = "tools/lint/lockorder.json"

# -- MLA008: thread-context inference --------------------------------------
# Functions seeded DISPATCH-thread (the one device-stream owner):
# BatchRun's unit generator and the scheduler's advance/loop. Thread
# targets and run_in_executor callees seed WORKER; every async def in
# a serving module seeds EVENT_LOOP.
DISPATCH_SEEDS: tuple[tuple[str, str], ...] = (
    ("BatchRun", "units"),
    ("UnitScheduler", "_advance"),
    ("UnitScheduler", "_loop"),
)
# Calls that BLOCK the calling thread — flagged when reachable in
# event-loop context outside an executor hop. Dotted prefixes match
# the trailing segments of the call chain (``np.savez`` matches
# ``np.savez_compressed`` via the startswith check in the rule).
EVENT_LOOP_BLOCKING_PREFIXES = (
    "time.sleep",
    "np.savez", "np.save", "np.load",
    "numpy.savez", "numpy.save", "numpy.load",
    "socket.socket", "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "os.system", "os.popen",
    "urllib.request.urlopen", "request.urlopen",
    "requests.get", "requests.post",
    "http.client.HTTPConnection",
)
# Bare attribute names that block or dispatch device work regardless
# of receiver: jax fences and host<->device transfers have no
# business on the event loop (they belong to the dispatch thread or
# an executor worker — the r13 spill-under-lock shape).
EVENT_LOOP_BLOCKING_ATTRS = frozenset({
    "block_until_ready", "device_put", "device_get",
})

# -- MLA009: terminal-frame wait discipline --------------------------------
# Counters that only SETTLE after a stream's terminal frame (their
# mutation runs on the dispatch thread during batch cleanup, strictly
# after the last frame reaches the awaiting test): asserting them
# lexically after a terminal read without a condition wait is the
# r17/r18 flake class.
SETTLE_AFTER_TERMINAL = ("kv_pages_in_use",)
# An await of a call whose name contains one of these consumed a
# stream to its terminal frame...
TERMINAL_READ_HINTS = ("collect", "gather")
# ...and one of these between the terminal read and the assert means
# the test waited for the state to settle (condition waits, engine
# stop/drain joins, and this suite's own `_quiesce`/`_settle`
# helpers). A ``while`` loop polling the counter inline counts as a
# wait too (the rule special-cases it).
SETTLE_WAIT_HINTS = (
    "wait", "stop", "drain", "join", "shutdown", "quiesce", "settle",
)

# -- MLA004: async purity --------------------------------------------------
# Modules that run ON the event loop and must not import jax or call
# blocking primitives outside run_in_executor.
ASYNC_PURE_MODULES = ("mlapi_tpu/serving/router.py",)

# (module, attr) call pairs that block the calling thread.
BLOCKING_CALLS = frozenset({
    ("time", "sleep"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("socket", "socket"), ("socket", "create_connection"),
    ("os", "system"), ("os", "popen"),
    ("urllib.request", "urlopen"), ("request", "urlopen"),
    ("requests", "get"), ("requests", "post"),
})
# Bare builtins that block (sync file IO on the event loop).
BLOCKING_BUILTINS = frozenset({"open"})

# -- MLA005: metrics -------------------------------------------------------
# Dotted metric tokens. Brace shorthand in docs
# (``generate.shed_{queue_full,...}``) stops the match at the brace,
# leaving a prefix the satisfiability check handles; file-path
# lookalikes (``batcher.py::...``) are filtered in the rule.
METRIC_NAME_RE = r"(?:generate|batcher|router|replica|http|model|tenant)\.[A-Za-z0-9_]+(?:\.[A-Za-z0-9_]+)*"
# Families whose exported names are constructed dynamically (router
# relabels replica gauges, sums arbitrary replica counters; http
# route labels are f-strings; the r22 per-model and per-tenant
# families key on registry ids / tenant names). A scraped/doc name
# under these prefixes is satisfiable by construction.
DYNAMIC_METRIC_PREFIXES = (
    "replica.", "router.", "http.", "model.", "tenant.",
)

# -- default scan set ------------------------------------------------------
DEFAULT_PY_GLOBS = (
    "mlapi_tpu/**/*.py",
    "tests/**/*.py",
    "tools/**/*.py",
    "bench.py",
)
# The fixtures are DELIBERATE violations (the negative tests); the
# clean-tree run must not see them. datasets/docs_corpus holds
# corpus text, not code.
DEFAULT_EXCLUDES = (
    "tests/lint_fixtures/",
    "mlapi_tpu/datasets/docs_corpus/",
)


@dataclass
class Config:
    root: Path = REPO_ROOT
    py_globs: tuple[str, ...] = DEFAULT_PY_GLOBS
    exclude_prefixes: tuple[str, ...] = DEFAULT_EXCLUDES
    # Role anchors (repo-relative); rules no-op when absent so a
    # fixture Config can exercise one rule in isolation.
    faults_module: str = "mlapi_tpu/serving/faults.py"
    latency_stats_module: str = "mlapi_tpu/serving/requests.py"
    # Where fire() seams live / where donation+locks apply.
    production_prefix: str = "mlapi_tpu/"
    serving_prefix: str = "mlapi_tpu/serving/"
    # Where fault-matrix coverage and metric scrapes are read from.
    test_prefix: str = "tests/"
    bench_files: tuple[str, ...] = ("bench.py",)
    doc_files: tuple[str, ...] = ("README.md", "docs/DESIGN.md")
    async_pure_modules: tuple[str, ...] = ASYNC_PURE_MODULES
    lock_registry: dict = field(
        default_factory=lambda: dict(LOCK_REGISTRY)
    )
    distinctive_attrs: dict = field(
        default_factory=lambda: dict(DISTINCTIVE_ATTRS)
    )
    baseline_file: str = "tools/lint/baseline.txt"
    # MLA007 / MLA008 / MLA009 knobs (fixture Configs override).
    instance_bindings: dict = field(
        default_factory=lambda: dict(INSTANCE_BINDINGS)
    )
    lockorder_artifact: str = LOCKORDER_ARTIFACT
    dispatch_seeds: tuple = DISPATCH_SEEDS
    blocking_prefixes: tuple = EVENT_LOOP_BLOCKING_PREFIXES
    blocking_attrs: frozenset = EVENT_LOOP_BLOCKING_ATTRS
    settle_counters: tuple = SETTLE_AFTER_TERMINAL
    terminal_read_hints: tuple = TERMINAL_READ_HINTS
    settle_wait_hints: tuple = SETTLE_WAIT_HINTS
