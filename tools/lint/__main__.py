"""CLI: ``python -m tools.lint`` — run every rule, honor
suppressions, report with exit codes CI can gate on.

Exit codes: 0 clean, 1 findings, 2 configuration error (malformed or
stale suppression). ``--format=github`` emits GitHub Actions
``::error`` annotations for future CI; the default is the
``RULE file:line [symbol]: message`` lines the tier-1 test parses.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

# `python -m tools.lint` from the repo root already has the root on
# sys.path; a direct `python tools/lint/__main__.py` does not.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

from tools.lint import load_project, run_rules  # noqa: E402
from tools.lint.baseline import (  # noqa: E402
    SuppressionError,
    apply_suppressions,
)
from tools.lint.config import Config  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="invariant-aware static analysis for this repo",
    )
    ap.add_argument(
        "--format", choices=("text", "github"), default="text",
        help="finding output format (github = Actions annotations)",
    )
    ap.add_argument(
        "--rules", "--rule", default=None,
        help="comma-separated rule IDs to run (default: all); "
        "--rule MLA007 is the single-rule triage spelling",
    )
    ap.add_argument(
        "--lockorder-out", default=None, metavar="PATH",
        help="write the MLA007 lock-order graph artifact (the "
        "machine-readable partial order the runtime witness "
        "enforces) to PATH and exit 0/1 as usual; regenerate the "
        "committed tools/lint/lockorder.json with this after any "
        "change to lock scopes",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule IDs/titles and exit",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore baseline + inline suppressions (triage mode)",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        from tools.lint.rules import ALL_RULES

        for rule in ALL_RULES:
            print(f"{rule.id}  {rule.title}")
        return 0

    t0 = time.perf_counter()
    cfg = Config()
    proj = load_project(cfg)
    rule_ids = (
        {r.strip() for r in args.rules.split(",")} if args.rules else None
    )
    if rule_ids is not None:
        from tools.lint.rules import ALL_RULES

        known = {r.id for r in ALL_RULES}
        unknown = rule_ids - known
        if unknown:
            # A typo'd --rules selecting nothing would exit 0 having
            # checked nothing — a gate that silently passed.
            print(
                f"lint: unknown rule id(s) {sorted(unknown)}; known: "
                f"{sorted(known)}",
                file=sys.stderr,
            )
            return 2
    findings = run_rules(proj, cfg, rule_ids)
    if args.lockorder_out:
        from tools.lint.rules.lockorder import render_artifact

        Path(args.lockorder_out).write_text(render_artifact(proj, cfg))
        print(f"lint: wrote {args.lockorder_out}", file=sys.stderr)
    if args.no_baseline:
        reported, suppressed = findings, []
    else:
        try:
            reported, suppressed = apply_suppressions(
                proj, cfg, findings, rule_ids
            )
        except SuppressionError as e:
            print(f"lint: suppression error: {e}", file=sys.stderr)
            return 2

    for f in reported:
        print(
            f.render_github() if args.format == "github" else f.render()
        )
    dt = time.perf_counter() - t0
    print(
        f"lint: {len(proj.files)} files, {len(reported)} finding(s)"
        f"{f', {len(suppressed)} suppressed' if suppressed else ''}"
        f" in {dt:.2f}s",
        file=sys.stderr,
    )
    return 1 if reported else 0


if __name__ == "__main__":
    sys.exit(main())
