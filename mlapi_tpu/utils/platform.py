"""Platform selection override.

The deploy image's ``sitecustomize`` registers the TPU PJRT plugin and
pins ``jax_platforms`` at the *config* level, which beats the
``JAX_PLATFORMS`` env var. ``MLAPI_TPU_PLATFORM`` re-pins the config
after import (backends initialise lazily, so doing this before the
first computation wins) — the supported way to force a CLI onto CPU,
e.g. for a bench fallback when the accelerator transport is wedged.
"""

from __future__ import annotations

import os


def apply_platform_override() -> str | None:
    """Honor ``$MLAPI_TPU_PLATFORM`` (e.g. ``cpu``); returns the value
    applied, if any. Call before any JAX computation."""
    platform = os.environ.get("MLAPI_TPU_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    return platform or None
