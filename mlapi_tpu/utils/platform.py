"""Platform selection override.

The deploy image's ``sitecustomize`` registers the TPU PJRT plugin and
pins ``jax_platforms`` at the *config* level, which beats the
``JAX_PLATFORMS`` env var. ``MLAPI_TPU_PLATFORM`` re-pins the config
after import (backends initialise lazily, so doing this before the
first computation wins) — the supported way to force a CLI onto CPU,
e.g. for a bench fallback when the accelerator transport is wedged.
"""

from __future__ import annotations

import os


def apply_platform_override(env_var: str = "MLAPI_TPU_PLATFORM") -> str | None:
    """Re-pin ``jax_platforms`` from ``env_var`` (e.g. to ``cpu``);
    returns the value applied, if any. Call before any JAX computation.

    Pass ``env_var="JAX_PLATFORMS"`` to restore the standard env var's
    intent when sitecustomize has clobbered it at the config level.
    """
    platform = os.environ.get(env_var)
    if platform:
        import jax

        if jax.config.jax_platforms != platform:
            jax.config.update("jax_platforms", platform)
    return platform or None
