"""In-process metrics: counters and latency histograms.

Backs the serving layer's ``/metrics`` endpoint and the bench harness
(the ``BASELINE.json`` north-star metric is requests/sec/chip and p50
latency on ``/predict`` — this is where those numbers come from at
runtime). The reference has no metrics at all (SURVEY §5).

Thread-safe enough for the serving model: the event loop plus the
batcher's single dispatch thread. Quantiles come from a reservoir
sample, not fixed buckets, so p50/p99 stay sharp at sub-millisecond
scales without bucket tuning.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field


def nearest_rank(values: list[float], q: float) -> float | None:
    """Nearest-rank quantile over unsorted values (shared by the
    serving histograms and the load generator so both report identical
    semantics)."""
    if not values:
        return None
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, int(q * len(ordered)))]


@dataclass
class Counter:
    name: str
    value: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Histogram:
    """Reservoir-sampled latency histogram (values in milliseconds)."""

    def __init__(self, name: str, reservoir_size: int = 4096):
        self.name = name
        self.count = 0
        self.total = 0.0
        self._reservoir: list[float] = []
        self._size = reservoir_size
        self._rng = random.Random(0)
        self._lock = threading.Lock()

    def observe(self, value_ms: float) -> None:
        with self._lock:
            self.count += 1
            self.total += value_ms
            if len(self._reservoir) < self._size:
                self._reservoir.append(value_ms)
            else:
                i = self._rng.randrange(self.count)
                if i < self._size:
                    self._reservoir[i] = value_ms

    def quantile(self, q: float) -> float | None:
        with self._lock:
            sample = list(self._reservoir)
        return nearest_rank(sample, q)

    def summary(self) -> dict:
        return {
            "count": self.count,
            "mean_ms": (self.total / self.count) if self.count else None,
            "p50_ms": self.quantile(0.50),
            "p90_ms": self.quantile(0.90),
            "p99_ms": self.quantile(0.99),
        }


class MetricsRegistry:
    """Named counters + histograms, rendered as one JSON object."""

    def __init__(self):
        self._counters: dict[str, Counter] = {}
        self._histograms: dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter(name))

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            return self._histograms.setdefault(name, Histogram(name))

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            histograms = dict(self._histograms)
        return {
            "counters": {n: c.value for n, c in counters.items()},
            "histograms": {n: h.summary() for n, h in histograms.items()},
        }
