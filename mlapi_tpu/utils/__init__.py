"""Shared utilities: label vocabulary, structured logging, metrics, registry."""

from mlapi_tpu.utils.vocab import LabelVocab  # noqa: F401
