"""String-label vocabulary.

The reference trains on the raw UCI Iris CSV whose labels are strings
(``Iris-setosa`` / ``Iris-versicolor`` / ``Iris-virginica``) and returns
the string label from ``/predict`` (reference ``main.py:24-27``; label
origin: the notebook's ``pd.read_csv`` with explicit column names).
JAX models work on integer class ids, so the vocab — the string↔id
mapping — is part of the model artifact and travels with every
checkpoint (see ``mlapi_tpu.checkpoint``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass(frozen=True)
class LabelVocab:
    """Immutable ordered mapping between string class labels and int ids."""

    labels: tuple[str, ...]
    _index: dict[str, int] = field(init=False, repr=False, compare=False)

    def __post_init__(self) -> None:
        if len(set(self.labels)) != len(self.labels):
            raise ValueError(f"duplicate labels in vocab: {self.labels}")
        object.__setattr__(
            self, "_index", {label: i for i, label in enumerate(self.labels)}
        )

    @classmethod
    def from_labels(cls, raw_labels) -> "LabelVocab":
        """Build a vocab from an iterable of (possibly repeated) labels.

        Order is sorted for determinism — the same dataset always yields
        the same vocab regardless of row order.
        """
        return cls(labels=tuple(sorted({str(x) for x in raw_labels})))

    @property
    def size(self) -> int:
        return len(self.labels)

    def encode(self, raw_labels) -> np.ndarray:
        """Map string labels to an int32 id array."""
        try:
            return np.asarray([self._index[str(x)] for x in raw_labels], dtype=np.int32)
        except KeyError as e:
            raise ValueError(f"label {e.args[0]!r} not in vocab {self.labels}") from None

    def decode(self, ids) -> list[str]:
        """Map int ids back to string labels."""
        return [self.labels[int(i)] for i in np.asarray(ids).reshape(-1)]

    def to_json(self) -> dict:
        return {"labels": list(self.labels)}

    @classmethod
    def from_json(cls, obj: dict) -> "LabelVocab":
        return cls(labels=tuple(obj["labels"]))
