"""One registry implementation for models, datasets, and anything
else addressed by config name."""

from __future__ import annotations

from typing import Callable, Generic, TypeVar

T = TypeVar("T")


class Registry(Generic[T]):
    def __init__(self, kind: str):
        self.kind = kind
        self._entries: dict[str, T] = {}

    def register(self, name: str) -> Callable[[T], T]:
        def deco(obj: T) -> T:
            if name in self._entries:
                raise ValueError(f"{self.kind} {name!r} already registered")
            self._entries[name] = obj
            return obj

        return deco

    def get(self, name: str) -> T:
        try:
            return self._entries[name]
        except KeyError:
            raise ValueError(
                f"unknown {self.kind} {name!r}; registered: {self.names()}"
            ) from None

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def names(self) -> list[str]:
        return sorted(self._entries)
