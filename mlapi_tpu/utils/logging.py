"""Structured logging.

The reference's only observability is ``print(df)`` (``main.py:34``).
This module gives every subsystem a namespaced logger with a single
consistent format; serving adds request metrics on top
(``mlapi_tpu.utils.metrics``).
"""

from __future__ import annotations

import logging
import os
import sys

_ROOT = "mlapi_tpu"
_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    root = logging.getLogger(_ROOT)
    if not root.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
        root.addHandler(handler)
    root.setLevel(os.environ.get("MLAPI_TPU_LOG_LEVEL", "INFO").upper())
    root.propagate = False
    _configured = True


def get_logger(name: str) -> logging.Logger:
    """Logger namespaced under the framework root (e.g. ``serving.asgi``)."""
    _configure()
    return logging.getLogger(f"{_ROOT}.{name}")
