"""Typed config system covering the ``BASELINE.json:6-12`` ladder.

The reference has no config at all — hardcoded filename
(``main.py:19``), hardcoded dataset URL and split in the notebook
(SURVEY §5). Here every training run is described by one
``TrainConfig`` (buildable from YAML or CLI flags), and the five
ladder configs ship as named presets.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any


@dataclass(frozen=True)
class TrainConfig:
    """One training run: model, data, optimization, parallelism."""

    name: str
    model: str
    model_kwargs: dict[str, Any] = field(default_factory=dict)
    dataset: str = "iris"
    dataset_kwargs: dict[str, Any] = field(default_factory=dict)

    steps: int = 500
    batch_size: int | None = None  # None = full batch
    optimizer: str = "adam"
    learning_rate: float = 0.1
    weight_decay: float = 0.0
    seed: int = 0
    eval_every: int = 0

    # Parallelism: mesh shape over (data, model) axes, or THREE dims
    # (data, fsdp, model) to add ZeRO-style parameter/optimizer-state
    # sharding. None = no mesh (single device). (8, 1) = pure DP over
    # 8 chips, (2, 4) = DP x TP, (1, 8, 1) = FSDP over 8 chips
    # (per-device params + AdamW moments drop ~8x; same math,
    # reduce-scatter/all-gather instead of all-reduce). The CLI's
    # --mesh-shape d,f,m overrides per run.
    mesh_shape: tuple[int, ...] | None = None

    checkpoint_dir: str | None = None

    # Knowledge distillation (drafts for speculative decoding): train
    # against a teacher checkpoint's softened logits. ``distill_from``
    # is a checkpoint path — usually given per-run via the CLI's
    # ``--distill-from`` rather than baked into a preset. A preset
    # designed AROUND distillation sets ``distill_required=True`` so
    # running it without a teacher fails loudly instead of silently
    # training a plain hard-label model under a "distilled" name.
    distill_from: str | None = None
    distill_temperature: float = 2.0
    distill_alpha: float = 0.5
    distill_required: bool = False

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["mesh_shape"] = list(self.mesh_shape) if self.mesh_shape else None
        return d

    @classmethod
    def from_json(cls, obj: dict) -> "TrainConfig":
        obj = dict(obj)
        if obj.get("mesh_shape") is not None:
            obj["mesh_shape"] = tuple(obj["mesh_shape"])
        return cls(**obj)

    @classmethod
    def from_yaml(cls, path: str | Path) -> "TrainConfig":
        import yaml

        with open(path) as f:
            return cls.from_json(yaml.safe_load(f))


# --- the ladder (BASELINE.json:6-12) ------------------------------------

_PRESETS: dict[str, TrainConfig] = {}


def register_preset(cfg: TrainConfig) -> TrainConfig:
    if cfg.name in _PRESETS:
        raise ValueError(f"preset {cfg.name!r} already registered")
    _PRESETS[cfg.name] = cfg
    return cfg


def preset_available(cfg: TrainConfig) -> bool:
    """True iff the preset's model and dataset are both registered in
    this build (the ladder lands incrementally; a preset only shows up
    in the CLI once it can actually run)."""
    from mlapi_tpu.datasets import dataset_registered
    from mlapi_tpu.models import model_registered

    return model_registered(cfg.model) and dataset_registered(cfg.dataset)


def get_preset(name: str) -> TrainConfig:
    try:
        cfg = _PRESETS[name]
    except KeyError:
        raise ValueError(
            f"unknown preset {name!r}; available: {sorted(_PRESETS)}"
        ) from None
    if not preset_available(cfg):
        raise ValueError(
            f"preset {name!r} needs model {cfg.model!r} and dataset "
            f"{cfg.dataset!r}, which are not both registered in this build"
        )
    return cfg


def preset_names(*, only_available: bool = True) -> list[str]:
    if not only_available:
        return sorted(_PRESETS)
    return sorted(n for n, c in _PRESETS.items() if preset_available(c))


register_preset(
    TrainConfig(
        name="iris-linear",
        model="linear",
        model_kwargs={"num_features": 4, "num_classes": 3},
        dataset="iris",
        steps=500,
        learning_rate=0.1,
        weight_decay=1e-3,
    )
)

register_preset(
    TrainConfig(
        name="mnist-softmax",
        model="linear",
        model_kwargs={"num_features": 784, "num_classes": 10},
        dataset="mnist",
        steps=2000,
        batch_size=256,
        learning_rate=1e-3,
        eval_every=500,
    )
)

register_preset(
    TrainConfig(
        name="fashion-mlp",
        model="mlp",
        model_kwargs={
            "num_features": 784,
            "num_classes": 10,
            "hidden_dims": [256, 128],
        },
        dataset="fashion_mnist",
        steps=3000,
        batch_size=256,
        learning_rate=1e-3,
        eval_every=500,
        mesh_shape=(8, 1),  # pure data-parallel over a v5e-8
    )
)

# Real-data anchors for the configs 2-3 model families: the MNIST /
# Fashion-MNIST files cannot be fetched in this air-gapped build, so
# the same linear / MLP architectures also train on the REAL
# handwritten-digits scans scikit-learn bundles (datasets/digits.py) —
# published accuracies that mean something, next to the clearly-marked
# synthetic rows.
register_preset(
    TrainConfig(
        name="digits-softmax",
        model="linear",
        model_kwargs={"num_features": 64, "num_classes": 10},
        dataset="digits",
        steps=2000,
        batch_size=256,
        learning_rate=1e-3,
        eval_every=500,
    )
)

register_preset(
    TrainConfig(
        name="digits-mlp",
        model="mlp",
        model_kwargs={
            "num_features": 64,
            "num_classes": 10,
            "hidden_dims": [256, 128],
        },
        dataset="digits",
        steps=3000,
        batch_size=256,
        learning_rate=1e-3,
        eval_every=500,
        mesh_shape=(8, 1),
    )
)

register_preset(
    TrainConfig(
        name="criteo-widedeep",
        model="wide_deep",
        model_kwargs={
            "num_dense": 13,
            "vocab_sizes": [100_000] * 26,
            "embed_dim": 16,
            "hidden_dims": [256, 128],
            "num_classes": 2,
        },
        dataset="criteo",
        steps=2000,
        batch_size=1024,
        # TRUE-sparse rowwise-AdaGrad tables + AdamW dense
        # (train/sparse_embed.py): gradients w.r.t. gathered rows and
        # scatter updates of touched rows ONLY — the dense [F, V, D]
        # cotangent and full-table optimizer sweep (the step's
        # dominant HBM traffic, BASELINE.md roofline) never
        # materialize. Numerically IDENTICAL trajectory to the dense
        # recsys-adamw it replaces (tests/test_sparse_embed.py pins
        # leaf-for-leaf equality), measured 8.9x step time on CPU at
        # this exact config (220.5 -> 24.8 ms/step); the r04 dense
        # convergence numbers therefore stand unchanged (400 steps:
        # 0.5481 vs dense-AdamW 0.5442 test acc).
        optimizer="recsys-sparse-adamw",
        learning_rate=1e-3,
        eval_every=500,
        mesh_shape=(2, 4),  # DP x model-sharded embeddings
    )
)

register_preset(
    TrainConfig(
        name="sst2-bert",
        model="bert_classifier",
        # attention_impl="flash": the in-house Pallas kernel. Full
        # attention materializes [B, H, L, L] scores per layer — at
        # batch 128 that is the dominant HBM traffic and why MFU FELL
        # with batch size (0.503@32 -> 0.486@128, r03); the flash
        # kernel keeps scores in VMEM tiles, so the flagship training
        # config now exercises the kernel the repo built for it.
        model_kwargs={
            "bert_preset": "bert-base-uncased", "num_classes": 2,
            "attention_impl": "flash",
        },
        dataset="sst2",
        steps=3000,
        batch_size=32,
        optimizer="adamw",
        learning_rate=2e-5,
        eval_every=500,
        mesh_shape=(2, 4),  # DP x TP
    )
)

# Config-5 real-data proxy: BERT text classification on 100% real
# local prose (repo docs windows labeled by source file — see
# datasets/docs_clf.py). Same task shape as SST-2, every byte real;
# the residual gap (pretrained weights + GLUE labels) is what
# --from-hf closes when a local HF checkpoint exists.
register_preset(
    TrainConfig(
        name="docsclf-bert",
        model="bert_classifier",
        model_kwargs={
            "vocab_size": 260, "hidden_size": 64, "num_layers": 2,
            "num_heads": 4, "intermediate_size": 128,
            "max_positions": 128, "num_classes": 4,
        },
        dataset="docs_clf",
        dataset_kwargs={"seq_len": 128},
        steps=300,
        batch_size=64,
        optimizer="adamw",
        learning_rate=1e-3,
        eval_every=100,
    )
)

# Decoder-family LM presets: next-token training on the repo's own
# documentation (datasets/textlm.py — real English prose, zero-egress),
# producing checkpoints that serve via /generate. These demonstrate the
# full generative pipeline (corpus -> fit -> checkpoint -> serving);
# the corpus is ~50k tokens, so they train in seconds, not to quality.
register_preset(
    TrainConfig(
        name="docs-gpt",
        model="gpt_lm",
        model_kwargs={
            "vocab_size": 260, "hidden_size": 128, "num_layers": 2,
            "num_heads": 4, "max_positions": 256,
            "compute_dtype": "float32",
        },
        dataset="docs_text",
        dataset_kwargs={"seq_len": 128},
        steps=300,
        batch_size=64,
        optimizer="adamw",
        learning_rate=3e-4,
        eval_every=100,
    )
)

# Speculative-decoding draft for docs-gpt: same tokenizer/corpus,
# ~1/10th the weights. Train both and serve with
#   python -m mlapi_tpu.serving --checkpoint <docs-gpt ckpt> \
#       --draft-checkpoint <docs-gpt-draft ckpt>
register_preset(
    TrainConfig(
        name="docs-gpt-draft",
        model="gpt_lm",
        model_kwargs={
            "vocab_size": 260, "hidden_size": 48, "num_layers": 1,
            "num_heads": 4, "max_positions": 256,
            "compute_dtype": "float32",
        },
        dataset="docs_text",
        dataset_kwargs={"seq_len": 128},
        steps=300,
        batch_size=64,
        optimizer="adamw",
        learning_rate=1e-3,
        eval_every=100,
    )
)

# DISTILLED draft for docs-gpt: same serving-side contract as
# docs-gpt-draft, but trained against the target's softened logits
# (pass --distill-from <docs-gpt ckpt>). A hard-label draft agrees
# with the target only where the data forces it; a distilled draft
# matches the target's own distribution — the quantity speculative
# acceptance actually tests — which is what moves acceptance (0.31-
# 0.46 on the independent pair) toward useful territory.
register_preset(
    TrainConfig(
        name="docs-gpt-draft-distilled",
        model="gpt_lm",
        model_kwargs={
            "vocab_size": 260, "hidden_size": 48, "num_layers": 1,
            "num_heads": 4, "max_positions": 256,
            "compute_dtype": "float32",
        },
        dataset="docs_text",
        dataset_kwargs={"seq_len": 128},
        steps=600,
        batch_size=64,
        optimizer="adamw",
        learning_rate=1e-3,
        eval_every=200,
        distill_temperature=2.0,
        distill_alpha=0.1,  # mostly match the teacher, lightly ground
        distill_required=True,
    )
)

register_preset(
    TrainConfig(
        name="docs-llama",
        model="llama_lm",
        model_kwargs={
            "vocab_size": 260, "hidden_size": 128, "num_layers": 2,
            "num_heads": 4, "num_kv_heads": 2, "max_positions": 256,
            "compute_dtype": "float32",
        },
        dataset="docs_text",
        dataset_kwargs={"seq_len": 128},
        steps=300,
        batch_size=64,
        optimizer="adamw",
        learning_rate=3e-4,
        eval_every=100,
    )
)
