"""Llama-style decoder: RMSNorm, rotary positions, SwiGLU, GQA.

Second decoder family in the zoo (reference repo has none —
`/root/reference` is a serving-only sklearn tutorial; this family
exists because a complete framework serves the architectures users
actually deploy). Differences from :class:`mlapi_tpu.models.gpt.GptLM`
and why they matter on TPU:

- **Rotary position embeddings** instead of a learned ``wpe`` table:
  positions enter as a per-row rotation of q/k, so the KV cache stores
  *rotated* keys and decode needs no position-table lookup. Left-pad
  bucketing composes exactly: row ``b``'s effective position is
  ``idx - n_pad[b]`` (clamped), the same shift discipline the GPT
  path proves bucket-invariance with.
- **Grouped-query attention** (``num_kv_heads < num_heads``): the
  cache shrinks by the group factor — the serving cache is HBM-
  resident state per concurrent request, so GQA directly raises the
  max decode batch. K/V heads are broadcast to query heads with a
  reshape-free ``jnp.repeat`` at attention time (XLA fuses it).
- **RMSNorm + SwiGLU, no biases** — fewer, larger fused ops.

The incremental-decoding machinery (prefill program, chunked
``lax.scan`` decode, per-row sampling streams, top-k/top-p) is SHARED
with the GPT family via the model-generic helpers in ``gpt.py``
(``_generate_fn``, ``prefill_fn``, ``decode_chunk_fn``): this class
plugs in through ``prefill_core``/``decode_step``/``init_cache``, so
the serving engine (`serving/engine.py::TextGenerationEngine`) works
unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from mlapi_tpu.models import register_model


def _rms_norm(x, scale, eps: float = 1e-6):
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + eps)
    return (x32 * inv * scale.astype(jnp.float32)).astype(x.dtype)


def _rope(x, positions, theta: float):
    """Rotate ``x [B, L, H, D]`` by per-row-and-position angles.

    ``positions``: ``[B, L]`` int32 effective positions (already
    n_pad-shifted and clamped by callers). rotate-half convention:
    pairs are (x[..., :D/2], x[..., D/2:]).

    Written as ``x * cos + rotate_half(x) * sin`` over the FULL lane
    dim, with ``rotate_half`` a constant-index gather — deliberately
    NOT the textbook slice-halves-and-concatenate. Under GSPMD,
    slice+concat over a dim the ``model`` axis shards finer than one
    KV head (GQA: ``wk`` is ``[h, kvh*hd]``; a TP degree above
    ``kvh`` splits heads) MISCOMPILES on this jax/XLA version — the
    partitioner returns scrambled values, wrong by O(1) even at
    position 0 where rope is the identity (repro pinned in
    tests/test_llama.py::test_rope_is_identity_at_position_zero_tp).
    The gather formulation partitions correctly under every layout
    and is arithmetically identical (same multiplies/adds per lane).
    """
    d = x.shape[-1]
    half = d // 2
    lane = jnp.arange(d)
    inv_freq = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    # Per-lane angle: lane j pairs with lane (j + half) % d and both
    # use frequency j % half.
    ang = positions.astype(jnp.float32)[..., None] * inv_freq[lane % half]
    cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)  # [B, L, 1, D]
    sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    # rotate_half(x)[j] = -x[j + half] (j < half) else x[j - half].
    perm = jnp.concatenate([lane[half:], lane[:half]])
    sign = jnp.where(lane < half, -1.0, 1.0).astype(x.dtype)
    xr = jnp.take(x, perm, axis=-1) * sign
    return x * cos + xr * sin


@register_model("llama_lm")
@dataclass(frozen=True)
class LlamaLM:
    """Decoder-only causal LM, Llama-family architecture."""

    input_kind = "text"

    vocab_size: int = 512
    hidden_size: int = 256
    num_layers: int = 4
    num_heads: int = 4
    num_kv_heads: int | None = None  # None -> MHA (== num_heads)
    intermediate_size: int | None = None  # None -> 8/3 * h, 128-rounded
    max_positions: int = 256
    rope_theta: float = 10_000.0
    compute_dtype: str = "bfloat16"
    # "full" | "flash" | "ring" — same contract as GptLM.apply.
    attention_impl: str = "full"
    mesh: object = None
    seq_axis: str = "seq"
    ring_block_impl: str = "einsum"
    ring_zigzag: bool = False
    # KV-cache storage format — same contract as ``GptLM.kv_quant``
    # ("none" | "int8"); composes with GQA (the int8 payload shrinks
    # the ALREADY-grouped [B, L, KVH, D] cache a further ~2x).
    kv_quant: str = "none"
    # Cache-read attention — same contract as
    # ``GptLM.decode_attn_impl`` ("einsum" | "flash"; "flash" covers
    # single-token decode AND multi-token extend spans). The flash
    # kernels are GQA-native: scales and payload index per KV head,
    # queries grouped in-register — the repeated K/V tensor the
    # einsum path broadcasts (``_repeat_kv``) never exists.
    decode_attn_impl: str = "einsum"

    def __post_init__(self):
        from mlapi_tpu.ops.quant import KV_FORMATS

        if self.kv_quant not in KV_FORMATS:
            raise ValueError(
                f"unknown kv_quant {self.kv_quant!r}; one of {KV_FORMATS}"
            )
        if self.decode_attn_impl not in ("einsum", "flash"):
            raise ValueError(
                f"unknown decode_attn_impl {self.decode_attn_impl!r}; "
                'one of ("einsum", "flash")'
            )
        if self.attention_impl not in ("full", "flash", "ring"):
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        if self.attention_impl == "ring" and self.mesh is None:
            raise ValueError('attention_impl="ring" requires a mesh')
        if self.ring_zigzag and self.ring_block_impl != "flash":
            raise ValueError('ring_zigzag needs ring_block_impl="flash"')
        if self.num_kv_heads is not None and self.num_kv_heads < 1:
            raise ValueError(f"num_kv_heads must be >= 1, got {self.num_kv_heads}")
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must divide evenly into heads")
        if self.num_heads % self.kv_heads:
            raise ValueError(
                f"num_heads ({self.num_heads}) must be a multiple of "
                f"num_kv_heads ({self.kv_heads})"
            )
        if self.head_dim % 2:
            raise ValueError("rotary embeddings need an even head_dim")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self) -> int:
        return self.num_heads if self.num_kv_heads is None else self.num_kv_heads

    @property
    def ffn_size(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        return max(128, (8 * self.hidden_size // 3 + 127) // 128 * 128)

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array) -> dict:
        h, f, v = self.hidden_size, self.ffn_size, self.vocab_size
        kvh, hd = self.kv_heads, self.head_dim
        keys = iter(jax.random.split(rng, 2 + 7 * self.num_layers))

        def w(k, shape, scale=0.02):
            return scale * jax.random.normal(k, shape)

        params = {
            "wte": w(next(keys), (v, h)),
            "lm_head": w(next(keys), (h, v)),
            "rms_f_scale": jnp.ones((h,)),
        }
        for n in range(self.num_layers):
            params[f"layer_{n}"] = {
                "wq": w(next(keys), (h, h)),
                "wk": w(next(keys), (h, kvh * hd)),
                "wv": w(next(keys), (h, kvh * hd)),
                "wo": w(next(keys), (h, h)),
                "rms1_scale": jnp.ones((h,)),
                "w_gate": w(next(keys), (h, f)),
                "w_up": w(next(keys), (h, f)),
                "w_down": w(next(keys), (f, h)),
                "rms2_scale": jnp.ones((h,)),
            }
        return jax.tree.map(lambda a: a.astype(jnp.float32), params)

    # ------------------------------------------------------------------
    def _qkv(self, layer, xn, positions):
        """Project + rope one block's q/k/v. ``positions`` is the
        per-row effective position of every residual-stream slot."""
        from mlapi_tpu.models.lora import lora_apply

        cdt = jnp.dtype(self.compute_dtype)
        b, l, _ = xn.shape
        nh, kvh, hd = self.num_heads, self.kv_heads, self.head_dim
        q = lora_apply(
            layer, "wq", xn, xn @ layer["wq"].astype(cdt)
        ).reshape(b, l, nh, hd)
        k = lora_apply(
            layer, "wk", xn, xn @ layer["wk"].astype(cdt)
        ).reshape(b, l, kvh, hd)
        v = lora_apply(
            layer, "wv", xn, xn @ layer["wv"].astype(cdt)
        ).reshape(b, l, kvh, hd)
        return _rope(q, positions, self.rope_theta), _rope(
            k, positions, self.rope_theta
        ), v

    def _block(self, layer, x, positions, attend):
        # lora_apply: per-tenant serving delta — static no-op unless
        # the dispatch augmented this layer with a "lora" sub-dict
        # (serving/adapter_store.py slot pool).
        from mlapi_tpu.models.lora import lora_apply

        cdt = jnp.dtype(self.compute_dtype)
        xn = _rms_norm(x, layer["rms1_scale"]).astype(cdt)
        q, k, v = self._qkv(layer, xn, positions)
        ctx = attend(q, k, v).reshape(x.shape[0], x.shape[1], -1)
        wo = lora_apply(layer, "wo", ctx, ctx @ layer["wo"].astype(cdt))
        x = x + wo.astype(jnp.float32)

        xn = _rms_norm(x, layer["rms2_scale"]).astype(cdt)
        gate = jax.nn.silu(
            lora_apply(
                layer, "w_gate", xn, xn @ layer["w_gate"].astype(cdt)
            ).astype(jnp.float32)
        ).astype(cdt)
        up = lora_apply(layer, "w_up", xn, xn @ layer["w_up"].astype(cdt))
        gu = gate * up
        down = lora_apply(
            layer, "w_down", gu, gu @ layer["w_down"].astype(cdt)
        )
        return x + down.astype(jnp.float32)

    def _repeat_kv(self, k):
        group = self.num_heads // self.kv_heads
        return k if group == 1 else jnp.repeat(k, group, axis=2)

    def apply(self, params: dict, token_ids) -> jax.Array:
        """``[B, L]`` ids → ``[B, L, V]`` next-token logits (causal)."""
        from mlapi_tpu.ops import full_attention

        b, l = token_ids.shape
        x = params["wte"][token_ids]
        positions = jnp.broadcast_to(jnp.arange(l)[None], (b, l))

        if self.attention_impl == "flash":
            from mlapi_tpu.ops.pallas import flash_attention

            def attend(q, k, v):
                # The kernel is GQA-native: raw kv heads go straight
                # in, no repeated K/V tensor in HBM.
                return flash_attention(
                    q, k, v, causal=True,
                    interpret=jax.default_backend() != "tpu",
                )
        elif self.attention_impl == "ring":
            from mlapi_tpu.ops import ring_self_attention

            def attend(q, k, v):
                return ring_self_attention(
                    self.mesh, q, self._repeat_kv(k), self._repeat_kv(v),
                    causal=True, seq_axis=self.seq_axis, head_axis="model",
                    block_impl=self.ring_block_impl,
                    zigzag=self.ring_zigzag,
                )
        else:
            def attend(q, k, v):
                return full_attention(
                    q, self._repeat_kv(k), self._repeat_kv(v), causal=True
                )

        for n in range(self.num_layers):
            x = self._block(params[f"layer_{n}"], x, positions, attend)
        x = _rms_norm(x, params["rms_f_scale"])
        return x.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)

    # -- incremental decoding (shared engine contract) -----------------
    def init_cache(self, batch: int, max_len: int) -> dict:
        """``[B, max_len, KVH, D]`` per layer — GQA shrinks this by
        ``num_heads / num_kv_heads`` vs the query-head count; under
        ``kv_quant="int8"`` the layer holds int8 payload + f32 scales
        instead (``ops/quant.init_kv_cache``)."""
        from mlapi_tpu.ops.quant import init_kv_cache

        cdt = jnp.dtype(self.compute_dtype)
        return {
            f"layer_{n}": init_kv_cache(
                batch, max_len, self.kv_heads, self.head_dim, cdt,
                self.kv_quant,
            )
            for n in range(self.num_layers)
        }

    def prefill_core(self, params, prompt_ids, n_pad, total_len: int,
                     cache=None, pos0=None):
        """Full causal forward over a left-padded ``[B, P]`` prompt,
        writing ROTATED K (and V) into a fresh cache — the dispatch
        target of ``gpt._prefill_core`` (see that docstring for the
        padding/alignment contract, and ``GptLM.prefill_core`` for the
        page-native ``cache``/``pos0`` variant: rotary phases key on
        effective positions, which the caller's virtual-slot ``n_pad``
        keeps invariant under the offset, so the stored rotated K is
        identical wherever the block lands)."""
        from mlapi_tpu.ops import full_attention
        from mlapi_tpu.ops.quant import kv_cache_append

        b, p = prompt_ids.shape
        cache = self.init_cache(b, total_len) if cache is None else dict(cache)
        if pos0 is None:
            pos0 = jnp.int32(0)
        cdt = jnp.dtype(self.compute_dtype)

        positions = jnp.maximum(jnp.arange(p)[None, :] - n_pad[:, None], 0)
        mask = (jnp.arange(p)[None, :] >= n_pad[:, None]).astype(jnp.float32)
        x = params["wte"][prompt_ids]
        for n in range(self.num_layers):
            layer = params[f"layer_{n}"]
            kv_seen = {}

            def attend(q, k, v, *, _kv=kv_seen):
                _kv["k"], _kv["v"] = k, v
                return full_attention(
                    q, self._repeat_kv(k), self._repeat_kv(v),
                    mask=mask, causal=True,
                )

            x = self._block(layer, x, positions, attend)
            # Rotated K / raw V quantize at the append, exactly like
            # the GPT family (the prompt block itself attended
            # full-precision above).
            cache[f"layer_{n}"] = kv_cache_append(
                cache[f"layer_{n}"], kv_seen["k"], kv_seen["v"],
                pos0, cdt,
            )
        x = _rms_norm(x, params["rms_f_scale"])
        last_logits = x[:, -1].astype(jnp.float32) @ params["lm_head"].astype(
            jnp.float32
        )
        return cache, last_logits

    def decode_step(self, params, cache, token_ids, pos, n_pad=None,
                    prefix_len=None, prefix_lo=None):
        """One cached decode step — same contract as
        ``GptLM.decode_step`` (``[B, 1]`` ids at traced cache position
        ``pos``; per-row ``n_pad`` shifts rotary positions and masks
        pad keys; ``prefix_len``/``prefix_lo`` describe a shared
        prefix-cache region). The cache write + masked attention is
        the shared ``gpt.cached_attend``, with GQA's kv-head broadcast
        plugged in.
        """
        from mlapi_tpu.models.gpt import cached_attend, decode_valid_and_shift
        from mlapi_tpu.ops.quant import kv_cache_seq_len

        cdt = jnp.dtype(self.compute_dtype)
        b = token_ids.shape[0]
        max_len = kv_cache_seq_len(cache)
        if n_pad is None:
            n_pad = jnp.zeros((b,), jnp.int32)

        valid, shift = decode_valid_and_shift(
            max_len, pos, n_pad, prefix_len, prefix_lo
        )
        positions = jnp.maximum(pos - shift, 0)[:, None]  # [B, 1]
        x = params["wte"][token_ids]
        new_cache = {}

        for n in range(self.num_layers):
            layer = params[f"layer_{n}"]

            def attend(q, k_new, v_new, *, _n=n):
                out, new_cache[f"layer_{_n}"] = cached_attend(
                    cache[f"layer_{_n}"], q, k_new, v_new, pos, valid,
                    cdt, self.head_dim, expand=self._repeat_kv,
                    impl=self.decode_attn_impl, mesh=self.mesh,
                )
                return out

            x = self._block(layer, x, positions, attend)

        x = _rms_norm(x, params["rms_f_scale"])
        logits = x[:, 0].astype(jnp.float32) @ params["lm_head"].astype(
            jnp.float32
        )
        return logits, new_cache

    def extend_core(self, params, cache, token_ids, pos0, n_pad,
                    prefix_len, prefix_lo, all_logits: bool = False):
        """Fused block forward against an existing cache — same
        contract as ``GptLM.extend_core`` (rotary positions per row,
        GQA kv broadcast via the shared ``cached_attend``; under
        ``decode_attn_impl="flash"`` the block reads the cache through
        the GQA-native flash-extend kernel, where the repeated K/V
        tensor the einsum path broadcasts never exists)."""
        from mlapi_tpu.models.gpt import (
            cached_attend, extend_positions_and_mask,
        )
        from mlapi_tpu.ops.quant import kv_cache_seq_len

        cdt = jnp.dtype(self.compute_dtype)
        max_len = kv_cache_seq_len(cache)
        posq, mask = extend_positions_and_mask(
            max_len, token_ids.shape[1], pos0, n_pad, prefix_len,
            prefix_lo,
        )
        x = params["wte"][token_ids]
        new_cache = {}

        for n in range(self.num_layers):
            layer = params[f"layer_{n}"]

            def attend(q, k_new, v_new, *, _n=n):
                out, new_cache[f"layer_{_n}"] = cached_attend(
                    cache[f"layer_{_n}"], q, k_new, v_new, pos0, mask,
                    cdt, self.head_dim, expand=self._repeat_kv,
                    impl=self.decode_attn_impl, mesh=self.mesh,
                )
                return out

            x = self._block(layer, x, posq, attend)

        x = _rms_norm(x, params["rms_f_scale"])
        if not all_logits:
            x = x[:, -1]
        logits = x.astype(jnp.float32) @ params["lm_head"].astype(
            jnp.float32
        )
        return new_cache, logits

    def generate(self, params, prompt_ids, **kwargs):
        """Same surface as ``GptLM.generate`` (the whole prefill +
        chunked-scan + sampling pipeline is the shared machinery in
        ``gpt.py``)."""
        from mlapi_tpu.models.gpt import run_generate

        return run_generate(self, params, prompt_ids, **kwargs)

    # ------------------------------------------------------------------
    def param_shardings(self, layout=None) -> dict:
        """Megatron TP: q/k/v/gate/up column-sharded, wo/w_down
        row-sharded, embeddings + head vocab-sharded."""
        from mlapi_tpu.parallel import SpecLayout

        lo = layout or SpecLayout()
        specs = {
            "wte": lo.embedding_rows(),
            "lm_head": lo.attn_qkv(),  # [h, V]: column(vocab)-sharded
            "rms_f_scale": lo.replicated(),
        }
        for n in range(self.num_layers):
            specs[f"layer_{n}"] = {
                "wq": lo.attn_qkv(),
                "wk": lo.attn_qkv(),
                "wv": lo.attn_qkv(),
                "wo": lo.attn_out(),
                "rms1_scale": lo.replicated(),
                "w_gate": lo.attn_qkv(),
                "w_up": lo.attn_qkv(),
                "w_down": lo.attn_out(),
                "rms2_scale": lo.replicated(),
            }
        return specs
