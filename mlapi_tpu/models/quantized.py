"""Transparent weight-only-quantized model wrapper.

``QuantizedModel(inner)`` exposes the decoder-protocol / classifier
surface of ``inner`` but expects its ``params`` tree to hold int8
``{"q", "scale"}`` leaves (see ``ops/quant.py``). Dequantization
happens INSIDE each traced method, so every jitted program — serving
forward, prefill, decode chunk, admission prefill — reads int8 weights
from HBM and expands them in-register on the way into the matmul. No
model family needs to know: the wrapper satisfies the same protocol
the engines and ``models/gpt.py``'s model-generic machinery consume,
and it is hashable/frozen so the ``lru_cache``'d program factories key
on it like any other model config.

Composes with int8 KV-CACHE quantization orthogonally: the cache
format is the INNER model's ``kv_quant`` field (forwarded by
``__getattr__``), so ``QuantizedModel(replace(inner, kv_quant="int8"))``
serves int8 weights AND an int8 cache — the engine's
``--quantize int8 --kv-quant int8``. Weight dequantization happens in
the wrapper's traced methods; cache quantize/dequantize happens inside
the inner model's append/read seams (``ops/quant.kv_cache_append`` /
``kv_cache_kv``). Neither knows about the other.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from mlapi_tpu.ops.quant import dequantize_tree


@dataclass(frozen=True)
class QuantizedModel:
    """Weight-only int8 view over any model family."""

    inner: object

    # Anything not overridden (vocab_size, max_positions, num_heads,
    # input_kind, init_cache, ...) is the inner model's.
    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _deq(self, params):
        # Dequantize to f32: every model site applies its own compute
        # cast (`.astype(cdt)` at use), exactly as with float params.
        return dequantize_tree(params, jnp.float32)

    def init(self, rng):
        return self.inner.init(rng)

    def apply(self, params, *args, **kwargs):
        return self.inner.apply(self._deq(params), *args, **kwargs)

    def prefill_core(self, params, prompt_ids, n_pad, total_len: int,
                     cache=None, pos0=None):
        return self.inner.prefill_core(
            self._deq(params), prompt_ids, n_pad, total_len,
            cache=cache, pos0=pos0,
        )

    def decode_step(self, params, cache, token_ids, pos, n_pad=None,
                    prefix_len=None, prefix_lo=None):
        return self.inner.decode_step(
            self._deq(params), cache, token_ids, pos, n_pad,
            prefix_len, prefix_lo,
        )

    def extend_core(self, params, cache, token_ids, pos0, n_pad,
                    prefix_len, prefix_lo, all_logits: bool = False):
        # The inner model's decode_attn_impl/mesh route the block's
        # cache read (einsum oracle or the flash-extend kernel), so
        # int8 WEIGHTS and a kernel-native int8 CACHE read compose in
        # one program: weights dequantize here, cache tiles dequantize
        # inside the kernel — neither knows about the other.
        return self.inner.extend_core(
            self._deq(params), cache, token_ids, pos0, n_pad,
            prefix_len, prefix_lo, all_logits,
        )

    def generate(self, params, prompt_ids, **kwargs):
        # Route through the model-generic path with SELF as the model
        # so prefill/decode dequantize inside the traced program —
        # delegating to inner.generate would re-enter with the inner
        # model and skip dequantization.
        if not hasattr(self.inner, "generate"):
            raise AttributeError(
                f"{type(self.inner).__name__} is not a generative model"
            )
        from mlapi_tpu.models.gpt import run_generate

        return run_generate(self, params, prompt_ids, **kwargs)

    def param_shardings(self, layout=None):
        """The INNER model's TP layout, verbatim: placement
        (``parallel.mesh.place_params``) maps each float leaf's spec
        onto the quantized ``{"q", "scale"}`` pair — ``q`` takes the
        float spec, per-channel ``scale`` keeps the channel axis's
        placement — so ``--quantize int8`` composes with
        ``--mesh-shape`` with no model-specific code."""
        spec_fn = getattr(self.inner, "param_shardings", None)
        if spec_fn is None:
            raise NotImplementedError(
                f"{type(self.inner).__name__} declares no param "
                "shardings; quantized mesh serving needs the inner "
                "model's TP layout"
            )
        return spec_fn(layout)
