"""Functional model zoo.

Every model is a lightweight stateless object with two methods:

- ``init(rng) -> params``   — build the parameter pytree.
- ``apply(params, x) -> logits`` — pure forward pass, safe under
  ``jax.jit`` / ``jax.grad`` / ``shard_map``.

Models carry no parameters themselves (params are explicit pytrees),
so the same model object can be used for training, checkpointing, and
serving, and params can be sharded over a mesh without the model
object knowing.

Registry: ``get_model(name, **kwargs)`` builds a model by config name.
"""

from __future__ import annotations

from typing import Any, Callable

_REGISTRY: dict[str, Callable[..., Any]] = {}


def register_model(name: str):
    """Decorator registering a model factory under a config name."""

    def deco(factory):
        if name in _REGISTRY:
            raise ValueError(f"model {name!r} already registered")
        _REGISTRY[name] = factory
        return factory

    return deco


def get_model(name: str, **kwargs):
    """Build a model by registry name (e.g. ``linear``, ``mlp``)."""
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None
    return factory(**kwargs)


def registered_models() -> list[str]:
    return sorted(_REGISTRY)


# Import model modules for their registration side effects.
from mlapi_tpu.models import linear as _linear  # noqa: E402,F401
from mlapi_tpu.models.linear import LinearClassifier  # noqa: E402,F401
