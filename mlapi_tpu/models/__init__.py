"""Functional model zoo.

Every model is a lightweight stateless object with two methods:

- ``init(rng) -> params``   — build the parameter pytree.
- ``apply(params, x) -> logits`` — pure forward pass, safe under
  ``jax.jit`` / ``jax.grad`` / ``shard_map``.

Models carry no parameters themselves (params are explicit pytrees),
so the same model object can be used for training, checkpointing, and
serving, and params can be sharded over a mesh without the model
object knowing.

Registry: ``get_model(name, **kwargs)`` builds a model by config name.
"""

from __future__ import annotations

from mlapi_tpu.utils.registry import Registry

_REGISTRY: Registry = Registry("model")
register_model = _REGISTRY.register


def get_model(name: str, **kwargs):
    """Build a model by registry name (e.g. ``linear``, ``mlp``)."""
    return _REGISTRY.get(name)(**kwargs)


def model_registered(name: str) -> bool:
    return name in _REGISTRY


def registered_models() -> list[str]:
    return _REGISTRY.names()


# Import model modules for their registration side effects.
from mlapi_tpu.models import linear as _linear  # noqa: E402,F401
from mlapi_tpu.models import mlp as _mlp  # noqa: E402,F401
from mlapi_tpu.models import wide_deep as _wide_deep  # noqa: E402,F401
from mlapi_tpu.models import bert as _bert  # noqa: E402,F401
from mlapi_tpu.models import gpt as _gpt  # noqa: E402,F401
from mlapi_tpu.models import llama as _llama  # noqa: E402,F401
from mlapi_tpu.models.bert import BertClassifier  # noqa: E402,F401
from mlapi_tpu.models.gpt import GptLM  # noqa: E402,F401
from mlapi_tpu.models.lora import LoraModel  # noqa: E402,F401
from mlapi_tpu.models.quantized import QuantizedModel  # noqa: E402,F401
from mlapi_tpu.models.linear import LinearClassifier  # noqa: E402,F401
from mlapi_tpu.models.llama import LlamaLM  # noqa: E402,F401
from mlapi_tpu.models.mlp import MLPClassifier  # noqa: E402,F401
from mlapi_tpu.models.wide_deep import WideDeepClassifier  # noqa: E402,F401
