"""Linear (multinomial logistic-regression) classifier.

TPU-native replacement for the reference's pickled sklearn
``LogisticRegression`` (reference ``main.py:19-22``, trained in
``Logistic Regression.ipynb``). The forward pass is a single
``x @ W + b`` — one fused MXU matmul under ``jax.jit`` — and
probabilities come from ``jax.nn.softmax`` over the same logits, so
unlike the reference (which runs the matmul twice: ``predict`` at
``main.py:21`` then ``predict_proba`` at ``main.py:22``) prediction and
probability share one device call.

Params pytree: ``{"w": [d, k], "b": [k]}``.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from mlapi_tpu.models import register_model


@register_model("linear")
@dataclass(frozen=True)
class LinearClassifier:
    """Multinomial softmax classifier: ``logits = x @ W + b``.

    With ``num_classes=2`` this degenerates to logistic regression
    (softmax over two logits ≡ sigmoid of their difference).
    """

    num_features: int
    num_classes: int
    param_dtype: jnp.dtype = jnp.float32

    def init(self, rng: jax.Array) -> dict:
        """Zero-init params — convex objective, no symmetry to break."""
        del rng
        return {
            "w": jnp.zeros((self.num_features, self.num_classes), self.param_dtype),
            "b": jnp.zeros((self.num_classes,), self.param_dtype),
        }

    def apply(self, params: dict, x: jax.Array) -> jax.Array:
        """Forward pass: ``[batch, d] -> [batch, k]`` logits."""
        return x @ params["w"] + params["b"]
